"""GBDT engine unit tests: binning, histograms, grower, objectives, model IO."""

import jax.numpy as jnp
import numpy as np
import pytest

from synapseml_tpu.gbdt import BoosterConfig, train_booster
from synapseml_tpu.gbdt.boosting import Booster
from synapseml_tpu.gbdt.grower import GrowerConfig, forest_predict, grow_tree
from synapseml_tpu.ops.histogram import leaf_histograms
from synapseml_tpu.ops.quantize import apply_bins, compute_bin_mapper


def test_bin_mapper_quantiles():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(5000, 3)).astype(np.float32)
    m = compute_bin_mapper(X, max_bin=64)
    binned = np.asarray(apply_bins(m, X))
    assert binned.max() < 64
    # bins should be roughly balanced for a continuous feature
    counts = np.bincount(binned[:, 0], minlength=64)
    nz = counts[counts > 0]
    assert nz.min() > 15


def test_bin_mapper_few_distinct_values():
    X = np.repeat(np.array([[0.0], [1.0], [2.0]], np.float32), 10, axis=0)
    m = compute_bin_mapper(X, max_bin=255)
    binned = np.asarray(apply_bins(m, X)).ravel()
    assert len(np.unique(binned)) == 3


def test_bin_mapper_nan_goes_last():
    X = np.array([[0.0], [1.0], [np.nan]], np.float32)
    base = np.linspace(0, 1, 100)[:, None].astype(np.float32)
    m = compute_bin_mapper(np.concatenate([X, base]), max_bin=16)
    binned = np.asarray(apply_bins(m, X)).ravel()
    assert binned[2] == binned.max()
    assert binned[2] > binned[1] > binned[0]


def test_leaf_histogram_matches_numpy():
    rng = np.random.default_rng(1)
    n, f, b, leaves = 500, 4, 16, 3
    binned = rng.integers(0, b, size=(n, f)).astype(np.uint8)
    node = rng.integers(0, leaves, size=n).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1, size=n).astype(np.float32)
    hist = np.asarray(leaf_histograms(jnp.asarray(binned), jnp.asarray(node),
                                      jnp.asarray(g), jnp.asarray(h), leaves, b))
    for leaf in range(leaves):
        for feat in range(f):
            mask = node == leaf
            expect_g = np.bincount(binned[mask, feat], weights=g[mask], minlength=b)
            np.testing.assert_allclose(hist[leaf, feat, :, 0], expect_g, rtol=1e-4, atol=1e-4)
    # count channel sums to n for every feature
    assert np.allclose(hist[..., 2].sum(axis=(0, 2)), n)


def test_grow_tree_perfect_split():
    """A single feature perfectly separating labels must be found."""
    n = 200
    X = np.linspace(0, 1, n)[:, None].astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    # max_bin > #distinct values → midpoint boundaries → the exact 0.5 split
    # exists (min_data_in_bin=1: the default 3 merges single-sample bins,
    # matching native LightGBM's minDataPerBin default)
    m = compute_bin_mapper(X, max_bin=255, min_data_in_bin=1)
    binned = apply_bins(m, X)
    g = jnp.asarray(0.5 - y)   # logistic grad at score 0
    h = jnp.full(n, 0.25)
    cfg = GrowerConfig(num_leaves=4, num_bins=255, min_data_in_leaf=5)
    tree, node = grow_tree(binned, g, h, jnp.ones(n), jnp.ones(1, bool),
                           jnp.zeros(1, bool), jnp.zeros(1, jnp.int32), cfg)
    assert int(tree.num_splits) >= 1
    # first split must be on feature 0 near the middle
    assert int(tree.split_feature[0]) == 0
    node = np.asarray(node)
    # left group gets positive leaf value (negative grad sum → pulls up)
    vals = np.asarray(tree.leaf_value)[node]
    assert (vals[y == 1] > 0).all() and (vals[y == 0] < 0).all()


def test_monotone_constraint_enforced():
    rng = np.random.default_rng(2)
    n = 2000
    X = rng.uniform(size=(n, 1)).astype(np.float32)
    y = np.sin(X[:, 0] * 6).astype(np.float32)    # non-monotone target
    cfg = BoosterConfig(objective="regression", num_iterations=20,
                        monotone_constraints=[1])
    bst = train_booster(X, y, cfg)
    grid = np.linspace(0.01, 0.99, 50)[:, None].astype(np.float32)
    pred = bst.predict(grid)
    assert (np.diff(pred) >= -1e-6).all()


def test_categorical_split():
    rng = np.random.default_rng(3)
    n = 2000
    cats = rng.integers(0, 10, size=n)
    y = np.isin(cats, [2, 5, 7]).astype(np.float32)   # value only via subset
    X = np.stack([cats.astype(np.float32), rng.normal(size=n).astype(np.float32)], 1)
    cfg = BoosterConfig(objective="binary", num_iterations=10)
    bst = train_booster(X, y, cfg, categorical_features=[0])
    p = bst.predict(X)
    assert ((p > 0.5) == (y > 0.5)).mean() > 0.99


def test_objectives_gradient_check():
    from synapseml_tpu.gbdt.objectives import get_objective

    rng = np.random.default_rng(4)
    score = jnp.asarray(rng.normal(size=50).astype(np.float32))
    w = jnp.ones(50)
    for name, y in [
        ("binary", (rng.uniform(size=50) > 0.5).astype(np.float32)),
        ("regression", rng.normal(size=50).astype(np.float32)),
        ("poisson", rng.poisson(3.0, size=50).astype(np.float32)),
        ("tweedie", rng.gamma(2.0, size=50).astype(np.float32)),
    ]:
        import jax

        obj = get_objective(name, num_class=1)
        loss = {
            "binary": lambda s: -jnp.mean(yj * jax.nn.log_sigmoid(s)
                                          + (1 - yj) * jax.nn.log_sigmoid(-s)) * 50,
            "regression": lambda s: 0.5 * jnp.sum((s - yj) ** 2),
            "poisson": lambda s: jnp.sum(jnp.exp(s) - yj * s),
            "tweedie": lambda s: jnp.sum(-yj * jnp.exp((1 - 1.5) * s) / (1 - 1.5)
                                         + jnp.exp((2 - 1.5) * s) / (2 - 1.5)),
        }[name]
        yj = jnp.asarray(y)
        g_expect = jax.grad(loss)(score)
        g, h = obj.grad_hess(score, yj, w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_expect), rtol=2e-3, atol=2e-3)
        assert (np.asarray(h) > 0).all()


def test_model_string_roundtrip(binary_data):
    Xtr, Xte, ytr, yte = binary_data
    cfg = BoosterConfig(objective="binary", num_iterations=10)
    bst = train_booster(Xtr, ytr, cfg)
    s = bst.model_string()
    assert s.startswith("tree\nversion=v3")
    b2 = Booster.from_model_string(s)
    np.testing.assert_allclose(b2.predict(Xte), bst.predict(Xte), atol=1e-5)


def test_feature_importances(binary_data):
    Xtr, _, ytr, _ = binary_data
    bst = train_booster(Xtr, ytr, BoosterConfig(objective="binary", num_iterations=5))
    imp = bst.feature_importances("split")
    assert imp.sum() > 0 and (imp >= 0).all()
    gain = bst.feature_importances("gain")
    assert gain.sum() > 0


def test_shap_additivity(binary_data):
    Xtr, Xte, ytr, _ = binary_data
    bst = train_booster(Xtr, ytr, BoosterConfig(objective="binary", num_iterations=10))
    sh = bst.feature_shap(Xte[:20])
    raw = bst.raw_score(Xte[:20])
    np.testing.assert_allclose(sh.sum(axis=1), raw, atol=1e-4)


def test_warm_start_continues(binary_data):
    Xtr, Xte, ytr, yte = binary_data
    cfg = BoosterConfig(objective="binary", num_iterations=5)
    b1 = train_booster(Xtr, ytr, cfg)
    b2 = train_booster(Xtr, ytr, BoosterConfig(objective="binary", num_iterations=5),
                       init_model=b1)
    assert b2.num_trees == 10
    from sklearn.metrics import log_loss

    assert log_loss(yte, b2.predict(Xte)) < log_loss(yte, b1.predict(Xte))


def test_dataset_prebinned_matches_raw(binary_data):
    """Dataset (LightGBM-Dataset analog: bin once, device-resident) must give
    the identical model to the raw-matrix path."""
    from synapseml_tpu.gbdt import Dataset

    X, _, y, _ = binary_data
    cfg = BoosterConfig(objective="binary", num_iterations=5, num_leaves=15)
    b_raw = train_booster(X, y, cfg)
    ds = Dataset(X, y).block_until_ready()
    b_ds = train_booster(ds, None, cfg)
    np.testing.assert_allclose(b_raw.predict(X[:100]), b_ds.predict(X[:100]),
                               rtol=1e-6)
    # labels/weights ride along; reuse across configs skips re-binning
    cfg2 = BoosterConfig(objective="binary", num_iterations=3, num_leaves=7,
                         seed=3)
    b2 = train_booster(ds, None, cfg2)
    assert len(b2.trees) == 3


@pytest.mark.parametrize("impl", ["scan", "scatter", "sort32"])
def test_partition_impl_matches_sort(binary_data, impl):
    """Every alternate stable-partition primitive must grow bitwise-identical
    trees to the argsort-based one (same src permutation by construction)."""
    X, _, y, _ = binary_data
    # baseline spelled out: env-flipped defaults must not make this vacuous
    cfg_s = BoosterConfig(objective="binary", num_iterations=4, num_leaves=15,
                          partition_impl="sort", row_layout="partition")
    cfg_c = BoosterConfig(objective="binary", num_iterations=4, num_leaves=15,
                          partition_impl=impl, row_layout="partition")
    b_s = train_booster(X, y, cfg_s)
    b_c = train_booster(X, y, cfg_c)
    for ts, tc in zip(b_s.trees, b_c.trees):
        np.testing.assert_array_equal(np.asarray(ts.split_feature),
                                      np.asarray(tc.split_feature))
        np.testing.assert_allclose(np.asarray(ts.leaf_value),
                                   np.asarray(tc.leaf_value), rtol=1e-6)


@pytest.mark.parametrize("layout", ["masked", "gather"])
def test_row_layout_matches_partition(binary_data, layout):
    """Every alternate row layout (masked: no row movement, full-N masked
    histograms; gather: pos-only permutation with child gathers) must grow
    identical trees to the partitioned grower, including NaN routing."""
    X, _, y, _ = binary_data
    X = np.array(X)
    X[::7, 3] = np.nan                 # exercise learned missing direction
    for extra in ({"num_leaves": 15},
                  {"num_leaves": 31, "min_data_in_leaf": 5}):
        cfg_p = BoosterConfig(objective="binary", num_iterations=4,
                              row_layout="partition", partition_impl="sort",
                              **extra)
        cfg_m = BoosterConfig(objective="binary", num_iterations=4,
                              row_layout=layout, partition_impl="sort",
                              **extra)
        b_p = train_booster(X, y, cfg_p)
        b_m = train_booster(X, y, cfg_m)
        for tp, tm in zip(b_p.trees, b_m.trees):
            np.testing.assert_array_equal(np.asarray(tp.split_feature),
                                          np.asarray(tm.split_feature))
            np.testing.assert_array_equal(np.asarray(tp.split_bin),
                                          np.asarray(tm.split_bin))
            np.testing.assert_array_equal(np.asarray(tp.default_left),
                                          np.asarray(tm.default_left))
            np.testing.assert_allclose(np.asarray(tp.leaf_value),
                                       np.asarray(tm.leaf_value), rtol=1e-5,
                                       atol=1e-7)
        np.testing.assert_allclose(b_p.predict(X[:100]), b_m.predict(X[:100]),
                                   rtol=1e-5)


@pytest.mark.parametrize("layout", ["masked", "gather"])
def test_row_layout_categorical(layout):
    rng = np.random.default_rng(3)
    n = 2000
    cats = rng.integers(0, 10, size=n)
    y = np.isin(cats, [2, 5, 7]).astype(np.float32)
    X = np.stack([cats.astype(np.float32),
                  rng.normal(size=n).astype(np.float32)], 1)
    cfg = BoosterConfig(objective="binary", num_iterations=8,
                        row_layout=layout)
    bst = train_booster(X, y, cfg, categorical_features=[0])
    p = bst.predict(X)
    assert ((p > 0.5) == (y > 0.5)).mean() > 0.99


def test_sparse_csr_input_matches_dense():
    """scipy CSR input (the reference's sparse dataset path) must train the
    identical model to the densified matrix, via Dataset and directly."""
    import scipy.sparse as sp

    from synapseml_tpu.gbdt import Dataset

    rng = np.random.default_rng(7)
    n, f = 3000, 12
    dense = rng.normal(size=(n, f)).astype(np.float32)
    dense[rng.uniform(size=(n, f)) < 0.8] = 0.0      # 80% sparse
    y = (dense[:, 0] + 0.5 * dense[:, 1] > 0.1).astype(np.float32)
    csr = sp.csr_matrix(dense)

    cfg = BoosterConfig(objective="binary", num_iterations=5, num_leaves=15)
    b_dense = train_booster(dense, y, cfg)
    b_csr = train_booster(csr, y, cfg)
    np.testing.assert_allclose(b_dense.predict(dense[:100]),
                               b_csr.predict(dense[:100]), rtol=1e-6)

    ds = Dataset(csr, label=y)
    assert ds.X is None and ds._sparse is not None
    b_ds = train_booster(ds, None, cfg)
    np.testing.assert_allclose(b_dense.predict(dense[:100]),
                               b_ds.predict(dense[:100]), rtol=1e-6)
    # warm start needs raw rows -> densified on demand from the kept CSR
    b_warm = train_booster(ds, None, cfg, init_model=b_ds)
    assert b_warm.num_trees == 10


def test_sparse_nan_election_beyond_sample():
    """NaN-bin election for sparse input must see the FULL matrix: a NaN that
    exists only outside the boundary sample still gets a dedicated NaN bin."""
    import scipy.sparse as sp

    from synapseml_tpu.gbdt import Dataset

    rng = np.random.default_rng(11)
    n = 3000
    dense = rng.normal(size=(n, 3)).astype(np.float32)
    dense[rng.uniform(size=(n, 3)) < 0.7] = 0.0
    # NaNs in feature 1 confined to the TAIL rows: with bin_sample_count=256
    # and seed=0 the row sample misses most of them with high probability,
    # but the full-matrix election must still flag the feature
    dense[n - 5:, 1] = np.nan
    csr = sp.csr_matrix(dense)
    ds = Dataset(csr, bin_sample_count=256)
    assert bool(ds.mapper.nan_mask[1])
    binned = np.asarray(ds.binned)
    nanbin = ds.mapper.nan_bins[1]
    assert (binned[n - 5:, 1] == nanbin).all()

    # predict accepts CSR too
    y = (np.nan_to_num(dense[:, 0]) > 0).astype(np.float32)
    b = train_booster(Dataset(csr, label=y),
                      None, BoosterConfig(objective="binary", num_iterations=3))
    p_csr = b.predict(csr[:50])
    p_dense = b.predict(dense[:50])
    np.testing.assert_allclose(p_csr, p_dense, rtol=1e-6)


def test_feature_fraction_bynode():
    """Per-node feature sampling: deterministic per seed, actually restricts
    the per-node search, and samples identically in the fused scan and the
    host loop (a no-op callback forces the host path)."""
    rng = np.random.default_rng(5)
    n = 2000
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2] > 0).astype(np.float32)
    cfg = BoosterConfig(objective="binary", num_iterations=5, num_leaves=15,
                        feature_fraction_bynode=0.5, seed=9)
    b1 = train_booster(X, y, cfg)
    b2 = train_booster(X, y, cfg)
    for t1, t2 in zip(b1.trees, b2.trees):        # deterministic
        np.testing.assert_array_equal(np.asarray(t1.split_feature),
                                      np.asarray(t2.split_feature))
    b_full = train_booster(X, y, BoosterConfig(
        objective="binary", num_iterations=5, num_leaves=15, seed=9))
    diff = any(not np.array_equal(np.asarray(a.split_feature),
                                  np.asarray(b.split_feature))
               for a, b in zip(b1.trees, b_full.trees))
    assert diff, "bynode sampling had no effect on split choices"
    # fused (b1) vs host loop (callback forces host path) must match exactly
    b_host = train_booster(X, y, cfg, callbacks=[lambda it, trees: None])
    for tf, th in zip(b1.trees, b_host.trees):
        np.testing.assert_array_equal(np.asarray(tf.split_feature),
                                      np.asarray(th.split_feature))
        np.testing.assert_allclose(np.asarray(tf.leaf_value),
                                   np.asarray(th.leaf_value), rtol=1e-6)
    # accuracy stays sane
    assert ((b1.predict(X) > 0.5) == (y > 0.5)).mean() > 0.9


def test_stratified_pos_neg_bagging():
    """posBaggingFraction / negBaggingFraction: per-class sampling rates show
    up in the realized in-bag class balance; fused and host paths agree."""
    rng = np.random.default_rng(6)
    n = 4000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    cfg = BoosterConfig(objective="binary", num_iterations=4,
                        bagging_freq=1, pos_bagging_fraction=0.9,
                        neg_bagging_fraction=0.2, seed=3)
    b = train_booster(X, y, cfg)
    # with negatives sampled at 0.2 vs positives 0.9, root counts shrink
    # asymmetrically; verify via internal_count of the first tree's root
    root_count = int(np.asarray(b.trees[0].internal_count)[0])
    expected = 0.9 * (y > 0).sum() + 0.2 * (y == 0).sum()
    assert abs(root_count - expected) < 0.15 * expected
    # host path (forced by callback) samples identically
    b_host = train_booster(X, y, cfg, callbacks=[lambda it, trees: None])
    for tf, th in zip(b.trees, b_host.trees):
        np.testing.assert_array_equal(np.asarray(tf.split_feature),
                                      np.asarray(th.split_feature))


def test_dart_weighted_drop_runs():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(1000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    for uniform in (False, True):
        cfg = BoosterConfig(objective="binary", num_iterations=8,
                            boosting_type="dart", drop_rate=0.5,
                            skip_drop=0.0, uniform_drop=uniform, seed=2)
        b = train_booster(X, y, cfg)
        assert b.num_trees == 8
        assert ((b.predict(X) > 0.5) == (y > 0.5)).mean() > 0.9


def test_fused_cache_key_covers_stratified_bagging():
    """Two same-process fits differing only in neg_bagging_fraction must not
    share a fused executable (the fractions are traced-in constants)."""
    rng = np.random.default_rng(12)
    X = rng.normal(size=(2000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    c1 = BoosterConfig(objective="binary", num_iterations=3, bagging_freq=1,
                       seed=2)
    c2 = BoosterConfig(objective="binary", num_iterations=3, bagging_freq=1,
                       seed=2, neg_bagging_fraction=0.2)
    rc1 = int(np.asarray(train_booster(X, y, c1).trees[0].internal_count)[0])
    rc2 = int(np.asarray(train_booster(X, y, c2).trees[0].internal_count)[0])
    assert rc1 == 2000 and rc2 < 1500, (rc1, rc2)
    # non-binary objectives reject stratified bagging (native parity)
    with pytest.raises(ValueError):
        train_booster(X, np.abs(X[:, 0]),
                      BoosterConfig(objective="regression", num_iterations=2,
                                    bagging_freq=1, pos_bagging_fraction=0.5))


def test_depth_bounded_inference_matches_full_walk(binary_data):
    """Predictions with the true-max-depth pointer chase must equal the
    worst-case num_leaves-1 walk."""
    from synapseml_tpu.gbdt.grower import forest_max_depth, forest_predict

    Xtr, Xte, ytr, _ = binary_data
    bst = train_booster(Xtr, ytr, BoosterConfig(objective="binary",
                                                num_iterations=8))
    d = forest_max_depth(bst.trees)
    assert 1 <= d <= bst.config.num_leaves - 1
    forest = bst.forest()
    full = forest_predict(forest, jnp.asarray(Xte[:100]), output="sum")
    fast = forest_predict(forest, jnp.asarray(Xte[:100]), output="sum",
                          depth=d)
    np.testing.assert_allclose(np.asarray(fast), np.asarray(full), rtol=1e-6)
    # the booster's own predict path uses the cached depth
    assert bst._depth_cache == d
    p = bst.predict(Xte[:50])
    assert np.isfinite(p).all()


def test_dump_model_json(binary_data):
    """dumpModel parity: LightGBM-format JSON with a recursive
    tree_structure whose leaf values reproduce the model's predictions."""
    import json

    Xtr, Xte, ytr, _ = binary_data
    bst = train_booster(Xtr, ytr, BoosterConfig(objective="binary",
                                                num_iterations=4))
    doc = json.loads(bst.dump_model())
    assert doc["name"] == "tree" and doc["num_tree_per_iteration"] == 1
    assert len(doc["tree_info"]) == 4
    assert doc["objective"].startswith("binary")
    t0 = doc["tree_info"][0]["tree_structure"]
    assert t0["decision_type"] in ("<=", "==") and "left_child" in t0

    # walk the JSON tree by hand for a few rows; raw sum must match raw_score
    def walk(node, row):
        while "leaf_value" not in node:
            f, thr = node["split_feature"], node["threshold"]
            x = row[f]
            if np.isnan(x):
                go_left = node["default_left"]
            else:
                go_left = x <= thr
            node = node["left_child"] if go_left else node["right_child"]
        return node["leaf_value"]

    # base score is folded into the first tree's leaves (LightGBM stores no
    # separate base), so the plain leaf sum IS the raw score
    raw = bst.raw_score(Xte[:20])
    for i in range(20):
        s = sum(walk(t["tree_structure"], Xte[i]) for t in doc["tree_info"])
        np.testing.assert_allclose(s, raw[i], rtol=1e-5, atol=1e-6)

    # categorical split: "a||b" threshold string, and routing matches
    rng = np.random.default_rng(3)
    cats = rng.integers(0, 8, size=1500)
    yc = np.isin(cats, [2, 5]).astype(np.float32)
    Xc = np.stack([cats.astype(np.float32),
                   rng.normal(size=1500).astype(np.float32)], 1)
    bc = train_booster(Xc, yc, BoosterConfig(objective="binary",
                                             num_iterations=2),
                       categorical_features=[0])
    dc = json.loads(bc.dump_model())
    root = dc["tree_info"][0]["tree_structure"]
    assert root["decision_type"] == "=="
    left_cats = {int(v) for v in root["threshold"].split("||")}
    assert left_cats and left_cats <= set(range(8))

    def walk_cat(node, row):
        while "leaf_value" not in node:
            if node["decision_type"] == "==":
                inset = str(int(row[node["split_feature"]])) in                     node["threshold"].split("||")
                node = node["left_child"] if inset else node["right_child"]
            else:
                node = (node["left_child"]
                        if row[node["split_feature"]] <= node["threshold"]
                        else node["right_child"])
        return node["leaf_value"]

    raw_c = bc.raw_score(Xc[:30])
    for i in range(30):
        s = sum(walk_cat(t["tree_structure"], Xc[i])
                for t in dc["tree_info"])
        np.testing.assert_allclose(s, raw_c[i], rtol=1e-4, atol=1e-5)


def test_predict_num_iteration(binary_data):
    """num_iteration-limited scoring equals a booster truncated to that many
    rounds (LightGBM predict num_iteration semantics)."""
    Xtr, Xte, ytr, _ = binary_data
    bst = train_booster(Xtr, ytr, BoosterConfig(objective="binary",
                                                num_iterations=8))
    short = Booster(bst.mapper, bst.config, bst.trees[:3],
                    bst.tree_weights[:3], bst.base_score)
    np.testing.assert_allclose(bst.raw_score(Xte[:50], num_iteration=3),
                               short.raw_score(Xte[:50]), rtol=1e-6)
    # out-of-range request clamps to the full model
    np.testing.assert_allclose(bst.raw_score(Xte[:50], num_iteration=99),
                               bst.raw_score(Xte[:50]), rtol=1e-6)

    # rf: prefix scoring must RE-average over the prefix count
    rf = train_booster(Xtr, ytr, BoosterConfig(
        objective="binary", num_iterations=6, boosting_type="rf",
        bagging_freq=1, bagging_fraction=0.6, seed=4))
    rf_short = Booster(rf.mapper, rf.config, rf.trees[:2],
                       rf.tree_weights[:2], rf.base_score)
    np.testing.assert_allclose(rf.raw_score(Xte[:50], num_iteration=2),
                               rf_short.raw_score(Xte[:50]), rtol=1e-5)


def test_multiclass_shap_additivity():
    """Multiclass pred_contrib: per-class blocks of (F+1) whose sums equal
    the per-class raw scores (LightGBM layout)."""
    rng = np.random.default_rng(13)
    n, f, k = 600, 5, 3
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.float32) \
        + (X[:, 1] > 0.5)
    bst = train_booster(X, y.astype(np.float32),
                        BoosterConfig(objective="multiclass", num_class=k,
                                      num_iterations=4))
    sh = bst.feature_shap(X[:25])
    assert sh.shape == (25, k * (f + 1))
    raw = bst.raw_score(X[:25])                    # (N, K)
    blocks = sh.reshape(25, k, f + 1)
    np.testing.assert_allclose(blocks.sum(axis=2), raw, atol=1e-4)


def test_new_native_params():
    """minDataPerBin / maxBinByFeature / cat_l2 / seeds / start_iteration."""
    rng = np.random.default_rng(14)
    X = rng.normal(size=(2000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    # maxBinByFeature caps a single feature's bins
    m = compute_bin_mapper(X, max_bin=63, max_bin_by_feature=[8, 63, 63, 63])
    assert m.num_bins[0] <= 8 and m.num_bins[1] > 8

    # min_data_in_bin merges under-filled bins
    sparse_vals = np.concatenate([np.zeros(1990), np.arange(10)]).astype(
        np.float32)[:, None]
    m1 = compute_bin_mapper(sparse_vals, max_bin=255, min_data_in_bin=1)
    m3 = compute_bin_mapper(sparse_vals, max_bin=255, min_data_in_bin=5)
    assert m3.num_bins[0] < m1.num_bins[0]

    # cat_l2 regularizes categorical gains (huge value suppresses cat splits)
    cats = rng.integers(0, 6, size=2000).astype(np.float32)
    Xc = np.stack([cats, X[:, 1]], 1)
    yc = np.isin(cats, [1, 4]).astype(np.float32)
    b_lo = train_booster(Xc, yc, BoosterConfig(objective="binary",
                                               num_iterations=1, cat_l2=0.0),
                         categorical_features=[0])
    b_hi = train_booster(Xc, yc, BoosterConfig(objective="binary",
                                               num_iterations=1, cat_l2=1e9),
                         categorical_features=[0])
    assert int(np.asarray(b_lo.trees[0].split_type)[0]) == 1
    assert int(np.asarray(b_hi.trees[0].split_type)[0]) == 0

    # independent seeds change the sampled feature masks
    import jax

    from synapseml_tpu.gbdt.boosting import _sample_features_impl
    base = BoosterConfig(objective="binary", feature_fraction=0.5, seed=7)
    alt = BoosterConfig(objective="binary", feature_fraction=0.5, seed=7,
                        feature_fraction_seed=99)
    key = jax.random.PRNGKey(7)
    masks_a = [np.asarray(_sample_features_impl(base, 24, key, it))
               for it in range(4)]
    masks_b = [np.asarray(_sample_features_impl(alt, 24, key, it))
               for it in range(4)]
    assert any(not np.array_equal(a, b) for a, b in zip(masks_a, masks_b))

    # start_iteration drops the leading rounds at predict time
    bst = train_booster(X, y, BoosterConfig(objective="binary",
                                            num_iterations=6))
    import dataclasses
    bst.config = dataclasses.replace(bst.config, start_iteration=2)
    tail = Booster(bst.mapper,
                   dataclasses.replace(bst.config, start_iteration=0),
                   bst.trees[2:], bst.tree_weights[2:], bst.base_score)
    np.testing.assert_allclose(bst.raw_score(X[:50]),
                               tail.raw_score(X[:50]), rtol=1e-6)
    # SHAP honors the window (additivity against the windowed prediction)
    sh = bst.feature_shap(X[:10])
    np.testing.assert_allclose(sh.sum(axis=1), bst.raw_score(X[:10]),
                               atol=1e-4)
    # ...but warm starts must NOT inherit the window: continued training sees
    # the full margin
    b2 = train_booster(X, y, BoosterConfig(objective="binary",
                                           num_iterations=2),
                       init_model=bst)
    full = Booster(bst.mapper,
                   dataclasses.replace(bst.config, start_iteration=0),
                   bst.trees, bst.tree_weights, bst.base_score)
    b2_ref = train_booster(X, y, BoosterConfig(objective="binary",
                                               num_iterations=2),
                           init_model=full)
    np.testing.assert_allclose(
        np.asarray(b2.trees[-1].leaf_value),
        np.asarray(b2_ref.trees[-1].leaf_value), rtol=1e-6)


def test_categorical_onehot_and_group_params():
    """maxCatToOnehot (one-vs-rest for small cardinality) and
    minDataPerGroup (thin groups excluded) semantics."""
    rng = np.random.default_rng(15)
    n = 3000
    cats = rng.integers(0, 3, size=n)          # 3 categories <= onehot cap 4
    y = (cats == 1).astype(np.float32)
    X = np.stack([cats.astype(np.float32),
                  rng.normal(size=n).astype(np.float32)], 1)
    bst = train_booster(X, y, BoosterConfig(objective="binary",
                                            num_iterations=10,
                                            min_data_per_group=1),
                        categorical_features=[0])
    t0 = bst.trees[0]
    assert int(np.asarray(t0.split_type)[0]) == 1
    # one-vs-rest: exactly ONE category in the left bitset
    bits = np.asarray(t0.cat_bitset)[0]
    popcount = sum(bin(int(w)).count("1") for w in bits)
    assert popcount == 1
    assert ((bst.predict(X) > 0.5) == (y > 0.5)).mean() > 0.99

    # minDataPerGroup: a tiny perfectly-separating category is ignored when
    # the threshold exceeds its size
    cats2 = np.where(np.arange(n) < 20, 7, rng.integers(0, 3, size=n))
    y2 = (cats2 == 7).astype(np.float32)
    X2 = np.stack([cats2.astype(np.float32),
                   rng.normal(size=n).astype(np.float32)], 1)
    b_lo = train_booster(X2, y2, BoosterConfig(objective="binary",
                                               num_iterations=1,
                                               min_data_per_group=1,
                                               min_data_in_leaf=5),
                         categorical_features=[0])
    b_hi = train_booster(X2, y2, BoosterConfig(objective="binary",
                                               num_iterations=1,
                                               min_data_per_group=100,
                                               min_data_in_leaf=5),
                         categorical_features=[0])
    # low threshold isolates category 7 immediately; high threshold cannot
    bits_lo = np.asarray(b_lo.trees[0].cat_bitset)[0]
    assert (bits_lo[7 >> 5] >> (7 & 31)) & 1
    bits_hi = np.asarray(b_hi.trees[0].cat_bitset)[0]
    assert not ((bits_hi[7 >> 5] >> (7 & 31)) & 1)


def test_xgboost_dart_mode_runs():
    rng = np.random.default_rng(16)
    X = rng.normal(size=(1000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    b = train_booster(X, y, BoosterConfig(objective="binary",
                                          num_iterations=6,
                                          boosting_type="dart",
                                          drop_rate=0.5, skip_drop=0.0,
                                          xgboost_dart_mode=True, seed=3))
    assert b.num_trees == 6
    assert ((b.predict(X) > 0.5) == (y > 0.5)).mean() > 0.9


def test_weighted_quantile_zero_weight_tail_finite():
    """ADVICE r2: zero-weight rows sort last as an inf sentinel; when the
    quantile lands strictly inside the last positive row's span, the
    interpolation partner must NOT read the inf tail."""
    from synapseml_tpu.gbdt.objectives import _weighted_quantile

    y = jnp.asarray([1.0, 2.0, 7.0])
    w = jnp.asarray([1.0, 9.0, 0.0])       # third row bagged-out / padding
    q = float(_weighted_quantile(y, w, 0.5))
    assert np.isfinite(q), q
    # quantile of {1 (w=1), 2 (w=9)} at 0.5 interpolates inside row 2's span
    assert 1.0 <= q <= 2.0, q
    # init_score path end-to-end: an l1 fit with a zero-weight row stays finite
    rng = np.random.default_rng(5)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    yy = X[:, 0].astype(np.float32)
    sw = np.ones(64, np.float32)
    sw[-1] = 0.0
    b = train_booster(X, yy, BoosterConfig(objective="regression_l1",
                                           num_iterations=3),
                      sample_weight=sw)
    assert np.isfinite(b.predict(X)).all()


def test_fused_cache_key_covers_sampling_seeds():
    """ADVICE r2: extra_seed / feature_fraction_seed are traced-in Python
    ints — two fits differing only in them must not share an executable
    (i.e. must produce different sampling streams, hence different trees)."""
    from synapseml_tpu.gbdt.boosting import _fused_static_key

    base = dict(objective="binary", num_iterations=3, boosting_type="goss",
                feature_fraction=0.5, seed=7)
    c1 = BoosterConfig(**base)
    c2 = BoosterConfig(**base, extra_seed=99)
    c3 = BoosterConfig(**base, feature_fraction_seed=42)
    g = c1.grower(False)
    ks = {_fused_static_key(c, g, 512, 4, 1, 0, "auc", None)
          for c in (c1, c2, c3)}
    assert len(ks) == 3
    rng = np.random.default_rng(17)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.normal(size=512) > 0).astype(np.float32)
    t1 = train_booster(X, y, c1).trees
    t3 = train_booster(X, y, c3).trees
    diff = any(not np.array_equal(np.asarray(a.split_feature),
                                  np.asarray(b.split_feature))
               for a, b in zip(t1, t3))
    assert diff, "feature_fraction_seed had no effect (stale fused cache?)"


def test_cat_counts_from_full_column():
    """ADVICE r2: cat_counts (maxCatToOnehot decision) counts distinct
    categories on the FULL column, not the bin-boundary subsample."""
    rng = np.random.default_rng(3)
    n = 5000
    X = rng.normal(size=(n, 2)).astype(np.float32)
    # category column: values 0..2 everywhere except ONE row with value 7
    c = rng.integers(0, 3, size=n).astype(np.float32)
    c[1234] = 7.0
    X[:, 1] = c
    m = compute_bin_mapper(X, sample_count=100, categorical_features=[1],
                           seed=0)
    assert int(m.cat_counts[1]) == 4


def test_cat_presence_sparse_and_override():
    """Sparse path: cat bin occupancy from the FULL CSR matrix (implicit
    zeros + explicit entries), not the boundary sample."""
    import scipy.sparse as sp

    from synapseml_tpu.gbdt.dataset import bin_sparse

    rng = np.random.default_rng(9)
    n = 4000
    dense = np.zeros((n, 3), np.float32)
    dense[:, 0] = rng.normal(size=n)
    # cat col: mostly implicit zeros, a few 1s/2s, ONE row of category 6
    idx = rng.choice(n, size=60, replace=False)
    dense[idx, 1] = rng.integers(1, 3, size=60).astype(np.float32)
    dense[idx[0], 1] = 6.0
    dense[:, 2] = rng.normal(size=n)
    mapper, binned = bin_sparse(sp.csr_matrix(dense), None, 255,
                                bin_sample_count=200,
                                categorical_features=[1], seed=0)
    # distinct bins: {0, 1 or 2 (at least one), 6} — exact count from FULL data
    expect = len(np.unique(dense[:, 1]))
    assert int(mapper.cat_counts[1]) == expect, (mapper.cat_counts[1], expect)


def test_param_list_default_not_shared():
    """get() must hand out a COPY of mutable class-level defaults."""
    from synapseml_tpu.models.gbdt import LightGBMRanker

    r1 = LightGBMRanker()
    lst = r1.getEvalAt()
    lst.append(99)
    assert r1.getEvalAt() == [1, 2, 3, 4, 5]
    assert LightGBMRanker().getEvalAt() == [1, 2, 3, 4, 5]


def test_shap_additivity_with_missing_values():
    """pred_contrib must follow the PREDICTION path's missing routing:
    contributions on NaN rows sum to the raw score (LightGBM TreeSHAP uses
    the same Decision fn as inference)."""
    from synapseml_tpu.gbdt.shap import forest_shap

    rng = np.random.default_rng(23)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    X[rng.random(500) < 0.3, 0] = np.nan
    X[:, 3] = rng.integers(0, 4, size=500)
    X[rng.random(500) < 0.2, 3] = np.nan
    y = (np.nan_to_num(X[:, 0]) + X[:, 1] > 0).astype(np.float32)
    bst = train_booster(X, y, BoosterConfig(objective="binary",
                                            num_iterations=5, num_leaves=8),
                        categorical_features=[3])
    Xt = X[:80]
    contrib = forest_shap(bst, Xt)
    np.testing.assert_allclose(contrib.sum(axis=1), bst.raw_score(Xt),
                               rtol=1e-4, atol=1e-4)


def test_shap_additivity_categorical_edge_values():
    """Categorical SHAP routing parity on edge inputs: -0.5 (tests category
    0), +inf / out-of-range (clip to last tracked bit) — same conversion as
    the prediction path, no crash."""
    from synapseml_tpu.gbdt.shap import forest_shap

    rng = np.random.default_rng(29)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    X[:, 2] = rng.integers(0, 4, size=400)
    y = ((X[:, 2] == 0) | (X[:, 0] > 0.8)).astype(np.float32)
    bst = train_booster(X, y, BoosterConfig(objective="binary",
                                            num_iterations=4, num_leaves=8),
                        categorical_features=[2])
    Xt = X[:12].copy()
    Xt[0, 2] = -0.5          # truncates to category 0
    Xt[1, 2] = np.inf        # clips to the last tracked bit
    Xt[2, 2] = 1e9           # out-of-range
    Xt[3, 2] = -7.0          # clips to -1 -> never a member
    contrib = forest_shap(bst, Xt)
    np.testing.assert_allclose(contrib.sum(axis=1), bst.raw_score(Xt),
                               rtol=1e-4, atol=1e-4)


def test_map_metric_hand_computed_and_early_stopping():
    """map@k eval (LightGBM MapMetric): hand-computed AP on a known ranking,
    plus metric="map@2" driving ranker validation without error."""
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import make_grouped, map_at_k

    # one query, 4 docs; scores rank doc order [d0, d1, d2, d3];
    # relevance [1, 0, 1, 0] -> AP@4 = (1/1 + 2/3) / 2 = 0.8333
    labels = np.asarray([1.0, 0.0, 1.0, 0.0])
    scores = np.asarray([4.0, 3.0, 2.0, 1.0])
    gi = make_grouped(labels, np.asarray([4]))
    v = float(map_at_k(jnp.asarray(labels), jnp.asarray(scores), gi, 4))
    assert abs(v - (1.0 + 2.0 / 3.0) / 2.0) < 1e-6, v
    # AP@1: only d0 counted, denom min(2,1)=1 -> 1.0
    v1 = float(map_at_k(jnp.asarray(labels), jnp.asarray(scores), gi, 1))
    assert abs(v1 - 1.0) < 1e-6, v1

    rng = np.random.default_rng(11)
    n, q = 600, 30
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (rng.random(n) < 0.3).astype(np.float32)
    sizes = np.full(q, n // q, np.int64)
    cfg = BoosterConfig(objective="lambdarank", num_iterations=8,
                        metric="map@2", early_stopping_round=3)
    bst = train_booster(X, y, cfg, group_sizes=sizes,
                        valid=(X, y, None, sizes))
    assert bst.num_trees >= 1


def test_mape_metric_not_misrouted_to_ranking():
    """'mape' must reach the pointwise metric table — startswith('map')
    would have misrouted it into the ranking branch."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(300, 3)).astype(np.float32)
    y = np.abs(X[:, 0]).astype(np.float32) + 1.0
    b = train_booster(X, y, BoosterConfig(objective="mape", metric="mape",
                                          num_iterations=4),
                      valid=(X, y))
    assert b.num_trees >= 1
    assert np.isfinite(b.predict(X[:10])).all()


def test_objective_loss_metrics_drive_validation():
    """Exp-family / robust objectives early-stop on their own loss
    (LightGBM default metric = the objective), with cfg hyper-parameters
    reaching the metric."""
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import METRICS

    # hand-check: quantile pinball at alpha 0.8 on a known pair
    y = jnp.asarray([2.0, 0.0])
    pred = jnp.asarray([0.0, 1.0])
    v = float(METRICS["quantile"](y, pred, alpha=0.8))
    # d = [2, -1]: max(.8*2, -.2*2)=1.6; max(.8*-1, -.2*-1)=0.2 -> mean 0.9
    assert abs(v - 0.9) < 1e-6, v
    # poisson NLL decreases as pred approaches y
    a = float(METRICS["poisson"](jnp.asarray([3.0]), jnp.asarray([3.0])))
    b = float(METRICS["poisson"](jnp.asarray([3.0]), jnp.asarray([1.0])))
    assert a < b

    rng = np.random.default_rng(31)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    yv = np.exp(X[:, 0] * 0.5 + 0.1 * rng.normal(size=400)).astype(np.float32)
    for obj in ("poisson", "tweedie", "quantile", "huber", "fair", "gamma"):
        bst = train_booster(X, yv, BoosterConfig(objective=obj,
                                                 num_iterations=4,
                                                 early_stopping_round=3),
                            valid=(X, yv))
        assert bst.num_trees >= 1, obj
        assert np.isfinite(bst.predict(X[:5])).all(), obj


def test_cross_entropy_soft_labels():
    """cross_entropy/xentropy: binary log-loss over CONTINUOUS labels in
    [0,1] (LightGBM xentropy); prediction is a probability."""
    rng = np.random.default_rng(37)
    X = rng.normal(size=(500, 3)).astype(np.float32)
    # soft targets: a noisy probability driven by f0
    y = (1.0 / (1.0 + np.exp(-2.0 * X[:, 0]))
         + 0.05 * rng.normal(size=500)).clip(0, 1).astype(np.float32)
    for obj in ("cross_entropy", "xentropy"):
        bst = train_booster(X, y, BoosterConfig(objective=obj,
                                                num_iterations=6,
                                                early_stopping_round=3),
                            valid=(X, y))
        p = bst.predict(X[:100])
        assert ((p >= 0) & (p <= 1)).all()
        # correlation with the soft target, not just finiteness
        assert np.corrcoef(p, y[:100])[0, 1] > 0.7


def test_weighted_validation_metrics():
    """Validation sample weights (valid[2]) weight the eval metric —
    LightGBM semantics. A weight vector concentrated on mispredicted rows
    must change the metric value."""
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import METRICS

    y = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    p = jnp.asarray([0.9, 0.1, 0.2, 0.8])    # rows 2,3 badly predicted
    unw = float(METRICS["binary_logloss"](y, p))
    heavy = float(METRICS["binary_logloss"](y, p,
                                            weight=jnp.asarray(
                                                [0.0, 0.0, 1.0, 1.0])))
    light = float(METRICS["binary_logloss"](y, p,
                                            weight=jnp.asarray(
                                                [1.0, 1.0, 0.0, 0.0])))
    assert light < unw < heavy
    # weighted rmse hand-check: sqrt((1*4 + 3*1)/4)
    r = float(METRICS["rmse"](jnp.asarray([0.0, 0.0]),
                              jnp.asarray([2.0, 1.0]),
                              weight=jnp.asarray([1.0, 3.0])))
    assert abs(r - np.sqrt((4.0 + 3.0) / 4.0)) < 1e-6

    # end-to-end: the recorded best_score IS the weighted logloss of the
    # best iteration's predictions (reverting the wv plumbing would leave
    # best_score at the unweighted value and fail this)
    rng = np.random.default_rng(41)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    yy = (X[:, 0] > 0).astype(np.float32)
    wv = np.ones(400, np.float32)
    wv[:200] = 10.0
    b = train_booster(X, yy, BoosterConfig(objective="binary",
                                           num_iterations=4,
                                           metric="binary_logloss"),
                      valid=(X, yy, wv, None))
    pred_best = b.predict(X, num_iteration=b.best_iteration + 1)
    expect_w = float(METRICS["binary_logloss"](
        jnp.asarray(yy), jnp.asarray(pred_best), weight=jnp.asarray(wv)))
    expect_unw = float(METRICS["binary_logloss"](jnp.asarray(yy),
                                                 jnp.asarray(pred_best)))
    assert abs(b.best_score - expect_w) < 1e-5, (b.best_score, expect_w)
    assert abs(expect_w - expect_unw) > 1e-6   # the weights actually matter


def test_auc_tie_correction():
    """AUC handles tied scores via the trapezoid rule (half credit), with
    weights — validated against hand computation and random agreement with
    the rank formula when no ties exist."""
    import jax.numpy as jnp

    from synapseml_tpu.gbdt.objectives import auc

    # all scores tied -> AUC exactly 0.5 (previously 0.0/1.0 by sort order)
    y = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    s = jnp.zeros(4)
    assert abs(float(auc(y, s)) - 0.5) < 1e-6
    # hand case: scores [1,1,2], labels [0,1,1]: pos@1 ties one neg (0.5),
    # pos@2 beats one neg (1.0) -> auc = 1.5/2
    v = float(auc(jnp.asarray([0.0, 1.0, 1.0]), jnp.asarray([1.0, 1.0, 2.0])))
    assert abs(v - 0.75) < 1e-6
    # weighted hand case: same but neg weight 2: pos@1 -> 0.5*2, pos@2 -> 2
    v = float(auc(jnp.asarray([0.0, 1.0, 1.0]), jnp.asarray([1.0, 1.0, 2.0]),
                  jnp.asarray([2.0, 1.0, 1.0])))
    assert abs(v - (1.0 + 2.0) / (2.0 * 2.0)) < 1e-6
    # no ties: matches the Mann-Whitney rank statistic computed in numpy
    rng = np.random.default_rng(3)
    yy = (rng.random(200) > 0.5).astype(np.float32)
    sc = rng.normal(size=200).astype(np.float32)
    got = float(auc(jnp.asarray(yy), jnp.asarray(sc)))
    pos_s, neg_s = sc[yy > 0], sc[yy == 0]
    expect = (pos_s[:, None] > neg_s[None, :]).mean()
    assert abs(got - float(expect)) < 1e-5


def test_label_gain_table_wired():
    """labelGain (LightGBMRankerParams) replaces the default 2^label - 1
    gains in BOTH the lambdarank objective and the NDCG eval."""
    from synapseml_tpu.gbdt.objectives import make_grouped, ndcg_at_k

    labels = np.asarray([2.0, 1.0, 0.0])
    scores = np.asarray([1.0, 2.0, 3.0])   # worst ordering
    gi = make_grouped(labels, np.asarray([3]))
    # custom gains [0, 1, 10]: DCG = 0/1 + 1/log2(3) + 10/2;
    # IDCG = 10/1 + 1/log2(3) + 0
    got = float(ndcg_at_k(jnp.asarray(labels), jnp.asarray(scores), gi, 3,
                          label_gain=(0.0, 1.0, 10.0)))
    import math

    dcg = 1.0 / math.log2(3) + 10.0 / 2.0
    idcg = 10.0 + 1.0 / math.log2(3)
    assert abs(got - dcg / idcg) < 1e-6, got
    # default table still matches the old formula
    got_d = float(ndcg_at_k(jnp.asarray(labels), jnp.asarray(scores), gi, 3))
    dcg_d = 1.0 / math.log2(3) + 3.0 / 2.0
    idcg_d = 3.0 + 1.0 / math.log2(3)
    assert abs(got_d - dcg_d / idcg_d) < 1e-6

    # training with a degenerate gain table that nulls label 1 must differ
    # from the default (the table reaches the objective)
    rng = np.random.default_rng(19)
    n, q = 400, 20
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = rng.integers(0, 3, size=n).astype(np.float32)
    sizes = np.full(q, n // q, np.int64)
    b1 = train_booster(X, y, BoosterConfig(objective="lambdarank",
                                           num_iterations=4, seed=3),
                       group_sizes=sizes)
    b2 = train_booster(X, y, BoosterConfig(objective="lambdarank",
                                           num_iterations=4, seed=3,
                                           label_gain=(0.0, 0.0, 100.0)),
                       group_sizes=sizes)
    assert not np.allclose(b1.predict(X[:50]), b2.predict(X[:50]))


def test_label_gain_ragged_groups_and_validation():
    """Pad slots contribute ZERO gain even when the table's entry 0 is
    nonzero (ragged groups), and an undersized table fails fast like
    LightGBM."""
    import math

    from synapseml_tpu.gbdt.objectives import make_grouped, ndcg_at_k

    # ragged: group sizes (1, 3); nonzero gain for label 0
    labels = np.asarray([1.0, 1.0, 0.0, 0.0])
    scores = np.asarray([5.0, 3.0, 2.0, 1.0])
    gi = make_grouped(labels, np.asarray([1, 3]))
    got = float(ndcg_at_k(jnp.asarray(labels), jnp.asarray(scores), gi, 3,
                          label_gain=(1.0, 7.0)))
    # group 1 (single relevant doc): ndcg 1.0. group 2: perfect order of
    # [1,0,0] -> dcg = 7 + 1/log2(3) + 1/2, idcg identical -> 1.0
    assert abs(got - 1.0) < 1e-6, got
    with pytest.raises(ValueError, match="label_gain"):
        rng = np.random.default_rng(5)
        X = rng.normal(size=(40, 2)).astype(np.float32)
        y = rng.integers(0, 4, size=40).astype(np.float32)
        train_booster(X, y, BoosterConfig(objective="lambdarank",
                                          num_iterations=2,
                                          label_gain=(0.0, 1.0)),
                      group_sizes=np.full(4, 10, np.int64))


def test_serving_fn_matches_predict():
    """serving_fn (single fused jitted dispatch, the io/serving handler
    path) must agree with predict() for binary and multiclass models."""
    import numpy as np

    from synapseml_tpu.gbdt import BoosterConfig, Dataset, train_booster

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 6)).astype(np.float32)
    yb = (X[:, 0] * X[:, 1] > 0).astype(np.float32)
    b = train_booster(Dataset(X, yb), None,
                      BoosterConfig(objective="binary", num_iterations=10,
                                    num_leaves=15))
    np.testing.assert_allclose(np.asarray(b.serving_fn()(X)), b.predict(X),
                               rtol=1e-6, atol=1e-6)

    ym = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(np.float32)
    bm = train_booster(Dataset(X, ym), None,
                       BoosterConfig(objective="multiclass", num_class=3,
                                     num_iterations=6, num_leaves=7))
    np.testing.assert_allclose(np.asarray(bm.serving_fn()(X)),
                               bm.predict(X), rtol=1e-6, atol=1e-6)

    # the prediction window must apply to serving too (code-review r5)
    bw = train_booster(Dataset(X, yb), None,
                       BoosterConfig(objective="binary", num_iterations=10,
                                     num_leaves=15, start_iteration=4))
    np.testing.assert_allclose(np.asarray(bw.serving_fn()(X)),
                               bw.predict(X), rtol=1e-6, atol=1e-6)
