"""Websocket SpeechToTextSDK protocol tests against an in-process fake
Speech service (VERDICT missing #5; reference speech/SpeechToTextSDK.scala).
The fake server implements the server side of RFC 6455 plus the Speech USP
framing, so the full client path — handshake, speech.config, chunked audio,
phrase events, turn.end — is exercised without a network."""

import socket
import threading

import numpy as np
import pytest

from synapseml_tpu.core.table import Table
from synapseml_tpu.io.websocket import (OP_BINARY, OP_TEXT, WebSocketClient,
                                        decode_frame, encode_frame,
                                        server_handshake)
from synapseml_tpu.services.speech import (ConversationTranscription,
                                           SpeechToTextSDK, usp_audio_message,
                                           usp_parse_text, usp_text_message)


class FakeSpeechServer:
    """Accepts one websocket session and speaks the Speech USP protocol."""

    def __init__(self, hypotheses=("hel", "hello")):
        self.hypotheses = hypotheses
        self.received_audio = b""
        self.config = None
        self.request_headers = None
        self.sock, self.client_sock = socket.socketpair()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _send_text(self, text):
        self.sock.sendall(encode_frame(OP_TEXT, text.encode(), mask=False))

    def _run(self):
        try:
            self.request_headers = server_handshake(self.sock)
            ended = False
            while not ended:
                opcode, fin, payload = decode_frame(self.sock)
                if opcode == OP_TEXT:
                    hdrs, body = usp_parse_text(payload)
                    if hdrs.get("path") == "speech.config":
                        self.config = body
                elif opcode == OP_BINARY:
                    hlen = int.from_bytes(payload[:2], "big")
                    audio = payload[2 + hlen:]
                    if not audio:
                        ended = True
                    else:
                        self.received_audio += audio
            rid = "rid"
            self._send_text(usp_text_message("speech.startDetected", rid, {}))
            for h in self.hypotheses:
                self._send_text(usp_text_message("speech.hypothesis", rid,
                                                 {"Text": h}))
            self._send_text(usp_text_message(
                "speech.phrase", rid,
                {"RecognitionStatus": "Success", "DisplayText": "hello world",
                 "Offset": 0, "Duration": 12345}))
            self._send_text(usp_text_message("speech.endDetected", rid, {}))
            self._send_text(usp_text_message("turn.end", rid, {}))
        except Exception:
            pass


def _stage(server, **kwargs):
    return (SpeechToTextSDK(**kwargs)
            .set("url", "wss://fake.local")
            .set("subscriptionKey", "k")
            .set("wsTransport", lambda url, headers: server.client_sock)
            .set("outputCol", "events").set("errorCol", "errs"))


def test_full_protocol_roundtrip():
    server = FakeSpeechServer()
    audio = bytes(np.arange(40000, dtype=np.uint8))
    df = Table({"audio": np.array([audio], dtype=object)})
    out = _stage(server).transform(df)
    server.thread.join(timeout=5)
    assert out["errs"][0] is None, out["errs"][0]
    events = out["events"][0]
    # final phrase captured, hypotheses excluded by default
    assert [e["_path"] for e in events] == ["speech.phrase"]
    assert events[0]["DisplayText"] == "hello world"
    # every audio byte arrived across chunked binary messages
    assert server.received_audio == audio
    # speech.config was sent and auth headers reached the handshake
    assert server.config and "context" in server.config
    assert server.request_headers.get("ocp-apim-subscription-key") == "k"
    assert "x-connectionid" in server.request_headers


def test_intermediate_hypotheses_streamed():
    server = FakeSpeechServer()
    df = Table({"audio": np.array([b"\x00" * 100], dtype=object)})
    out = _stage(server).set("streamIntermediateResults", True).transform(df)
    events = out["events"][0]
    paths = [e["_path"] for e in events]
    assert paths == ["speech.hypothesis", "speech.hypothesis", "speech.phrase"]
    assert events[0]["Text"] == "hel"


def test_conversation_transcription_shares_protocol():
    server = FakeSpeechServer()
    df = Table({"audio": np.array([b"\x01" * 64], dtype=object)})
    stage = (ConversationTranscription()
             .set("url", "wss://fake.local")
             .set("wsTransport", lambda url, headers: server.client_sock)
             .set("outputCol", "events").set("errorCol", "errs"))
    out = stage.transform(df)
    assert out["errs"][0] is None
    assert out["events"][0][0]["DisplayText"] == "hello world"


def test_ws_url_shape():
    s = SpeechToTextSDK().setLocation("eastus")
    url = s._ws_url(None, None)
    assert url.startswith("wss://eastus.stt.speech.microsoft.com/speech/"
                          "recognition/conversation/cognitiveservices/v1")
    assert "language=en-US" in url and "format=simple" in url


def test_usp_framing_helpers():
    msg = usp_text_message("speech.config", "abc", {"x": 1})
    hdrs, body = usp_parse_text(msg.encode())
    assert hdrs["path"] == "speech.config"
    assert hdrs["x-requestid"] == "abc"
    assert body == {"x": 1}
    framed = usp_audio_message("abc", b"\xde\xad")
    hlen = int.from_bytes(framed[:2], "big")
    assert framed[2 + hlen:] == b"\xde\xad"
    assert b"Path: audio" in framed[2:2 + hlen]


def test_websocket_frames_roundtrip():
    a, b = socket.socketpair()
    payload = b"x" * 70000          # forces the 64-bit length path
    a.sendall(encode_frame(OP_BINARY, payload, mask=True))
    opcode, fin, got = decode_frame(b)
    assert opcode == OP_BINARY and fin and got == payload
    a.close(), b.close()


def test_handshake_rejection_raises():
    a, b = socket.socketpair()

    def bad_server():
        b.recv(65536)
        b.sendall(b"HTTP/1.1 403 Forbidden\r\n\r\n")

    t = threading.Thread(target=bad_server, daemon=True)
    t.start()
    ws = WebSocketClient("ws://x.local/", sock=a)
    with pytest.raises(Exception, match="handshake"):
        ws.connect()
