"""Kernel selftests must be callable from INSIDE an active jit trace.

The grower reaches ``child_histogram`` / ``segmented_histograms_available``
while tracing (under ``lax.switch`` inside the fused boosting scan), so the
``functools.cache``d on-device selftests can be FIRST-invoked mid-trace.
Under an ambient trace every jnp op produces tracers — without the
``ensure_compile_time_eval`` escape (ops/hist_kernel._eager_selftest) the
``np.asarray`` comparisons raise TracerArrayConversionError. Observed
on-chip 2026-08-02: the round-5 bench's first ``train_booster`` trace died
exactly there, and ``_tpu_segmented_ok`` mis-cached False (silently
degrading the segmented kernel for the whole process).

Reference analog: LightGBM's GPU tree learner probes its OpenCL kernels once
at setup, never during graph construction — the JAX design must make the
mid-trace probe safe instead, because trace time IS setup time here.
"""

import jax
import jax.numpy as jnp


def _clear_caches(hk, ak):
    hk._tpu_kernel_selftest.cache_clear()
    hk._tpu_segmented_ok.cache_clear()
    hk._tpu_level_ok.cache_clear()
    ak._tpu_flash_selftest.cache_clear()
    ak._tpu_flash_block_selftest.cache_clear()


def test_selftests_inside_jit_trace_match_eager():
    from synapseml_tpu.ops import attention_kernel as ak
    from synapseml_tpu.ops import hist_kernel as hk

    _clear_caches(hk, ak)
    eager = {
        "mode": hk._tpu_kernel_selftest(256),
        "seg": hk._tpu_segmented_ok(256),
        "level": hk._tpu_level_ok(256, 4),
        "flash": ak._tpu_flash_selftest(),
        "block": ak._tpu_flash_block_selftest(),
    }
    _clear_caches(hk, ak)
    traced = {}

    def f(x):
        traced["mode"] = hk._tpu_kernel_selftest(256)
        traced["seg"] = hk._tpu_segmented_ok(256)
        traced["level"] = hk._tpu_level_ok(256, 4)
        traced["flash"] = ak._tpu_flash_selftest()
        traced["block"] = ak._tpu_flash_block_selftest()
        return x + 1.0

    jax.jit(f)(jnp.ones(4))
    assert traced == eager
    # selftest verdicts are plain python values, never tracers
    assert isinstance(traced["mode"], str)
    assert all(isinstance(traced[k], bool)
               for k in ("seg", "level", "flash", "block"))


def test_selftest_inside_switch_branch_trace():
    """The exact shape of the on-chip failure: first selftest call from a
    ``lax.switch`` branch body mid-trace."""
    from synapseml_tpu.ops import attention_kernel as ak
    from synapseml_tpu.ops import hist_kernel as hk

    _clear_caches(hk, ak)

    def branch(x):
        hk._tpu_kernel_selftest(256)
        hk._tpu_segmented_ok(256)
        return x * 2.0

    def f(x):
        return jax.lax.switch(0, [branch, lambda x: x], x)

    out = jax.jit(f)(jnp.ones(3))
    assert float(out[0]) == 2.0
