"""Quantized ONNX inference ops (QDQ / QLinear / integer family).

The reference serves quantized graphs through onnxruntime's int8 kernels;
here they dequantize to float and ride the MXU (int8 buys nothing over bf16
on TPU). Semantics are pinned against the ONNX spec formulas computed in
numpy — per-tensor and per-axis scales, zero points, saturation, and the
QLinear decomposition identity.
"""

import numpy as np

from synapseml_tpu.onnx.importer import OnnxFunction
from synapseml_tpu.onnx.modelgen import _attr, _vi
from synapseml_tpu.onnx.protoio import Graph, Model, Node, Tensor


def _model(nodes, inputs, outputs, inits=None):
    return Model(graph=Graph(
        nodes=nodes, initializers=inits or {},
        inputs=inputs, outputs=outputs, name="q"), opset=17)


def _run(model, feeds):
    m = Model.parse(model.encode())
    fn = OnnxFunction(m)
    return fn(feeds)


class TestQDQ:
    def test_dequantize_per_tensor(self):
        x = np.asarray([[0, 128, 255]], np.uint8)
        n = Node(op_type="DequantizeLinear", inputs=["x", "s", "z"],
                 outputs=["y"])
        m = _model([n], [_vi("x", [1, 3])], [_vi("y", [1, 3])],
                   {"s": Tensor.from_array("s", np.float32(0.5)),
                    "z": Tensor.from_array("z", np.uint8(128))})
        out = _run(m, {"x": x})
        np.testing.assert_allclose(np.asarray(out["y"]),
                                   (x.astype(np.float32) - 128) * 0.5)

    def test_quantize_saturates(self):
        x = np.asarray([[-1000.0, 0.0, 1000.0]], np.float32)
        n = Node(op_type="QuantizeLinear", inputs=["x", "s", "z"],
                 outputs=["y"])
        m = _model([n], [_vi("x", [1, 3])], [_vi("y", [1, 3])],
                   {"s": Tensor.from_array("s", np.float32(1.0)),
                    "z": Tensor.from_array("z", np.int8(0))})
        out = _run(m, {"x": x})
        got = np.asarray(out["y"])
        assert got.dtype == np.int8
        np.testing.assert_array_equal(got, [[-128, 0, 127]])

    def test_per_axis_dequantize(self):
        x = np.arange(6, dtype=np.uint8).reshape(2, 3)
        s = np.asarray([0.5, 2.0], np.float32)       # axis 0
        z = np.asarray([1, 2], np.uint8)
        n = Node(op_type="DequantizeLinear", inputs=["x", "s", "z"],
                 outputs=["y"], attrs={"axis": _attr("axis", 0)})
        m = _model([n], [_vi("x", [2, 3])], [_vi("y", [2, 3])],
                   {"s": Tensor.from_array("s", s),
                    "z": Tensor.from_array("z", z)})
        out = _run(m, {"x": x})
        want = (x.astype(np.float32) - z[:, None]) * s[:, None]
        np.testing.assert_allclose(np.asarray(out["y"]), want)

    def test_dynamic_quantize(self):
        x = np.asarray([[-1.0, 0.0, 2.0, 3.0]], np.float32)
        n = Node(op_type="DynamicQuantizeLinear", inputs=["x"],
                 outputs=["y", "ys", "yzp"])
        m = _model([n], [_vi("x", [1, 4])],
                   [_vi("y", [1, 4]), _vi("ys", []), _vi("yzp", [])])
        out = _run(m, {"x": x})
        scale = float(np.asarray(out["ys"]))
        zp = float(np.asarray(out["yzp"]))
        assert abs(scale - 4.0 / 255) < 1e-6
        got = (np.asarray(out["y"]).astype(np.float32) - zp) * scale
        np.testing.assert_allclose(got, x, atol=scale)


class TestQLinear:
    def test_qlinear_matmul_matches_decomposition(self):
        rng = np.random.default_rng(0)
        a = rng.integers(0, 255, (4, 8)).astype(np.uint8)
        b = rng.integers(0, 255, (8, 3)).astype(np.uint8)
        a_s, a_z = np.float32(0.02), np.uint8(120)
        b_s, b_z = np.float32(0.05), np.uint8(130)
        y_s, y_z = np.float32(0.1), np.uint8(128)
        n = Node(op_type="QLinearMatMul",
                 inputs=["a", "as", "az", "b", "bs", "bz", "ys", "yz"],
                 outputs=["y"])
        inits = {"as": Tensor.from_array("as", a_s),
                 "az": Tensor.from_array("az", a_z),
                 "b": Tensor.from_array("b", b),
                 "bs": Tensor.from_array("bs", b_s),
                 "bz": Tensor.from_array("bz", b_z),
                 "ys": Tensor.from_array("ys", y_s),
                 "yz": Tensor.from_array("yz", y_z)}
        m = _model([n], [_vi("a", [4, 8])], [_vi("y", [4, 3])], inits)
        out = _run(m, {"a": a})
        af = (a.astype(np.float32) - 120) * 0.02
        bf = (b.astype(np.float32) - 130) * 0.05
        want = np.clip(np.round((af @ bf) / 0.1) + 128, 0, 255)
        assert np.asarray(out["y"]).dtype == np.uint8
        got = np.asarray(out["y"]).astype(np.float64)
        assert np.abs(got - want).max() <= 1     # round-at-half ties

    def test_qlinear_conv(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 255, (1, 2, 5, 5)).astype(np.uint8)
        w = rng.integers(0, 255, (3, 2, 3, 3)).astype(np.uint8)
        bias = rng.integers(-100, 100, (3,)).astype(np.int32)
        x_s, x_z = np.float32(0.03), np.uint8(128)
        w_s, w_z = np.float32(0.01), np.uint8(127)
        y_s, y_z = np.float32(0.2), np.uint8(128)
        n = Node(op_type="QLinearConv",
                 inputs=["x", "xs", "xz", "w", "ws", "wz", "ys", "yz", "b"],
                 outputs=["y"],
                 attrs={"pads": _attr("pads", [1, 1, 1, 1])})
        inits = {"xs": Tensor.from_array("xs", x_s),
                 "xz": Tensor.from_array("xz", x_z),
                 "w": Tensor.from_array("w", w),
                 "ws": Tensor.from_array("ws", w_s),
                 "wz": Tensor.from_array("wz", w_z),
                 "ys": Tensor.from_array("ys", y_s),
                 "yz": Tensor.from_array("yz", y_z),
                 "b": Tensor.from_array("b", bias)}
        m = _model([n], [_vi("x", [1, 2, 5, 5])], [_vi("y", [1, 3, 5, 5])],
                   inits)
        out = _run(m, {"x": x})
        # numpy reference: dequantize, correlate, add scaled bias, requantize
        import scipy.signal as sp

        xf = (x.astype(np.float32) - 128) * 0.03
        wf = (w.astype(np.float32) - 127) * 0.01
        ref = np.zeros((1, 3, 5, 5), np.float32)
        for o in range(3):
            for c in range(2):
                ref[0, o] += sp.correlate2d(xf[0, c], wf[o, c], mode="same")
            ref[0, o] += bias[o] * 0.03 * 0.01
        want = np.clip(np.round(ref / 0.2) + 128, 0, 255)
        got = np.asarray(out["y"]).astype(np.float64)
        assert (np.abs(got - want) <= 1).mean() > 0.99


class TestInteger:
    def test_matmul_integer(self):
        rng = np.random.default_rng(2)
        a = rng.integers(0, 255, (3, 6)).astype(np.uint8)
        b = rng.integers(-128, 127, (6, 4)).astype(np.int8)
        n = Node(op_type="MatMulInteger", inputs=["a", "b", "az", "bz"],
                 outputs=["y"])
        inits = {"b": Tensor.from_array("b", b),
                 "az": Tensor.from_array("az", np.uint8(100)),
                 "bz": Tensor.from_array("bz", np.int8(-5))}
        m = _model([n], [_vi("a", [3, 6])], [_vi("y", [3, 4])], inits)
        out = _run(m, {"a": a})
        want = ((a.astype(np.int64) - 100) @ (b.astype(np.int64) + 5))
        np.testing.assert_array_equal(np.asarray(out["y"]), want)

    def test_matmul_integer_per_row_zero_point(self):
        """1-D a_zero_point is per-ROW (spec) — broadcast on axis M, not K
        (code-review r4 finding)."""
        rng = np.random.default_rng(4)
        a = rng.integers(0, 255, (3, 6)).astype(np.uint8)
        b = rng.integers(-128, 127, (6, 4)).astype(np.int8)
        azp = np.asarray([10, 20, 30], np.uint8)
        n = Node(op_type="MatMulInteger", inputs=["a", "b", "az"],
                 outputs=["y"])
        inits = {"b": Tensor.from_array("b", b),
                 "az": Tensor.from_array("az", azp)}
        m = _model([n], [_vi("a", [3, 6])], [_vi("y", [3, 4])], inits)
        out = _run(m, {"a": a})
        want = ((a.astype(np.int64) - azp[:, None].astype(np.int64))
                @ b.astype(np.int64))
        np.testing.assert_array_equal(np.asarray(out["y"]), want)

    def test_conv_integer_per_channel_weight_zero_point(self):
        rng = np.random.default_rng(5)
        x = rng.integers(0, 20, (1, 1, 4, 4)).astype(np.uint8)
        w = rng.integers(0, 10, (2, 1, 2, 2)).astype(np.uint8)
        wzp = np.asarray([1, 3], np.uint8)
        n = Node(op_type="ConvInteger", inputs=["x", "w", "xz", "wz"],
                 outputs=["y"])
        inits = {"w": Tensor.from_array("w", w),
                 "xz": Tensor.from_array("xz", np.uint8(0)),
                 "wz": Tensor.from_array("wz", wzp)}
        m = _model([n], [_vi("x", [1, 1, 4, 4])], [_vi("y", [1, 2, 3, 3])],
                   inits)
        out = _run(m, {"x": x})
        for o in range(2):
            wf = w[o, 0].astype(np.int64) - int(wzp[o])
            for i in range(3):
                for j in range(3):
                    want = (x[0, 0, i:i + 2, j:j + 2].astype(np.int64)
                            * wf).sum()
                    assert np.asarray(out["y"])[0, o, i, j] == want

    def test_conv_integer(self):
        rng = np.random.default_rng(3)
        x = rng.integers(0, 20, (1, 1, 4, 4)).astype(np.uint8)
        w = rng.integers(0, 10, (1, 1, 2, 2)).astype(np.uint8)
        n = Node(op_type="ConvInteger", inputs=["x", "w", "xz"],
                 outputs=["y"])
        inits = {"w": Tensor.from_array("w", w),
                 "xz": Tensor.from_array("xz", np.uint8(5))}
        m = _model([n], [_vi("x", [1, 1, 4, 4])], [_vi("y", [1, 1, 3, 3])],
                   inits)
        out = _run(m, {"x": x})
        xf = x.astype(np.int64) - 5
        want = np.zeros((3, 3), np.int64)
        for i in range(3):
            for j in range(3):
                want[i, j] = (xf[0, 0, i:i + 2, j:j + 2]
                              * w[0, 0].astype(np.int64)).sum()
        np.testing.assert_array_equal(np.asarray(out["y"])[0, 0], want)
