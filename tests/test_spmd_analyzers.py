"""Fixture tests for the SPMD-safety analyzers (tools/analysis).

Covers the axis-environment model (``axismap``) and the four analyzers
built on it — collectives, sharding, donation, resource-discipline — each
with must-flag and must-not-flag fixtures, plus the incremental cache,
``--jobs`` pool, ``--stats``, SARIF output and unused-suppression audit
of the runner. The must-not cases encode the false-positive guards that
were tuned against the live tree (seeded RNG is replica-uniform; call
outputs don't inherit input sharding; replicated cond predicates may have
asymmetric arms).
"""

import json
import subprocess
import sys
import textwrap
import time

from tools.analysis.analyzers import (Context, collectives, donation,
                                      resources, sharding)
from tools.analysis.axismap import AxisMap
from tools.analysis.core import REPO, Project


def _ctx(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    project = Project.from_targets(sorted(files), repo=str(tmp_path))
    return Context(project)


_COMPAT = """\
    import jax

    shard_map = jax.shard_map
    """


# ------------------------------------------------------------------- axismap

def test_axis_env_through_compat_shim(tmp_path):
    """The module-alias re-export (core/compat.py's shape) resolves: a
    shard_map imported through the shim still binds the mesh axes."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _inner(x):
            return jax.lax.psum(x, "data")

        f = shard_map(_inner, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    env = ctx.axismap.env_of("synapseml_tpu.mod._inner")
    assert env.complete
    assert env.axes == {"data"}


def test_axis_env_ambient_mesh_is_incomplete(tmp_path):
    """``with mesh:`` introduces axes ambiently; the env must never claim
    completeness (pjit may or may not bind the names)."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax

        mesh = jax.make_mesh((4,), ("data",))

        def run(x):
            with mesh:
                return _inner(x)

        def _inner(x):
            return x
        """})
    env = ctx.axismap.env_of("synapseml_tpu.mod.run")
    assert not env.complete
    assert "data" in env.axes


def test_axismap_live_tree_sees_compat_shim_sites():
    """Spot check against the real tree: the shard_map applications that go
    through core/compat.py's shim are detected, and — because every live
    site takes ``mesh`` as a runtime parameter — their envs stay
    conservatively incomplete (no C1 false positives possible)."""
    project = Project.from_targets(["synapseml_tpu"], repo=REPO)
    am = AxisMap(project)
    targets = {s.target.full_name for s in am.shard_sites if s.target}
    assert "synapseml_tpu.vw.learner._run_pass_sharded.local_pass" in targets
    env = am.env_of(
        "synapseml_tpu.vw.learner._run_pass_sharded.local_pass")
    assert env.direct
    assert not env.complete


# --------------------------------------------------------------- collectives

def test_collectives_flags_out_of_scope_axis(tmp_path):
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _inner(x):
            return jax.lax.psum(x, "model")

        f = shard_map(_inner, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    found = collectives.run(ctx)
    assert any("'model'" in f.message and "not bound" in f.message
               for f in found)


def test_collectives_accepts_in_scope_axis(tmp_path):
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _inner(x):
            return jax.lax.psum(x, "data")

        f = shard_map(_inner, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    assert collectives.run(ctx) == []


def test_axismap_learns_seq_axis(tmp_path):
    """make_mesh({"seq": p, "data": d}) binds the seq axis: the axis env of
    a shard_map'd ring step is complete and includes 'seq'."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        def make_mesh(shape):
            return jax.make_mesh(tuple(shape.values()), tuple(shape))

        mesh = make_mesh({"seq": 4, "data": 2})

        def _ring_step(k):
            perm = [(i, (i + 1) % 4) for i in range(4)]
            return jax.lax.ppermute(k, "seq", perm)

        f = shard_map(_ring_step, mesh=mesh,
                      in_specs=(P("data", "seq"),),
                      out_specs=P("data", "seq"))
        """})
    env = ctx.axismap.env_of("synapseml_tpu.mod._ring_step")
    assert env.complete
    assert env.axes == {"seq", "data"}


def test_collectives_accepts_ring_ppermute_idiom(tmp_path):
    """The ring rotation (ppermute of K/V around the seq axis) is clean:
    the axis is bound by the enclosing shard_map's seq-bearing mesh."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("seq",))

        def _ring(q, k, v):
            rank = jax.lax.axis_index("seq")
            perm = [(i, (i + 1) % 4) for i in range(4)]
            k = jax.lax.ppermute(k, "seq", perm)
            v = jax.lax.ppermute(v, "seq", perm)
            return q + k + v

        f = shard_map(_ring, mesh=mesh,
                      in_specs=(P(None, "seq"),) * 3,
                      out_specs=P(None, "seq"))
        """})
    assert collectives.run(ctx) == []


def test_collectives_accepts_ulysses_all_to_all_idiom(tmp_path):
    """The Ulysses re-shard (all_to_all seq<->heads, both directions) is
    clean under a seq-bearing mesh."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("seq",))

        def _ulysses(q):
            qh = jax.lax.all_to_all(q, "seq", split_axis=2, concat_axis=1,
                                    tiled=True)
            return jax.lax.all_to_all(qh, "seq", split_axis=1,
                                      concat_axis=2, tiled=True)

        f = shard_map(_ulysses, mesh=mesh,
                      in_specs=(P(None, "seq", None, None),),
                      out_specs=P(None, "seq", None, None))
        """})
    assert collectives.run(ctx) == []


def test_collectives_flags_seq_collective_on_seqless_mesh(tmp_path):
    """The same ring/Ulysses idioms under a mesh WITHOUT a seq axis must
    flag — proves the clean fixtures above aren't vacuously passing."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _ring(k):
            perm = [(i, (i + 1) % 4) for i in range(4)]
            k = jax.lax.ppermute(k, "seq", perm)
            return jax.lax.all_to_all(k, "seq", split_axis=2,
                                      concat_axis=1, tiled=True)

        f = shard_map(_ring, mesh=mesh, in_specs=(P(None, "data"),),
                      out_specs=P(None, "data"))
        """})
    found = collectives.run(ctx)
    assert any("ppermute" in f.message and "'seq'" in f.message
               and "not bound" in f.message for f in found)
    assert any("all_to_all" in f.message and "'seq'" in f.message
               and "not bound" in f.message for f in found)


def test_axismap_live_tree_sees_seq_attention_sites():
    """The real ring/Ulysses shard_map applications are detected; their
    meshes are runtime parameters, so the envs stay conservatively
    incomplete (no false C1 findings against the seq modules)."""
    project = Project.from_targets(["synapseml_tpu/parallel"], repo=REPO)
    am = AxisMap(project)
    targets = {s.target.full_name for s in am.shard_sites if s.target}
    assert ("synapseml_tpu.parallel.ring_attention.ring_self_attention."
            "_ring") in targets
    assert ("synapseml_tpu.parallel.ulysses.ulysses_self_attention."
            "_ulysses") in targets


_QUANT = """\
    def allreduce_sum_quantized(x, axis, *, bits=8, block=256):
        return x

    def reduce_scatter_sum_quantized(x, axis, *, bits=8, block=256):
        return x
    """


def test_collectives_flags_quantized_wrapper_out_of_scope(tmp_path):
    """The repo's int8 wire ops are first-class performers: an axis name
    the surrounding shard_map never binds is flagged even through the
    ``axis=`` keyword (jax spells it ``axis_name=``)."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/qcoll.py": _QUANT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from synapseml_tpu.qcoll import allreduce_sum_quantized
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _inner(x):
            return allreduce_sum_quantized(x, axis="model")

        f = shard_map(_inner, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    found = collectives.run(ctx)
    assert any("allreduce_sum_quantized" in f.message
               and "'model'" in f.message and "not bound" in f.message
               for f in found)


def test_collectives_accepts_quantized_wrapper_in_scope(tmp_path):
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/qcoll.py": _QUANT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from synapseml_tpu.qcoll import reduce_scatter_sum_quantized
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _inner(x):
            return reduce_scatter_sum_quantized(x, "data", bits=8)

        f = shard_map(_inner, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    assert collectives.run(ctx) == []


def test_collectives_flags_quantized_wrapper_divergent_branch(tmp_path):
    """C2 sees the wrappers too: the int8 allreduce under a
    ``process_index()`` branch is the same static deadlock as a psum."""
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/qcoll.py": _QUANT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.qcoll import allreduce_sum_quantized

        def step(x):
            if jax.process_index() == 0:
                x = allreduce_sum_quantized(x, "data")
            return x
        """})
    found = collectives.run(ctx)
    assert any("allreduce_sum_quantized" in f.message
               and "deadlock" in f.message for f in found)


def test_collectives_flags_divergent_branch_deadlock(tmp_path):
    """The seeded deadlock: only process 0 reaches the sync point."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from jax.experimental import multihost_utils

        def save(x):
            if jax.process_index() == 0:
                return multihost_utils.process_allgather(x)
            return x
        """})
    found = collectives.run(ctx)
    assert len(found) == 1
    assert "deadlock" in found[0].message
    assert "process_index" in found[0].message


def test_collectives_flags_divergent_early_exit(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from jax.experimental import multihost_utils

        def save(x):
            if jax.process_index() != 0:
                return None
            multihost_utils.sync_global_devices("save")
            return x
        """})
    found = collectives.run(ctx)
    assert len(found) == 1
    assert "early exit" in found[0].message


def test_collectives_flags_transitive_performer(tmp_path):
    """A call into a function that psums, under a divergent branch."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax

        def _reduce(x):
            return jax.lax.psum(x, "data")

        def run(x):
            if jax.process_index() == 0:
                return _reduce(x)
            return x
        """})
    found = collectives.run(ctx)
    assert any("_reduce" in f.message and "deadlock" in f.message
               for f in found)


def test_collectives_seeded_rng_is_not_divergent(tmp_path):
    """np.random.default_rng(seed) yields the same stream on every host —
    branching on it is replica-uniform (the gbdt subsampling pattern)."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from jax.experimental import multihost_utils

        def subsample(x, seed):
            sub = np.random.default_rng(seed).choice(10)
            if sub > 3:
                return multihost_utils.process_allgather(x)
            return x
        """})
    assert collectives.run(ctx) == []


def test_collectives_unseeded_rng_is_divergent(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from jax.experimental import multihost_utils

        def subsample(x):
            if np.random.random() > 0.5:
                return multihost_utils.process_allgather(x)
            return x
        """})
    assert len(collectives.run(ctx)) == 1


def test_collectives_flags_divergent_cond_arm_mismatch(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax

        def f(x):
            i = jax.lax.axis_index("data")
            return jax.lax.cond(i == 0,
                                lambda v: jax.lax.psum(v, "data"),
                                lambda v: v, x)
        """})
    found = collectives.run(ctx)
    assert any("different collective sequences" in f.message
               for f in found)


def test_collectives_replicated_cond_predicate_is_clean(tmp_path):
    """The gbdt grower pattern: lax.cond(do, step, identity) where the
    predicate derives from a psummed (replicated) value — asymmetric arms
    are legal because every device takes the same one."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax

        def grow(x):
            gain = jax.lax.psum(x, "data")
            return jax.lax.cond(gain > 0,
                                lambda v: jax.lax.psum(v, "data"),
                                lambda v: v, x)
        """})
    assert [f for f in collectives.run(ctx)
            if "different collective sequences" in f.message] == []


# ------------------------------------------------------------------ sharding

def test_sharding_flags_in_specs_arity_mismatch(tmp_path):
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _two(a, b):
            return a

        f = shard_map(_two, mesh=mesh, in_specs=(P("data"),),
                      out_specs=P("data"))
        """})
    found = sharding.run(ctx)
    assert any("1 spec(s)" in f.message and "2 positional" in f.message
               for f in found)


def test_sharding_accepts_matching_specs(tmp_path):
    ctx = _ctx(tmp_path, {
        "synapseml_tpu/compat.py": _COMPAT,
        "synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))

        def _two(a, b):
            return a

        f = shard_map(_two, mesh=mesh, in_specs=(P("data"), P("data")),
                      out_specs=P("data"))
        """})
    assert sharding.run(ctx) == []


def test_sharding_flags_axis_missing_from_mesh(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = jax.make_mesh((4,), ("data",))
        sh = NamedSharding(mesh, P("model"))
        """})
    found = sharding.run(ctx)
    assert any("'model'" in f.message and "not present on the mesh"
               in f.message for f in found)


def test_sharding_flags_host_access_on_global_array(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from synapseml_tpu.parallel.mesh import to_global_rows

        def export(mesh, spec, x):
            g = to_global_rows(mesh, spec, x)
            return np.asarray(g)
        """})
    found = sharding.run(ctx)
    assert len(found) == 1
    assert "globally-sharded" in found[0].message


def test_sharding_host_access_guarded_or_gathered_is_clean(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        import numpy as np
        from jax.experimental import multihost_utils
        from synapseml_tpu.parallel.mesh import to_global_rows

        def export(mesh, spec, x):
            g = to_global_rows(mesh, spec, x)
            h = multihost_utils.process_allgather(g)
            return np.asarray(h)

        def export_primary(mesh, spec, x):
            g = to_global_rows(mesh, spec, x)
            if jax.process_index() == 0:
                np.save("out.npy", np.asarray(g))
        """})
    assert sharding.run(ctx) == []


def test_sharding_flags_host_access_on_placed_tree(tmp_path):
    """apply_tree_shardings is a global-array producer (the ZeRO trainer's
    param placement): np.asarray on its output must flag, while the
    host_copy gather (a call output) clears the taint."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from synapseml_tpu.parallel.mesh import (apply_tree_shardings,
                                                 host_copy)

        def export(tree, sh):
            placed = apply_tree_shardings(tree, sh)
            return np.asarray(placed)

        def export_gathered(tree, sh):
            placed = apply_tree_shardings(tree, sh)
            h = host_copy(placed)
            return np.asarray(h)
        """})
    found = sharding.run(ctx)
    assert len(found) == 1
    assert "placed" in found[0].message and "globally-sharded" in found[0].message


def test_sharding_call_outputs_do_not_inherit_taint(tmp_path):
    """A jitted function fed a sharded array may psum/gather internally —
    its output sharding is unknown, so np.asarray on it stays quiet (the
    boosting.py run_scan metric-value pattern)."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from synapseml_tpu.parallel.mesh import to_global_rows

        def train(mesh, spec, x, step):
            g = to_global_rows(mesh, spec, x)
            metric = step(g)
            return np.asarray(metric)
        """})
    assert sharding.run(ctx) == []


# ------------------------------------------------------------------ donation

def test_donation_flags_unguarded_literal_donate(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(s, x):
            return s + x
        """})
    found = donation.run(ctx)
    assert len(found) == 1
    assert "backend" in found[0].message


def test_donation_computed_donate_is_assumed_guarded(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.core.compat import donate_argnums_if_supported

        def _impl(s, x):
            return s + x

        def make():
            return jax.jit(_impl,
                           donate_argnums=donate_argnums_if_supported(0))
        """})
    assert donation.run(ctx) == []


def test_donation_backend_guard_in_reach_is_clean(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax

        def _impl(s, x):
            return s + x

        def make():
            donate = (0,) if jax.default_backend() != "cpu" else ()
            return jax.jit(_impl, donate_argnums=donate)
        """})
    assert donation.run(ctx) == []


def test_donation_flags_read_after_donate(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,),
                 static_argnames=("n",))
        def step(s, x):
            return s + x

        def train(s, xs):
            out = step(s, xs)
            return s + 1
        """})
    found = donation.run(ctx)
    assert any("read after being donated" in f.message for f in found)


def test_donation_rebinding_idiom_is_clean(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(s, x):
            return s + x

        def train(s, xs):
            s = step(s, xs)
            return s
        """})
    found = donation.run(ctx)
    assert [f for f in found if "donated" in f.message
            and "read after" in f.message] == []


def test_donation_flags_loop_without_rebinding(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(s, x):
            return s + x

        def train(s, xs):
            out = None
            for x in xs:
                out = step(s, x)
            return out
        """})
    found = donation.run(ctx)
    assert any("inside a loop without being rebound" in f.message
               for f in found)


# -------------------------------------------------------- resource-discipline

def test_resources_flags_leak_on_exception_path(tmp_path):
    """close() exists but a fallible call sits between create and close."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        import socket

        def probe(host):
            s = socket.create_connection((host, 80))
            s.sendall(b"ping")
            s.close()
        """})
    found = resources.run(ctx)
    assert len(found) == 1
    assert "happy path only" in found[0].message


def test_resources_flags_never_closed(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        from concurrent.futures import ThreadPoolExecutor

        def run(tasks):
            ex = ThreadPoolExecutor(4)
            return [t() for t in tasks]
        """})
    found = resources.run(ctx)
    assert len(found) == 1
    assert "never closed" in found[0].message


def test_resources_try_finally_and_with_are_clean(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        import socket

        def ok_with(host):
            with socket.create_connection((host, 80)) as s:
                s.sendall(b"x")

        def ok_finally(host):
            s = socket.create_connection((host, 80))
            try:
                s.sendall(b"x")
            finally:
                s.close()
        """})
    assert resources.run(ctx) == []


def test_resources_escape_and_daemon_thread_are_clean(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        import socket
        import threading

        class Client:
            def connect(self, host):
                self.sock = socket.create_connection((host, 80))

        def background(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
        """})
    assert resources.run(ctx) == []


def test_resources_interprocedural_factory_leak(tmp_path):
    """A factory's call site owns the resource and must close it."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        import socket

        def _connect(host):
            s = socket.create_connection((host, 80))
            return s

        def use(host):
            c = _connect(host)
            c.sendall(b"x")
        """})
    found = resources.run(ctx)
    assert len(found) == 1
    assert "`c`" in found[0].message


def test_resources_flags_discarded_resource(tmp_path):
    ctx = _ctx(tmp_path, {"synapseml_tpu/io/serving.py": """\
        import subprocess

        def fire(cmd):
            subprocess.Popen(cmd)
        """})
    found = resources.run(ctx)
    assert len(found) == 1
    assert "discarded" in found[0].message


# --------------------------------------------------- runner: cache/jobs/sarif

def _write_corpus(root, nfiles=24):
    pkg = root / "synapseml_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    for i in range(nfiles):
        fns = "\n\n".join(
            f"@jax.jit\ndef f{j}(x):\n    return jnp.sum(x) * {j}"
            for j in range(20))
        (pkg / f"mod{i}.py").write_text(
            "import jax\nimport jax.numpy as jnp\n\n" + fns + "\n")


def _run_cli(args, cwd=REPO):
    t0 = time.perf_counter()
    proc = subprocess.run([sys.executable, "tools/analysis/run.py"] + args,
                          cwd=cwd, capture_output=True, text=True,
                          timeout=300)
    return proc, time.perf_counter() - t0


def test_warm_cache_jobs_beats_cold_serial(tmp_path):
    """Acceptance gate: --jobs 4 with a warm incremental cache must be
    measurably faster than the cold serial run on the same corpus."""
    _write_corpus(tmp_path)
    cache = str(tmp_path / ".analysis_cache")
    cold, t_cold = _run_cli(["--repo", str(tmp_path), "--cache-dir", cache])
    assert cold.returncode == 0, cold.stdout + cold.stderr
    warm, t_warm = _run_cli(["--repo", str(tmp_path), "--cache-dir", cache,
                             "--jobs", "4"])
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert "(cached)" in warm.stdout
    assert t_warm < t_cold * 0.7, (
        f"warm cached run ({t_warm:.2f}s) not measurably faster than cold "
        f"serial ({t_cold:.2f}s)")


def test_cache_invalidates_on_content_change(tmp_path):
    _write_corpus(tmp_path, nfiles=2)
    cache = str(tmp_path / ".analysis_cache")
    args = ["--repo", str(tmp_path), "--cache-dir", cache]
    first, _ = _run_cli(args)
    assert first.returncode == 0
    warm, _ = _run_cli(args)
    assert "(cached)" in warm.stdout
    # same mtime-insensitive content change -> miss + new finding
    (tmp_path / "synapseml_tpu" / "mod0.py").write_text(
        "def f():\n    return zzz_missing\n")
    third, _ = _run_cli(args)
    assert third.returncode == 1
    assert "(cached)" not in third.stdout
    assert "undefined-names" in third.stdout


def test_jobs_pool_matches_serial_findings(tmp_path):
    root = tmp_path
    (root / "synapseml_tpu").mkdir()
    (root / "synapseml_tpu" / "mod.py").write_text(textwrap.dedent("""\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(s, x):
            return s + x

        def bad(x):
            if jax.process_index() == 0:
                return jax.lax.psum(x, "data")
            return x
        """))
    serial, _ = _run_cli(["--repo", str(root)])
    par, _ = _run_cli(["--repo", str(root), "--jobs", "4"])
    assert serial.returncode == par.returncode == 1
    assert sorted(l for l in serial.stdout.splitlines() if ": [" in l) \
        == sorted(l for l in par.stdout.splitlines() if ": [" in l)


def test_stats_table_and_syntax_error_are_clear(tmp_path):
    (tmp_path / "synapseml_tpu").mkdir()
    (tmp_path / "synapseml_tpu" / "mod.py").write_text("def f(:\n")
    proc, _ = _run_cli(["--repo", str(tmp_path), "--stats"])
    assert proc.returncode == 1
    assert "Traceback" not in proc.stdout + proc.stderr
    assert "[syntax]" in proc.stdout
    assert "do not parse" in proc.stdout
    assert "analyzer" in proc.stdout and "time" in proc.stdout


def test_sarif_output_is_valid_and_quiet_on_stdout(tmp_path):
    (tmp_path / "synapseml_tpu").mkdir()
    (tmp_path / "synapseml_tpu" / "mod.py").write_text(
        "def f():\n    return zzz_missing\n")
    proc, _ = _run_cli(["--repo", str(tmp_path), "--format", "sarif"])
    assert proc.returncode == 1
    log = json.loads(proc.stdout)          # stdout is pure SARIF
    assert log["version"] == "2.1.0"
    results = log["runs"][0]["results"]
    assert any(r["ruleId"] == "undefined-names" for r in results)
    assert "undefined-names" in proc.stderr  # humans read stderr


def test_unused_suppression_audit(tmp_path):
    (tmp_path / "synapseml_tpu").mkdir()
    (tmp_path / "synapseml_tpu" / "mod.py").write_text(textwrap.dedent("""\
        def f():
            return 1  # lint-ok: locks justified-by-nothing
        def g():
            return zzz_missing  # lint-ok: undefined-names real one
        def h():
            return 2  # lint-ok: not-an-analyzer
        """))
    proc, _ = _run_cli(["--repo", str(tmp_path)])
    assert proc.returncode == 1
    assert "suppressed nothing" in proc.stdout           # stale lint-ok
    assert "unknown analyzer id" in proc.stdout          # typo'd id
    # the honest suppression absorbed its finding and is not reported
    assert "mod.py:4" not in proc.stdout


def test_suppression_inside_string_literal_is_inert(tmp_path):
    (tmp_path / "synapseml_tpu").mkdir()
    (tmp_path / "synapseml_tpu" / "mod.py").write_text(textwrap.dedent('''\
        DOC = """use # lint-ok: undefined-names to suppress"""

        def f():
            return zzz_missing
        '''))
    proc, _ = _run_cli(["--repo", str(tmp_path)])
    assert proc.returncode == 1
    assert "undefined-names" in proc.stdout
    assert "unused-suppression" not in proc.stdout


def test_update_baseline_prunes_and_reports(tmp_path):
    (tmp_path / "synapseml_tpu").mkdir()
    mod = tmp_path / "synapseml_tpu" / "mod.py"
    mod.write_text("def f():\n    return zzz_missing\n"
                   "def g():\n    return yyy_missing\n")
    base = str(tmp_path / "baseline.json")
    first, _ = _run_cli(["--repo", str(tmp_path), "--baseline", base,
                         "--update-baseline"])
    assert "2 accepted" in first.stdout
    mod.write_text("def f():\n    return zzz_missing\n")
    second, _ = _run_cli(["--repo", str(tmp_path), "--baseline", base,
                          "--update-baseline"])
    assert "baseline pruned:" in second.stdout
    assert "yyy_missing" in second.stdout
    assert "1 stale entry dropped" in second.stdout


# --- parallel/transfer.py rendezvous helpers (PR 13) --------------------

def test_collectives_flags_device_transfer_divergent_branch(tmp_path):
    """A transfer hop is an all-process rendezvous: a hop only process 0
    reaches is the same static deadlock as a bare collective."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.parallel.transfer import device_transfer

        def hop(x, sh):
            if jax.process_index() == 0:
                return device_transfer(x, sh, op="transfer.hop")
            return x
        """})
    found = collectives.run(ctx)
    assert len(found) == 1
    assert "deadlock" in found[0].message
    assert "device_transfer" in found[0].message


def test_collectives_unconditional_device_transfer_is_clean(tmp_path):
    """The pipeline idiom: every process calls the hop; only the payload
    argument (not control flow) depends on ownership."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import jax
        from synapseml_tpu.parallel.transfer import (device_transfer,
                                                     share_scalars)

        def hop(owner, ys, spec, sh):
            out = device_transfer(ys if owner else spec, sh,
                                  op="transfer.hop")
            vals = share_scalars([1.0, 2.0], src_process=0)
            return out, vals
        """})
    assert collectives.run(ctx) == []


def test_sharding_flags_host_access_on_device_transfer(tmp_path):
    """device_transfer places onto the target submesh — its result is a
    globally-sharded array, not host-addressable everywhere."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from synapseml_tpu.parallel.transfer import device_transfer

        def export(x, sh):
            g = device_transfer(x, sh, op="transfer.hop")
            return np.asarray(g)
        """})
    found = sharding.run(ctx)
    assert len(found) == 1
    assert "globally-sharded" in found[0].message


def test_sharding_device_transfer_fetched_via_host_fetch_is_clean(tmp_path):
    """host_fetch is the sanctioned gather: its output is host-local, so
    numpy access on it is fine (call outputs clear input taint)."""
    ctx = _ctx(tmp_path, {"synapseml_tpu/mod.py": """\
        import numpy as np
        from synapseml_tpu.parallel.transfer import device_transfer, host_fetch

        def export(x, sh):
            g = device_transfer(x, sh, op="transfer.hop")
            h = host_fetch(g)
            return np.asarray(h)
        """})
    assert sharding.run(ctx) == []
