"""Causal inference tests (reference: causal test suites — DoubleML ATE
recovery, DiD interaction coefficient, synthetic control weights; SURVEY.md §4)."""

import numpy as np
import pytest

from synapseml_tpu.core.table import Table
from synapseml_tpu.causal import (DiffInDiffEstimator, DoubleMLEstimator,
                                  OrthoForestDMLEstimator, ResidualTransformer,
                                  SyntheticControlEstimator,
                                  SyntheticDiffInDiffEstimator,
                                  constrained_least_squares,
                                  linear_regression_with_se)


def _dml_data(n=600, true_ate=2.0, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    propensity = 1 / (1 + np.exp(-X[:, 0]))
    T = (rng.uniform(size=n) < propensity).astype(np.float64)
    Y = true_ate * T + X[:, 1] + 0.5 * X[:, 0] + rng.normal(scale=0.5, size=n)
    return Table({"features": X.astype(np.float32), "treatment": T, "outcome": Y})


class TestSolvers:
    def test_ols_recovers_coefficients(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 2))
        y = 3.0 * X[:, 0] - 1.0 * X[:, 1] + 0.5 + rng.normal(scale=0.1, size=500)
        beta, se = linear_regression_with_se(X, y)
        np.testing.assert_allclose(beta, [3.0, -1.0, 0.5], atol=0.05)
        assert (se > 0).all()

    def test_constrained_ls_on_simplex(self):
        rng = np.random.default_rng(1)
        A = rng.normal(size=(50, 5)).astype(np.float32)
        w_true = np.array([0.6, 0.4, 0, 0, 0])
        b = A @ w_true
        w, _ = constrained_least_squares(A, b, max_iter=500)
        assert w.min() >= 0 and abs(w.sum() - 1) < 1e-5
        np.testing.assert_allclose(w[:2], [0.6, 0.4], atol=0.05)


class TestDoubleML:
    def test_recovers_ate(self):
        from synapseml_tpu.models import LightGBMRegressor

        df = _dml_data()
        dml = DoubleMLEstimator(
            treatmentModel=LightGBMRegressor(numIterations=20),
            outcomeModel=LightGBMRegressor(numIterations=20),
            maxIter=6, seed=3)
        model = dml.fit(df)
        ate = model.get_avg_treatment_effect()
        assert ate == pytest.approx(2.0, abs=0.5)
        lo, hi = model.get_confidence_interval()
        assert lo < ate < hi
        assert 0 <= model.get_pvalue() <= 1

    def test_missing_models_rejected(self):
        with pytest.raises(ValueError, match="treatmentModel"):
            DoubleMLEstimator().fit(_dml_data(50))


class TestDiffInDiff:
    def _panel(self, effect=1.5, n_units=30, n_times=10, seed=0):
        rng = np.random.default_rng(seed)
        unit_fe = rng.normal(size=n_units)
        time_fe = np.linspace(0, 1, n_times)
        treated = np.arange(n_units) < 6
        post = np.arange(n_times) >= 6
        rows = {"unit": [], "time": [], "outcome": [], "treatment": [],
                "postTreatment": []}
        for u in range(n_units):
            for t in range(n_times):
                y = unit_fe[u] + time_fe[t] + rng.normal(scale=0.05)
                if treated[u] and post[t]:
                    y += effect
                rows["unit"].append(u)
                rows["time"].append(t)
                rows["outcome"].append(y)
                rows["treatment"].append(float(treated[u]))
                rows["postTreatment"].append(float(post[t]))
        return Table({k: np.asarray(v) for k, v in rows.items()})

    def test_did_interaction(self):
        model = DiffInDiffEstimator().fit(self._panel())
        s = model.getSummary()
        assert s.treatmentEffect == pytest.approx(1.5, abs=0.1)
        assert s.standardError > 0

    def test_synthetic_control(self):
        model = SyntheticControlEstimator(maxIter=300).fit(self._panel())
        s = model.getSummary()
        assert s.treatmentEffect == pytest.approx(1.5, abs=0.2)
        assert s.unitWeights is not None and s.unitWeights.min() >= 0

    def test_synthetic_did(self):
        model = SyntheticDiffInDiffEstimator(maxIter=300).fit(self._panel())
        s = model.getSummary()
        assert s.treatmentEffect == pytest.approx(1.5, abs=0.2)
        assert s.timeWeights is not None

    def test_no_controls_rejected(self):
        df = self._panel()
        df = Table({k: df[k] for k in df.columns})
        df["treatment"] = np.ones(df.num_rows)
        with pytest.raises(ValueError, match="treated and control"):
            SyntheticControlEstimator().fit(df)


class TestOrthoForest:
    def test_heterogeneous_effect_sign(self):
        from synapseml_tpu.models import LightGBMRegressor

        rng = np.random.default_rng(0)
        n = 800
        X = rng.normal(size=(n, 3)).astype(np.float32)
        H = rng.uniform(-1, 1, size=(n, 1)).astype(np.float32)
        T = (rng.uniform(size=n) < 0.5).astype(np.float64)
        effect = np.where(H[:, 0] > 0, 3.0, -1.0)
        Y = effect * T + X[:, 0] + rng.normal(scale=0.3, size=n)
        df = Table({"features": X, "heterogeneityFeatures": H,
                    "treatment": T, "outcome": Y})
        est = OrthoForestDMLEstimator(
            treatmentModel=LightGBMRegressor(numIterations=10),
            outcomeModel=LightGBMRegressor(numIterations=10),
            numTrees=30)
        out = est.fit(df).transform(df)
        eff = out["EffectAverage"]
        assert eff[H[:, 0] > 0.3].mean() > eff[H[:, 0] < -0.3].mean() + 1.0


class TestResidual:
    def test_residual(self):
        df = Table({"label": np.array([1.0, 0.0]),
                    "prediction": np.array([0.8, 0.3])})
        out = ResidualTransformer().transform(df)
        np.testing.assert_allclose(out["residual"], [0.2, -0.3])

    def test_probability_vector(self):
        df = Table({"label": np.array([1.0]),
                    "prediction": np.array([[0.3, 0.7]])})
        out = ResidualTransformer().transform(df)
        np.testing.assert_allclose(out["residual"], [0.3])
