"""Online-learning acceptance suite (ISSUE: serving→training loop tentpole).

Drives synapseml_tpu/online on CPU against the chaos battery:

* FeedbackLog bounding/dedup/quarantine — delayed, duplicated, NaN, and
  adversarial rewards never reach the learner, overflow sheds oldest-first
  and never blocks;
* chaos_reward_stream determinism + conservation (no silent drops);
* OnlineLearnerLoop learns from propensity-logged traffic, snapshots on
  cadence, and kill-mid-update → restore → replay is bit-for-bit equal to
  the uninterrupted run (corrupt newest snapshot falls back);
* StreamingAnomalyLoop flags outliers with a causally-adaptive threshold
  and has the same kill→resume equivalence;
* PromotionGate promotes only interval-clears-incumbent candidates,
  survives a kill mid-promotion with the incumbent serving, and rolls back
  a live-reward regression;
* TestChaosInvariant — the end-to-end property: every accepted prediction
  request is answered by a gate-approved, never-regressed policy version,
  under the full battery at once.

Everything is scripted or seeded — reruns see the same fault sequence.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.core.checkpoint import CheckpointStore, PreemptionError
from synapseml_tpu.core.table import Table
from synapseml_tpu.io.serving import ModelRegistry, ServingServer
from synapseml_tpu.online import (AnomalyEvent, FeedbackEvent, FeedbackLog,
                                  GreedyPolicy, OnlineLearnerLoop,
                                  PromotionGate, StreamLoop,
                                  StreamingAnomalyLoop,
                                  access_anomaly_stream_scorer,
                                  anomaly_feedback_log, iforest_stream_scorer,
                                  make_policy_handler, policy_builder)
from synapseml_tpu.testing import (ChaosPreemption, ChaosSwap, bit_flip,
                                   chaos_reward_stream)
from synapseml_tpu.vw.learner import VWConfig, make_sparse_batch

CFG = VWConfig(num_bits=10, batch_size=8, learning_rate=0.5)
K = 3          # actions per decision
BEST = 2       # action with the high reward


def _featurize(_v=None):
    """Fixed 3-action candidate set (shared context folded in)."""
    return list(make_sparse_batch([[a * 7 + 1, a * 7 + 2] for a in range(K)],
                                  [[1.0, 1.0]] * K, pad_to=4))


def _reward(action: int) -> float:
    return 0.9 if action == BEST else 0.1


def _events(n, seed=0, policy=None):
    """n logged interactions; uniform logging unless a policy chooses."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        acts = _featurize()
        if policy is None:
            a, p = int(rng.integers(1, K + 1)), 1.0 / K
        else:
            a, p = policy.choose(acts)
        out.append(FeedbackEvent(key=f"e{seed}.{i}", actions=acts, action=a,
                                 probability=p, reward=_reward(a)))
    return out


def _fill(log, events):
    return [log.offer(ev) for ev in events]


# ---------------------------------------------------------------------------
# FeedbackLog
# ---------------------------------------------------------------------------

class TestFeedbackLog:
    def test_accept_and_fifo_drain(self):
        log = FeedbackLog(capacity=100)
        evs = _events(10)
        assert _fill(log, evs) == ["accepted"] * 10
        assert len(log) == 10
        got = log.drain(4)
        assert [e.key for e in got] == [e.key for e in evs[:4]]
        assert [e.key for e in log.drain(100)] == [e.key for e in evs[4:]]
        assert log.drain(5) == []

    def test_duplicates_dropped_once(self):
        log = FeedbackLog()
        ev = _events(1)[0]
        assert log.offer(ev) == "accepted"
        assert log.offer(ev) == "duplicate"
        assert log.offer(dataclasses.replace(ev, reward=0.5)) == "duplicate"
        assert len(log) == 1 and log.duplicates == 2

    def test_quarantine_reasons(self):
        log = FeedbackLog(reward_min=0.0, reward_max=1.0)
        ok = _events(1)[0]
        cases = {
            "nonfinite_reward": dataclasses.replace(ok, reward=float("nan")),
            "reward_out_of_range": dataclasses.replace(ok, reward=1e9),
            "bad_propensity": dataclasses.replace(ok, probability=0.0),
            "bad_action": dataclasses.replace(ok, action=K + 1),
        }
        for reason, ev in cases.items():
            assert log.offer(ev) == "quarantined", reason
        malformed = FeedbackEvent(key="m", actions=_featurize(), action=1,
                                  probability=0.5, reward="not-a-number")
        assert log.offer(malformed) == "quarantined"
        no_actions = FeedbackEvent(key="n", actions=[], action=1,
                                   probability=0.5, reward=0.5)
        assert log.offer(no_actions) == "quarantined"
        snap = log.snapshot()
        assert len(log) == 0 and snap["accepted"] == 0
        for reason in cases:
            assert snap["quarantined"][reason] >= 1
        assert snap["quarantined"]["malformed"] == 1

    def test_overflow_sheds_oldest_never_blocks(self):
        log = FeedbackLog(capacity=5)
        evs = _events(12)
        for ev in evs:
            assert log.offer(ev) == "accepted"   # returns immediately
        assert len(log) == 5 and log.shed_oldest == 7
        # the five NEWEST survived
        assert [e.key for e in log.drain(99)] == [e.key for e in evs[-5:]]

    def test_dedup_window_is_bounded(self):
        log = FeedbackLog(capacity=1000, dedup_window=4)
        evs = _events(6)
        _fill(log, evs)
        # the first key has been evicted from the dedup LRU: re-offer passes
        assert log.offer(evs[0]) == "accepted"
        assert log.offer(evs[-1]) == "duplicate"   # still in the window


# ---------------------------------------------------------------------------
# chaos_reward_stream
# ---------------------------------------------------------------------------

class TestChaosRewardStream:
    RATES = dict(delay_rate=0.2, dup_rate=0.15, nan_rate=0.1,
                 adversarial_rate=0.1)

    def test_deterministic_per_seed(self):
        evs = _events(60)
        a = [(e.key, repr(e.reward)) for e in
             chaos_reward_stream(evs, seed=3, **self.RATES)]
        b = [(e.key, repr(e.reward)) for e in
             chaos_reward_stream(evs, seed=3, **self.RATES)]
        c = [(e.key, repr(e.reward)) for e in
             chaos_reward_stream(evs, seed=4, **self.RATES)]
        assert a == b
        assert a != c

    def test_conservation_no_silent_drops(self):
        evs = _events(100)
        stream = chaos_reward_stream(evs, seed=1, **self.RATES)
        got = list(stream)
        # every input key emitted at least once, duplicates on top
        assert {e.key for e in got} == {e.key for e in evs}
        assert len(got) == len(evs) + stream.duplicated
        assert stream.delayed > 0 and stream.duplicated > 0
        assert stream.nans > 0 and stream.adversarial > 0

    def test_log_absorbs_corrupted_stream(self):
        evs = _events(150)
        stream = chaos_reward_stream(evs, seed=2, **self.RATES)
        log = FeedbackLog(capacity=10_000)
        verdicts = [log.offer(e) for e in stream]
        snap = log.snapshot()
        # accounting closes: every emitted event is accepted, deduped, or
        # quarantined — nothing vanishes
        assert len(verdicts) == snap["accepted"] + snap["duplicates"] \
            + sum(snap["quarantined"].values())
        assert snap["quarantined"].get("nonfinite_reward", 0) >= stream.nans
        assert snap["quarantined"].get("reward_out_of_range", 0) \
            >= stream.adversarial
        # only clean events reached the queue, each exactly once
        drained = log.drain(10_000)
        assert len(drained) == len({e.key for e in drained})
        assert all(math.isfinite(e.reward) and 0 <= e.reward <= 1
                   for e in drained)


# ---------------------------------------------------------------------------
# OnlineLearnerLoop
# ---------------------------------------------------------------------------

class TestOnlineLearnerLoop:
    def test_learns_best_action_from_uniform_logs(self):
        log = FeedbackLog(capacity=10_000)
        _fill(log, _events(256, seed=5))
        loop = OnlineLearnerLoop(log, CFG)
        assert loop.run_until_drained() == 256 // CFG.batch_size
        scores = GreedyPolicy(loop.state, CFG).scores(_featurize())
        assert int(np.argmax(scores)) == BEST - 1
        assert scores[BEST - 1] > 0.5 > scores[0]

    def test_snapshot_cadence_and_meta(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        log = FeedbackLog(capacity=10_000)
        _fill(log, _events(64, seed=6))
        loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=2)
        loop.run_until_drained()
        assert loop.last_snapshot_base == "ckpt_00000008"
        ckpt = store.load_latest()
        assert ckpt.meta["updates"] == 8 and ckpt.meta["events_seen"] == 64

    def test_kill_mid_update_resume_bit_for_bit(self, tmp_path):
        evs = _events(64, seed=7)
        # reference: uninterrupted run
        ref_log = FeedbackLog(capacity=10_000)
        _fill(ref_log, evs)
        ref = OnlineLearnerLoop(ref_log, CFG)
        ref.run_until_drained()
        # chaos run: die entering update 5 (snapshots at 2 and 4 exist)
        store = CheckpointStore(str(tmp_path), keep_last=5)
        log = FeedbackLog(capacity=10_000)
        _fill(log, evs)
        loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=2)
        with pytest.raises(PreemptionError):
            with ChaosPreemption(at={"online.update": [4]}):
                loop.run_until_drained()
        # resume: restore newest snapshot, replay from its event offset
        resumed = OnlineLearnerLoop(FeedbackLog(capacity=10_000), CFG,
                                    store=store, snapshot_every=2)
        assert resumed.restore_latest()
        assert resumed.updates == 4 and resumed.events_seen == 32
        _fill(resumed.log, evs[resumed.events_seen:])
        resumed.run_until_drained()
        assert resumed.updates == ref.updates
        for f in ("weights", "acc", "bias", "bias_acc"):
            np.testing.assert_array_equal(
                np.asarray(getattr(resumed.state, f)),
                np.asarray(getattr(ref.state, f)), err_msg=f)

    def test_corrupt_newest_snapshot_falls_back(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=5)
        log = FeedbackLog(capacity=10_000)
        _fill(log, _events(64, seed=8))
        loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=2)
        loop.run_until_drained()
        bit_flip(str(tmp_path))   # corrupt the newest snapshot's artifact
        resumed = OnlineLearnerLoop(FeedbackLog(), CFG, store=store)
        assert resumed.restore_latest()
        assert resumed.updates == 6    # fell back past the corrupted 8

    def test_config_mismatch_refuses_restore(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=3)
        log = FeedbackLog()
        _fill(log, _events(8, seed=9))
        OnlineLearnerLoop(log, CFG, store=store,
                          snapshot_every=1).run_until_drained()
        other = dataclasses.replace(CFG, learning_rate=0.01)
        bad = OnlineLearnerLoop(FeedbackLog(), other, store=store)
        with pytest.raises(ValueError, match="different learner config"):
            bad.restore_latest()

    def test_background_thread_drains_and_joins_on_close(self):
        log = FeedbackLog(capacity=10_000)
        loop = OnlineLearnerLoop(log, CFG, drain_interval=0.005)
        with loop:
            _fill(log, _events(64, seed=10))
            deadline = time.monotonic() + 10.0
            while loop.events_seen < 64 and time.monotonic() < deadline:
                time.sleep(0.01)
        assert loop.events_seen == 64
        assert loop._thread is None        # close() joined the drain thread

    def test_background_thread_survives_poisoned_update(self):
        class Exploding(StreamLoop):
            def _update(self, events):
                raise RuntimeError("poisoned batch")

        log = FeedbackLog(capacity=100)
        _fill(log, _events(4, seed=11))
        loop = Exploding(log, batch_size=1, drain_interval=0.005)
        with loop:
            deadline = time.monotonic() + 10.0
            while len(log) and time.monotonic() < deadline:
                time.sleep(0.01)
        assert loop.errors == 4 and loop.updates == 0   # logged, not dead


# ---------------------------------------------------------------------------
# Streaming anomaly
# ---------------------------------------------------------------------------

def _iforest_model(seed=0):
    from synapseml_tpu.isolationforest import IsolationForest
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(256, 4))
    return IsolationForest(numEstimators=20, contamination=0.05,
                           randomSeed=3).fit(Table({"features": list(X)})), X


class TestStreamingAnomaly:
    def test_flags_outliers_threshold_adapts(self):
        model, X = _iforest_model()
        log = anomaly_feedback_log()
        for i in range(64):                       # warmup: inliers only
            log.offer(AnomalyEvent(key=f"in{i}", features=X[i]))
        loop = StreamingAnomalyLoop(log, iforest_stream_scorer(model),
                                    batch_size=16, window=64, min_window=32,
                                    contamination=0.05)
        loop.run_until_drained()
        warm_flagged = loop.flagged
        assert math.isfinite(loop.threshold)
        for i in range(8):                        # now far-out outliers
            log.offer(AnomalyEvent(key=f"out{i}",
                                   features=np.full(4, 9.0) + i))
        loop.run_until_drained()
        assert loop.flagged >= warm_flagged + 8   # every outlier flagged
        assert loop.scored == 72

    def test_cold_window_never_flags(self):
        model, X = _iforest_model()
        log = anomaly_feedback_log()
        for i in range(8):
            log.offer(AnomalyEvent(key=f"o{i}", features=np.full(4, 9.0)))
        loop = StreamingAnomalyLoop(log, iforest_stream_scorer(model),
                                    batch_size=4, min_window=32)
        loop.run_until_drained()
        assert loop.flagged == 0 and loop.threshold == math.inf

    def test_nonfinite_features_quarantined(self):
        log = anomaly_feedback_log()
        assert log.offer(AnomalyEvent(
            key="nan", features=np.array([1.0, float("nan")]))) \
            == "quarantined"
        assert log.offer(AnomalyEvent(key="none", features=None)) \
            == "quarantined"
        assert log.snapshot()["quarantined"] == {"nonfinite_features": 1,
                                                 "malformed": 1}

    def test_kill_mid_scoring_resume_bit_for_bit(self, tmp_path):
        model, X = _iforest_model(seed=1)
        feed = [AnomalyEvent(key=f"s{i}", features=X[i % 256] * (1 + i / 64))
                for i in range(128)]

        def fresh(store=None):
            log = anomaly_feedback_log(capacity=10_000)
            return StreamingAnomalyLoop(
                log, iforest_stream_scorer(model), store=store,
                batch_size=16, window=64, min_window=16,
                contamination=0.1, snapshot_every=2)

        ref = fresh()
        for ev in feed:
            ref.log.offer(ev)
        ref.run_until_drained()

        store = CheckpointStore(str(tmp_path), keep_last=5)
        loop = fresh(store)
        for ev in feed:
            loop.log.offer(ev)
        with pytest.raises(PreemptionError):
            with ChaosPreemption(at={"online.anomaly": [5]}):
                loop.run_until_drained()
        resumed = fresh(store)
        assert resumed.restore_latest()
        assert resumed.updates == 4
        for ev in feed[resumed.events_seen:]:
            resumed.log.offer(ev)
        resumed.run_until_drained()
        assert resumed.threshold == ref.threshold
        assert resumed.flagged == ref.flagged and resumed.scored == ref.scored
        np.testing.assert_array_equal(np.asarray(resumed._scores),
                                      np.asarray(ref._scores))

    def test_access_anomaly_scorer_adapter(self):
        from synapseml_tpu.cyber.access_anomaly import AccessAnomaly
        rng = np.random.default_rng(2)
        n = 200
        df = Table({
            "tenant_id": np.zeros(n, np.int64),
            "user": np.array([f"u{i % 8}" for i in range(n)], object),
            "res": np.array([f"r{(i % 8) // 2}" for i in range(n)], object),
        })
        model = AccessAnomaly(tenantCol="tenant_id", userCol="user",
                              resCol="res", maxIter=5, rankParam=4).fit(df)
        log = anomaly_feedback_log()
        for i in range(32):
            log.offer(AnomalyEvent(key=f"a{i}", features={
                "tenant": 0, "user": f"u{i % 8}", "res": f"r{(i % 8) // 2}"}))
        loop = StreamingAnomalyLoop(log, access_anomaly_stream_scorer(model),
                                    batch_size=8, min_window=8,
                                    contamination=0.1)
        loop.run_until_drained()
        assert loop.scored == 32 and math.isfinite(loop.threshold)


# ---------------------------------------------------------------------------
# PromotionGate
# ---------------------------------------------------------------------------

def _serving_stack():
    """(registry, gate) around an unstarted server serving the uniform
    incumbent v0 — swap/rollback semantics are fully exercised without TCP."""
    from synapseml_tpu.vw.learner import VWState
    incumbent = GreedyPolicy(VWState.init(CFG.num_bits), CFG, epsilon=1.0,
                             seed=0, version="v0")
    srv = ServingServer(make_policy_handler(incumbent, _featurize))
    reg = ModelRegistry(srv, version="v0")
    gate = PromotionGate(reg, min_samples=50, regression_window=10,
                         regression_tolerance=0.05)
    return incumbent, reg, gate


def _trained_store(tmp_path, gate=None, n=256, seed=12):
    """Train a candidate into a CheckpointStore off uniform logged traffic,
    feeding the same events to the gate as evidence."""
    store = CheckpointStore(str(tmp_path), keep_last=4)
    log = FeedbackLog(capacity=10_000)
    loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=4)
    for ev in _events(n, seed=seed):
        if log.offer(ev) == "accepted" and gate is not None:
            gate.record(ev)
    loop.run_until_drained()
    return store


class TestPromotionGate:
    def test_insufficient_samples_refuses(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = _trained_store(tmp_path)     # no evidence recorded
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert not dec.promoted and dec.reason == "insufficient_samples"
        assert reg.active == "v0"

    def test_promotes_interval_clearing_candidate(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = _trained_store(tmp_path, gate)
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert dec.promoted and dec.reason == "interval_clears_incumbent"
        assert dec.interval[0] > dec.incumbent_value
        assert abs(dec.incumbent_value - (0.9 + 2 * 0.1) / 3) < 0.1
        assert reg.active == dec.candidate_version != "v0"
        assert reg.active in gate.approved_versions

    def test_refuses_no_better_candidate(self, tmp_path):
        _, reg, gate = _serving_stack()
        # evidence where EVERY action pays the same: no candidate can beat
        # the incumbent's logged mean
        log = FeedbackLog(capacity=10_000)
        store = CheckpointStore(str(tmp_path), keep_last=4)
        loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=4)
        for ev in _events(256, seed=13):
            flat = dataclasses.replace(ev, reward=0.5)
            if log.offer(flat) == "accepted":
                gate.record(flat)
        loop.run_until_drained()
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert not dec.promoted
        assert dec.reason == "interval_overlaps_incumbent"
        assert reg.active == "v0"

    def test_kill_mid_promotion_keeps_incumbent(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = _trained_store(tmp_path, gate)
        with ChaosSwap(at="flip") as cs:
            dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert not dec.promoted and dec.reason == "swap_failed"
        assert len(cs.kills) == 1
        assert reg.active == "v0" and reg.swap_failures == 1
        assert gate.approved_versions == {"v0"}
        # the chaos is one-shot: the retry goes through
        dec2 = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert dec2.promoted and reg.active == dec2.candidate_version

    def test_empty_store_refuses(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = CheckpointStore(str(tmp_path), keep_last=2)
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert not dec.promoted and dec.reason == "no_verifiable_checkpoint"
        assert reg.active == "v0"

    def test_live_regression_rolls_back(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = _trained_store(tmp_path, gate)
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert dec.promoted
        rolled = False
        for _ in range(gate.regression_window):
            rolled = gate.observe_live(0.0) or rolled
        assert rolled and gate.rollbacks == 1
        assert reg.active == "v0"              # back on the prior approved
        assert not gate.snapshot()["watchdog_armed"]

    def test_healthy_live_reward_disarms_watchdog(self, tmp_path):
        _, reg, gate = _serving_stack()
        store = _trained_store(tmp_path, gate)
        dec = gate.try_promote(store, policy_builder(CFG, _featurize))
        assert dec.promoted
        for _ in range(gate.regression_window):
            assert not gate.observe_live(0.9)
        assert reg.active == dec.candidate_version
        assert gate.rollbacks == 0
        assert not gate.snapshot()["watchdog_armed"]


# ---------------------------------------------------------------------------
# The end-to-end chaos invariant
# ---------------------------------------------------------------------------

def _post(url, value, timeout=10.0):
    body = json.dumps(value).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, None


@pytest.mark.slow
class TestChaosInvariant:
    """Accepted prediction requests are ALWAYS answered by a promoted,
    never-regressed policy version — under kill-mid-update,
    kill-mid-promotion, a corrupted snapshot, and a delayed/duplicated/NaN/
    adversarial reward stream, all in one run."""

    def test_full_battery(self, tmp_path):
        from synapseml_tpu.vw.learner import VWState
        incumbent = GreedyPolicy(VWState.init(CFG.num_bits), CFG,
                                 epsilon=1.0, seed=0, version="v0")
        srv = ServingServer(make_policy_handler(incumbent, _featurize),
                            port=0, max_batch_latency=0.0).start()
        served = []      # every (status, version) a client observed

        def ask(n=4):
            for _ in range(n):
                status, reply = _post(srv.url, {})
                if status == 200:
                    served.append(reply["version"])

        try:
            reg = ModelRegistry(srv, version="v0")
            gate = PromotionGate(reg, min_samples=100, regression_window=20,
                                 regression_tolerance=0.05)
            store = CheckpointStore(str(tmp_path), keep_last=5)
            log = FeedbackLog(capacity=10_000)
            loop = OnlineLearnerLoop(log, CFG, store=store, snapshot_every=2)

            # phase 1 — corrupted reward stream into the log while serving
            ask()
            stream = chaos_reward_stream(
                _events(320, seed=20), seed=21, delay_rate=0.15,
                dup_rate=0.1, nan_rate=0.1, adversarial_rate=0.1)
            for ev in stream:
                if log.offer(ev) == "accepted":
                    gate.record(ev)
            assert stream.nans > 0 and stream.adversarial > 0
            assert sum(log.snapshot()["quarantined"].values()) > 0

            # phase 2 — learner killed mid-update, restores, replays
            with pytest.raises(PreemptionError):
                with ChaosPreemption(at={"online.update": [6]}):
                    loop.run_until_drained()
            ask()
            leftover = log.drain(100_000)     # events the dead loop held
            loop = OnlineLearnerLoop(FeedbackLog(capacity=10_000), CFG,
                                     store=store, snapshot_every=2)
            assert loop.restore_latest() and loop.updates > 0
            for ev in leftover:
                loop.log.offer(ev)
            loop.run_until_drained()
            assert loop.updates >= 6

            # phase 3 — promotion killed mid-swap: incumbent keeps serving
            builder = policy_builder(CFG, _featurize, epsilon=0.05, seed=7)
            with ChaosSwap(at="flip"):
                dec = gate.try_promote(store, builder)
            assert not dec.promoted and dec.reason == "swap_failed"
            assert reg.active == "v0"
            ask()

            # phase 4 — newest snapshot corrupted: digest check falls back
            # to an older verified snapshot, promotion still succeeds
            bit_flip(str(tmp_path))
            dec = gate.try_promote(store, builder)
            assert dec.promoted, dec
            assert reg.active == dec.candidate_version
            ask()

            # phase 5 — live reward regresses: auto-rollback to v0
            for _ in range(gate.regression_window):
                gate.observe_live(0.0)
            assert gate.rollbacks == 1 and reg.active == "v0"
            ask()

            # THE invariant: every answered request came from a version the
            # gate approved (v0 or the promoted candidate), and the version
            # serving now is approved
            assert served and set(served) <= gate.approved_versions
            assert reg.active in gate.approved_versions
            # and the rollback target was itself approved (never-regressed)
            assert served[-1] == "v0"
        finally:
            srv.stop()
