"""Isolation-forest tests (reference: isolationforest wrapper + LinkedIn
estimator behavior; SURVEY.md §2 N8)."""

import numpy as np
import pytest

from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.core.table import Table
from synapseml_tpu.isolationforest import IsolationForest


def _data(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    X[:5] += 8.0  # obvious outliers
    return Table({"features": X})


class TestIsolationForest:
    def test_outliers_score_higher(self):
        df = _data()
        model = IsolationForest(numEstimators=50, maxSamples=64.0,
                                randomSeed=7).fit(df)
        out = model.transform(df)
        s = out[model.getScoreCol()]
        assert s.shape == (300,)
        assert (0 <= s).all() and (s <= 1).all()
        # the 5 shifted rows should rank in the top scores
        top10 = np.argsort(-s)[:10]
        assert len(set(range(5)) & set(top10)) >= 4

    def test_contamination_thresholds_labels(self):
        df = _data()
        model = IsolationForest(numEstimators=50, maxSamples=64.0,
                                contamination=0.02, randomSeed=7).fit(df)
        out = model.transform(df)
        labels = out[model.getPredictionCol()]
        assert 1 <= labels.sum() <= 20
        # without contamination, all labels are 0
        m0 = IsolationForest(numEstimators=20, maxSamples=32.0).fit(df)
        assert m0.transform(df)[m0.getPredictionCol()].sum() == 0

    def test_empty_rejected(self):
        with pytest.raises(ValueError, match="empty"):
            IsolationForest().fit(Table({"features": np.zeros((0, 3))}))

    def test_save_load(self, tmp_path):
        df = _data(100)
        model = IsolationForest(numEstimators=10, maxSamples=32.0,
                                randomSeed=1).fit(df)
        p = str(tmp_path / "iforest")
        model.save(p)
        loaded = PipelineStage.load(p)
        np.testing.assert_allclose(
            loaded.transform(df)[loaded.getScoreCol()],
            model.transform(df)[model.getScoreCol()])
