"""Stage-coverage fuzzing meta-test.

Reference: src/test/.../core/test/fuzzing/FuzzingTest.scala — reflects over
every PipelineStage in the jar and FAILS if any stage lacks fuzzing coverage.
Here: a registry of TestObjects covers each concrete stage; the meta-test
discovers all stage classes and asserts coverage (experiment fuzzing counts
the classes it touches, including fitted Model classes); serialization and
getter/setter fuzzing run over the same registry (Fuzzing.scala traits).
"""

import json

import numpy as np

from synapseml_tpu.core.pipeline import Pipeline, PipelineModel, Transformer
from synapseml_tpu.core.table import Table
from synapseml_tpu.io.http import HTTPResponseData
from synapseml_tpu.testing import (TestObject, discover_stage_classes,
                                   experiment_fuzz, getter_setter_fuzz,
                                   serialization_fuzz)

RNG = np.random.default_rng(7)


# --------------------------------------------------------------------------
# shared tiny datasets

def _tab(n=40, f=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return Table({"features": X, "label": y,
                  "a": X[:, 0].astype(np.float64),
                  "b": X[:, 1].astype(np.float64),
                  "text": np.array(["the quick brown fox"] * n, object),
                  "group": np.arange(n) % 4})


def _imgs(n=4, h=8, w=8):
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = RNG.uniform(0, 255, size=(h, w, 3)).astype(np.float32)
    return Table({"image": col,
                  "label": (np.arange(n) % 2).astype(np.float64)})


_SERVICE_JSON = {
    "results": {"documents": [{"sentiment": "neutral"}]},
    "choices": [{"text": "ok", "message": {"role": "assistant",
                                           "content": "ok"}}],
    "data": [{"embedding": [0.1, 0.2]}],
    "value": [{"contentUrl": "http://x/1.jpg"}],
    "isAnomaly": False,
    "translations": [{"text": "ok"}],
    "status": "succeeded",
}


def _stub_handler(req, send):
    return HTTPResponseData(200, "OK", {},
                            json.dumps(_SERVICE_JSON).encode())


def _service_df():
    series = np.empty(2, dtype=object)
    msgs = np.empty(2, dtype=object)
    mv = np.empty(2, dtype=object)
    for i in range(2):
        series[i] = [{"timestamp": f"2026-01-0{j+1}T00:00:00Z",
                      "value": float(j)} for j in range(12)]
        msgs[i] = [{"role": "user", "content": "hi"}]
        mv[i] = [{"variable": "v", "timestamp": "2026-01-01T00:00:00Z",
                  "value": 1.0}]
    audio = np.empty(2, dtype=object)
    imgb = np.empty(2, dtype=object)
    for i in range(2):
        audio[i] = b"RIFFfake"
        imgb[i] = b"\x89PNGfake"
    return Table({
        "text": np.array(["hello world", "guten tag"], object),
        "prompt": np.array(["say hi", "say bye"], object),
        "messages": msgs, "series": series, "mvseries": mv,
        "q": np.array(["cats", "dogs"], object),
        "audio": audio, "imageBytes": imgb,
        "imageUrl": np.array(["http://x/a.jpg", "http://x/b.jpg"], object),
        "timestamp": np.array(["2026-01-01T00:00:00Z",
                               "2026-01-02T00:00:00Z"], object),
        "value": np.array([1.0, 2.0]),
        "grp": np.array(["g", "g"], object),
        "faceId": np.array(["f-1", "f-2"], object),
        "faceIds": np.array([["f-1", "f-2"], ["f-3"]], object),
        "address": np.array(["1 Main St", "2 High St"], object),
        "lat": np.array([47.6, 47.7]),
        "lon": np.array([-122.3, -122.4]),
    })


def _onnx_payload():
    from synapseml_tpu.onnx import Graph, Model as OModel, Node, Tensor, ValueInfo

    W = RNG.normal(size=(4, 3)).astype(np.float32)
    g = Graph(nodes=[Node(op_type="MatMul", inputs=["x", "W"], outputs=["out"])],
              initializers={"W": Tensor.from_array("W", W)},
              inputs=[ValueInfo(name="x", elem_type=1, shape=["N", 4])],
              outputs=[ValueInfo(name="out", elem_type=1, shape=["N", 3])])
    return OModel(graph=g).encode()


# --------------------------------------------------------------------------
# the registry (TestObject per concrete stage / estimator family)

def _registry():
    from synapseml_tpu.automl import (FindBestModel, HyperparamBuilder,
                                      TuneHyperparameters)
    from synapseml_tpu.causal import (DiffInDiffEstimator, DoubleMLEstimator,
                                      OrthoForestDMLEstimator,
                                      ResidualTransformer,
                                      SyntheticControlEstimator,
                                      SyntheticDiffInDiffEstimator)
    from synapseml_tpu.cyber import (AccessAnomaly, ComplementAccessTransformer,
                                     IdIndexer, LinearScalarScaler,
                                     MultiIndexer, StandardScalarScaler)
    from synapseml_tpu.dl import DeepTextClassifier, DeepVisionClassifier
    from synapseml_tpu.explainers import (ICETransformer, ImageLIME, ImageSHAP,
                                          TabularLIME, TabularSHAP, TextLIME,
                                          TextSHAP, VectorLIME, VectorSHAP)
    from synapseml_tpu.featurize import (CleanMissingData, CountSelector,
                                         DataConversion, Featurize,
                                         IndexToValue, MultiNGram,
                                         PageSplitter, TextFeaturizer,
                                         ValueIndexer)
    from synapseml_tpu.image import (ImageSetAugmenter, SuperpixelTransformer,
                                     UnrollImage)
    from synapseml_tpu.io.http import (CustomInputParser, CustomOutputParser,
                                       HTTPRequestData, HTTPTransformer,
                                       JSONInputParser, JSONOutputParser,
                                       SimpleHTTPTransformer,
                                       StringOutputParser)
    from synapseml_tpu.isolationforest import IsolationForest
    from synapseml_tpu.models import (LightGBMClassifier, LightGBMRanker,
                                      LightGBMRegressor)
    from synapseml_tpu.nn import KNN, ConditionalKNN
    from synapseml_tpu.onnx import ImageFeaturizer, ONNXModel
    from synapseml_tpu.recommendation import (RankingAdapter, RankingEvaluator,
                                              RankingTrainValidationSplit,
                                              RecommendationIndexer, SAR)
    from synapseml_tpu import services as S
    from synapseml_tpu.stages import (Cacher, ClassBalancer, DropColumns,
                                      DynamicMiniBatchTransformer,
                                      EnsembleByKey, Explode,
                                      FixedMiniBatchTransformer, FlattenBatch,
                                      Lambda, MultiColumnAdapter,
                                      PartitionConsolidator, RenameColumn,
                                      Repartition, SelectColumns,
                                      StratifiedRepartition, SummarizeData,
                                      TextPreprocessor, Timer,
                                      TimeIntervalMiniBatchTransformer,
                                      UDFTransformer, UnicodeNormalize)
    from synapseml_tpu.train import (ComputeModelStatistics,
                                     ComputePerInstanceStatistics,
                                     TrainClassifier, TrainRegressor)
    from synapseml_tpu.vw import (VowpalWabbitClassifier,
                                  VowpalWabbitContextualBandit,
                                  VowpalWabbitCSETransformer,
                                  VowpalWabbitDSJsonTransformer,
                                  VowpalWabbitFeaturizer, VowpalWabbitGeneric,
                                  VowpalWabbitGenericProgressive,
                                  VowpalWabbitInteractions,
                                  VowpalWabbitRegressor)

    tab = _tab()
    imgs = _imgs()
    svc = _service_df()

    objs = []
    add = objs.append

    # --- models / gbdt -------------------------------------------------
    add(TestObject(LightGBMClassifier(numIterations=5), tab))
    add(TestObject(LightGBMRegressor(numIterations=5), tab))
    rank_df = tab.with_column("label", (RNG.integers(0, 3, 40)).astype(np.float64))
    add(TestObject(LightGBMRanker(numIterations=4, groupCol="group"), rank_df))

    # --- vw ------------------------------------------------------------
    vw_df = Table({"features": tab["features"],
                   "label": tab["label"]})
    add(TestObject(VowpalWabbitClassifier(numPasses=3), vw_df))
    add(TestObject(VowpalWabbitRegressor(numPasses=3), vw_df))
    add(TestObject(VowpalWabbitFeaturizer(inputCols=["a", "b"]), None, tab))
    fz = VowpalWabbitFeaturizer(inputCols=["a"], outputCol="fa").transform(tab)
    fz = VowpalWabbitFeaturizer(inputCols=["b"], outputCol="fb").transform(fz)
    add(TestObject(VowpalWabbitInteractions(inputCols=["fa", "fb"]), None, fz))
    lines = np.array(["1 | x:1 y:2", "-1 | x:0.5 y:1"] * 10, object)
    add(TestObject(VowpalWabbitGeneric(
        passThroughArgs="--loss_function logistic --passes 2"), Table({"value": lines})))
    add(TestObject(VowpalWabbitGenericProgressive(
        passThroughArgs="--loss_function logistic"), None, Table({"value": lines})))
    from synapseml_tpu.vw.learner import make_sparse_batch
    cb_rows = []
    for i in range(30):
        acts = [make_sparse_batch([[a + 1, 10 + a]], [[1.0, 0.5]])[0]
                for a in range(3)]
        cb_rows.append({"features": acts, "chosenAction": (i % 3) + 1,
                        "label": float(i % 2), "probability": 1.0 / 3})
    add(TestObject(VowpalWabbitContextualBandit(numPasses=2),
                   Table.from_rows(cb_rows), skip_serialization=True))
    ds_lines = np.array([json.dumps(
        {"EventId": f"e{i}", "_label_cost": -1.0, "_label_probability": 0.5,
         "_labelIndex": 0, "a": [1, 2], "p": [0.5, 0.5]}) for i in range(6)],
        object)
    add(TestObject(VowpalWabbitDSJsonTransformer(), None,
                   Table({"value": ds_lines})))
    parsed = VowpalWabbitDSJsonTransformer().transform(Table({"value": ds_lines}))
    parsed["reward"] = -parsed["cost"]
    parsed["probabilityPredicted"] = np.full(6, 0.5)
    add(TestObject(VowpalWabbitCSETransformer(), None, parsed))

    # --- dl ------------------------------------------------------------
    add(TestObject(DeepVisionClassifier(backbone="tiny", batchSize=8,
                                        maxEpochs=1), _imgs(8)))
    add(TestObject(DeepTextClassifier(maxEpochs=1, batchSize=4, hiddenSize=16),
                   Table({"text": np.array(["good", "bad"] * 8, object),
                          "label": np.array([1.0, 0.0] * 8)})))

    # --- onnx ----------------------------------------------------------
    payload = _onnx_payload()
    om = ONNXModel(miniBatchSize=8)
    om.setModelPayload(payload)
    om.setFeedDict({"x": "features"})
    om.setFetchDict({"out": "out"})
    add(TestObject(om, None, tab))
    # deprecated CNTKModel shim: same payload via a model FILE (its API);
    # unique per-process path — a fixed name in the shared tempdir would
    # collide across parallel runs (code-review r5)
    import os
    import tempfile

    fd, cntk_path = tempfile.mkstemp(suffix=".onnx", prefix="fuzz_cntk_")
    with os.fdopen(fd, "wb") as fh:
        fh.write(payload)
    from synapseml_tpu.dl import CNTKModel

    add(TestObject(CNTKModel(miniBatchSize=8)
                   .setModelLocation(cntk_path)
                   .setInputCol("features").setOutputCol("out"), None, tab))
    imf = ImageFeaturizer(inputCol="image", outputCol="feat", imageHeight=3,
                          imageWidth=3, headless=False)
    from synapseml_tpu.onnx import Graph, Model as OModel, Node, Tensor, ValueInfo
    Wi = RNG.normal(scale=0.1, size=(27, 2)).astype(np.float32)
    gi = Graph(nodes=[Node(op_type="Flatten", inputs=["img"], outputs=["f"],
                           attrs={}),
                      Node(op_type="MatMul", inputs=["f", "Wi"],
                           outputs=["logits"])],
               initializers={"Wi": Tensor.from_array("Wi", Wi)},
               inputs=[ValueInfo(name="img", elem_type=1, shape=["N", 3, 3, 3])],
               outputs=[ValueInfo(name="logits", elem_type=1, shape=["N", 2])])
    imf.setModelPayload(OModel(graph=gi).encode())
    add(TestObject(imf, None, imgs))

    # --- nn ------------------------------------------------------------
    knn_df = Table({"features": tab["features"], "values": np.arange(40)})
    add(TestObject(KNN(k=2), knn_df))
    ck_df = knn_df.with_column("labels", np.array(["u", "v"] * 20, object))
    conds = np.empty(40, dtype=object)
    for i in range(40):
        conds[i] = ["u"]
    add(TestObject(ConditionalKNN(k=2), ck_df,
                   ck_df.with_column("conditioner", conds)))

    # --- recommendation ------------------------------------------------
    ratings = Table({"user": (np.arange(40) % 5).astype(np.int64),
                     "item": (np.arange(40) % 8).astype(np.int64),
                     "rating": np.ones(40, np.float32)})
    add(TestObject(SAR(supportThreshold=1), ratings))
    raw_r = Table({"u": np.array([f"u{i%3}" for i in range(12)], object),
                   "i": np.array([f"i{i%4}" for i in range(12)], object),
                   "rating": np.ones(12, np.float32)})
    add(TestObject(RecommendationIndexer(
        userInputCol="u", itemInputCol="i", userOutputCol="user",
        itemOutputCol="item"), raw_r))
    add(TestObject(RankingAdapter(recommender=SAR(supportThreshold=1), k=2),
                   ratings, skip_serialization=True))
    add(TestObject(RankingTrainValidationSplit(
        estimator=SAR(supportThreshold=1),
        evaluator=RankingEvaluator(k=2, metricName="recallAtK"),
        estimatorParamMaps=[{}], trainRatio=0.7), ratings,
        skip_serialization=True))

    # --- isolation forest / cyber --------------------------------------
    add(TestObject(IsolationForest(numEstimators=8, maxSamples=16.0), tab))
    access = Table({"tenant": np.array(["t"] * 20, object),
                    "user": np.array([f"u{i%4}" for i in range(20)], object),
                    "res": np.array([f"r{i%3}" for i in range(20)], object),
                    "likelihood": np.ones(20)})
    add(TestObject(AccessAnomaly(maxIter=3, rankParam=3), access))
    add(TestObject(ComplementAccessTransformer(
        indexedColNamesArr=["user", "res"]), None, access))
    add(TestObject(IdIndexer(inputCol="user", partitionKey="tenant",
                             outputCol="uix"), access))
    add(TestObject(MultiIndexer(indexers=[
        IdIndexer(inputCol="user", partitionKey="tenant", outputCol="uix")]),
        access, skip_serialization=True))
    add(TestObject(StandardScalarScaler(inputCol="likelihood",
                                        partitionKey="tenant",
                                        outputCol="z"), access))
    add(TestObject(LinearScalarScaler(inputCol="likelihood",
                                      partitionKey="tenant",
                                      outputCol="s"), access))

    # --- causal ---------------------------------------------------------
    dml_df = Table({"features": tab["features"],
                    "treatment": (tab["a"] > 0).astype(np.float64),
                    "outcome": tab["b"],
                    "heterogeneityFeatures": tab["features"][:, :1]})
    add(TestObject(DoubleMLEstimator(
        treatmentModel=LightGBMRegressor(numIterations=3),
        outcomeModel=LightGBMRegressor(numIterations=3), maxIter=1), dml_df,
        skip_serialization=True))
    add(TestObject(OrthoForestDMLEstimator(
        treatmentModel=LightGBMRegressor(numIterations=3),
        outcomeModel=LightGBMRegressor(numIterations=3), numTrees=3), dml_df,
        skip_serialization=True))
    panel_rows = []
    for u in range(8):
        for t in range(6):
            panel_rows.append({"unit": u, "time": t,
                               "outcome": float(u + t + (u < 2 and t >= 3)),
                               "treatment": float(u < 2),
                               "postTreatment": float(t >= 3)})
    panel = Table.from_rows(panel_rows)
    add(TestObject(DiffInDiffEstimator(), panel))
    add(TestObject(SyntheticControlEstimator(maxIter=50), panel))
    add(TestObject(SyntheticDiffInDiffEstimator(maxIter=50), panel))
    add(TestObject(ResidualTransformer(observedCol="label",
                                       predictedCol="a"), None, tab))

    # --- explainers / image ---------------------------------------------
    inner = LightGBMClassifier(numIterations=3).fit(tab)
    add(TestObject(VectorLIME(model=inner, targetCol="probability",
                              targetClasses=[1], numSamples=20), None, tab,
                   skip_serialization=True))
    add(TestObject(VectorSHAP(model=inner, targetCol="probability",
                              targetClasses=[1], numSamples=20), None, tab,
                   skip_serialization=True))
    class _ColModel(Transformer):
        def _transform(self, df):
            score = (df["a"] > 0).astype(np.float64)
            return df.with_column("probability",
                                  np.stack([1 - score, score], axis=1))

    add(TestObject(TabularLIME(model=_ColModel(), inputCols=["a", "b"],
                               targetCol="probability", targetClasses=[1],
                               numSamples=20, backgroundData=tab), None, tab,
                   skip_serialization=True))
    add(TestObject(TabularSHAP(model=_ColModel(), inputCols=["a", "b"],
                               targetCol="probability", targetClasses=[1],
                               numSamples=20, backgroundData=tab), None, tab,
                   skip_serialization=True))

    class _TextModel(Transformer):
        def _transform(self, df):
            score = np.array([float("good" in t) for t in df["text"]])
            return df.with_column("probability",
                                  np.stack([1 - score, score], axis=1))

    text_df = Table({"text": np.array(["good day", "bad day"] * 4, object)})
    add(TestObject(TextLIME(model=_TextModel(), targetClasses=[1],
                            numSamples=20), None, text_df,
                   skip_serialization=True))
    add(TestObject(TextSHAP(model=_TextModel(), targetClasses=[1],
                            numSamples=20), None, text_df,
                   skip_serialization=True))

    class _ImgModel(Transformer):
        def _transform(self, df):
            col = df["image"]
            score = np.array([float(np.asarray(v).mean() > 100) for v in col])
            return df.with_column("probability",
                                  np.stack([1 - score, score], axis=1))

    add(TestObject(ImageLIME(model=_ImgModel(), targetClasses=[1], cellSize=4.0,
                             numSamples=10), None, imgs,
                   skip_serialization=True))
    add(TestObject(ImageSHAP(model=_ImgModel(), targetClasses=[1], cellSize=4.0,
                             numSamples=10), None, imgs,
                   skip_serialization=True))
    add(TestObject(ICETransformer(model=inner, targetCol="prediction",
                                  categoricalFeatures=["a"]), None, tab,
                   skip_serialization=True))
    add(TestObject(SuperpixelTransformer(inputCol="image", cellSize=4.0),
                   None, imgs))
    add(TestObject(UnrollImage(inputCol="image"), None, imgs))
    add(TestObject(ImageSetAugmenter(inputCol="image"), None, imgs))

    # --- featurize -------------------------------------------------------
    miss = Table({"x": np.array([1.0, np.nan, 3.0, 4.0]),
                  "y": np.array([1.0, 2.0, np.nan, 4.0])})
    add(TestObject(CleanMissingData(inputCols=["x", "y"],
                                    outputCols=["x2", "y2"]), miss))
    add(TestObject(DataConversion(cols=["a"], convertTo="float"), None, tab))
    add(TestObject(Featurize(inputCols=["a", "b", "text"],
                             outputCol="feat2", numFeatures=64), tab))
    add(TestObject(ValueIndexer(inputCol="text", outputCol="tix"), tab,
                   also_covers=[IndexToValue]))
    idx_model = ValueIndexer(inputCol="text", outputCol="tix").fit(tab)
    add(TestObject(IndexToValue(inputCol="tix", outputCol="t2",
                                levels=list(idx_model.get("levels"))), None,
                   idx_model.transform(tab)))
    add(TestObject(CountSelector(inputCol="features", outputCol="sel"), tab))
    add(TestObject(TextFeaturizer(inputCol="text", outputCol="tf",
                                  numFeatures=32), tab))
    add(TestObject(MultiNGram(inputCol="text", outputCol="ngrams",
                              lengths=[1, 2]), None, tab))
    add(TestObject(PageSplitter(inputCol="text", outputCol="pages",
                                maximumPageLength=10), None, tab))

    # --- stages ----------------------------------------------------------
    add(TestObject(UDFTransformer(inputCol="a", outputCol="a2")
                   .setUDF(lambda col: col * 2), None, tab))
    add(TestObject(Lambda().setTransform(lambda t: t), None, tab))
    add(TestObject(Cacher(), None, tab))
    add(TestObject(Timer(stage=DropColumns(cols=["text"])), tab,
                   skip_serialization=True))
    add(TestObject(DropColumns(cols=["text"]), None, tab))
    add(TestObject(SelectColumns(cols=["a", "b"]), None, tab))
    add(TestObject(RenameColumn(inputCol="a", outputCol="a_renamed"),
                   None, tab))
    add(TestObject(Repartition(n=2), None, tab))
    explode_df = Table({"k": np.arange(3),
                        "vals": np.array([[1, 2], [3], [4, 5, 6]], object)})
    add(TestObject(Explode(inputCol="vals", outputCol="v"), None, explode_df))
    add(TestObject(FixedMiniBatchTransformer(batchSize=8), None, tab))
    add(TestObject(DynamicMiniBatchTransformer(), None, tab))
    add(TestObject(TimeIntervalMiniBatchTransformer(maxBatchSize=8),
                   None, tab))
    batched = FixedMiniBatchTransformer(batchSize=8).transform(tab)
    add(TestObject(FlattenBatch(), None, batched))
    add(TestObject(ClassBalancer(inputCol="label"), tab))
    add(TestObject(StratifiedRepartition(labelCol="label", mode="equal"),
                   None, tab))
    add(TestObject(EnsembleByKey(keys=["group"], cols=["a"]), None, tab))
    add(TestObject(PartitionConsolidator(numPartitions=2, concurrency=2),
                   None, tab))
    add(TestObject(SummarizeData(), None, tab))
    add(TestObject(TextPreprocessor(inputCol="text", outputCol="tp",
                                    normFunc="lowercase"), None, tab))
    add(TestObject(UnicodeNormalize(inputCol="text", outputCol="un",
                                    form="NFKD"), None, tab))
    add(TestObject(MultiColumnAdapter(baseStage=RenameColumn(),
                                      inputCols=["a", "b"],
                                      outputCols=["a3", "b3"]), tab,
                   skip_serialization=True))

    # --- train / automl --------------------------------------------------
    add(TestObject(TrainClassifier(model=LightGBMClassifier(numIterations=3),
                                   labelCol="label"), tab,
                   skip_serialization=True))
    add(TestObject(TrainRegressor(model=LightGBMRegressor(numIterations=3),
                                  labelCol="b"), tab,
                   skip_serialization=True))
    pred_df = Table({"label": tab["label"],
                     "prediction": tab["label"],
                     "probability": np.stack([1 - tab["label"],
                                              tab["label"]], axis=1)})
    add(TestObject(ComputeModelStatistics(evaluationMetric="classification"),
                   None, pred_df))
    add(TestObject(ComputePerInstanceStatistics(), None, pred_df))
    from synapseml_tpu.automl import DiscreteHyperParam
    space = (HyperparamBuilder()
             .addHyperparam("numIterations", DiscreteHyperParam([2, 3]))
             .build())
    add(TestObject(TuneHyperparameters(model=LightGBMClassifier(),
                                       paramSpace=space, searchMode="grid",
                                       numFolds=2, evaluationMetric="AUC"),
                   tab, skip_serialization=True))
    m1 = LightGBMClassifier(numIterations=2).fit(tab)
    m2 = LightGBMClassifier(numIterations=3).fit(tab)
    add(TestObject(FindBestModel(models=[m1, m2], evaluationMetric="AUC",
                                 labelCol="label"), tab,
                   skip_serialization=True))

    # --- exploratory -----------------------------------------------------
    from synapseml_tpu.exploratory import (AggregateBalanceMeasure,
                                           DistributionBalanceMeasure,
                                           FeatureBalanceMeasure)
    cohort = Table({"gender": np.array(["M"] * 6 + ["F"] * 4, object),
                    "label": np.array([1, 1, 1, 1, 0, 0, 1, 0, 0, 0],
                                      np.float64)})
    add(TestObject(FeatureBalanceMeasure(sensitiveCols=["gender"],
                                         labelCol="label"), None, cohort))
    add(TestObject(DistributionBalanceMeasure(sensitiveCols=["gender"]),
                   None, cohort))
    add(TestObject(AggregateBalanceMeasure(sensitiveCols=["gender"]),
                   None, cohort))

    # --- pipeline --------------------------------------------------------
    add(TestObject(Pipeline(stages=[DropColumns(cols=["text"]),
                                    LightGBMClassifier(numIterations=3)]),
                   tab, also_covers=[PipelineModel]))

    # --- io --------------------------------------------------------------
    add(TestObject(HTTPTransformer(inputCol="req", outputCol="resp")
                   .setHandler(_stub_handler), None,
                   _req_df(), skip_serialization=True))
    add(TestObject(SimpleHTTPTransformer(inputCol="value", outputCol="out",
                                         url="http://stub.local/",
                                         handler=_stub_handler), None,
                   Table({"value": np.array([1, 2])}), skip_serialization=True))
    add(TestObject(JSONInputParser(inputCol="value", outputCol="req",
                                   url="http://stub.local/"), None,
                   Table({"value": np.array([1, 2])})))
    ci = CustomInputParser(inputCol="value", outputCol="req")
    ci.setUDF(lambda v: HTTPRequestData(url="http://stub.local/"))
    add(TestObject(ci, None, Table({"value": np.array([1])}),
                   skip_serialization=True))
    resp_df = Table({"resp": _resp_col()})
    add(TestObject(JSONOutputParser(inputCol="resp", outputCol="out"),
                   None, resp_df))
    add(TestObject(StringOutputParser(inputCol="resp", outputCol="out"),
                   None, resp_df))
    co = CustomOutputParser(inputCol="resp", outputCol="out")
    co.setUDF(lambda r: r.status_code)
    add(TestObject(co, None, resp_df, skip_serialization=True))

    # --- services (stub handler; request construction + parsing) --------
    svc_objs = [
        S.TextSentiment(url="http://stub.local/l"),
        S.KeyPhraseExtractor(url="http://stub.local/l"),
        S.NER(url="http://stub.local/l"),
        S.PII(url="http://stub.local/l"),
        S.EntityLinking(url="http://stub.local/l"),
        S.LanguageDetector(url="http://stub.local/l"),
        S.AnalyzeHealthText(url="http://stub.local/l"),
        S.OpenAICompletion(url="http://stub.local", deploymentName="d"),
        S.OpenAIChatCompletion(url="http://stub.local", deploymentName="d"),
        S.OpenAIEmbedding(url="http://stub.local", deploymentName="d",
                          textCol="text"),
        S.OpenAIPrompt(url="http://stub.local", deploymentName="d",
                       promptTemplate="echo {text}"),
        S.Translate(url="http://stub.local", toLanguage=["de"]),
        S.Detect(url="http://stub.local"),
        S.BreakSentence(url="http://stub.local"),
        S.Transliterate(url="http://stub.local", language="ja",
                        fromScript="Jpan", toScript="Latn"),
        S.DictionaryLookup(url="http://stub.local", fromLanguage="en",
                           toLanguage="de"),
        S.AnalyzeImage(url="http://stub.local/vision",
                       imageUrlCol="imageUrl"),
        S.DescribeImage(url="http://stub.local/vision",
                        imageUrlCol="imageUrl"),
        S.TagImage(url="http://stub.local/vision", imageUrlCol="imageUrl"),
        S.OCR(url="http://stub.local/vision", imageUrlCol="imageUrl"),
        S.GenerateThumbnails(url="http://stub.local/vision",
                             imageUrlCol="imageUrl"),
        S.DetectFace(url="http://stub.local/face", imageUrlCol="imageUrl"),
        S.DetectLastAnomaly(url="http://stub.local/anomaly"),
        S.DetectAnomalies(url="http://stub.local/anomaly"),
        S.SimpleDetectAnomalies(url="http://stub.local/anomaly",
                                groupbyCol="grp"),
        S.DetectMultivariateAnomaly(url="http://stub.local/mv",
                                    modelId="m1", seriesCol="mvseries"),
        S.SpeechToText(url="http://stub.local/stt", audioDataCol="audio"),
        S.SpeechToTextSDK(url="http://stub.local/stt", audioDataCol="audio"),
        S.TextToSpeech(url="http://stub.local/tts"),
        S.AnalyzeDocument(url="http://stub.local", imageBytesCol="imageBytes",
                          maxPollRetries=1, pollInterval=0.01),
        S.BingImageSearch(url="http://stub.local/bing"),
        S.AddressGeocoder(url="http://stub.local/maps",
                          subscriptionKey="k"),
        S.ReverseAddressGeocoder(url="http://stub.local/maps",
                                 subscriptionKey="k"),
        S.CheckPointInPolygon(url="http://stub.local/maps",
                              subscriptionKey="k", userDataIdentifier="udid"),
        S.AnalyzeLayout(url="http://stub.local", imageBytesCol="imageBytes",
                        maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeReceipts(url="http://stub.local", imageBytesCol="imageBytes",
                          maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeBusinessCards(url="http://stub.local",
                               imageBytesCol="imageBytes",
                               maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeInvoices(url="http://stub.local", imageBytesCol="imageBytes",
                          maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeIDDocuments(url="http://stub.local",
                             imageBytesCol="imageBytes",
                             maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeDocumentRead(url="http://stub.local",
                              imageBytesCol="imageBytes",
                              maxPollRetries=1, pollInterval=0.01),
        S.AnalyzeCustomModel(url="http://stub.local", modelId="custom-1",
                             imageBytesCol="imageBytes",
                             maxPollRetries=1, pollInterval=0.01),
        # round-2 additions (VERDICT missing #6): face ops, custom-model
        # management, unified/async language, document translation, batch
        # search indexing, streaming speech, multivariate lifecycle
        S.EntityDetector(url="http://stub.local/l"),
        S.AnalyzeText(url="http://stub.local/l", kind="KeyPhraseExtraction"),
        S.TextAnalyze(url="http://stub.local/l", maxPollRetries=1,
                      pollInterval=0.01),
        S.DictionaryExamples(url="http://stub.local", fromLanguage="en",
                             toLanguage="de"),
        S.DocumentTranslator(serviceName="stub", sourceUrl="http://s/c1",
                             targetUrl="http://s/c2", url="http://stub.local"),
        S.ReadImage(url="http://stub.local/vision", imageUrlCol="imageUrl",
                    maxPollRetries=1, pollInterval=0.01),
        S.RecognizeText(url="http://stub.local/vision",
                        imageUrlCol="imageUrl", maxPollRetries=1,
                        pollInterval=0.01),
        S.RecognizeDomainSpecificContent(url="http://stub.local/vision",
                                         imageUrlCol="imageUrl"),
        S.FindSimilarFace(url="http://stub.local/face", faceIdCol="faceId"),
        S.GroupFaces(url="http://stub.local/face", faceIdsCol="faceIds"),
        S.IdentifyFaces(url="http://stub.local/face", faceIdsCol="faceIds",
                        personGroupId="pg"),
        S.VerifyFaces(url="http://stub.local/face", faceId1Col="faceId",
                      faceId2Col="faceId"),
        S.GetCustomModel(url="http://stub.local", modelId="custom-1"),
        S.ListCustomModels(url="http://stub.local"),
        S.DetectLastMultivariateAnomaly(url="http://stub.local/mv",
                                        modelId="m1", seriesCol="mvseries"),
        S.SimpleDetectMultivariateAnomaly(url="http://stub.local/mv",
                                          modelId="m1", seriesCol="mvseries",
                                          maxPollRetries=1,
                                          pollInterval=0.01),
        S.AddDocuments(url="http://stub.local/search", subscriptionKey="k"),
        S.SpeakerEmotionInference(url="http://stub.local/ssml"),
        S.ConversationTranscription(url="http://stub.local/cts",
                                    audioDataCol="audio"),
    ]
    for t in svc_objs:
        t.set("handler", _stub_handler)
        add(TestObject(t, None, svc, skip_serialization=True))

    # FormOntologyLearner: estimator over AnalyzeDocument outputs
    ana = np.empty(2, dtype=object)
    for i in range(2):
        ana[i] = {"analyzeResult": {"documents": [
            {"fields": {"Total": {"type": "number", "valueNumber": 10.5},
                        "Vendor": {"type": "string",
                                   "valueString": f"acme{i}"}}}]}}
    onto_df = Table({"analyzed": ana})
    add(TestObject(S.FormOntologyLearner(inputCol="analyzed"),
                   onto_df, onto_df, skip_serialization=True))

    # SimpleFitMultivariateAnomaly: full train -> poll -> READY lifecycle
    def _mvad_handler(req, send):
        return HTTPResponseData(
            201, "Created", {"Location": "http://stub.local/mv/models/m123"},
            json.dumps({"modelInfo": {"status": "READY"}}).encode())

    fitter = S.SimpleFitMultivariateAnomaly(
        url="http://stub.local/mv", dataSource="http://blob/x",
        startTime="2026-01-01T00:00:00Z", endTime="2026-01-02T00:00:00Z",
        seriesCol="mvseries", maxPollRetries=2, pollInterval=0.01)
    fitter.set("handler", _mvad_handler)
    add(TestObject(fitter, svc, svc, skip_serialization=True))

    # FormOntologyTransformer reached via its learner AND directly
    add(TestObject(S.FormOntologyTransformer(
        inputCol="analyzed", ontology={"Total": "number"}), None, onto_df,
        skip_serialization=True))
    return objs


def _req_df():
    from synapseml_tpu.io.http import HTTPRequestData

    col = np.empty(2, dtype=object)
    for i in range(2):
        col[i] = HTTPRequestData.from_json_body("http://stub.local/", {"v": i})
    return Table({"req": col})


def _resp_col():
    col = np.empty(2, dtype=object)
    for i in range(2):
        col[i] = HTTPResponseData(200, "OK", {}, b'{"ok": true}')
    return Table({"resp": col})["resp"]


# classes legitimately without their own TestObject
EXEMPT = {
    "synapseml_tpu.core.pipeline.Estimator",      # abstract bases
    "synapseml_tpu.core.pipeline.Transformer",
    "synapseml_tpu.core.pipeline.Model",
    "synapseml_tpu.explainers.base.LocalExplainerBase",
    "synapseml_tpu.services.base.CognitiveServiceBase",
    "synapseml_tpu.services.base.HasServiceParams",
    "synapseml_tpu.services.base.HasSetLocation",
    "synapseml_tpu.services.base.HasAsyncReply",
}


_OBJS = None


def _objs():
    global _OBJS
    if _OBJS is None:
        _OBJS = _registry()
    return _OBJS


class TestFuzzing:
    def test_experiment_fuzzing_and_coverage(self):
        """FuzzingTest.scala analog: every concrete stage class must be
        exercised by some TestObject."""
        touched = set()
        failures = []
        for obj in _objs():
            try:
                touched |= experiment_fuzz(obj)
            except Exception as e:  # noqa: BLE001
                failures.append(f"{type(obj.stage).__name__}: {e}")
        assert not failures, "experiment fuzzing failures:\n  " + \
            "\n  ".join(failures)

        discovered = discover_stage_classes()
        missing = []
        for cls in discovered:
            fq = f"{cls.__module__}.{cls.__name__}"
            if cls not in touched and fq not in EXEMPT:
                missing.append(fq)
        assert not missing, (
            "stages without fuzzing coverage (add a TestObject to "
            "tests/test_fuzzing.py _registry or an EXEMPT entry):\n  "
            + "\n  ".join(sorted(missing)))

    def test_serialization_fuzzing(self, tmp_path):
        failures = []
        for obj in _objs():
            if obj.skip_serialization:
                continue
            try:
                serialization_fuzz(obj, str(tmp_path))
            except Exception as e:  # noqa: BLE001
                failures.append(f"{type(obj.stage).__name__}: {e}")
        assert not failures, "serialization fuzzing failures:\n  " + \
            "\n  ".join(failures)

    def test_getter_setter_fuzzing(self):
        failures = []
        for obj in _objs():
            try:
                getter_setter_fuzz(obj)
            except Exception as e:  # noqa: BLE001
                failures.append(f"{type(obj.stage).__name__}: {e}")
        assert not failures, "getter/setter fuzzing failures:\n  " + \
            "\n  ".join(failures)
