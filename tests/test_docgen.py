"""Docs-site generator (tools/docgen — the reference's docgen + website
analog, SURVEY §2.9)."""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_docgen_builds_site():
    with tempfile.TemporaryDirectory() as d:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "docgen", "docgen.py"),
             "--out", d], capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        pages = [f for f in os.listdir(d) if f.endswith(".html")]
        assert "index.html" in pages and "api.html" in pages
        api = open(os.path.join(d, "api.html"), encoding="utf-8").read()
        assert "<nav>" in api and "<table>" in api     # params tables render
        assert "numIterations" in api                  # real param surfaced


def test_md_renderer_subset():
    sys.path.insert(0, os.path.join(REPO, "tools", "docgen"))
    from docgen import md_to_html

    h = md_to_html("# T\n\npara `c` **b**\n\n- a\n- b\n\n```py\nx=1\n```\n\n"
                   "| h |\n|---|\n| v |\n")
    for frag in ("<h1>T</h1>", "<code>c</code>", "<strong>b</strong>",
                 "<li>a</li>", "<pre><code", "<th>h</th>", "<td>v</td>"):
        assert frag in h, (frag, h)
