"""Real-architecture ONNX validation (VERDICT next-round #5): a ResNet-50
(175 nodes: conv/batchnorm/pool/gemm/residual adds) and a transformer encoder
(50 nodes: matmul/layernorm/softmax attention) written through our protobuf
writer, imported, sliced at intermediate outputs, and run batched through
ONNXModel.transform — the ONNXModel.scala:145-423 parity surface."""

import numpy as np
import pytest

from synapseml_tpu.core.table import Table
from synapseml_tpu.onnx.importer import OnnxFunction, import_model
from synapseml_tpu.onnx.model import ONNXModel
from synapseml_tpu.onnx.modelgen import make_resnet, make_transformer_encoder
from synapseml_tpu.onnx.protoio import Model


@pytest.fixture(scope="module")
def resnet_bytes():
    return make_resnet(50, num_classes=10, image_size=32).encode()


@pytest.fixture(scope="module")
def transformer_bytes():
    return make_transformer_encoder().encode()


def test_resnet50_is_a_real_model(resnet_bytes):
    m = Model.parse(resnet_bytes)
    ops = [n.op_type for n in m.graph.nodes]
    assert len(ops) >= 50
    for required in ("Conv", "BatchNormalization", "MaxPool",
                     "GlobalAveragePool", "Gemm", "Add", "Relu"):
        assert required in ops
    # 53 convolutions = 1 stem + 3*(3+4+6+3) bottleneck + 4 projections
    assert ops.count("Conv") == 53


def test_resnet50_forward_and_determinism(resnet_bytes):
    fn = OnnxFunction(Model.parse(resnet_bytes))
    x = np.random.default_rng(0).normal(size=(4, 3, 32, 32)).astype(np.float32)
    out1 = fn({"data": x})["logits"]
    out2 = fn({"data": x})["logits"]
    assert out1.shape == (4, 10)
    np.testing.assert_array_equal(out1, out2)
    # batch consistency: row-wise == batched
    row = fn({"data": x[:1]})["logits"]
    np.testing.assert_allclose(row[0], out1[0], rtol=1e-4, atol=1e-4)


def test_resnet50_slice_at_intermediate_output(resnet_bytes):
    """ONNXModel.scala:203-227 model-slicing parity: fetch an internal
    activation; the plan must prune all nodes not needed for it."""
    m = Model.parse(resnet_bytes)
    full = OnnxFunction(m)
    sliced = OnnxFunction(m, outputs=["stage1_block0_out", "features"])
    assert len(sliced._plan) < len(full._plan)
    x = np.random.default_rng(1).normal(size=(2, 3, 32, 32)).astype(np.float32)
    outs = sliced({"data": x})
    assert outs["stage1_block0_out"].shape[1] == 512   # 128 * 4 bottleneck
    assert outs["features"].shape == (2, 2048)
    # intermediate must match the value computed inside the full run
    full_outs = OnnxFunction(m, outputs=["features", "logits"])({"data": x})
    np.testing.assert_allclose(outs["features"], full_outs["features"],
                               rtol=1e-4, atol=1e-4)


def test_resnet50_batched_transform_with_postops(resnet_bytes):
    rng = np.random.default_rng(2)
    n = 10
    imgs = rng.normal(size=(n, 3, 32, 32)).astype(np.float32)
    df = Table({"image": list(imgs)})
    stage = (ONNXModel()
             .setModelPayload(resnet_bytes)
             .setFeedDict({"data": "image"})
             .setFetchDict({"raw": "logits"})
             .setSoftMaxDict({"raw": "probs"})
             .setArgMaxDict({"raw": "pred"})
             .setMiniBatchSize(4))
    out = stage.transform(df)
    probs = np.stack(list(out["probs"]))
    preds = np.asarray(list(out["pred"]))
    assert probs.shape == (n, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-4)
    assert (preds == probs.argmax(axis=1)).all()


def test_transformer_attention_ops_and_slice(transformer_bytes):
    m = Model.parse(transformer_bytes)
    ops = [n.op_type for n in m.graph.nodes]
    assert len(ops) >= 50
    for required in ("MatMul", "LayerNormalization", "Softmax", "Transpose",
                     "Gelu", "ReduceMean", "Gemm"):
        assert required in ops
    fn = OnnxFunction(m, outputs=["layer0_out", "logits"])
    x = np.random.default_rng(3).normal(size=(3, 32, 64)).astype(np.float32)
    outs = fn({"embeddings": x})
    assert outs["layer0_out"].shape == (3, 32, 64)
    assert outs["logits"].shape == (3, 2)
    assert np.isfinite(outs["logits"]).all()


def test_transformer_batched_transform(transformer_bytes):
    rng = np.random.default_rng(4)
    n = 6
    embs = rng.normal(size=(n, 32, 64)).astype(np.float32)
    df = Table({"emb": list(embs)})
    stage = (ONNXModel()
             .setModelPayload(transformer_bytes)
             .setFeedDict({"embeddings": "emb"})
             .setFetchDict({"logits": "logits"})
             .setMiniBatchSize(3))
    out = stage.transform(df)
    logits = np.stack(list(out["logits"]))
    assert logits.shape == (n, 2)
    # equals direct forward
    direct = OnnxFunction(Model.parse(transformer_bytes))({"embeddings": embs})
    np.testing.assert_allclose(logits, direct["logits"], rtol=1e-4, atol=1e-4)


def test_file_roundtrip(tmp_path, resnet_bytes):
    p = tmp_path / "resnet50.onnx"
    p.write_bytes(resnet_bytes)
    fn = import_model(p.read_bytes())
    x = np.zeros((1, 3, 32, 32), np.float32)
    assert fn({"data": x})["logits"].shape == (1, 10)
