"""com.microsoft contrib ops (ORT transformer-optimizer fusion set).

Each fused op is pinned against its decomposition built from plain ONNX /
numpy math: Attention vs an explicit per-head softmax attention,
SkipLayerNormalization vs add+LayerNorm, EmbedLayerNormalization vs
gather+add+LayerNorm, the Gelu variants vs their defining formulas.
"""

import numpy as np

from synapseml_tpu.onnx.importer import OnnxFunction
from synapseml_tpu.onnx.modelgen import _attr, _vi
from synapseml_tpu.onnx.protoio import Graph, Model, Node, Tensor


def _run(nodes, inputs, outputs, feeds, inits=None):
    m = Model(graph=Graph(nodes=nodes, initializers=inits or {},
                          inputs=inputs, outputs=outputs, name="g"),
              opset=17)
    fn = OnnxFunction(Model.parse(m.encode()))
    return fn(feeds)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _layernorm(h, gamma, beta, eps=1e-12):
    mean = h.mean(-1, keepdims=True)
    var = h.var(-1, keepdims=True)
    return (h - mean) / np.sqrt(var + eps) * gamma + beta


class TestGelus:
    def test_fastgelu_formula(self):
        x = np.linspace(-3, 3, 13).astype(np.float32)
        bias = np.float32(0.25) * np.ones(13, np.float32)
        n = Node(op_type="FastGelu", inputs=["x", "b"], outputs=["y"])
        out = _run([n], [_vi("x", [13])], [_vi("y", [13])], {"x": x},
                   {"b": Tensor.from_array("b", bias)})
        xb = (x + 0.25).astype(np.float64)
        want = 0.5 * xb * (1 + np.tanh(
            0.7978845608028654 * (xb + 0.044715 * xb ** 3)))
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=1e-5,
                                   atol=1e-6)

    def test_biasgelu_exact_erf(self):
        from scipy.special import erf

        x = np.linspace(-2, 2, 9).astype(np.float32)
        bias = np.full(9, -0.1, np.float32)
        n = Node(op_type="BiasGelu", inputs=["x", "b"], outputs=["y"])
        out = _run([n], [_vi("x", [9])], [_vi("y", [9])], {"x": x},
                   {"b": Tensor.from_array("b", bias)})
        xb = (x - 0.1).astype(np.float64)
        want = xb * 0.5 * (1 + erf(xb / np.sqrt(2)))
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=1e-5,
                                   atol=1e-6)


class TestFusedMatMul:
    def test_trans_and_alpha(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 3)).astype(np.float32)
        b = rng.normal(size=(5, 4)).astype(np.float32)
        n = Node(op_type="FusedMatMul", inputs=["a", "b"], outputs=["y"],
                 attrs={"transA": _attr("transA", 1),
                        "transB": _attr("transB", 1),
                        "alpha": _attr("alpha", 0.5)})
        out = _run([n], [_vi("a", [4, 3]), _vi("b", [5, 4])],
                   [_vi("y", [3, 5])], {"a": a, "b": b})
        np.testing.assert_allclose(np.asarray(out["y"]), 0.5 * (a.T @ b.T),
                                   rtol=1e-5)


class TestSkipLayerNorm:
    def test_matches_decomposition(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 5, 8)).astype(np.float32)
        skip = rng.normal(size=(2, 5, 8)).astype(np.float32)
        gamma = rng.normal(size=8).astype(np.float32)
        beta = rng.normal(size=8).astype(np.float32)
        bias = rng.normal(size=8).astype(np.float32)
        n = Node(op_type="SkipLayerNormalization",
                 inputs=["x", "s", "g", "be", "bi"], outputs=["y"])
        out = _run([n], [_vi("x", [2, 5, 8]), _vi("s", [2, 5, 8])],
                   [_vi("y", [2, 5, 8])], {"x": x, "s": skip},
                   {"g": Tensor.from_array("g", gamma),
                    "be": Tensor.from_array("be", beta),
                    "bi": Tensor.from_array("bi", bias)})
        want = _layernorm(x + skip + bias, gamma, beta)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=1e-4,
                                   atol=1e-5)


class TestEmbedLayerNorm:
    def test_matches_decomposition(self):
        rng = np.random.default_rng(2)
        V, P, H, B, S = 30, 10, 8, 2, 6
        ids = rng.integers(0, V, (B, S)).astype(np.int32)
        seg = rng.integers(0, 2, (B, S)).astype(np.int32)
        we = rng.normal(size=(V, H)).astype(np.float32)
        pe = rng.normal(size=(P, H)).astype(np.float32)
        se = rng.normal(size=(2, H)).astype(np.float32)
        gamma = rng.normal(size=H).astype(np.float32)
        beta = rng.normal(size=H).astype(np.float32)
        mask = np.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 0, 0, 0, 0]],
                          np.int32)
        n = Node(op_type="EmbedLayerNormalization",
                 inputs=["ids", "seg", "we", "pe", "se", "g", "b", "m"],
                 outputs=["y", "mi"])
        out = _run([n], [_vi("ids", [B, S]), _vi("seg", [B, S]),
                         _vi("m", [B, S])],
                   [_vi("y", [B, S, H]), _vi("mi", [B])],
                   {"ids": ids, "seg": seg, "m": mask},
                   {"we": Tensor.from_array("we", we),
                    "pe": Tensor.from_array("pe", pe),
                    "se": Tensor.from_array("se", se),
                    "g": Tensor.from_array("g", gamma),
                    "b": Tensor.from_array("b", beta)})
        want = _layernorm(we[ids] + pe[np.arange(S)][None] + se[seg],
                          gamma, beta)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(out["mi"]), [4, 2])


class TestAttention:
    def _reference(self, x, w, b, nh, mask=None, uni=False):
        B, S, _ = x.shape
        Hout = w.shape[1] // 3
        hd = Hout // nh
        qkv = x @ w + b
        q, k, v = qkv[..., :Hout], qkv[..., Hout:2 * Hout], qkv[..., 2 * Hout:]

        def heads(t):
            return t.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        logits = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
        if mask is not None:
            logits = np.where(mask[:, None, None, :].astype(bool), logits,
                              -10000.0)
        if uni:
            logits = np.where(np.tril(np.ones((S, S), bool))[None, None],
                              logits, -10000.0)
        return (_softmax(logits) @ v).transpose(0, 2, 1, 3).reshape(
            B, S, Hout)

    def test_masked_attention(self):
        rng = np.random.default_rng(3)
        B, S, Hin, nh, Hout = 2, 5, 8, 2, 8
        x = rng.normal(size=(B, S, Hin)).astype(np.float32)
        w = (rng.normal(size=(Hin, 3 * Hout)) * 0.3).astype(np.float32)
        b = rng.normal(size=3 * Hout).astype(np.float32)
        mask = np.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.int32)
        n = Node(op_type="Attention", inputs=["x", "w", "b", "m"],
                 outputs=["y"], attrs={"num_heads": _attr("num_heads", nh)})
        out = _run([n], [_vi("x", [B, S, Hin]), _vi("m", [B, S])],
                   [_vi("y", [B, S, Hout])], {"x": x, "m": mask},
                   {"w": Tensor.from_array("w", w),
                    "b": Tensor.from_array("b", b)})
        want = self._reference(x, w, b, nh, mask)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_qkv_hidden_sizes_and_scale(self):
        """Non-uniform V width + a custom scale attr (code-review r4): the
        packed projection must slice at q/k/v offsets, not equal thirds."""
        rng = np.random.default_rng(5)
        B, S, Hin, nh = 1, 3, 4, 2
        qh = kh = 4
        vh = 8
        x = rng.normal(size=(B, S, Hin)).astype(np.float32)
        w = (rng.normal(size=(Hin, qh + kh + vh)) * 0.3).astype(np.float32)
        b = np.zeros(qh + kh + vh, np.float32)
        n = Node(op_type="Attention", inputs=["x", "w", "b"], outputs=["y"],
                 attrs={"num_heads": _attr("num_heads", nh),
                        "qkv_hidden_sizes": _attr("qkv_hidden_sizes",
                                                  [qh, kh, vh]),
                        "scale": _attr("scale", 0.25)})
        out = _run([n], [_vi("x", [B, S, Hin])], [_vi("y", [B, S, vh])],
                   {"x": x}, {"w": Tensor.from_array("w", w),
                              "b": Tensor.from_array("b", b)})
        qkv = x @ w
        q, k, v = qkv[..., :qh], qkv[..., qh:qh + kh], qkv[..., qh + kh:]
        qH = q.reshape(B, S, nh, qh // nh).transpose(0, 2, 1, 3)
        kH = k.reshape(B, S, nh, kh // nh).transpose(0, 2, 1, 3)
        vH = v.reshape(B, S, nh, vh // nh).transpose(0, 2, 1, 3)
        logits = (qH @ kH.transpose(0, 1, 3, 2)) * 0.25
        want = (_softmax(logits) @ vH).transpose(0, 2, 1, 3).reshape(
            B, S, vh)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-4,
                                   atol=2e-5)

    def test_unidirectional(self):
        rng = np.random.default_rng(4)
        B, S, H, nh = 1, 4, 6, 3
        x = rng.normal(size=(B, S, H)).astype(np.float32)
        w = (rng.normal(size=(H, 3 * H)) * 0.3).astype(np.float32)
        b = np.zeros(3 * H, np.float32)
        n = Node(op_type="Attention", inputs=["x", "w", "b"],
                 outputs=["y"],
                 attrs={"num_heads": _attr("num_heads", nh),
                        "unidirectional": _attr("unidirectional", 1)})
        out = _run([n], [_vi("x", [B, S, H])], [_vi("y", [B, S, H])],
                   {"x": x}, {"w": Tensor.from_array("w", w),
                              "b": Tensor.from_array("b", b)})
        want = self._reference(x, w, b, nh, uni=True)
        np.testing.assert_allclose(np.asarray(out["y"]), want, rtol=2e-4,
                                   atol=2e-5)
