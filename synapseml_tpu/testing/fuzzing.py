"""Generic stage fuzzing.

Reference: core/.../core/test/fuzzing/Fuzzing.scala — ``TestObject`` (stage +
fitting/transform DataFrames, :36-52), ``ExperimentFuzzing`` (fit/transform
smoke, :420), ``SerializationFuzzing`` (save/load round-trip of the stage AND
fitted models with output equality, :452), ``GetterSetterFuzzing`` (:542).
The reflection-driven meta-test lives in tests/test_fuzzing.py.
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from dataclasses import dataclass, field
from typing import List, Optional, Set, Type

import numpy as np

from ..core.pipeline import Estimator, PipelineStage, Transformer
from ..core.table import Table


@dataclass
class TestObject:
    """A stage plus the data that exercises it (Fuzzing.scala:36-52)."""
    stage: PipelineStage
    fit_df: Optional[Table] = None        # for estimators
    transform_df: Optional[Table] = None  # defaults to fit_df
    # classes this object intentionally also covers (e.g. produced Model)
    also_covers: List[type] = field(default_factory=list)
    # skip save/load comparison (e.g. nondeterministic or unserializable)
    skip_serialization: bool = False

    @property
    def tdf(self) -> Optional[Table]:
        return self.transform_df if self.transform_df is not None else self.fit_df


def discover_stage_classes(package="synapseml_tpu") -> Set[Type[PipelineStage]]:
    """All concrete PipelineStage subclasses in the package
    (FuzzingTest.scala's jar reflection analog)."""
    pkg = importlib.import_module(package)
    for m in pkgutil.walk_packages(pkg.__path__, package + "."):
        try:
            importlib.import_module(m.name)
        except Exception:  # noqa: BLE001  (optional deps)
            pass

    def subs(c):
        out = set(c.__subclasses__())
        for s in list(out):
            out |= subs(s)
        return out

    found = set()
    for c in subs(PipelineStage):
        if not c.__module__.startswith(package):
            continue
        if c.__name__.startswith("_") or inspect.isabstract(c):
            continue
        found.add(c)
    return found


def experiment_fuzz(obj: TestObject) -> Set[type]:
    """Fit/transform smoke test; returns every class it touched."""
    touched: Set[type] = {type(obj.stage)}
    stage = obj.stage
    if isinstance(stage, Estimator):
        if obj.fit_df is None:
            raise AssertionError(
                f"{type(stage).__name__}: estimator TestObject needs fit_df")
        model = stage.fit(obj.fit_df)
        touched.add(type(model))
        if obj.tdf is not None:
            out = model.transform(obj.tdf)
            assert isinstance(out, Table)
    elif isinstance(stage, Transformer):
        out = stage.transform(obj.tdf)
        assert isinstance(out, Table)
    touched.update(obj.also_covers)
    return touched


def serialization_fuzz(obj: TestObject, tmp_dir: str) -> None:
    """Save/load round-trip with output equality
    (SerializationFuzzing:452 + DataFrameEquality)."""
    import os

    stage = obj.stage
    path = os.path.join(tmp_dir, type(stage).__name__)
    if isinstance(stage, Estimator):
        model = stage.fit(obj.fit_df)
        model.save(path, overwrite=True)
        loaded = PipelineStage.load(path)
        if obj.tdf is not None:
            _assert_tables_close(model.transform(obj.tdf),
                                 loaded.transform(obj.tdf))
        # the estimator itself must round-trip too
        est_path = path + "_est"
        stage.save(est_path, overwrite=True)
        PipelineStage.load(est_path)
    else:
        stage.save(path, overwrite=True)
        loaded = PipelineStage.load(path)
        if obj.tdf is not None:
            _assert_tables_close(stage.transform(obj.tdf),
                                 loaded.transform(obj.tdf))


def getter_setter_fuzz(obj: TestObject) -> None:
    """Every simple param: get → set → get round-trips (GetterSetter:542)."""
    stage = obj.stage
    for name, p in stage._params.items():
        cap = name[0].upper() + name[1:]
        getter = getattr(stage, "get" + cap, None)
        setter = getattr(stage, "set" + cap, None)
        if getter is None or setter is None:
            continue
        val = stage.get(name)
        if val is None:
            continue
        setter(val)
        after = getattr(stage, "get" + cap)()
        if isinstance(val, (list, dict)):
            assert after == val, f"{type(stage).__name__}.{name}"
        elif isinstance(val, float) and np.isnan(val):
            pass
        elif not isinstance(val, (np.ndarray, Table)):
            assert after == val, f"{type(stage).__name__}.{name}"


def _assert_tables_close(a: Table, b: Table) -> None:
    assert set(a.columns) == set(b.columns), (a.columns, b.columns)
    for c in a.columns:
        va, vb = a[c], b[c]
        assert va.shape == vb.shape, f"column {c}: {va.shape} vs {vb.shape}"
        if va.dtype == object or vb.dtype == object:
            for x, y in zip(va.ravel(), vb.ravel()):
                if isinstance(x, np.ndarray):
                    if np.issubdtype(np.asarray(x).dtype, np.number):
                        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)
                    else:
                        np.testing.assert_array_equal(x, y)
                else:
                    assert _eq_or_close(x, y), f"column {c}: {x!r} != {y!r}"
        elif np.issubdtype(va.dtype, np.number):
            np.testing.assert_allclose(va, vb, rtol=1e-5, atol=1e-6)
        else:
            assert (va == vb).all(), f"column {c}"


def _eq_or_close(x, y) -> bool:
    if isinstance(x, float) and isinstance(y, float):
        return abs(x - y) <= 1e-6 + 1e-5 * abs(y) or (np.isnan(x) and np.isnan(y))
    return x == y
