"""Deterministic, seedable fault-injection harness (chaos testing).

The serving/IO stack has three failure surfaces, and this module wraps each
of them so failure behavior is a CI property instead of folklore
(tests/test_chaos_serving.py drives all of it on CPU):

1. **HTTP openers** — :class:`ChaosHTTP` implements the ``opener`` protocol
   that ``io.http.send_with_retries`` / ``services.base`` accept, injecting
   latency, timeouts, 429/5xx, and connection resets between the client code
   and a real (or canned) responder.
2. **The serving handler** — :func:`chaotic_handler` wraps the
   ``Table -> Table`` callable behind :class:`~synapseml_tpu.io.serving.
   ServingServer` with slow batches, thrown exceptions, and per-row poison.
3. **Collective ops** — :func:`chaos_collectives` installs a hook inside
   ``parallel.collectives`` that can stall or fail collective calls. The
   hook fires at *trace time* for jitted code (the same point the env knobs
   resolve), which is exactly where an off-chip test can observe it.

Everything is driven by either an explicit ``script`` (a list of outcomes
consumed one per call — fully deterministic) or seeded rates via
``random.Random(seed)`` (deterministic per seed). No decision reads the
wall clock.

:class:`FlakyHTTPServer` is the backend-side counterpart: a real TCP server
whose per-request behavior follows a script (respond / 5xx / reset / go
silent), used to fault-test the gateway's sibling retry, cooldown, and
circuit breaker against genuine transport errors.
"""

from __future__ import annotations

import errno as _errno
import io as _io
import json as _json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Sequence, Tuple, Union

# an injected transport fault; ConnectionError so existing except-clauses
# (URLError/OSError handlers) treat it like the real thing
class FaultInjected(ConnectionError):
    pass


# outcome vocabulary (script entries / _decide results):
#   "ok"            — pass through / succeed
#   int (e.g. 503)  — HTTP error status
#   "reset"         — connection reset (transport error)
#   "timeout"       — injected timeout (transport error)
#   ("slow", s)     — sleep s seconds, then succeed
Outcome = Union[str, int, Tuple[str, float]]


class ChaosSchedule:
    """Deterministic outcome source: a finite ``script`` consumed first
    (then ``after`` forever), else seeded rates. Thread-safe; ``calls`` and
    ``outcomes`` record every decision for assertions."""

    def __init__(self, seed: int = 0, script: Optional[Sequence[Outcome]] = None,
                 after: Outcome = "ok", error_rate: float = 0.0,
                 error_codes: Sequence[int] = (503,), reset_rate: float = 0.0,
                 timeout_rate: float = 0.0, latency_s: float = 0.0):
        self.rng = random.Random(seed)
        self.script: List[Outcome] = list(script or [])
        self.after = after
        self.error_rate = error_rate
        self.error_codes = tuple(error_codes)
        self.reset_rate = reset_rate
        self.timeout_rate = timeout_rate
        self.latency_s = latency_s
        self.calls = 0
        self.outcomes: List[Outcome] = []
        self._lock = threading.Lock()

    def next_outcome(self) -> Outcome:
        with self._lock:
            self.calls += 1
            if self.script:
                out = self.script.pop(0)
            elif self.error_rate or self.reset_rate or self.timeout_rate:
                r = self.rng.random()
                if r < self.reset_rate:
                    out = "reset"
                elif r < self.reset_rate + self.timeout_rate:
                    out = "timeout"
                elif r < (self.reset_rate + self.timeout_rate
                          + self.error_rate):
                    out = self.rng.choice(self.error_codes)
                else:
                    out = "ok"
            else:
                out = self.after
            self.outcomes.append(out)
            return out


class _CannedResponse:
    """Minimal urlopen-response stand-in (context manager + status/reason/
    headers/read) for canned 2xx replies."""

    def __init__(self, status: int = 200, body: bytes = b"{}",
                 headers: Optional[dict] = None):
        self.status = status
        self.reason = "OK"
        self.headers = dict(headers or {"Content-Type": "application/json"})
        self._body = body

    def read(self) -> bytes:
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ChaosHTTP:
    """Fault-injecting HTTP opener.

    Use as ``send_with_retries(req, opener=chaos)`` or set as the ``opener``
    param on ``HTTPTransformer`` / any ``CognitiveServiceBase`` subclass. On
    "ok" it forwards to ``inner`` (default: real ``urllib.request.urlopen``)
    unless a ``responder`` is given, in which case the canned
    ``responder(request) -> (status, body_bytes)`` result is returned without
    touching the network — fully hermetic chaos tests.
    """

    def __init__(self, schedule: Optional[ChaosSchedule] = None,
                 responder: Optional[Callable] = None, inner=None, **sched_kw):
        self.schedule = schedule or ChaosSchedule(**sched_kw)
        self.responder = responder
        self.inner = inner

    def open(self, request, timeout: Optional[float] = None):
        out = self.schedule.next_outcome()
        if self.schedule.latency_s:
            time.sleep(self.schedule.latency_s)
        if isinstance(out, tuple) and out[0] == "slow":
            time.sleep(out[1])
            out = "ok"
        if out == "reset":
            raise FaultInjected("chaos: connection reset by peer")
        if out == "timeout":
            raise TimeoutError("chaos: injected timeout")
        if isinstance(out, int) and out >= 400:
            raise urllib.error.HTTPError(
                getattr(request, "full_url", "chaos://"), out,
                f"chaos injected {out}", {},
                _io.BytesIO(b'{"error": "chaos"}'))
        if self.responder is not None:
            status, body = self.responder(request)
            return _CannedResponse(status, body)
        open_fn = self.inner or urllib.request.urlopen
        return open_fn(request, timeout=timeout)

    # services-layer escape hatch: a ``handler`` (HTTPRequestData, send) that
    # routes the default send through this opener — for call sites that take
    # a handler but not an opener
    def as_handler(self):
        from ..io.http import send_with_retries

        def handler(req, send):
            return send_with_retries(req, opener=self)

        return handler


def chaotic_handler(handler: Callable, schedule: Optional[ChaosSchedule] = None,
                    poison: Optional[Callable] = None,
                    slow_s: float = 0.0, **sched_kw) -> Callable:
    """Wrap a serving handler (``Table -> Table``) with injected faults.

    Per call: consume one schedule outcome — "reset"/"timeout"/int all raise
    (a handler exception is a handler exception; the server's isolation and
    500-mapping take it from there); ``("slow", s)`` and ``slow_s`` sleep
    before delegating. ``poison(value) -> bool`` marks individual request
    payloads: any poisoned row in the batch raises, so a server WITHOUT
    per-row isolation 500s the whole batch and one WITH isolation fails only
    the poisoned row — the distinction test_chaos_serving asserts.

    The wrapped handler forwards the server's optional ``budget=`` kwarg when
    the inner handler accepts it.
    """
    sched = schedule or ChaosSchedule(**sched_kw)
    import inspect

    try:
        inner_takes_budget = "budget" in inspect.signature(handler).parameters
    except (TypeError, ValueError):
        inner_takes_budget = False

    def wrapped(df, budget: Optional[float] = None):
        out = sched.next_outcome()
        if slow_s:
            time.sleep(slow_s)
        if isinstance(out, tuple) and out[0] == "slow":
            time.sleep(out[1])
            out = "ok"
        if out != "ok":
            raise FaultInjected(f"chaos handler fault: {out}")
        if poison is not None and "value" in df:
            for v in df["value"]:
                if poison(v):
                    raise FaultInjected("chaos: poisoned row in batch")
        if inner_takes_budget:
            return handler(df, budget=budget)
        return handler(df)

    return wrapped


class chaos_collectives:
    """Context manager installing a fault hook inside
    ``parallel.collectives``: every helper calls the hook with its op name
    before doing any work. Outcomes: "ok" passes, ("slow", s) stalls the
    host (trace-time for jitted code), anything else raises
    :class:`FaultInjected`. Nesting is not supported (single global hook)."""

    def __init__(self, schedule: Optional[ChaosSchedule] = None, **sched_kw):
        self.schedule = schedule or ChaosSchedule(**sched_kw)
        self.seen: List[str] = []

    def _hook(self, name: str) -> None:
        self.seen.append(name)
        out = self.schedule.next_outcome()
        if isinstance(out, tuple) and out[0] == "slow":
            time.sleep(out[1])
            return
        if out != "ok":
            raise FaultInjected(f"chaos collective fault in {name}: {out}")

    def __enter__(self) -> "chaos_collectives":
        from ..parallel import collectives as _c

        if _c._CHAOS_HOOK is not None:
            raise RuntimeError("chaos_collectives does not nest")
        _c._CHAOS_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..parallel import collectives as _c

        _c._CHAOS_HOOK = None


class FlakyHTTPServer:
    """A real TCP backend whose per-REQUEST behavior follows a script —
    the worker-side fault source for gateway/breaker tests.

    Outcomes per request: int status → respond (keep-alive) with a canned
    JSON body; "reset" → close the socket mid-request (client sees
    ECONNRESET/EOF); "ignore" → read the request and never respond (client
    times out); "ok" → 200. After the script: "ok" forever. ``requests``
    counts requests actually read off the wire — the probe-count signal the
    breaker tests assert on.
    """

    def __init__(self, script: Optional[Sequence[Outcome]] = None,
                 body: bytes = b'{"chaos": true}'):
        self.script: List[Outcome] = list(script or [])
        self.body = body
        self.requests = 0
        self._lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _next(self) -> Outcome:
        with self._lock:
            self.requests += 1
            return self.script.pop(0) if self.script else "ok"

    def _read_request(self, conn: socket.socket) -> bool:
        """Read one HTTP request (headers + content-length body); False on
        EOF/garbage (connection done)."""
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = conn.recv(4096)
            if not chunk:
                return False
            buf += chunk
        head, _, rest = buf.partition(b"\r\n\r\n")
        length = 0
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            if k.strip().lower() == b"content-length":
                length = int(v.strip() or 0)
        while len(rest) < length:
            chunk = conn.recv(4096)
            if not chunk:
                return False
            rest += chunk
        return True

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30)
            while not self._stop.is_set():
                if not self._read_request(conn):
                    return
                out = self._next()
                if out == "reset":
                    # RST instead of FIN: SO_LINGER(0) aborts the connection
                    conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
                    return
                if out == "ignore":
                    while not self._stop.is_set():   # hold the socket open,
                        time.sleep(0.05)             # never respond
                    return
                if isinstance(out, tuple) and out[0] == "slow":
                    time.sleep(out[1])
                    out = "ok"
                status = out if isinstance(out, int) else 200
                payload = self.body
                head = (f"HTTP/1.1 {status} X\r\n"
                        f"Content-Type: application/json\r\n"
                        f"Content-Length: {len(payload)}\r\n\r\n")
                conn.sendall(head.encode() + payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def start(self) -> "FlakyHTTPServer":
        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except OSError:
                    return
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()

        self._accept_thread = threading.Thread(target=accept_loop, daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "FlakyHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def canned_json_responder(obj) -> Callable:
    """``responder`` helper for :class:`ChaosHTTP`: always 200 with ``obj``
    as the JSON body."""
    body = _json.dumps(obj).encode()

    def responder(_request):
        return 200, body

    return responder


# ---------------------------------------------------------------------------
# Training-path chaos: preemption kills, checkpoint corruptors, NaN batches
# (tests/test_checkpoint_recovery.py drives all of it on CPU)
# ---------------------------------------------------------------------------

class ChaosPreemption:
    """Context manager killing a training loop at its
    :func:`~synapseml_tpu.core.checkpoint.preemption_point` boundaries —
    the deterministic stand-in for a TPU-pod preemption (SIGTERM mid-step).

    Kill triggers, combinable:

    * ``at`` — mapping of phase name (or phase prefix ending in ``.``) to a
      set of step indices; the FIRST matching call raises
      :class:`~synapseml_tpu.core.checkpoint.PreemptionError`. Each entry
      fires once (a resumed run re-visits the same step and must survive).
    * ``kill_rate`` — seeded probability of dying at any boundary.
    * ``max_kills`` — stop injecting after this many kills (default 1).

    ``calls`` records every boundary visited, ``kills`` every injected
    death. PreemptionError derives from BaseException, so no library
    except-Exception handler can swallow the kill. Nesting is not supported
    (single global hook)."""

    def __init__(self, at: Optional[dict] = None, kill_rate: float = 0.0,
                 seed: int = 0, max_kills: int = 1):
        self.at = {k: set(v) for k, v in (at or {}).items()}
        self.kill_rate = kill_rate
        self.rng = random.Random(seed)
        self.max_kills = max_kills
        self.calls: List[Tuple[str, int]] = []
        self.kills: List[Tuple[str, int]] = []
        self._lock = threading.Lock()

    def _hook(self, phase: str, step: int) -> None:
        from ..core.checkpoint import PreemptionError
        from ..core.logging import record_failure

        with self._lock:
            self.calls.append((phase, step))
            if len(self.kills) >= self.max_kills:
                return
            die = False
            for pat, steps in self.at.items():
                if (phase == pat or (pat.endswith(".")
                                     and phase.startswith(pat))) \
                        and step in steps:
                    steps.discard(step)   # one-shot: resume survives this step
                    die = True
                    break
            if not die and self.kill_rate and \
                    self.rng.random() < self.kill_rate:
                die = True
            if not die:
                return
            self.kills.append((phase, step))
        record_failure("chaos.preemption", phase=phase, step=int(step))
        raise PreemptionError(f"chaos: preempted at {phase}[{step}]")

    def __enter__(self) -> "ChaosPreemption":
        from ..core import checkpoint as _ck

        if _ck._PREEMPT_HOOK is not None:
            raise RuntimeError("ChaosPreemption does not nest")
        _ck._PREEMPT_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..core import checkpoint as _ck

        _ck._PREEMPT_HOOK = None


class chaos_nan_batches:
    """Context manager poisoning DL training batches with NaN at the given
    step indices (one-shot per step, so a post-rollback replay proceeds) —
    installs ``dl.trainer._CHAOS_BATCH_HOOK``. The poisoned input makes the
    LOSS genuinely non-finite, exercising the NonFiniteGuard end to end
    rather than faking a NaN loss value."""

    def __init__(self, at_steps: Sequence[int]):
        self.at_steps = set(int(s) for s in at_steps)
        self.poisoned: List[int] = []
        self._lock = threading.Lock()

    def _hook(self, step, xb, yb):
        with self._lock:
            if step not in self.at_steps:
                return xb, yb
            self.at_steps.discard(step)
            self.poisoned.append(int(step))
        import numpy as _np

        xb = _np.asarray(xb, _np.float32).copy()
        xb[0] = _np.nan
        return xb, yb

    def __enter__(self) -> "chaos_nan_batches":
        from ..dl import trainer as _t

        if _t._CHAOS_BATCH_HOOK is not None:
            raise RuntimeError("chaos_nan_batches does not nest")
        _t._CHAOS_BATCH_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..dl import trainer as _t

        _t._CHAOS_BATCH_HOOK = None


def _newest_checkpoint_artifacts(ckpt_dir: str) -> List[str]:
    """Artifact files (not the manifest) of the newest checkpoint in a
    CheckpointStore directory."""
    import os

    from ..core.checkpoint import MANIFEST_SUFFIX

    manifests = sorted(f for f in os.listdir(ckpt_dir)
                       if f.endswith(MANIFEST_SUFFIX))
    if not manifests:
        raise FileNotFoundError(f"no checkpoint manifests in {ckpt_dir}")
    base = manifests[-1][: -len(MANIFEST_SUFFIX)]
    return [os.path.join(ckpt_dir, f) for f in os.listdir(ckpt_dir)
            if f.startswith(base + ".") and not f.endswith(MANIFEST_SUFFIX)]


def torn_write(ckpt_dir: str, keep_bytes: int = 7) -> str:
    """Corrupt the NEWEST checkpoint like an interrupted write: truncate its
    artifact to ``keep_bytes`` bytes, leaving the manifest in place. The
    store must detect the size/digest mismatch and fall back. Returns the
    truncated file's path."""
    import os

    path = _newest_checkpoint_artifacts(ckpt_dir)[0]
    size = os.path.getsize(path)
    keep = min(max(keep_bytes, 0), max(size - 1, 0))   # always lose >=1 byte
    with open(path, "rb") as f:
        head = f.read(keep)
    with open(path, "wb") as f:
        f.write(head)
    return path


def bit_flip(ckpt_dir: str, offset: Optional[int] = None, bit: int = 3) -> str:
    """Corrupt the NEWEST checkpoint like storage bit rot: flip one bit in
    its artifact (middle byte by default). Size is unchanged, so only the
    CRC/SHA digests can catch it. Returns the flipped file's path."""
    path = _newest_checkpoint_artifacts(ckpt_dir)[0]
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        raise ValueError(f"cannot bit-flip empty file {path}")
    i = len(data) // 2 if offset is None else offset
    data[i] ^= 1 << (bit & 7)
    with open(path, "wb") as f:
        f.write(bytes(data))
    return path


# ---------------------------------------------------------------------------
# Chunk-stream chaos: slow, truncated, and killed-mid-chunk data sources
# (tests/test_oocore.py drives it on CPU; the asserted properties are the
# out-of-core invariants — a dying producer surfaces as ChunkStreamError with
# its thread joined, and a preemption kill at a chunk boundary resumes
# bit-for-bit through the CheckpointStore)
# ---------------------------------------------------------------------------

class chaos_chunk_stream:
    """Context manager corrupting the shared ingestion layer's producer side
    — the deterministic stand-in for a slow, truncating, or dying data
    source feeding :class:`~synapseml_tpu.io.ingest.ChunkPump`.

    Installs ``io.ingest._CHAOS_CHUNK_HOOK``, called as ``hook(k, chunk) ->
    chunk`` on the producer side (inside the pump thread for threaded pumps)
    before placement — exactly where a real loader stalls or dies. Per-pump
    chunk index ``k`` selects the fault:

    * ``delay`` — mapping of chunk index to seconds slept before the chunk
      is delivered (a stalled NFS read / slow decompression); the consumer
      must simply absorb the latency.
    * ``truncate_at`` — from this chunk index on, rows are sliced to
      ``truncate_rows`` (a short read). With the default 0 rows this
      produces an EMPTY chunk — downstream shape checks must reject it
      loudly rather than train on garbage.
    * ``kill_at`` — the producer raises :class:`FaultInjected` at this chunk
      index (the source process died mid-stream). The pump contract:
      the consumer sees :class:`~synapseml_tpu.io.ingest.ChunkStreamError`
      at its next boundary and the producer thread is joined.

    The DISK surface (``io.ingest._CHAOS_DISK_HOOK``) is separate: it fires
    on every chunk read back from disk — :class:`~synapseml_tpu.io.ingest.
    DiskChunkSource` slices and ``StreamedDataset(cache_dir=...)`` spilled
    ``.npy`` readbacks — so a disk fault cannot double-fire through the
    pump-side hook above:

    * ``disk_truncate_at`` — from this disk-read index on, the returned
      array loses its trailing elements down to ``disk_truncate_rows`` (a
      torn/short read). Consumers validate shape and must raise ``OSError``
      rather than bin garbage.
    * ``disk_eio_at`` — the read at this index raises ``OSError(EIO)``
      (a dying device / revoked mmap), which must surface to the caller.

    Faults fire on EVERY pump that passes the index (a training run opens a
    fresh pump per pass), subject to ``max_faults`` (default: unlimited for
    delays, 1 for kills — a resumed run must survive the same chunk).
    ``seen`` records every (k, rows) the hook observed; ``faults`` every
    injected corruption (disk faults as ``("disk_torn", k)`` /
    ``("disk_eio", k)``). Nesting is not supported (single global hook)."""

    def __init__(self, delay: Optional[dict] = None,
                 truncate_at: Optional[int] = None, truncate_rows: int = 0,
                 kill_at: Optional[int] = None, max_kills: int = 1,
                 disk_truncate_at: Optional[int] = None,
                 disk_truncate_rows: int = 0,
                 disk_eio_at: Optional[int] = None):
        self.delay = {int(k): float(v) for k, v in (delay or {}).items()}
        self.truncate_at = truncate_at
        self.truncate_rows = int(truncate_rows)
        self.kill_at = kill_at
        self.max_kills = int(max_kills)
        self.disk_truncate_at = disk_truncate_at
        self.disk_truncate_rows = int(disk_truncate_rows)
        self.disk_eio_at = disk_eio_at
        self.seen: List[Tuple[int, int]] = []
        self.faults: List[Tuple[str, int]] = []
        self._lock = threading.Lock()

    @staticmethod
    def _rows(chunk) -> int:
        # chunks are arrays or tuples of arrays; rows = leading dim of the
        # first array-like element
        first = chunk[0] if isinstance(chunk, tuple) else chunk
        try:
            return int(getattr(first, "shape", (len(first),))[0])
        except TypeError:
            return -1

    def _truncate(self, chunk):
        n = self.truncate_rows
        if isinstance(chunk, tuple):
            return tuple(c[:n] if hasattr(c, "__getitem__") else c
                         for c in chunk)
        return chunk[:n]

    def _hook(self, k: int, chunk):
        with self._lock:
            self.seen.append((k, self._rows(chunk)))
            sleep_s = self.delay.get(k, 0.0)
            kill = (self.kill_at is not None and k == self.kill_at
                    and sum(1 for f, _ in self.faults if f == "kill")
                    < self.max_kills)
            trunc = (self.truncate_at is not None and k >= self.truncate_at)
            if sleep_s:
                self.faults.append(("delay", k))
            if kill:
                self.faults.append(("kill", k))
            elif trunc:
                self.faults.append(("truncate", k))
        if sleep_s:
            time.sleep(sleep_s)
        if kill:
            raise FaultInjected(f"chaos: chunk source died at chunk {k}")
        if trunc:
            return self._truncate(chunk)
        return chunk

    def _disk(self, k: int, arr):
        with self._lock:
            eio = self.disk_eio_at is not None and k == self.disk_eio_at
            torn = (self.disk_truncate_at is not None
                    and k >= self.disk_truncate_at)
            if eio:
                self.faults.append(("disk_eio", k))
            elif torn:
                self.faults.append(("disk_torn", k))
        if eio:
            raise OSError(_errno.EIO,
                          f"chaos: injected EIO reading chunk {k}")
        if torn:
            return arr[..., : self.disk_truncate_rows]
        return arr

    def __enter__(self) -> "chaos_chunk_stream":
        from ..io import ingest as _ing

        if _ing._CHAOS_CHUNK_HOOK is not None \
                or _ing._CHAOS_DISK_HOOK is not None:
            raise RuntimeError("chaos_chunk_stream does not nest")
        _ing._CHAOS_CHUNK_HOOK = self._hook
        _ing._CHAOS_DISK_HOOK = self._disk
        return self

    def __exit__(self, *exc) -> None:
        from ..io import ingest as _ing

        _ing._CHAOS_CHUNK_HOOK = None
        _ing._CHAOS_DISK_HOOK = None


# ---------------------------------------------------------------------------
# Serving-fabric chaos: worker kills, heartbeat partitions, kill-mid-swap
# (tests/test_fabric.py drives all of it on CPU; the asserted property is the
# fabric invariant — an ACCEPTED request (non-503) is never dropped: it
# completes on some worker or 504s within its own deadline)
# ---------------------------------------------------------------------------

def kill_worker(worker) -> None:
    """Hard-kill a ServingServer like a process crash: no drain, no
    deregister farewell — the listener closes immediately, in-flight
    connections break, queued requests die with the process. The gateway
    must discover this the hard way (transport failures tripping the
    breaker, then heartbeat silence evicting the link) — which is exactly
    what this primitive exists to exercise. Idempotent."""
    worker._stop.set()
    worker._draining.set()
    if worker._httpd is not None:
        try:
            worker._httpd.shutdown()
            worker._httpd.server_close()
        except OSError:
            pass


class chaos_heartbeat_partition:
    """Context manager partitioning worker heartbeats away from the gateway
    while leaving the DATA path untouched — the nastiest membership case
    (the gateway evicts a worker that is still perfectly able to serve).

    Installs the ``io.distributed_serving._HEARTBEAT_HOOK`` consulted by
    every :class:`~synapseml_tpu.io.distributed_serving.WorkerAgent` beat:
    a partitioned beat is dropped on the floor (never sent). Deterministic
    control, combinable:

    * ``worker_ids`` — only these agents are affected (default: all).
    * ``partition()`` / ``heal()`` — explicit toggle (starts partitioned).
    * ``schedule`` — a :class:`ChaosSchedule` consulted per beat while
      partitioned is on; any non-"ok" outcome drops the beat.

    ``dropped`` records every dropped (worker_id) for assertions. Nesting
    is not supported (single global hook)."""

    def __init__(self, worker_ids: Optional[Sequence[str]] = None,
                 schedule: Optional[ChaosSchedule] = None,
                 partitioned: bool = True):
        self.worker_ids = set(worker_ids) if worker_ids is not None else None
        self.schedule = schedule
        self._partitioned = partitioned
        self.dropped: List[str] = []
        self._lock = threading.Lock()

    def partition(self) -> None:
        with self._lock:
            self._partitioned = True

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False

    def _hook(self, worker_id: str) -> bool:
        """True = let the beat through; False = drop it."""
        with self._lock:
            if not self._partitioned:
                return True
            if self.worker_ids is not None and \
                    worker_id not in self.worker_ids:
                return True
            if self.schedule is not None and \
                    self.schedule.next_outcome() == "ok":
                return True
            self.dropped.append(worker_id)
            return False

    def __enter__(self) -> "chaos_heartbeat_partition":
        from ..io import distributed_serving as _ds

        if _ds._HEARTBEAT_HOOK is not None:
            raise RuntimeError("chaos_heartbeat_partition does not nest")
        _ds._HEARTBEAT_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..io import distributed_serving as _ds

        _ds._HEARTBEAT_HOOK = None


def kill_gateway(gateway) -> None:
    """Hard-kill a ServingGateway like a process crash: the public
    listener closes immediately (in-flight forwards break back to their
    clients as connection errors), the gossip replicator stops (its
    liveness entry stops advancing, so peers declare it dead after
    ``peer_timeout`` and rehash its ring arcs; its leases expire after
    ``lease_ttl``), and ``gateway.alive()`` flips False — a
    :class:`~synapseml_tpu.io.distributed_serving.PromotionBroadcast` it
    was coordinating dies mid-round with
    :class:`~synapseml_tpu.io.distributed_serving.CoordinatorDied`,
    leaving the recovery to a surviving peer. No farewell of any kind is
    sent: peers and workers must discover the death the hard way, which
    is exactly what this primitive exists to exercise. Idempotent."""
    gateway._killed.set()
    gateway._repl_stop.set()
    if gateway._httpd is not None:
        try:
            gateway._httpd.shutdown()
            gateway._httpd.server_close()
        except OSError:
            pass


class chaos_control_plane_partition:
    """Context manager partitioning the gateways' REPLICATED control plane
    (gossip anti-entropy exchanges) while leaving data paths and worker
    heartbeats intact — the split-brain case: every gateway keeps serving
    from its last converged state while membership/lease/promotion updates
    stop flowing between the partitioned sides.

    Installs the ``io.distributed_serving._GOSSIP_HOOK`` consulted by every
    replicator before each exchange with ``(source_gateway_id, peer_url)``;
    a partitioned exchange is dropped (never dialed). Deterministic
    control, combinable:

    * ``gateway_ids`` — only exchanges ORIGINATED by these gateways are
      affected (default: all). One-sided partitions fall out of listing a
      single side.
    * ``partition()`` / ``heal()`` — explicit toggle (starts partitioned);
      after heal the next exchanges re-converge the fabric (anti-entropy
      is idempotent, so nothing is lost — replication lag just drains).
    * ``schedule`` — a :class:`ChaosSchedule` consulted per exchange while
      partitioned; any non-"ok" outcome drops it (flaky control plane).

    ``dropped`` records every dropped (gateway_id, peer_url) pair for
    assertions. Nesting is not supported (single global hook)."""

    def __init__(self, gateway_ids: Optional[Sequence[str]] = None,
                 schedule: Optional[ChaosSchedule] = None,
                 partitioned: bool = True):
        self.gateway_ids = set(gateway_ids) \
            if gateway_ids is not None else None
        self.schedule = schedule
        self._partitioned = partitioned
        self.dropped: List[Tuple[str, str]] = []
        self._lock = threading.Lock()

    def partition(self) -> None:
        with self._lock:
            self._partitioned = True

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False

    def _hook(self, gateway_id: str, peer_url: str) -> bool:
        """True = let the exchange through; False = drop it."""
        with self._lock:
            if not self._partitioned:
                return True
            if self.gateway_ids is not None and \
                    gateway_id not in self.gateway_ids:
                return True
            if self.schedule is not None and \
                    self.schedule.next_outcome() == "ok":
                return True
            self.dropped.append((gateway_id, peer_url))
            return False

    def __enter__(self) -> "chaos_control_plane_partition":
        from ..io import distributed_serving as _ds

        if _ds._GOSSIP_HOOK is not None:
            raise RuntimeError(
                "chaos_control_plane_partition does not nest")
        _ds._GOSSIP_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..io import distributed_serving as _ds

        _ds._GOSSIP_HOOK = None


class ChaosSwap:
    """Context manager killing a model hot-swap at a chosen stage — the
    deterministic stand-in for "the process handling the swap hit a bug /
    bad checkpoint / OOM mid-transition".

    Installs ``io.serving._SWAP_HOOK``, called by
    :class:`~synapseml_tpu.io.serving.ModelRegistry` at every swap state
    transition (``load`` → ``build`` → ``warmup`` → ``flip`` → ``done``).
    ``at`` names the stage(s) to die at; each entry fires once
    (``max_kills`` total, default 1), raising :class:`FaultInjected` —
    which the registry maps to a rolled-back
    :class:`~synapseml_tpu.io.serving.SwapError`. Any pre-flip kill must
    leave the OLD version serving uninterrupted; that is the property
    tests/test_fabric.py asserts. ``stages`` records every transition
    visited. Nesting is not supported (single global hook)."""

    def __init__(self, at: Union[str, Sequence[str]] = "warmup",
                 max_kills: int = 1):
        self.at = {at} if isinstance(at, str) else set(at)
        self.max_kills = max_kills
        self.stages: List[Tuple[str, str]] = []
        self.kills: List[Tuple[str, str]] = []
        self._lock = threading.Lock()

    def _hook(self, stage: str, version: str) -> None:
        with self._lock:
            self.stages.append((stage, version))
            if stage not in self.at or len(self.kills) >= self.max_kills:
                return
            self.kills.append((stage, version))
        raise FaultInjected(f"chaos: killed swap to {version!r} at {stage}")

    def __enter__(self) -> "ChaosSwap":
        from ..io import serving as _sv

        if _sv._SWAP_HOOK is not None:
            raise RuntimeError("ChaosSwap does not nest")
        _sv._SWAP_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..io import serving as _sv

        _sv._SWAP_HOOK = None


class chaos_tenant_flood:
    """Noisy-neighbor generator: ONE tenant floods a serving endpoint with
    a seeded burst while (optionally) its own handler is sabotaged — slow
    batches and/or non-finite outputs. tests/test_multitenant.py uses it to
    assert the isolation invariant: the abusive tenant sheds at its OWN
    429/503 boundary while every other tenant's p99 and availability hold.

    Two independent knobs, combinable:

    * **Flood** — :meth:`run` fires ``n_requests`` POSTs at ``url`` with
      the ``X-Tenant: <tenant>`` header from ``threads`` concurrent
      workers, bodies drawn from ``random.Random(seed)`` (deterministic
      per seed). Every ``(status, latency_s)`` lands in ``results``;
      :meth:`status_counts` tallies them for assertions.
    * **Sabotage** — entering the context manager swaps the victim
      tenant's handler on ``server`` for a wrapper that sleeps ``slow_s``
      per batch and/or (``nan=True``) replies with non-finite floats,
      exercising the serving NaN guard (per-tenant 500 → quarantine
      breaker). ``__exit__`` restores the original handler.

    No global hook is involved — the wrap is per-(server, tenant) — so
    unlike the other injectors this one nests freely (one instance per
    tenant under attack).
    """

    def __init__(self, url: str, tenant: str, n_requests: int = 100,
                 threads: int = 4, seed: int = 0, timeout: float = 5.0,
                 server=None, slow_s: float = 0.0, nan: bool = False):
        self.url = url
        self.tenant = tenant
        self.n_requests = n_requests
        self.threads = threads
        self.timeout = timeout
        self.rng = random.Random(seed)
        self.server = server
        self.slow_s = slow_s
        self.nan = nan
        self.results: List[Tuple[int, float]] = []
        self._lock = threading.Lock()
        self._orig_handler = None
        self._installed = False

    # -- sabotage: wrap the victim tenant's handler in place --
    def _sabotaged(self, inner: Callable) -> Callable:
        import numpy as _np

        from ..core.table import Table as _Table

        slow_s, emit_nan = self.slow_s, self.nan

        def wrapped(df, budget=None):
            if slow_s:
                time.sleep(slow_s)
            if emit_nan:
                # non-finite replies: json.dumps emits literal NaN, which
                # the server's qos guard converts to a per-tenant 500
                return _Table({
                    "id": df["id"],
                    "reply": _np.full(df.num_rows, _np.nan)})
            return inner(df)

        return wrapped

    def __enter__(self) -> "chaos_tenant_flood":
        if self.server is not None and (self.slow_s or self.nan):
            handlers = getattr(self.server, "tenant_handlers", None)
            if handlers and self.tenant in handlers:
                self._orig_handler = handlers[self.tenant]
                handlers[self.tenant] = self._sabotaged(self._orig_handler)
            else:
                self._orig_handler = self.server.handler
                self.server.handler = self._sabotaged(self._orig_handler)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            handlers = getattr(self.server, "tenant_handlers", None)
            if handlers and self.tenant in handlers:
                handlers[self.tenant] = self._orig_handler
            else:
                self.server.handler = self._orig_handler
            self._installed = False

    # -- flood --
    def _one(self, body: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json",
                     "X-Tenant": self.tenant})
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                resp.read()
                status = resp.status
        except urllib.error.HTTPError as e:
            e.read()
            status = e.code
        except (OSError, urllib.error.URLError):
            status = 599      # transport failure (reset/timeout)
        with self._lock:
            self.results.append((status, time.monotonic() - t0))

    def run(self) -> List[Tuple[int, float]]:
        """Fire the burst; blocks until every request has an outcome."""
        with self._lock:
            bodies = [_json.dumps(
                {"value": self.rng.random()}).encode()
                for _ in range(self.n_requests)]
        work = list(bodies)
        wlock = threading.Lock()

        def worker():
            while True:
                with wlock:
                    if not work:
                        return
                    body = work.pop()
                self._one(body)

        ts = [threading.Thread(target=worker, daemon=True)
              for _ in range(self.threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        with self._lock:
            return list(self.results)

    def status_counts(self) -> dict:
        """``{status: count}`` over everything :meth:`run` has sent."""
        with self._lock:
            out: dict = {}
            for status, _ in self.results:
                out[status] = out.get(status, 0) + 1
            return out


# ---------------------------------------------------------------------------
# Online-learning chaos: corrupted feedback/reward streams
# (tests/test_online.py drives it on CPU; the asserted property is the
# online invariant — the served policy version always passed the
# counterfactual gate, no matter what the reward stream does)
# ---------------------------------------------------------------------------

class chaos_reward_stream:
    """Seeded corruptor for ``(context, action, probability, reward)``
    feedback event streams — the failure model a real reward pipeline has
    (``online/feedback.FeedbackLog`` must absorb all of it):

    * **delayed** — an event is held back and released after up to
      ``max_delay`` later events (out-of-order arrival; join lag).
    * **duplicated** — the same event (same dedup key) is emitted twice
      (at-least-once delivery from the log shipper).
    * **NaN reward** — the reward field arrives non-finite (a poisoned
      join or a divide-by-zero upstream).
    * **adversarial reward** — the reward arrives wildly out of the
      declared ``[reward_min, reward_max]`` range (reward hacking / metric
      pipeline bugs), as ``adversarial_reward``.

    Wraps any iterable of events whose items expose a ``reward`` field via
    ``dataclasses.replace`` (e.g. ``online.feedback.FeedbackEvent``).
    Deterministic per ``seed``: the same stream + seed replays the same
    corruption sequence. ``delayed``/``duplicated``/``nans``/
    ``adversarial`` count every injected corruption for assertions; no
    event is ever silently dropped — every input event is emitted at least
    once (corrupted or not), so conservation asserts stay simple.
    """

    def __init__(self, events, seed: int = 0, delay_rate: float = 0.0,
                 max_delay: int = 4, dup_rate: float = 0.0,
                 nan_rate: float = 0.0, adversarial_rate: float = 0.0,
                 adversarial_reward: float = 1e9):
        self.events = events
        self.rng = random.Random(seed)
        self.delay_rate = delay_rate
        self.max_delay = max(int(max_delay), 1)
        self.dup_rate = dup_rate
        self.nan_rate = nan_rate
        self.adversarial_rate = adversarial_rate
        self.adversarial_reward = adversarial_reward
        self.delayed = 0
        self.duplicated = 0
        self.nans = 0
        self.adversarial = 0

    def _corrupt_reward(self, ev):
        import dataclasses

        r = self.rng.random()
        if r < self.nan_rate:
            self.nans += 1
            return dataclasses.replace(ev, reward=float("nan"))
        if r < self.nan_rate + self.adversarial_rate:
            self.adversarial += 1
            return dataclasses.replace(ev, reward=self.adversarial_reward)
        return ev

    def __iter__(self):
        #: (release_after_index, event) — held-back events re-entering later
        pending: List[Tuple[int, object]] = []
        i = 0
        for ev in self.events:
            i += 1
            ready = [e for due, e in pending if due <= i]
            pending = [(due, e) for due, e in pending if due > i]
            for e in ready:
                yield e
            ev = self._corrupt_reward(ev)
            if self.rng.random() < self.dup_rate:
                self.duplicated += 1
                yield ev            # the duplicate leads; the original
                yield ev            # follows immediately (same dedup key)
                continue
            if self.rng.random() < self.delay_rate:
                self.delayed += 1
                pending.append((i + self.rng.randint(1, self.max_delay), ev))
                continue
            yield ev
        # stream over: flush every still-held event, original order
        for _, e in sorted(pending, key=lambda p: p[0]):
            yield e


# ---------------------------------------------------------------------------
# Elastic-training chaos: hung collectives and hard-killed ranks
# (tests/test_elastic.py drives it; the asserted invariant is the elastic
# one — no committed step is ever lost, and a shrink->resume converges to
# the same model as the uninterrupted run)
# ---------------------------------------------------------------------------

class chaos_candidate:
    """Seeded per-candidate fault injector for the elastic AutoML scheduler.

    Installs ``automl.scheduler._CHAOS_HOOK`` (single global slot, same
    pattern as :class:`ChaosPreemption`); the scheduler invokes the hook as
    ``hook(key, rung, attempt)`` inside the budgeted task thread, *before*
    the candidate's fold fits. The action is a pure function of
    ``(seed, key, rung, attempt)`` — sha256-hashed to a uniform draw against
    the cumulative ``p_crash/p_hang/p_nan/p_slow`` thresholds — so a chaotic
    search interrupted and resumed replays the exact same faults as an
    uninterrupted one: the determinism the kill→resume invariant is proved
    against (tests/test_automl_elastic.py).

    * ``crash`` raises :class:`FaultInjected` (the scheduler retries up to
      its attempt budget; the *attempt* coordinate re-rolls the dice, so a
      retry may survive);
    * ``hang`` blocks on an internal event for up to ``hang_s`` seconds —
      the scheduler's budget reaper is expected to score the candidate NaN
      long before that backstop;
    * ``nan`` poisons the metric (the scheduler skips the fit and scores
      the chunk NaN);
    * ``slow`` sleeps ``slow_s`` then proceeds normally.
    """

    def __init__(self, seed: int = 0, p_crash: float = 0.0,
                 p_hang: float = 0.0, p_nan: float = 0.0,
                 p_slow: float = 0.0, hang_s: float = 30.0,
                 slow_s: float = 0.05):
        self.seed = int(seed)
        self.p_crash, self.p_hang = float(p_crash), float(p_hang)
        self.p_nan, self.p_slow = float(p_nan), float(p_slow)
        self.hang_s, self.slow_s = float(hang_s), float(slow_s)
        self.injected: List[Tuple[str, str, int, int]] = []
        self._lock = threading.Lock()
        self._release = threading.Event()

    def action(self, key: str, rung: int, attempt: int) -> Optional[str]:
        """The (pure, replayable) fault decision for one task attempt."""
        import hashlib as _hashlib

        blob = f"{self.seed}:{key}:{rung}:{attempt}".encode("utf-8")
        u = int.from_bytes(_hashlib.sha256(blob).digest()[:8], "big") / 2**64
        for name, p in (("crash", self.p_crash), ("hang", self.p_hang),
                        ("nan", self.p_nan), ("slow", self.p_slow)):
            if u < p:
                return name
            u -= p
        return None

    def release(self) -> None:
        """Unstick every hung candidate thread."""
        self._release.set()

    def _hook(self, key: str, rung: int, attempt: int) -> Optional[str]:
        act = self.action(key, rung, attempt)
        if act is None:
            return None
        with self._lock:
            self.injected.append((act, key, int(rung), int(attempt)))
        if act == "crash":
            raise FaultInjected(
                f"chaos_candidate crash: {key[:8]} rung {rung} "
                f"attempt {attempt}")
        if act == "hang":
            self._release.wait(self.hang_s)
            return None
        if act == "slow":
            time.sleep(self.slow_s)
            return None
        return "nan"

    def __enter__(self) -> "chaos_candidate":
        from ..automl import scheduler as _s

        if _s._CHAOS_HOOK is not None:
            raise RuntimeError("chaos_candidate does not nest")
        _s._CHAOS_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..automl import scheduler as _s

        _s._CHAOS_HOOK = None
        self._release.set()   # never leave an abandoned thread blocked

    def __del__(self):
        self._release.set()


class chaos_hang:
    """Context manager that HANGS a collective instead of failing it — the
    failure mode retries cannot see and the reason
    ``parallel.elastic.CollectiveWatchdog`` exists. Installs the
    ``parallel.collectives`` chaos hook; the ``at_call``-th call whose op
    name starts with ``op`` ("" matches every op) blocks on an internal
    event for up to ``hang_s`` seconds or until :meth:`release` /
    context-manager exit. The watchdog is expected to convert the hang into
    a :class:`~synapseml_tpu.parallel.elastic.PeerLostError` long before
    ``hang_s`` elapses — the deadline is only the backstop that keeps a
    watchdog-less test from deadlocking forever. Nesting is not supported
    (single global hook, same slot as :class:`chaos_collectives`)."""

    def __init__(self, op: str = "", at_call: int = 1, hang_s: float = 30.0):
        self.op, self.at_call, self.hang_s = op, int(at_call), float(hang_s)
        self.calls = 0
        self.hung: List[str] = []          # ops that actually blocked
        self._release = threading.Event()

    def release(self) -> None:
        """Unstick the hung call (it proceeds normally afterwards)."""
        self._release.set()

    def _hook(self, name: str) -> None:
        if self.op and not name.startswith(self.op):
            return
        self.calls += 1
        if self.calls == self.at_call:
            self.hung.append(name)
            self._release.wait(self.hang_s)

    def __enter__(self) -> "chaos_hang":
        from ..parallel import collectives as _c

        if _c._CHAOS_HOOK is not None:
            raise RuntimeError("chaos_hang does not nest")
        _c._CHAOS_HOOK = self._hook
        return self

    def __exit__(self, *exc) -> None:
        from ..parallel import collectives as _c

        _c._CHAOS_HOOK = None
        self._release.set()     # never leave a worker thread blocked behind

    def __del__(self):
        self._release.set()


def kill_rank(target, rank: Optional[int] = None) -> int:
    """Hard-kill one training process (SIGKILL: no atexit, no farewell —
    its heartbeat file simply stops updating), the process-level analog of
    :func:`kill_worker`. ``target`` is a ``subprocess.Popen``-like handle
    (``rank`` ignored) or a ``parallel.elastic.TrainingSupervisor`` whose
    ``procs[rank]`` is the victim. The corpse is reaped (``wait``) so a
    supervisor's next ``observe()`` sees a clean exit code, not a zombie.
    Returns the pid killed."""
    proc = target
    if hasattr(target, "procs"):
        ranks = sorted(target.procs)
        proc = target.procs[rank if rank is not None else ranks[0]]
    if proc is None:
        raise ValueError(f"rank {rank} has no live process to kill")
    proc.kill()
    proc.wait()
    return proc.pid
