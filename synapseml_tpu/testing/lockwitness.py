"""Runtime lock-order witness: cross-validate the static lock graph.

The ``lock-order`` analyzer *predicts* "acquires B while holding A" edges
from source; this module *observes* them. An opt-in instrumentation
context (:class:`LockWitness`) replaces the ``threading.Lock`` /
``threading.RLock`` factories with wrappers that attribute each created
lock to its creation site — the first stack frame inside
``synapseml_tpu/`` — and record, per thread, every (held-site,
acquired-site) pair taken by a *blocking* acquire. Locks created outside
the package pass through unwrapped, so stdlib internals cost nothing and
never pollute the report. ``threading.Condition()`` with no argument
allocates its RLock through the patched factory, so a project Condition's
internal lock resolves to the project's ``Condition(...)`` call site.

The diff against the static model is the cross-validation the tentpole
asks for, with an explicit contract:

* an **observed cycle** in the runtime edge graph is a real deadlock the
  test suite actually drove (two orders genuinely executed) — always a
  failure;
* an **observed-but-not-predicted** edge between two *statically known*
  lock sites is an analyzer recall bug: the code took an order the
  lock-order graph missed — file it against ``tools/analysis/lockmodel``;
* edges touching sites the static model doesn't know (dynamically created
  locks, fixtures) are reported separately and are informational.

Enable under pytest with ``SYNAPSEML_TPU_LOCK_WITNESS=/path/report.json``
(the session fixture in ``tests/conftest.py`` installs the witness and
writes the report at exit), then::

    python -m synapseml_tpu.testing.lockwitness /path/report.json

loads the report, rebuilds the static model and prints the diff —
non-zero exit only on an observed cycle. ci.sh runs this as a
non-blocking report step; the static analyzers remain the hard gate.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

Site = Tuple[str, int]                  # (repo-relative path, lineno)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)
_THIS_FILE = os.path.abspath(__file__)


def _creation_site() -> Optional[Site]:
    """First frame under ``synapseml_tpu/`` below the factory call, as a
    repo-relative (path, lineno). None → the lock belongs to foreign code."""
    f = sys._getframe(2)                # skip factory + this helper
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and os.path.abspath(fn).startswith(_PKG_DIR):
            rel = os.path.relpath(os.path.abspath(fn), _REPO_DIR)
            return (rel.replace(os.sep, "/"), f.f_lineno)
        f = f.f_back
    return None


class _WitnessLock:
    """Delegating wrapper recording acquisition order per thread.

    Implements the full Lock/RLock surface *plus* the private hooks
    ``Condition`` uses on its underlying lock (``_is_owned``,
    ``_acquire_restore``, ``_release_save``), so a wrapped RLock drops
    into a Condition unchanged. ``Condition.wait`` releases the lock via
    ``_release_save`` — the witness pops the held stack there too, so a
    waiting thread never appears to hold the lock it released.
    """

    __slots__ = ("_inner", "_site", "_witness")

    def __init__(self, inner, site: Site, witness: "LockWitness"):
        self._inner = inner
        self._site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._on_acquire(self._site, blocking=blocking)
        return got

    def release(self):
        self._witness._on_release(self._site)
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # --- Condition integration (RLock protocol) -------------------------
    # Condition probes these on its lock and substitutes defaults when
    # absent; the wrapper exposes them unconditionally, so each delegates
    # when the inner lock has the hook and mimics Condition's plain-Lock
    # fallback when it doesn't.
    def _is_owned(self):
        hook = getattr(self._inner, "_is_owned", None)
        if hook is not None:
            return hook()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _acquire_restore(self, state):
        hook = getattr(self._inner, "_acquire_restore", None)
        if hook is not None:
            hook(state)
        else:
            self._inner.acquire()
        self._witness._on_acquire(self._site, blocking=True)

    def _release_save(self):
        self._witness._on_release(self._site)
        hook = getattr(self._inner, "_release_save", None)
        if hook is not None:
            return hook()
        self._inner.release()

    def __repr__(self):
        return f"<witness {self._inner!r} @ {self._site[0]}:{self._site[1]}>"


class LockWitness:
    """Collects observed (held-site → acquired-site) edges suite-wide."""

    def __init__(self) -> None:
        self.edges: Dict[Tuple[Site, Site], int] = {}
        self.sites: Set[Site] = set()
        self._tls = threading.local()
        self._mu = threading.Lock()     # created BEFORE install: unwrapped
        self._real_lock = None
        self._real_rlock = None

    # --- recording ------------------------------------------------------
    def _stack(self) -> List[Site]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    def _on_acquire(self, site: Site, blocking: bool) -> None:
        st = self._stack()
        with self._mu:
            self.sites.add(site)
            if blocking and site not in st:
                # lockdep edge rule: every held lock orders before the new
                # one; a non-blocking acquire cannot wait → no edge, and a
                # reentrant re-acquire is not an ordering
                for held in st:
                    if held != site:
                        key = (held, site)
                        self.edges[key] = self.edges.get(key, 0) + 1
        st.append(site)

    def _on_release(self, site: Site) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == site:
                del st[i]
                break

    # --- installation ---------------------------------------------------
    def install(self) -> "LockWitness":
        if self._real_lock is not None:
            return self
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        witness = self

        def lock_factory():
            site = _creation_site()
            inner = witness._real_lock()
            return inner if site is None else _WitnessLock(inner, site,
                                                           witness)

        def rlock_factory():
            site = _creation_site()
            inner = witness._real_rlock()
            return inner if site is None else _WitnessLock(inner, site,
                                                           witness)

        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        return self

    def uninstall(self) -> None:
        if self._real_lock is None:
            return
        threading.Lock = self._real_lock
        threading.RLock = self._real_rlock
        self._real_lock = self._real_rlock = None

    def __enter__(self) -> "LockWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # --- reporting ------------------------------------------------------
    def observed_cycles(self) -> List[List[Site]]:
        return _site_cycles(set(self.edges))

    def report(self) -> dict:
        return {
            "sites": sorted(f"{p}:{ln}" for p, ln in self.sites),
            "edges": [{"src": f"{a[0]}:{a[1]}", "dst": f"{b[0]}:{b[1]}",
                       "count": n}
                      for (a, b), n in sorted(self.edges.items())],
            "cycles": [[f"{p}:{ln}" for p, ln in cyc]
                       for cyc in self.observed_cycles()],
        }

    def write(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.report(), fh, indent=2, sort_keys=True)


def _site_cycles(edges: Set[Tuple[Site, Site]]) -> List[List[Site]]:
    """Cycles in the observed site graph (DFS, one representative each)."""
    adj: Dict[Site, List[Site]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    cycles: List[List[Site]] = []
    seen_keys: Set[frozenset] = set()
    done: Set[Site] = set()
    for start in sorted(adj):
        if start in done:
            continue
        stack: List[Tuple[Site, int]] = [(start, 0)]
        path: List[Site] = [start]
        on_path = {start}
        while stack:
            node, idx = stack[-1]
            nbrs = adj.get(node, [])
            if idx >= len(nbrs):
                stack.pop()
                path.pop()
                on_path.discard(node)
                done.add(node)
                continue
            stack[-1] = (node, idx + 1)
            nxt = nbrs[idx]
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                key = frozenset(cyc)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(list(cyc))
            elif nxt not in done:
                stack.append((nxt, 0))
                path.append(nxt)
                on_path.add(nxt)
    return cycles


# --- diff vs the static model ----------------------------------------------

def _parse_site(s: str) -> Site:
    path, _, ln = s.rpartition(":")
    return (path, int(ln))


def diff_report(report: dict, predicted: Set[Tuple[Site, Site]],
                known: Dict[Site, str]) -> dict:
    """Split observed edges into predicted / unpredicted / harness / foreign.

    ``unpredicted`` — both endpoints are statically known product lock
    sites yet the static graph lacks the edge: an analyzer recall gap.
    ``harness`` — an endpoint lives under ``synapseml_tpu/testing/``:
    chaos injectors register runtime hooks the static call graph treats as
    opaque, so their orderings are outside the recall contract.
    ``foreign`` — an endpoint the static model never saw (dynamically
    created locks, stdlib internals of Event/Queue attributed to their
    project creation line): informational only.
    """
    matched, unpredicted, harness, foreign = [], [], [], []
    for e in report.get("edges", []):
        a, b = _parse_site(e["src"]), _parse_site(e["dst"])
        if any(s[0].startswith("synapseml_tpu/testing/") for s in (a, b)):
            tgt = harness
        elif a in known and b in known:
            tgt = matched if (a, b) in predicted else unpredicted
        else:
            tgt = foreign
        tgt.append(e)
    return {"matched": matched, "unpredicted": unpredicted,
            "harness": harness, "foreign": foreign,
            "cycles": report.get("cycles", [])}


def _load_static() -> Tuple[Set[Tuple[Site, Site]], Dict[Site, str]]:
    sys.path.insert(0, _REPO_DIR)
    from tools.analysis.core import DEFAULT_TARGETS, Project
    from tools.analysis.jitmap import JitMap
    from tools.analysis.lockmodel import LockModel

    project = Project.from_targets(DEFAULT_TARGETS)
    lm = LockModel(project, JitMap(project))
    return lm.predicted_site_edges(), lm.known_sites()


def main(argv: List[str]) -> int:
    if len(argv) != 1:
        print("usage: python -m synapseml_tpu.testing.lockwitness "
              "<report.json>", file=sys.stderr)
        return 2
    try:
        with open(argv[0], "r", encoding="utf-8") as fh:
            report = json.load(fh)
    except OSError as e:
        print(f"lockwitness: no report to check ({e})", file=sys.stderr)
        return 0
    predicted, known = _load_static()
    d = diff_report(report, predicted, known)
    print(f"lockwitness: {len(report.get('sites', []))} project lock "
          f"sites observed, {len(report.get('edges', []))} ordered edges "
          f"({len(d['matched'])} predicted, {len(d['unpredicted'])} "
          f"unpredicted, {len(d['harness'])} harness, "
          f"{len(d['foreign'])} foreign)")
    for e in d["unpredicted"]:
        print(f"  UNPREDICTED {e['src']} -> {e['dst']} (x{e['count']}) — "
              "static lock-order graph missed this order (recall gap)")
    for cyc in d["cycles"]:
        print(f"  CYCLE {' -> '.join(cyc)} — observed deadlock-capable "
              "order inversion")
    return 1 if d["cycles"] else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
