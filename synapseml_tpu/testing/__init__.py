"""Test frameworks: stage fuzzing and tolerance-CSV benchmarks.

Reference: core/src/test/scala/.../core/test/fuzzing/Fuzzing.scala (the
Serialization/Experiment/GetterSetter fuzzing traits applied to EVERY pipeline
stage, with a meta-test that fails on uncovered stages —
src/test/.../FuzzingTest.scala) and core/test/benchmarks/Benchmarks.scala
(named metric values compared to checked-in CSVs with per-row tolerance).
SURVEY.md §4 items 2-3.
"""

from .fuzzing import (TestObject, discover_stage_classes,
                      experiment_fuzz, getter_setter_fuzz,
                      serialization_fuzz)
from .benchmarks import Benchmarks
from .chaos import (ChaosHTTP, ChaosPreemption, ChaosSchedule, ChaosSwap,
                    FaultInjected, FlakyHTTPServer, bit_flip,
                    canned_json_responder, chaos_candidate,
                    chaos_chunk_stream, chaos_collectives, chaos_hang,
                    chaos_nan_batches, chaos_reward_stream,
                    chaos_tenant_flood, chaotic_handler, kill_rank,
                    torn_write)

__all__ = [
    "TestObject", "discover_stage_classes", "experiment_fuzz",
    "getter_setter_fuzz", "serialization_fuzz", "Benchmarks",
    "ChaosHTTP", "ChaosPreemption", "ChaosSchedule", "ChaosSwap",
    "FaultInjected", "FlakyHTTPServer", "bit_flip", "canned_json_responder",
    "chaos_candidate", "chaos_chunk_stream", "chaos_collectives", "chaos_hang",
    "chaos_nan_batches", "chaos_reward_stream", "chaos_tenant_flood",
    "chaotic_handler", "kill_rank", "torn_write",
]
