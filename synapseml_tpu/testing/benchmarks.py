"""Tolerance-CSV accuracy regression harness.

Reference: core/.../core/test/benchmarks/Benchmarks.scala:15-140 — tests add
named metric values; ``compare`` checks them against a checked-in CSV with
per-row tolerance and (re)generates the CSV when asked. Guards GBDT/VW
numerical parity exactly the way the reference's
``benchmarks_VerifyLightGBMClassifier*.csv`` files do.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List


class Benchmarks:
    def __init__(self, name: str,
                 resource_dir: str = None):
        self.name = name
        self.resource_dir = resource_dir or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            "tests", "resources", "benchmarks")
        self._rows: List[Dict] = []

    def add(self, metric_name: str, value: float,
            tolerance: float = 0.1) -> None:
        self._rows.append({"name": metric_name, "value": float(value),
                           "tolerance": float(tolerance)})

    addBenchmark = add

    @property
    def csv_path(self) -> str:
        return os.path.join(self.resource_dir, f"benchmarks_{self.name}.csv")

    def write(self) -> str:
        os.makedirs(self.resource_dir, exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=["name", "value", "tolerance"])
            w.writeheader()
            w.writerows(self._rows)
        return self.csv_path

    def compare(self, regenerate: bool = False) -> None:
        """Assert every recorded metric is within tolerance of the checked-in
        value (Benchmarks.scala verifyBenchmarks). ``regenerate=True`` (or env
        UPDATE_BENCHMARKS=1) rewrites the CSV instead. A missing CSV is an
        ERROR (as in the reference) — a typo'd name must not disarm the guard."""
        if regenerate or os.environ.get("UPDATE_BENCHMARKS") == "1":
            self.write()
            return
        if not os.path.exists(self.csv_path):
            raise AssertionError(
                f"no checked-in benchmark CSV at {self.csv_path}; run with "
                "UPDATE_BENCHMARKS=1 (or compare(regenerate=True)) to create it")
        with open(self.csv_path) as f:
            expected = {r["name"]: r for r in csv.DictReader(f)}
        errors = []
        for row in self._rows:
            exp = expected.get(row["name"])
            if exp is None:
                errors.append(f"{row['name']}: no checked-in value "
                              f"(got {row['value']:.6f})")
                continue
            want = float(exp["value"])
            tol = float(exp.get("tolerance", row["tolerance"]))
            if abs(row["value"] - want) > tol:
                errors.append(f"{row['name']}: {row['value']:.6f} vs "
                              f"checked-in {want:.6f} (tol {tol})")
        missing = set(expected) - {r["name"] for r in self._rows}
        for m in sorted(missing):
            errors.append(f"{m}: checked-in metric was not produced this run")
        if errors:
            raise AssertionError(
                f"benchmark regression ({self.csv_path}):\n  "
                + "\n  ".join(errors))
