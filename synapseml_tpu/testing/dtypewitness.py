"""Runtime dtype witness: cross-validate the static dtype-flow model.

The numerics analyzers *predict* what dtype reaches each mixed-precision
boundary; this module *observes* it. Product code carries lightweight
probes at annotated boundaries (the gbdt histogram wire, the seq-attention
accumulators/outputs, checkpoint leaf save/restore, quantized-collective
dequantization, BucketedRunner specs) of the form::

    _witness_observe("gbdt.wire.count", cnt, expect="float32")

where ``_witness_observe`` is a per-module 3-line shim that forwards to
:func:`observe` **only when this module is already imported and active**
(``sys.modules`` lookup — product code never imports the testing package,
so the probes are inert imports-wise and cost one dict lookup when the
witness is off). Inside jit the probe fires at trace time and records the
tracer's static dtype — exactly the quantity the static model predicts.

Per site the witness records the set of observed leaf dtype names; a probe
with ``expect=`` also records a **contract violation** when a leaf arrives
outside the allowed set (e.g. an f32 leaf arriving bf16 on the
exact-totals wire). The diff against the static model classifies each
(site, dtype) observation:

* **matched** — the static model predicted this dtype (or the site's
  dtype is provably input-dependent, which the model reports as
  unconstrained);
* **unpredicted** — the model pinned a different dtype for the site: a
  dtype-flow recall bug, file it against ``tools/analysis/dtypemodel``;
* **foreign** — a site string the static scan never saw (dynamically
  built probes): informational.

Enable under pytest with ``SYNAPSEML_TPU_DTYPE_WITNESS=/path/report.json``
(the session fixture in ``tests/conftest.py`` installs the witness and
writes the report at exit), then::

    python -m synapseml_tpu.testing.dtypewitness /path/report.json

prints the diff — non-zero exit **only on an observed contract
violation**; the static analyzers remain the hard gate. ci.sh runs this
over the gbdt-wire + dl-seq test subset.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Dict, List, Optional, Set

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)

#: lattice element -> runtime dtype name, mirroring dtypemodel's lattice
LATTICE_TO_RUNTIME = {
    "bool": "bool", "int8": "int8", "int16": "int16", "int32": "int32",
    "int64": "int64", "uint8": "uint8", "uint16": "uint16",
    "uint32": "uint32", "uint64": "uint64", "bf16": "bfloat16",
    "f16": "float16", "f32": "float32", "f64": "float64",
}

_ACTIVE: Optional["DtypeWitness"] = None


def active() -> bool:
    return _ACTIVE is not None


def observe(site: str, tree, expect=None):
    """Record the leaf dtypes of ``tree`` under ``site``; returns ``tree``
    unchanged so probes can wrap expressions. No-op when inactive."""
    w = _ACTIVE
    if w is not None:
        w.record(site, tree, expect)
    return tree


def _leaf_dtype_name(leaf) -> Optional[str]:
    dt = getattr(leaf, "dtype", None)
    if dt is None:
        return None
    return getattr(dt, "name", None) or str(dt)


def _tree_leaves(tree) -> List:
    try:
        from jax.tree_util import tree_leaves
        return tree_leaves(tree)
    except Exception:                      # jax absent: treat as one leaf
        return [tree]


def _expand_expect(expect) -> Set[str]:
    if isinstance(expect, str):
        return {expect}
    return set(expect)


class DtypeWitness:
    """Collects observed per-site leaf dtypes and contract violations."""

    def __init__(self) -> None:
        self.sites: Dict[str, Set[str]] = {}
        self.violations: List[dict] = []
        self._mu = threading.Lock()

    # --- recording ------------------------------------------------------
    def record(self, site: str, tree, expect=None) -> None:
        names = [n for n in (_leaf_dtype_name(lf)
                             for lf in _tree_leaves(tree)) if n is not None]
        allowed = _expand_expect(expect) if expect is not None else None
        with self._mu:
            got = self.sites.setdefault(site, set())
            for name in names:
                got.add(name)
                if allowed is not None and name not in allowed:
                    self.violations.append({
                        "site": site, "observed": name,
                        "expected": sorted(allowed)})

    # --- installation ---------------------------------------------------
    def install(self) -> "DtypeWitness":
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "DtypeWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # --- reporting ------------------------------------------------------
    def report(self) -> dict:
        return {
            "sites": {s: sorted(v) for s, v in sorted(self.sites.items())},
            "violations": list(self.violations),
        }

    def write(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.report(), fh, indent=2, sort_keys=True)


# --- diff vs the static model ----------------------------------------------

def diff_report(report: dict,
                predicted: Dict[str, Optional[Set[str]]]) -> dict:
    """Classify observed (site, dtype) pairs against the static model.

    ``predicted`` maps each statically discovered probe site to the set of
    runtime dtype names the dtype model pinned for it, or ``None`` when
    the model found the site but could not constrain the dtype
    (input-dependent — counts as matched, the model made no claim).
    """
    matched, unpredicted, foreign = [], [], []
    for site, names in sorted(report.get("sites", {}).items()):
        for name in names:
            entry = {"site": site, "dtype": name}
            if site not in predicted:
                foreign.append(entry)
            elif predicted[site] is None or name in predicted[site]:
                matched.append(entry)
            else:
                entry["predicted"] = sorted(predicted[site])
                unpredicted.append(entry)
    return {"matched": matched, "unpredicted": unpredicted,
            "foreign": foreign,
            "violations": report.get("violations", [])}


def _load_static() -> Dict[str, Optional[Set[str]]]:
    """Scan the package for ``_witness_observe("<site>", expr, ...)``
    probes and predict each site's dtypes with the static model."""
    import ast

    sys.path.insert(0, _REPO_DIR)
    from tools.analysis.core import DEFAULT_TARGETS, Project
    from tools.analysis.dtypemodel import DtypeModel

    project = Project.from_targets(DEFAULT_TARGETS)
    dtm = DtypeModel(project)
    predicted: Dict[str, Optional[Set[str]]] = {}
    for sf in dtm.files:
        for qual, info in sf.symbols.functions.items():
            facts = dtm.facts_for(info)
            for node in ast.walk(info.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "_witness_observe"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                site = node.args[0].value
                tree_arg = node.args[1] if len(node.args) > 1 else None
                names: Optional[Set[str]] = set()
                parts = (tree_arg.elts
                         if isinstance(tree_arg, (ast.Tuple, ast.List))
                         else [tree_arg] if tree_arg is not None else [])
                for part in parts:
                    lat = facts.info(part).dtype
                    run = LATTICE_TO_RUNTIME.get(lat)
                    if run is None:
                        names = None          # unconstrained
                        break
                    names.add(run)
                if not parts:
                    names = None
                cur = predicted.get(site)
                if site in predicted and (cur is None or names is None):
                    predicted[site] = None
                elif cur is not None and names is not None:
                    predicted[site] = cur | names
                else:
                    predicted[site] = names
    return predicted


def main(argv: List[str]) -> int:
    if len(argv) != 1:
        print("usage: python -m synapseml_tpu.testing.dtypewitness "
              "<report.json>", file=sys.stderr)
        return 2
    try:
        with open(argv[0], "r", encoding="utf-8") as fh:
            report = json.load(fh)
    except OSError as e:
        print(f"dtypewitness: no report to check ({e})", file=sys.stderr)
        return 0
    predicted = _load_static()
    d = diff_report(report, predicted)
    nsites = len(report.get("sites", {}))
    print(f"dtypewitness: {nsites} probe sites observed, "
          f"{len(predicted)} statically known "
          f"({len(d['matched'])} matched, {len(d['unpredicted'])} "
          f"unpredicted, {len(d['foreign'])} foreign)")
    for e in d["unpredicted"]:
        print(f"  UNPREDICTED {e['site']} observed {e['dtype']}, static "
              f"model pinned {e['predicted']} — dtype-flow recall gap")
    for v in d["violations"]:
        print(f"  VIOLATION {v['site']} observed {v['observed']}, contract "
              f"allows {v['expected']}")
    return 1 if d["violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
