"""ICE (Individual Conditional Expectation) / PDP explainer.

Reference: core/.../explainers/{ICEExplainer,ICEFeature}.scala — sweep each
requested feature over a grid (numeric) or its category values (categorical),
score the model at every (row, grid value), and output per-row curves
("individual" kind) or the averaged partial-dependence curve ("average").

TPU-first: the whole (rows × grid) sweep is materialized as one batched table
and scored in a single model.transform — one XLA launch per feature instead of
per (row, value)."""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.params import Param
from ..core.table import Table
from .base import LocalExplainerBase


class ICETransformer(LocalExplainerBase):
    kind = Param("kind", "individual (per-row curves) | average (PDP)", str, "individual")
    numericFeatures = Param(
        "numericFeatures", "List of {name, numSplits?, rangeMin?, rangeMax?} dicts", list, [])
    categoricalFeatures = Param(
        "categoricalFeatures", "List of {name, numTopValues?} dicts or names", list, [])
    dependenceNameCol = Param("dependenceNameCol", "Feature-name column in output",
                              str, "featureNames")
    featureValuesCol = Param("featureValuesCol", "Grid-values column in output",
                             str, "featureValues")

    def _grid_for_numeric(self, spec: dict, col: np.ndarray) -> np.ndarray:
        splits = int(spec.get("numSplits", 10))
        lo = float(spec.get("rangeMin", np.nanmin(col)))
        hi = float(spec.get("rangeMax", np.nanmax(col)))
        grid = np.linspace(lo, hi, splits + 1)
        if np.issubdtype(col.dtype, np.integer):
            # integer feature: evaluate at integer values only and report THE
            # SAME values, so curves and featureValues stay aligned
            grid = np.unique(np.round(grid)).astype(np.float64)
        return grid.astype(np.float64)

    def _grid_for_categorical(self, spec: dict, col: np.ndarray) -> np.ndarray:
        top = int(spec.get("numTopValues", 100))
        vals, counts = np.unique(col, return_counts=True)
        order = np.argsort(-counts)
        return vals[order][:top]

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        feats: List[tuple] = []
        for spec in (self.numericFeatures or []):
            spec = {"name": spec} if isinstance(spec, str) else dict(spec)
            feats.append((spec["name"], self._grid_for_numeric(spec, np.asarray(df[spec["name"]]))))
        for spec in (self.categoricalFeatures or []):
            spec = {"name": spec} if isinstance(spec, str) else dict(spec)
            feats.append((spec["name"], self._grid_for_categorical(spec, np.asarray(df[spec["name"]]))))
        if not feats:
            raise ValueError("ICETransformer needs numericFeatures and/or categoricalFeatures")

        names_out, values_out, curves = [], [], []
        for name, grid in feats:
            g = len(grid)
            # batched sweep: tile every row g times, overwrite the swept column
            rep = {c: np.repeat(df[c], g, axis=0) for c in df.columns}
            rep[name] = np.tile(grid, n).astype(df[name].dtype, copy=False)
            y = self._score(Table(rep)).reshape(n, g, -1)    # (n, g, k)
            names_out.append(name)
            values_out.append(grid)
            curves.append(y)

        if self.kind == "average":
            rows = {self.dependenceNameCol: np.array(names_out, object),
                    self.featureValuesCol: np.array(values_out, object),
                    self.outputCol: np.array([c.mean(0) for c in curves], object)}
            return Table(rows)
        out = df.copy()
        for name, grid, y in zip(names_out, values_out, curves):
            col = np.empty(n, object)
            for i in range(n):
                col[i] = y[i]
            out[f"{self.outputCol}_{name}"] = col
        return out
