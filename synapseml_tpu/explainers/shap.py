"""KernelSHAP explainers (tabular / vector / text / image).

Reference: core/.../explainers/{KernelSHAPBase,KernelSHAPSampler,TabularSHAP,
VectorSHAP,TextSHAP,ImageSHAP}.scala. Coalition sampling with Shapley-kernel
weights; weighted least squares on (coalition → model output); output vector =
[base value, shap_1..shap_M] per target class, plus the surrogate r² in
metricsCol — matching the reference's output layout."""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.params import Param
from ..core.table import Table
from ..image.superpixel import Superpixel, slic_segments
from .base import (LocalExplainerBase, default_num_samples, sample_coalitions,
                   sample_coalitions_batch, shap_kernel_lut)
from .solvers import solve_batched


class _SHAPParams(LocalExplainerBase):
    infWeight = Param("infWeight", "Parity param: the reference pins the empty/"
                      "full coalitions with this pseudo-infinite weight; here "
                      "both constraints are eliminated analytically instead "
                      "(exact, and float32/TPU-safe)", float, 1e8)

    def _fit_shap(self, coalitions: np.ndarray, y: np.ndarray, m: int,
                  base: np.ndarray = None):
        """coalitions (R,S,M) with row 0 = empty and row 1 = full, y (R,S,K) →
        (values (R,) object of (K,M+1), r2 (R,K)).

        ``base``: (R,K) expected model output on the background distribution.
        When absent-feature fills are stochastic background draws (tabular/
        vector), callers MUST pass the background mean — the single empty-
        coalition sample is one noisy draw and would corrupt every φ through
        the Σφ = f(x)−base constraint. For deterministic censoring (text/image
        masking) the empty-coalition evaluation IS the base.

        Uses the standard KernelSHAP constraint elimination: base = f(∅),
        Σφ = f(x)−base enforced exactly by substituting φ_{M-1}, then a
        finite-weight Shapley-kernel regression on the remaining M-1 players —
        numerically exact where the reference's 1e8 pseudo-weights lose the
        small-coalition signal in float32."""
        r, s, _ = coalitions.shape
        k = y.shape[2]
        if base is None:
            base = y[:, 0, :]                  # (R, K) = f(empty), deterministic case
        delta = y[:, 1, :] - base              # (R, K) = f(x) - base
        out = np.empty(r, object)

        if m == 1:
            for i in range(r):
                out[i] = np.concatenate([base[i][:, None], delta[i][:, None]], 1)
            return out, np.ones((r, k), np.float32)

        # per-row kernel weights — each row has its own coalition draw; the
        # kernel depends only on |z| so one size-indexed LUT serves all rows
        lut = shap_kernel_lut(m, inf_weight=0.0)   # empty/full rows get weight 0
        w = lut[coalitions.sum(axis=2).astype(np.int64)]
        z_last = coalitions[:, :, -1:]
        Zr = coalitions[:, :, :-1] - z_last    # (R, S, M-1)
        target = y - base[:, None, :] - z_last * delta[:, None, :]
        fit = solve_batched(Zr, target, w, 0.0)
        head = np.asarray(fit.coefs)           # (R, M-1, K)
        last = delta - head.sum(axis=1)        # (R, K)
        phi = np.concatenate([head, last[:, None, :]], axis=1)   # (R, M, K)

        # r² of the reconstructed surrogate on finite-weight coalitions
        pred = base[:, None, :] + np.einsum("rsm,rmk->rsk", coalitions, phi)
        wsum = np.maximum(w.sum(1), 1e-12)[:, None]
        ybar = (w[:, :, None] * y).sum(1) / wsum
        ss_res = (w[:, :, None] * (y - pred) ** 2).sum(1)
        ss_tot = np.maximum((w[:, :, None] * (y - ybar[:, None, :]) ** 2).sum(1), 1e-12)
        r2 = (1.0 - ss_res / ss_tot).astype(np.float32)

        for i in range(r):
            out[i] = np.concatenate([base[i][:, None], phi[i].T], axis=1)  # (K, M+1)
        return out, r2


class VectorSHAP(_SHAPParams):
    """KernelSHAP over a dense features column (VectorSHAP.scala): absent
    features take background-row values."""
    inputCol = Param("inputCol", "Features column", str, "features")
    backgroundData = Param("backgroundData", "Background Table (absent-feature fill)", object)

    def _transform(self, df: Table) -> Table:
        X = np.asarray(df[self.inputCol], np.float32)
        n, d = X.shape
        bg = self.get("backgroundData")
        bgX = np.asarray(bg[self.inputCol], np.float32) if bg is not None else X
        s = self.get("numSamples") or default_num_samples(d)
        rng = np.random.default_rng(0)

        coalitions = sample_coalitions_batch(rng, d, s, n)
        bg_rows = bgX[rng.integers(0, len(bgX), size=(n, s))]
        samples = np.where(coalitions > 0, X[:, None, :], bg_rows)
        y = self._score(Table({self.inputCol: samples.reshape(n * s, d)})).reshape(n, s, -1)
        # base = E_bg[f]: score (a subsample of) the background directly
        bg_eval = bgX if len(bgX) <= 256 else bgX[rng.choice(len(bgX), 256, replace=False)]
        base = np.tile(self._score(Table({self.inputCol: bg_eval})).mean(0), (n, 1))
        out_col, r2 = self._fit_shap(coalitions, y, d, base=base)
        out = df.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2)


class TabularSHAP(_SHAPParams):
    """KernelSHAP over named columns (TabularSHAP.scala)."""
    inputCols = Param("inputCols", "Columns to explain", list)
    backgroundData = Param("backgroundData", "Background Table", object)

    def _transform(self, df: Table) -> Table:
        cols: List[str] = list(self.inputCols or [])
        d = len(cols)
        bg = self.get("backgroundData") or df
        n = df.num_rows
        s = self.get("numSamples") or default_num_samples(d)
        rng = np.random.default_rng(0)

        coalitions = sample_coalitions_batch(rng, d, s, n)
        bg_idx = rng.integers(0, bg.num_rows, size=(n, s))
        sample_cols = {}
        for j, c in enumerate(cols):
            inst = np.asarray(df[c])
            bgv = np.asarray(bg[c])[bg_idx]                     # (n, s)
            on = coalitions[:, :, j] > 0
            merged = np.where(on, np.broadcast_to(inst[:, None], on.shape), bgv)
            sample_cols[c] = merged.reshape(-1)
        y = self._score(Table(sample_cols)).reshape(n, s, -1)
        bg_eval = bg if bg.num_rows <= 256 else bg.take(
            rng.choice(bg.num_rows, 256, replace=False))
        base = np.tile(self._score(bg_eval).mean(0), (n, 1))
        out_col, r2 = self._fit_shap(coalitions, y, d, base=base)
        out = df.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2)


class TextSHAP(_SHAPParams):
    """KernelSHAP over a text column (TextSHAP.scala): tokens are the players."""
    inputCol = Param("inputCol", "Text column", str, "text")
    tokensCol = Param("tokensCol", "Output tokens column", str, "tokens")

    def _transform(self, df: Table) -> Table:
        rng = np.random.default_rng(0)
        n = df.num_rows
        out_col = np.empty(n, object)
        tok_col = np.empty(n, object)
        r2_col = np.zeros((n, len(self.targetClasses or [0])), np.float32)
        for i in range(n):
            tokens = str(df[self.inputCol][i]).split()
            m = len(tokens)
            tok_col[i] = tokens
            if m == 0:
                out_col[i] = np.zeros((len(self.targetClasses or [0]), 1), np.float32)
                continue
            s = self.get("numSamples") or default_num_samples(m, cap=2048)
            coalitions = sample_coalitions(rng, m, s)
            texts = np.array([" ".join(t for t, b in zip(tokens, row) if b > 0)
                              for row in coalitions], object)
            y = self._score(Table({self.inputCol: texts}))
            vals, r2 = self._fit_shap(coalitions[None], y[None], m)
            out_col[i] = vals[0]
            r2_col[i] = r2[0]
        out = df.with_column(self.tokensCol, tok_col)
        out = out.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2_col)


class ImageSHAP(_SHAPParams):
    """KernelSHAP over an image column (ImageSHAP.scala): superpixels are the
    players; absent superpixels are censored to the fill color."""
    inputCol = Param("inputCol", "Image column", str, "image")
    superpixelCol = Param("superpixelCol", "Output segmentation column", str, "superpixels")
    cellSize = Param("cellSize", "Superpixel cell size", float, 16.0)
    modifier = Param("modifier", "Superpixel compactness", float, 130.0)

    def _transform(self, df: Table) -> Table:
        rng = np.random.default_rng(0)
        n = df.num_rows
        out_col = np.empty(n, object)
        seg_col = np.empty(n, object)
        r2_col = np.zeros((n, len(self.targetClasses or [0])), np.float32)
        for i in range(n):
            img = np.asarray(df[self.inputCol][i])
            segs = slic_segments(img, int(self.cellSize), self.modifier)
            k = int(segs.max()) + 1
            seg_col[i] = segs
            s = self.get("numSamples") or default_num_samples(k, cap=1024)
            coalitions = sample_coalitions(rng, k, s)
            imgs = np.empty(s, object)
            for j in range(s):
                imgs[j] = Superpixel.masked_image(img, segs, coalitions[j])
            y = self._score(Table({self.inputCol: imgs}))
            vals, r2 = self._fit_shap(coalitions[None], y[None], k)
            out_col[i] = vals[0]
            r2_col[i] = r2[0]
        out = df.with_column(self.superpixelCol, seg_col)
        out = out.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2_col)
