"""Responsible-AI explainers (SURVEY §2.7 explainers/, 23 files in reference).

LIME + KernelSHAP for tabular/vector/text/image, ICE/PDP, with all local
surrogate regressions solved as batched XLA linear algebra (solvers.py)."""

from .base import LocalExplainerBase
from .solvers import batched_lasso, batched_lstsq, solve_batched
from .lime import ImageLIME, TabularLIME, TextLIME, VectorLIME
from .shap import ImageSHAP, TabularSHAP, TextSHAP, VectorSHAP
from .ice import ICETransformer


class LocalExplainer:
    """Factory matching the reference's LocalExplainer object
    (explainers/LocalExplainer.scala:12-32)."""

    class LIME:
        tabular = TabularLIME
        vector = VectorLIME
        image = ImageLIME
        text = TextLIME

    class KernelSHAP:
        tabular = TabularSHAP
        vector = VectorSHAP
        image = ImageSHAP
        text = TextSHAP


__all__ = ["LocalExplainerBase", "LocalExplainer", "TabularLIME", "VectorLIME",
           "TextLIME", "ImageLIME", "TabularSHAP", "VectorSHAP", "TextSHAP",
           "ImageSHAP", "ICETransformer", "batched_lasso", "batched_lstsq",
           "solve_batched"]
