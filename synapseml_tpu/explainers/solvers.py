"""Weighted least-squares and lasso solvers for local surrogate models.

Reference: core/.../explainers/{LeastSquaresRegression,LassoRegression,
RegressionBase}.scala — per-row Breeze solves on executors (SURVEY §2.1 N9).
Here every row's local regression is solved in ONE vmapped XLA call:
(R rows) × (S samples, D features[, K targets]) → (R, D, K) coefficients, so a
whole DataFrame's explanations become a single batched linear-algebra program
on the MXU instead of R driver-side solves.

The batch dimension R is request-sized (however many rows the caller asked to
explain), so the solves dispatch through
:class:`core.inference.BucketedRunner` — one compile per ladder *bucket*
instead of one per observed R, the same shape-stability contract every
serving surface follows (docs/serving-perf.md). Runners are cached per
static configuration (``("lstsq", ridge)`` / ``("lasso", iters)``); the
per-row ``lam`` rides as a batch-leading array input, padded with the other
operands.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.inference import BucketedRunner


class FitResult(NamedTuple):
    coefs: jnp.ndarray       # (D, K)
    intercept: jnp.ndarray   # (K,)
    r2: jnp.ndarray          # (K,)


def _weighted_r2(X, y, w, coefs, intercept):
    pred = X @ coefs + intercept
    wsum = jnp.maximum(w.sum(), 1e-12)
    ybar = (w[:, None] * y).sum(0) / wsum
    ss_res = (w[:, None] * (y - pred) ** 2).sum(0)
    ss_tot = jnp.maximum((w[:, None] * (y - ybar) ** 2).sum(0), 1e-12)
    return 1.0 - ss_res / ss_tot


def _lstsq_single(X, y, w, ridge: float):
    """Weighted least squares with intercept: X (S,D), y (S,K), w (S,)."""
    S, D = X.shape
    Xa = jnp.concatenate([X, jnp.ones((S, 1), X.dtype)], axis=1)
    Xw = Xa * w[:, None]
    A = Xw.T @ Xa + ridge * jnp.eye(D + 1, dtype=X.dtype)
    b = Xw.T @ y
    sol = jnp.linalg.solve(A, b)                       # (D+1, K)
    coefs, intercept = sol[:-1], sol[-1]
    return FitResult(coefs, intercept, _weighted_r2(X, y, w, coefs, intercept))


def _lasso_single(X, y, w, lam: float, iters: int = 200):
    """Weighted lasso by FISTA on the normal equations (jit/scan friendly,
    fixed iteration count — the LARS solve in LassoRegression.scala done the
    XLA way). X (S,D), y (S,K), w (S,)."""
    S, D = X.shape
    K = y.shape[1]
    wsum = jnp.maximum(w.sum(), 1e-12)
    # center (weighted) so the intercept drops out of the prox step
    xbar = (w[:, None] * X).sum(0) / wsum
    ybar = (w[:, None] * y).sum(0) / wsum
    Xc = (X - xbar) * jnp.sqrt(w)[:, None]
    yc = (y - ybar) * jnp.sqrt(w)[:, None]
    G = Xc.T @ Xc
    L = jnp.maximum(jnp.trace(G), 1e-8)                # cheap Lipschitz bound
    eta = 1.0 / L
    Xty = Xc.T @ yc

    def body(carry, _):
        beta, z, t = carry
        grad = G @ z - Xty
        b_new = z - eta * grad
        b_new = jnp.sign(b_new) * jnp.maximum(jnp.abs(b_new) - eta * lam * S, 0.0)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = b_new + ((t - 1.0) / t_new) * (b_new - beta)
        return (b_new, z_new, t_new), None

    beta0 = jnp.zeros((D, K), X.dtype)
    (beta, _, _), _ = jax.lax.scan(body, (beta0, beta0, jnp.ones(())), None, length=iters)
    intercept = ybar - xbar @ beta
    return FitResult(beta, intercept, _weighted_r2(X, y, w, beta, intercept))


# --- bucketed dispatch -------------------------------------------------------
# one runner per static solver configuration; the runner owns the jit
# boundary (its fns are NOT pre-jitted) and compiles once per R-bucket

_MAX_ROWS_PER_CHUNK = 128
_runner_lock = threading.Lock()
_runners: Dict[Tuple, BucketedRunner] = {}


def _runner(kind: str, static) -> BucketedRunner:
    key = (kind, static)
    with _runner_lock:
        runner = _runners.get(key)
        if runner is None:
            if kind == "lstsq":
                def fn(X, y, w, _ridge=static):
                    return jax.vmap(
                        lambda a, b, c: _lstsq_single(a, b, c, _ridge)
                    )(X, y, w)
            else:
                def fn(X, y, w, lam, _iters=static):
                    return jax.vmap(
                        lambda a, b, c, l: _lasso_single(a, b, c, l, _iters)
                    )(X, y, w, lam)
            runner = BucketedRunner(fn, max_batch_size=_MAX_ROWS_PER_CHUNK,
                                    name=f"explainer_{kind}")
            _runners[key] = runner
        return runner


def solver_stats() -> Dict[str, dict]:
    """Per-runner compile/hit counters (observability for the recompile
    guard: steady-state explanations must not compile)."""
    with _runner_lock:
        return {f"{k[0]}:{k[1]}": r.stats() for k, r in _runners.items()}


def batched_lstsq(X, y, w, ridge: float = 1e-6) -> FitResult:
    """Bucketed vmapped weighted LS: X (R,S,D), y (R,S,K), w (R,S) →
    FitResult batched over R (numpy leaves)."""
    return _runner("lstsq", float(ridge))(
        np.asarray(X, np.float32), np.asarray(y, np.float32),
        np.asarray(w, np.float32))


def batched_lasso(X, y, w, lam, iters: int = 200) -> FitResult:
    """Bucketed vmapped weighted lasso; lam scalar or (R,)."""
    X = np.asarray(X, np.float32)
    lam_arr = np.ascontiguousarray(
        np.broadcast_to(np.asarray(lam, np.float32), (X.shape[0],)))
    return _runner("lasso", int(iters))(
        X, np.asarray(y, np.float32), np.asarray(w, np.float32), lam_arr)


def solve_batched(X, y, w, regularization: float = 0.0) -> FitResult:
    """Dispatch: lasso when regularization > 0, else (near-)OLS — mirroring
    LIMEBase's regParam semantics. Host-facing: accepts numpy, returns numpy
    (dispatched through the bucket ladder)."""
    if regularization > 0.0:
        return batched_lasso(X, y, w, regularization)
    return batched_lstsq(X, y, w)
