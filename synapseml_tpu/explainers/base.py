"""LocalExplainer base machinery.

Reference: core/.../explainers/LocalExplainer.scala:12-32 (factory),
SharedParams.scala (model/targetCol/targetClasses params), KernelSHAPBase.scala
/ LIMEBase.scala transform scaffolding: per row, generate S perturbed samples,
score them through the wrapped model, fit a weighted local surrogate, output
the coefficients."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table


class LocalExplainerBase(Transformer):
    model = Param("model", "The model/pipeline Transformer to explain", object)
    targetCol = Param("targetCol", "Model output column to explain "
                      "(probability/prediction/...)", str, "probability")
    targetClasses = Param("targetClasses", "Class indices to explain (classification)",
                          list, [0])
    targetClassesCol = Param("targetClassesCol", "Per-row class indices column", str)
    outputCol = Param("outputCol", "Output column of explanation weights", str, "explanation")
    metricsCol = Param("metricsCol", "Surrogate-fit metric column (r2)", str, "r2")
    numSamples = Param("numSamples", "Perturbed samples per row", int)

    def _score(self, samples: Table) -> np.ndarray:
        """Run the wrapped model over perturbed samples → (n, K) targets where
        K = len(targetClasses) for vector targets, else 1."""
        model = self.model
        if model is None:
            raise ValueError("explainer requires the `model` param (a fitted Transformer)")
        scored = model.transform(samples)
        tcol = self.targetCol
        if tcol not in scored:
            raise KeyError(f"targetCol {tcol!r} not in model output "
                           f"(columns: {scored.columns})")
        out = scored[tcol]
        out = np.asarray(out, np.float32) if out.dtype != object else \
            np.stack([np.asarray(o, np.float32) for o in out])
        if out.ndim == 1:
            return out[:, None]
        classes = [int(c) for c in (self.targetClasses or [0])]
        return out[:, classes]

    def _save_extra(self, path: str) -> None:
        import os
        m = self.get("model")
        if m is not None:
            m.save(os.path.join(path, "explained_model"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "explained_model")
        if os.path.isdir(p):
            self.set("model", PipelineStage.load(p))


def lime_kernel_weights(distances: np.ndarray, kernel_width: float) -> np.ndarray:
    """exp(-d²/w²) locality kernel (LIMEBase)."""
    return np.exp(-(distances ** 2) / (kernel_width ** 2)).astype(np.float32)


def shap_kernel_weights(num_features: int, coalition_sizes: np.ndarray,
                        inf_weight: float = 1e8) -> np.ndarray:
    """Shapley kernel π(z) = (M-1) / (C(M,|z|)·|z|·(M-|z|)); empty/full
    coalitions get infWeight (KernelSHAPBase infWeight param)."""
    from math import comb
    m = num_features
    w = np.empty(len(coalition_sizes), np.float64)
    for i, s in enumerate(coalition_sizes):
        s = int(s)
        if s == 0 or s == m:
            w[i] = inf_weight
        else:
            w[i] = (m - 1) / (comb(m, s) * s * (m - s))
    return w.astype(np.float32)


def sample_coalitions(rng: np.random.Generator, num_features: int,
                      num_samples: int) -> np.ndarray:
    """Coalition matrix (num_samples, M) ∈ {0,1}: first the empty and full
    coalitions, then sizes drawn ~ Shapley-kernel mass (KernelSHAPSampler)."""
    m = num_features
    if num_samples < 2:
        raise ValueError(f"numSamples must be >= 2 (empty + full coalition), got {num_samples}")
    out = np.zeros((num_samples, m), np.float32)
    out[1] = 1.0
    if num_samples == 2:
        return out
    sizes = np.arange(1, m)
    if len(sizes):
        p = (m - 1) / (sizes * (m - sizes))
        p = p / p.sum()
        draw = rng.choice(sizes, size=num_samples - 2, p=p)
        for i, s in enumerate(draw):
            on = rng.choice(m, size=s, replace=False)
            out[i + 2, on] = 1.0
    return out


def default_num_samples(num_features: int, cap: int = 5000) -> int:
    """2M+2048 heuristic (KernelSHAPBase default sample count)."""
    return min(2 * num_features + 2048, cap)
