"""LocalExplainer base machinery.

Reference: core/.../explainers/LocalExplainer.scala:12-32 (factory),
SharedParams.scala (model/targetCol/targetClasses params), KernelSHAPBase.scala
/ LIMEBase.scala transform scaffolding: per row, generate S perturbed samples,
score them through the wrapped model, fit a weighted local surrogate, output
the coefficients."""

from __future__ import annotations


import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table


class LocalExplainerBase(Transformer):
    model = Param("model", "The model/pipeline Transformer to explain", object)
    targetCol = Param("targetCol", "Model output column to explain "
                      "(probability/prediction/...)", str, "probability")
    targetClasses = Param("targetClasses", "Class indices to explain (classification)",
                          list, [0])
    targetClassesCol = Param("targetClassesCol", "Per-row class indices column", str)
    outputCol = Param("outputCol", "Output column of explanation weights", str, "explanation")
    metricsCol = Param("metricsCol", "Surrogate-fit metric column (r2)", str, "r2")
    numSamples = Param("numSamples", "Perturbed samples per row", int)

    def _score(self, samples: Table) -> np.ndarray:
        """Run the wrapped model over perturbed samples → (n, K) targets where
        K = len(targetClasses) for vector targets, else 1."""
        model = self.model
        if model is None:
            raise ValueError("explainer requires the `model` param (a fitted Transformer)")
        scored = model.transform(samples)
        tcol = self.targetCol
        if tcol not in scored:
            raise KeyError(f"targetCol {tcol!r} not in model output "
                           f"(columns: {scored.columns})")
        out = scored[tcol]
        out = np.asarray(out, np.float32) if out.dtype != object else \
            np.stack([np.asarray(o, np.float32) for o in out])
        if out.ndim == 1:
            return out[:, None]
        classes = [int(c) for c in (self.targetClasses or [0])]
        return out[:, classes]

    def _save_extra(self, path: str) -> None:
        import os
        m = self.get("model")
        if m is not None:
            m.save(os.path.join(path, "explained_model"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "explained_model")
        if os.path.isdir(p):
            self.set("model", PipelineStage.load(p))


def lime_kernel_weights(distances: np.ndarray, kernel_width: float) -> np.ndarray:
    """exp(-d²/w²) locality kernel (LIMEBase)."""
    return np.exp(-(distances ** 2) / (kernel_width ** 2)).astype(np.float32)


def shap_kernel_lut(num_features: int, inf_weight: float = 1e8) -> np.ndarray:
    """Size-indexed Shapley kernel weights: lut[s] = (M-1)/(C(M,s)·s·(M-s));
    lut[0] = lut[M] = inf_weight (the weights depend only on coalition size)."""
    from math import comb
    m = num_features
    lut = np.full(m + 1, inf_weight, np.float64)
    for s in range(1, m):
        lut[s] = (m - 1) / (comb(m, s) * s * (m - s))
    return lut.astype(np.float32)


def shap_kernel_weights(num_features: int, coalition_sizes: np.ndarray,
                        inf_weight: float = 1e8) -> np.ndarray:
    """Shapley kernel π(z) for a vector of coalition sizes (LUT-indexed)."""
    lut = shap_kernel_lut(num_features, inf_weight)
    return lut[np.asarray(coalition_sizes, np.int64)]


def sample_coalitions_batch(rng: np.random.Generator, num_features: int,
                            num_samples: int, num_rows: int = 1) -> np.ndarray:
    """Coalition tensor (R, S, M) ∈ {0,1}: per row, sample 0 = empty coalition,
    sample 1 = full, the rest uniform-within-size with sizes drawn ~
    Shapley-kernel mass (KernelSHAPSampler). Fully vectorized: size-s masks via
    rank-thresholded random keys."""
    m, s, r = num_features, num_samples, num_rows
    if s < 2:
        raise ValueError(f"numSamples must be >= 2 (empty + full coalition), got {s}")
    out = np.zeros((r, s, m), np.float32)
    out[:, 1] = 1.0
    if s > 2 and m > 1:
        sizes = np.arange(1, m)
        p = (m - 1) / (sizes * (m - sizes))
        p = p / p.sum()
        draw = rng.choice(sizes, size=(r, s - 2), p=p)            # (R, S-2)
        keys = rng.random((r, s - 2, m))
        ranks = np.argsort(np.argsort(keys, axis=-1), axis=-1)    # uniform ranks
        out[:, 2:] = (ranks < draw[:, :, None]).astype(np.float32)
    return out


def sample_coalitions(rng: np.random.Generator, num_features: int,
                      num_samples: int) -> np.ndarray:
    """(S, M) single-row convenience wrapper over sample_coalitions_batch."""
    return sample_coalitions_batch(rng, num_features, num_samples, 1)[0]


def coefs_to_column(coefs: np.ndarray) -> np.ndarray:
    """(R, D, K) solver output → object column of per-row (K, D) matrices."""
    r = coefs.shape[0]
    out = np.empty(r, object)
    for i in range(r):
        out[i] = coefs[i].T
    return out


def default_num_samples(num_features: int, cap: int = 5000) -> int:
    """2M+2048 heuristic (KernelSHAPBase default sample count)."""
    return min(2 * num_features + 2048, cap)
