"""LIME explainers (tabular / vector / text / image).

Reference: core/.../explainers/{LIMEBase,LIMESampler,TabularLIME,VectorLIME,
TextLIME,ImageLIME}.scala. Flow per instance: draw numSamples perturbations,
score through the wrapped model, weight by a locality kernel, fit a (lasso)
linear surrogate; output its coefficients.

TPU-first: for tabular/vector ALL rows' samples go through the model in ONE
batched transform and ALL local regressions solve in one vmapped XLA call
(solvers.batched_lasso) — the reference loops rows and solves with Breeze on
the driver."""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.params import Param
from ..core.table import Table
from ..image.superpixel import Superpixel, slic_segments
from .base import LocalExplainerBase, coefs_to_column, lime_kernel_weights
from .solvers import solve_batched


class _LIMEParams(LocalExplainerBase):
    kernelWidth = Param("kernelWidth", "Locality kernel width (fraction of sqrt(D))",
                        float, 0.75)
    regularization = Param("regularization", "Lasso regularization strength", float, 0.0)


class VectorLIME(_LIMEParams):
    """LIME over a dense 2-D features column (VectorLIME.scala)."""
    inputCol = Param("inputCol", "Features column to explain", str, "features")
    backgroundData = Param("backgroundData", "Background Table for sampling stats", object)

    def _transform(self, df: Table) -> Table:
        X = np.asarray(df[self.inputCol], np.float32)
        n, d = X.shape
        bg = self.get("backgroundData")
        bgX = np.asarray(bg[self.inputCol], np.float32) if bg is not None else X
        mu, sd = bgX.mean(0), bgX.std(0) + 1e-12
        s = self.get("numSamples") or 1000
        rng = np.random.default_rng(0)

        # (n, s, d) perturbations around each instance
        noise = rng.normal(size=(n, s, d)).astype(np.float32)
        samples = X[:, None, :] + noise * sd[None, None, :]
        states = (samples - mu) / sd                         # standardized regressors
        dist = np.sqrt((noise ** 2).sum(-1))                 # scaled distance
        kw = self.kernelWidth * np.sqrt(d)
        weights = lime_kernel_weights(dist, kw)

        flat = Table({self.inputCol: samples.reshape(n * s, d)})
        y = self._score(flat).reshape(n, s, -1)
        fit = solve_batched(states, y, weights, self.regularization)
        out = df.with_column(self.outputCol, coefs_to_column(np.asarray(fit.coefs)))
        return out.with_column(self.metricsCol, np.asarray(fit.r2))


class TabularLIME(_LIMEParams):
    """LIME over named numeric columns (TabularLIME.scala): samples are drawn
    from the background distribution per column; categorical columns perturb by
    resampling background values with a same-as-instance binary regressor."""
    inputCols = Param("inputCols", "Columns to explain", list)
    categoricalFeatures = Param("categoricalFeatures", "Which inputCols are categorical",
                                list, [])
    backgroundData = Param("backgroundData", "Background Table", object)

    def _transform(self, df: Table) -> Table:
        cols: List[str] = list(self.inputCols or [])
        cats = set(self.categoricalFeatures or [])
        bg = self.get("backgroundData") or df
        n = df.num_rows
        s = self.get("numSamples") or 1000
        d = len(cols)
        rng = np.random.default_rng(0)
        kw = self.kernelWidth * np.sqrt(d)

        states = np.empty((n, s, d), np.float32)
        sample_cols = {}
        dist2 = np.zeros((n, s), np.float32)
        for j, c in enumerate(cols):
            bgv = np.asarray(bg[c])
            inst = np.asarray(df[c])
            if c in cats or bgv.dtype == object:
                draw = rng.choice(bgv, size=(n, s))
                same = (draw == inst[:, None]).astype(np.float32)
                states[:, :, j] = same
                dist2 += (1.0 - same)
                sample_cols[c] = draw.reshape(-1)
            else:
                mu, sd = float(bgv.mean()), float(bgv.std()) + 1e-12
                noise = rng.normal(size=(n, s)).astype(np.float32)
                draw = inst[:, None].astype(np.float32) + noise * sd
                if np.issubdtype(inst.dtype, np.integer):
                    # score and regress on the SAME values: round first so the
                    # surrogate never sees variation the model didn't
                    draw = np.round(draw)
                states[:, :, j] = (draw - mu) / sd
                dist2 += ((draw - inst[:, None]) / sd) ** 2
                sample_cols[c] = draw.reshape(-1).astype(inst.dtype, copy=False)
        weights = lime_kernel_weights(np.sqrt(dist2), kw)

        flat = Table(sample_cols)
        y = self._score(flat).reshape(n, s, -1)
        fit = solve_batched(states, y, weights, self.regularization)
        out = df.with_column(self.outputCol, coefs_to_column(np.asarray(fit.coefs)))
        return out.with_column(self.metricsCol, np.asarray(fit.r2))


class TextLIME(_LIMEParams):
    """LIME over a text column (TextLIME.scala): binary token masking; the
    surrogate weighs each token's contribution."""
    inputCol = Param("inputCol", "Text column", str, "text")
    tokensCol = Param("tokensCol", "Output column of tokens", str, "tokens")
    samplingFraction = Param("samplingFraction", "Probability a token is kept", float, 0.7)

    def _transform(self, df: Table) -> Table:
        rng = np.random.default_rng(0)
        s = self.get("numSamples") or 1000
        n = df.num_rows
        out_col = np.empty(n, object)
        tok_col = np.empty(n, object)
        r2_col = np.zeros((n,), np.float32)
        for i in range(n):
            tokens = str(df[self.inputCol][i]).split()
            m = len(tokens)
            tok_col[i] = tokens
            if m == 0:
                out_col[i] = np.zeros((len(self.targetClasses or [0]), 0), np.float32)
                continue
            mask = (rng.random((s, m)) < self.samplingFraction).astype(np.float32)
            mask[0] = 1.0
            texts = np.array([" ".join(t for t, b in zip(tokens, row) if b > 0)
                              for row in mask], object)
            y = self._score(Table({self.inputCol: texts}))
            dist = 1.0 - mask.mean(1)
            weights = lime_kernel_weights(dist, self.kernelWidth)
            fit = solve_batched(mask[None], y[None], weights[None], self.regularization)
            out_col[i] = np.asarray(fit.coefs)[0].T
            r2_col[i] = float(np.asarray(fit.r2)[0].mean())
        out = df.with_column(self.tokensCol, tok_col)
        out = out.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2_col)


class ImageLIME(_LIMEParams):
    """LIME over an image column (ImageLIME.scala): superpixel masking; outputs
    per-superpixel weights + the segmentation map."""
    inputCol = Param("inputCol", "Image column (H,W,C arrays)", str, "image")
    superpixelCol = Param("superpixelCol", "Output segmentation column", str, "superpixels")
    cellSize = Param("cellSize", "Superpixel cell size", float, 16.0)
    modifier = Param("modifier", "Superpixel compactness", float, 130.0)
    samplingFraction = Param("samplingFraction", "Probability a superpixel is kept",
                             float, 0.7)

    def _transform(self, df: Table) -> Table:
        rng = np.random.default_rng(0)
        s = self.get("numSamples") or 256
        n = df.num_rows
        out_col = np.empty(n, object)
        seg_col = np.empty(n, object)
        r2_col = np.zeros((n,), np.float32)
        for i in range(n):
            img = np.asarray(df[self.inputCol][i])
            segs = slic_segments(img, int(self.cellSize), self.modifier)
            k = int(segs.max()) + 1
            seg_col[i] = segs
            mask = (rng.random((s, k)) < self.samplingFraction).astype(np.float32)
            mask[0] = 1.0
            imgs = np.empty(s, object)
            for j in range(s):
                imgs[j] = Superpixel.masked_image(img, segs, mask[j])
            y = self._score(Table({self.inputCol: imgs}))
            dist = 1.0 - mask.mean(1)
            weights = lime_kernel_weights(dist, self.kernelWidth)
            fit = solve_batched(mask[None], y[None], weights[None], self.regularization)
            out_col[i] = np.asarray(fit.coefs)[0].T
            r2_col[i] = float(np.asarray(fit.r2)[0].mean())
        out = df.with_column(self.superpixelCol, seg_col)
        out = out.with_column(self.outputCol, out_col)
        return out.with_column(self.metricsCol, r2_col)
