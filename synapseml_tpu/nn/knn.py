"""KNN / ConditionalKNN estimators.

Reference: nn/KNN.scala:49-127 and nn/ConditionalKNN.scala. ``fit`` indexes the
``featuresCol`` vectors with payloads from ``valuesCol``; ``transform`` answers
max-inner-product queries per row, emitting an output column of
``[{value, distance}, ...]`` (the reference's array-of-struct schema).
ConditionalKNN also reads a per-row ``conditionerCol`` collection and only
returns neighbors whose ``labelCol`` label is in it.

Unlike the reference — which broadcasts the tree and runs a serial UDF per row
— ``transform`` batches all query rows into one blocked MXU matmul + top-k.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ..core.params import Param, HasFeaturesCol, HasLabelCol, HasOutputCol
from ..core.pipeline import Estimator, Model
from ..core.table import Table
from .balltree import BallTree, ConditionalBallTree


class _KNNParams(HasFeaturesCol, HasOutputCol):
    valuesCol = Param("valuesCol",
                      "column holding values for each feature (key) that will "
                      "be returned when queried", str, "values")
    leafSize = Param("leafSize", "max size of the leaves of the ball index", int, 50)
    k = Param("k", "number of matches to return", int, 5)


def _features_matrix(df: Table, col: str) -> np.ndarray:
    arr = df[col]
    if arr.dtype == object:
        arr = np.stack([np.asarray(v, dtype=np.float32) for v in arr])
    return np.asarray(arr, dtype=np.float32)


class KNN(Estimator, _KNNParams):
    """Fit a max-inner-product index over the dataset (reference KNN.scala:49-77)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("outputCol"):
            self.setOutputCol(self.uid + "_output")

    def _fit(self, df: Table) -> "KNNModel":
        keys = _features_matrix(df, self.getFeaturesCol())
        values = list(df[self.getValuesCol()]) if self.getValuesCol() in df \
            else list(range(keys.shape[0]))
        tree = BallTree(keys, values, leaf_size=self.getLeafSize())
        return KNNModel(ballTree=tree, **{p: self.get(p) for p in self._paramMap})


class KNNModel(Model, _KNNParams):
    ballTree = Param("ballTree", "the ball index used for performing queries",
                     is_complex=True)

    def setBallTree(self, v: BallTree) -> "KNNModel":
        return self.set("ballTree", v)

    def getBallTree(self) -> BallTree:
        return self.get("ballTree")

    def _transform(self, df: Table) -> Table:
        tree: BallTree = self.getBallTree()
        q = _features_matrix(df, self.getFeaturesCol())
        idx, scores = tree.query_batch(q, self.getK())
        out = np.empty(len(idx), dtype=object)
        for r in range(len(idx)):
            out[r] = [{"value": tree.values[i], "distance": float(s)}
                      for i, s in zip(idx[r], scores[r])]
        return df.with_column(self.getOutputCol(), out)


class _ConditionalKNNParams(_KNNParams, HasLabelCol):
    conditionerCol = Param(
        "conditionerCol",
        "column holding identifiers for features that will be returned when "
        "queried", str, "conditioner")


class ConditionalKNN(Estimator, _ConditionalKNNParams):
    """KNN whose index carries labels; queries filter by per-row label sets
    (reference ConditionalKNN.scala:32-60)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("outputCol"):
            self.setOutputCol(self.uid + "_output")
        if not self.isSet("labelCol"):
            self.setLabelCol("labels")

    def _fit(self, df: Table) -> "ConditionalKNNModel":
        keys = _features_matrix(df, self.getFeaturesCol())
        values = list(df[self.getValuesCol()]) if self.getValuesCol() in df \
            else list(range(keys.shape[0]))
        labels = list(df[self.getLabelCol()])
        tree = ConditionalBallTree(keys, labels, values,
                                   leaf_size=self.getLeafSize())
        return ConditionalKNNModel(
            ballTree=tree, **{p: self.get(p) for p in self._paramMap})


class ConditionalKNNModel(Model, _ConditionalKNNParams):
    ballTree = Param("ballTree", "the conditional ball index used for queries",
                     is_complex=True)

    def setBallTree(self, v: ConditionalBallTree) -> "ConditionalKNNModel":
        return self.set("ballTree", v)

    def getBallTree(self) -> ConditionalBallTree:
        return self.get("ballTree")

    def _transform(self, df: Table) -> Table:
        tree: ConditionalBallTree = self.getBallTree()
        q = _features_matrix(df, self.getFeaturesCol())
        conds: List[Any] = [c if isinstance(c, (list, tuple, set, np.ndarray))
                            else [c] for c in df[self.getConditionerCol()]]
        idx, scores = tree.query_batch_conditional(q, conds, self.getK())
        out = np.empty(len(idx), dtype=object)
        for r in range(len(idx)):
            keep = np.isfinite(scores[r])
            out[r] = [{"value": tree.values[i], "distance": float(s)}
                      for i, s in zip(idx[r][keep], scores[r][keep])]
        return df.with_column(self.getOutputCol(), out)
