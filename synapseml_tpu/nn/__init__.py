"""Nearest neighbors — TPU-native maximum-inner-product search.

Reference: core/src/main/scala/com/microsoft/azure/synapse/ml/nn/
(BallTree.scala, KNN.scala:49-127, ConditionalKNN.scala; SURVEY.md §2.7).
The reference answers max-inner-product queries with a serial ball-tree
pointer chase per row (driver-built, broadcast, UDF per query). On TPU the
idiomatic design is batched: all queries × all keys as blocked matmuls on the
MXU with ``lax.top_k``, with an optional two-level ball index that prunes key
blocks by an inner-product upper bound for large corpora.
"""

from .balltree import BallTree, ConditionalBallTree
from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = [
    "BallTree",
    "ConditionalBallTree",
    "KNN",
    "KNNModel",
    "ConditionalKNN",
    "ConditionalKNNModel",
]
