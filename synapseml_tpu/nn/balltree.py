"""Maximum-inner-product search indexes.

Reference behavior: nn/BallTree.scala — ``findMaximumInnerProducts(query, k)``
returns the k keys with largest <query, key>, as (index, distance=inner product)
pairs; ConditionalBallTree additionally restricts candidates to keys whose label
is in a per-query ``conditioner`` set (nn/ConditionalKNN.scala:67-68).

TPU-native design: the hot path is a dense blocked matmul ``Q @ K.T`` on the MXU
followed by ``lax.top_k`` — brute force beats tree traversal on this hardware for
any corpus that fits in HBM, and it is exact. For large corpora a two-level
*ball index* prunes: keys are grouped into balls (split by the
farthest-pair heuristic the reference's tree uses, but only to a fixed block
depth so shapes stay static); each ball stores center and radius; a query
computes the Cauchy-Schwarz upper bound  <q, c> + |q| * r  per ball, keeps the
top blocks, and runs the exact matmul on the gathered subset. Conditioning is a
mask added to the score matrix before top-k (no reverse-index pointer walk).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


_TOPK_CACHE = {}


def _topk_scores(qm, km, mk, k: int):
    """jitted ``top_k(Q @ K.T)`` with the compile cache keyed per (k, masked) —
    module-level so repeated same-shape query batches reuse the executable."""
    import jax
    import jax.numpy as jnp

    key = (k, mk is not None)
    fn = _TOPK_CACHE.get(key)
    if fn is None:
        if mk is None:
            def fn(q, kk):
                return jax.lax.top_k(q @ kk.T, k)
        else:
            def fn(q, kk, m):
                return jax.lax.top_k(jnp.where(m, q @ kk.T, -jnp.inf), k)
        fn = _TOPK_CACHE.setdefault(key, jax.jit(fn))
    return fn(qm, km) if mk is None else fn(qm, km, mk)


class BestMatch(tuple):
    """(index, distance) with attribute access, mirroring nn/BallTree.scala BestMatch."""

    __slots__ = ()

    def __new__(cls, index: int, distance: float):
        return tuple.__new__(cls, (int(index), float(distance)))

    @property
    def index(self) -> int:
        return self[0]

    @property
    def distance(self) -> float:
        return self[1]


def _split_blocks(keys: np.ndarray, leaf_size: int) -> List[np.ndarray]:
    """Recursively split key indices by the farthest-pair heuristic until every
    block has <= max(leaf_size, sqrt(n)) points. Returns index blocks."""
    n = keys.shape[0]
    target = max(leaf_size, int(np.sqrt(n)))
    blocks: List[np.ndarray] = []
    stack = [np.arange(n)]
    while stack:
        idx = stack.pop()
        if idx.size <= target:
            blocks.append(idx)
            continue
        pts = keys[idx]
        mean = pts.mean(axis=0)
        # pivot1 = farthest from mean; pivot2 = farthest from pivot1
        d0 = ((pts - mean) ** 2).sum(axis=1)
        p1 = pts[int(np.argmax(d0))]
        d1 = ((pts - p1) ** 2).sum(axis=1)
        p2 = pts[int(np.argmax(d1))]
        d2 = ((pts - p2) ** 2).sum(axis=1)
        left = d1 <= d2
        if left.all() or (~left).all():  # degenerate (duplicate points)
            half = idx.size // 2
            stack.append(idx[:half])
            stack.append(idx[half:])
        else:
            stack.append(idx[left])
            stack.append(idx[~left])
    return blocks


class BallTree:
    """Exact max-inner-product index over a fixed key matrix.

    API parity with nn/BallTree.scala: ``keys`` (vectors), ``values`` (payload
    returned per match), ``leaf_size``, ``find_maximum_inner_products``.
    Batched queries go through :meth:`query_batch`, the TPU path.
    """

    def __init__(self, keys, values: Optional[Sequence[Any]] = None,
                 leaf_size: int = 50):
        self.keys = np.ascontiguousarray(np.asarray(keys, dtype=np.float32))
        if self.keys.ndim != 2:
            raise ValueError("keys must be [n, dim]")
        self.values = (list(values) if values is not None
                       else list(range(self.keys.shape[0])))
        if len(self.values) != self.keys.shape[0]:
            raise ValueError("values length must match number of keys")
        self.leaf_size = int(leaf_size)
        self._build_index()

    # --- index build ----------------------------------------------------
    def _build_index(self) -> None:
        blocks = _split_blocks(self.keys, self.leaf_size)
        self._block_of = np.empty(self.keys.shape[0], dtype=np.int32)
        centers, radii = [], []
        for b, idx in enumerate(blocks):
            self._block_of[idx] = b
            pts = self.keys[idx]
            c = pts.mean(axis=0)
            centers.append(c)
            radii.append(np.sqrt(((pts - c) ** 2).sum(axis=1).max()))
        self._centers = np.stack(centers).astype(np.float32)
        self._radii = np.asarray(radii, dtype=np.float32)
        self._blocks = blocks

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    # --- queries --------------------------------------------------------
    def query_batch(self, queries, k: int = 1,
                    mask: Optional[np.ndarray] = None,
                    prune: Optional[bool] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k inner products for a [q, dim] query batch.

        Returns (indices [q, k], scores [q, k]). ``mask`` is an optional
        [q, n] boolean of admissible keys (the conditioner). ``prune=None``
        auto-selects ball-pruning for corpora above ~64k keys.
        """
        import jax.numpy as jnp

        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        n = self.keys.shape[0]
        k = min(int(k), n)
        if prune is None:
            prune = mask is None and n >= 65536 and self.num_blocks > 8
        if prune and mask is None:  # mask requires the full score matrix
            return self._query_pruned(q, k)

        mk = None if mask is None else jnp.asarray(mask)
        scores, idx = _topk_scores(jnp.asarray(q), jnp.asarray(self.keys), mk, k)
        return np.asarray(idx), np.asarray(scores)

    def _query_pruned(self, q: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Exact two-pass search. Pass 1: top-k over the blocks with the best
        Cauchy-Schwarz upper bound  <q,c> + |q|·r  (a candidate budget's worth).
        Pass 2: the kth score from pass 1 is a per-query lower bound; any block
        whose upper bound beats it for some query might still hold a true
        neighbor, so the union is re-searched. Since the bound is sound, the
        result equals brute force."""
        import jax.numpy as jnp

        qn = np.linalg.norm(q, axis=1, keepdims=True)
        ub = q @ self._centers.T + qn * self._radii[None, :]  # [q, B]
        want = max(4096, 4 * k)
        order = np.argsort(-ub.max(axis=0))
        sizes = np.asarray([b.size for b in self._blocks])
        csum = np.cumsum(sizes[order])
        nb = int(np.searchsorted(csum, want) + 1)
        first = order[:nb]

        def _topk_subset(block_ids):
            cand = np.concatenate([self._blocks[i] for i in block_ids])
            scores, local = _topk_scores(
                jnp.asarray(q), jnp.asarray(self.keys[cand]), None,
                min(k, cand.size))
            return cand, np.asarray(local), np.asarray(scores)

        cand, local, scores = _topk_subset(first)
        thresh = scores[:, -1]  # per-query kth best so far
        rest = order[nb:]
        needed = rest[(ub[:, rest] >= thresh[:, None]).any(axis=0)]
        if needed.size:
            cand, local, scores = _topk_subset(np.concatenate([first, needed]))
        return cand[local], scores

    def find_maximum_inner_products(self, query, k: int = 1) -> List[BestMatch]:
        """Single-query API, parity with BallTree.scala:146-152."""
        idx, scores = self.query_batch(np.asarray(query)[None, :], k)
        return [BestMatch(i, s) for i, s in zip(idx[0], scores[0])]

    # camelCase alias matching the reference method name
    findMaximumInnerProducts = find_maximum_inner_products

    # --- persistence (BallTree is a ComplexParam in the reference) ------
    def save(self, filename: str) -> None:
        with open(filename, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(filename: str) -> "BallTree":
        with open(filename, "rb") as f:
            return pickle.load(f)

    def __repr__(self):
        return (f"{type(self).__name__}(keys={self.keys.shape}, "
                f"blocks={self.num_blocks}, leaf_size={self.leaf_size})")


class ConditionalBallTree(BallTree):
    """BallTree whose keys carry labels; queries restrict candidates to a
    conditioner label set (reference: nn/BallTree.scala ConditionalBallTree +
    ReverseIndex). Here the condition is a vectorized mask over the score
    matrix rather than a node-subset tree walk."""

    def __init__(self, keys, labels: Sequence[Any],
                 values: Optional[Sequence[Any]] = None, leaf_size: int = 50):
        super().__init__(keys, values, leaf_size)
        if len(labels) != self.keys.shape[0]:
            raise ValueError("labels length must match number of keys")
        self.labels = list(labels)
        self._label_arr = np.asarray(self.labels)

    def conditioner_mask(self, conditioners: Sequence[Sequence[Any]]) -> np.ndarray:
        """[q, n] admissibility mask from per-query label sets."""
        masks = np.zeros((len(conditioners), self.keys.shape[0]), dtype=bool)
        for i, cond in enumerate(conditioners):
            masks[i] = np.isin(self._label_arr, np.asarray(list(cond)))
        return masks

    def query_batch_conditional(self, queries, conditioners, k: int = 1):
        return self.query_batch(queries, k, mask=self.conditioner_mask(conditioners))

    def find_maximum_inner_products(self, query, conditioner=None,
                                    k: int = 1) -> List[BestMatch]:
        if conditioner is None:
            return super().find_maximum_inner_products(query, k)
        idx, scores = self.query_batch_conditional(
            np.asarray(query)[None, :], [conditioner], k)
        keep = np.isfinite(scores[0])
        return [BestMatch(i, s) for i, s in zip(idx[0][keep], scores[0][keep])]

    findMaximumInnerProducts = find_maximum_inner_products
