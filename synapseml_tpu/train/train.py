"""TrainClassifier / TrainRegressor.

Reference: core/.../train/{TrainClassifier,TrainRegressor}.scala — wrap any
estimator: auto-featurize raw columns (Featurize), index labels, fit, and
return a model that both featurizes and scores at transform time."""

from __future__ import annotations


import numpy as np

from ..core.params import Param, HasFeaturesCol, HasLabelCol
from ..core.pipeline import Estimator, Model
from ..core.table import Table
from ..featurize import Featurize, ValueIndexer


class _TrainBase(Estimator, HasLabelCol, HasFeaturesCol):
    model = Param("model", "Underlying estimator to train", object)
    numFeatures = Param("numFeatures", "Hash dimension for string columns", int, 256)

    def _featurizer(self, df: Table):
        cols = [c for c in df.columns if c not in (self.labelCol, self.featuresCol)]
        feat = Featurize(inputCols=cols, outputCol=self.featuresCol,
                         numFeatures=self.numFeatures)
        return feat.fit(df) if self.featuresCol not in df else None


class TrainClassifier(_TrainBase):
    """Auto-featurize + index labels + fit a classifier (TrainClassifier.scala)."""

    def _fit(self, df: Table) -> "TrainedClassifierModel":
        fz = self._featurizer(df)
        work = fz.transform(df) if fz is not None else df
        indexer = ValueIndexer(inputCol=self.labelCol,
                               outputCol="__label_indexed").fit(work)
        work = indexer.transform(work)
        if self.model is None:
            from ..models import LightGBMClassifier
            est = LightGBMClassifier()
        else:
            est = self.model.copy()  # never mutate the caller's estimator
        est.set("labelCol", "__label_indexed")
        est.set("featuresCol", self.featuresCol)
        fitted = est.fit(work)
        return TrainedClassifierModel(featurizer=fz, indexer=indexer, innerModel=fitted,
                                      labelCol=self.labelCol, featuresCol=self.featuresCol)


class TrainRegressor(_TrainBase):
    """Auto-featurize + fit a regressor (TrainRegressor.scala)."""

    def _fit(self, df: Table) -> "TrainedRegressorModel":
        fz = self._featurizer(df)
        work = fz.transform(df) if fz is not None else df
        if self.model is None:
            from ..models import LightGBMRegressor
            est = LightGBMRegressor()
        else:
            est = self.model.copy()  # never mutate the caller's estimator
        est.set("labelCol", self.labelCol)
        est.set("featuresCol", self.featuresCol)
        fitted = est.fit(work)
        return TrainedRegressorModel(featurizer=fz, innerModel=fitted,
                                     labelCol=self.labelCol, featuresCol=self.featuresCol)


class _TrainedBase(Model, HasLabelCol, HasFeaturesCol):
    featurizer = Param("featurizer", "Fitted Featurize model (None if pre-featurized)",
                       object)
    innerModel = Param("innerModel", "Fitted underlying model", object)

    def _apply_featurizer(self, df: Table) -> Table:
        fz = self.get("featurizer")
        return fz.transform(df) if fz is not None else df

    def _save_extra(self, path: str) -> None:
        import os
        for name in ("featurizer", "innerModel", "indexer"):
            m = self.get(name)
            if m is not None:
                m.save(os.path.join(path, name))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        for name in ("featurizer", "innerModel", "indexer"):
            p = os.path.join(path, name)
            if os.path.isdir(p):
                self.set(name, PipelineStage.load(p))


class TrainedClassifierModel(_TrainedBase):
    indexer = Param("indexer", "Fitted label ValueIndexerModel", object)

    def _transform(self, df: Table) -> Table:
        out = self.innerModel.transform(self._apply_featurizer(df))
        # map indexed predictions back to original label values
        idxr = self.get("indexer")
        if idxr is not None and "prediction" in out:
            levels = idxr.levels
            pred = np.asarray(out["prediction"], np.int64)
            vals = np.array([levels[i] if 0 <= i < len(levels) else None for i in pred])
            out = out.with_column("scored_labels", vals)
        return out


class TrainedRegressorModel(_TrainedBase):
    def _transform(self, df: Table) -> Table:
        return self.innerModel.transform(self._apply_featurizer(df))
