"""Evaluation metrics (reference: core/.../core/metrics/MetricConstants.scala +
train/ComputeModelStatistics.scala metric math). Vectorized NumPy/JAX — AUC via
rank statistic, NDCG for ranking parity."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class MetricConstants:
    AucSparkMetric = "AUC"
    AccuracySparkMetric = "accuracy"
    PrecisionSparkMetric = "precision"
    RecallSparkMetric = "recall"
    F1Metric = "f1"
    MseSparkMetric = "mse"
    RmseSparkMetric = "rmse"
    MaeSparkMetric = "mae"
    R2SparkMetric = "R^2"
    AllSparkMetrics = "all"
    ClassificationMetricsName = "classification"
    RegressionMetricsName = "regression"


def auc_score(y_true: np.ndarray, score: np.ndarray) -> float:
    """ROC AUC by the Mann-Whitney rank statistic (ties averaged)."""
    y = np.asarray(y_true, np.float64)
    s = np.asarray(score, np.float64)
    pos = y > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), np.float64)
    sorted_s = s[order]
    # average ranks over ties
    _, inv, counts = np.unique(sorted_s, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    avg = (cum - (counts - 1) / 2.0)
    ranks[order] = avg[inv]
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def binary_classification_metrics(y_true, y_pred, score=None) -> Dict[str, float]:
    y = np.asarray(y_true, np.float64)
    p = np.asarray(y_pred, np.float64)
    tp = float(((p > 0) & (y > 0)).sum())
    fp = float(((p > 0) & (y <= 0)).sum())
    fn = float(((p <= 0) & (y > 0)).sum())
    tn = float(((p <= 0) & (y <= 0)).sum())
    prec = tp / (tp + fp) if tp + fp > 0 else 0.0
    rec = tp / (tp + fn) if tp + fn > 0 else 0.0
    out = {
        "accuracy": (tp + tn) / max(len(y), 1),
        "precision": prec,
        "recall": rec,
        "f1": 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0,
        "confusion_matrix": np.array([[tn, fp], [fn, tp]]),
    }
    if score is not None:
        out["AUC"] = auc_score(y, score)
    return out


def multiclass_metrics(y_true, y_pred) -> Dict[str, float]:
    y = np.asarray(y_true)
    p = np.asarray(y_pred)
    classes = np.unique(np.concatenate([y, p]))
    k = len(classes)
    lut = {c: i for i, c in enumerate(classes)}
    cm = np.zeros((k, k), np.float64)
    for a, b in zip(y, p):
        cm[lut[a], lut[b]] += 1
    diag = np.diag(cm)
    prec = np.where(cm.sum(0) > 0, diag / np.maximum(cm.sum(0), 1), 0.0)
    rec = np.where(cm.sum(1) > 0, diag / np.maximum(cm.sum(1), 1), 0.0)
    return {"accuracy": float(diag.sum() / max(cm.sum(), 1)),
            "macro_precision": float(prec.mean()),
            "macro_recall": float(rec.mean()),
            "confusion_matrix": cm}


def regression_metrics(y_true, y_pred) -> Dict[str, float]:
    y = np.asarray(y_true, np.float64)
    p = np.asarray(y_pred, np.float64)
    err = p - y
    mse = float((err ** 2).mean()) if len(y) else float("nan")
    ss_tot = float(((y - y.mean()) ** 2).sum()) if len(y) else 0.0
    return {"mse": mse, "rmse": float(np.sqrt(mse)), "mae": float(np.abs(err).mean()),
            "R^2": 1.0 - (err ** 2).sum() / ss_tot if ss_tot > 0 else float("nan")}


def ranking_ndcg(y_true, score, groups, k: Optional[int] = None) -> float:
    """Mean NDCG@k over query groups (LightGBMRanker eval parity)."""
    y = np.asarray(y_true, np.float64)
    s = np.asarray(score, np.float64)
    g = np.asarray(groups)
    vals = []
    for q in np.unique(g):
        m = g == q
        yy, ss = y[m], s[m]
        kk = len(yy) if k is None else min(k, len(yy))
        order = np.argsort(-ss)[:kk]
        gains = (2.0 ** yy[order] - 1) / np.log2(np.arange(2, kk + 2))
        ideal = np.sort(yy)[::-1][:kk]
        igains = (2.0 ** ideal - 1) / np.log2(np.arange(2, kk + 2))
        vals.append(gains.sum() / igains.sum() if igains.sum() > 0 else 0.0)
    return float(np.mean(vals)) if vals else float("nan")
