"""ComputeModelStatistics / ComputePerInstanceStatistics.

Reference: core/.../train/ComputeModelStatistics.scala (scored DataFrame →
one-row metrics table; evaluationMetric selects classification vs regression)
and ComputePerInstanceStatistics.scala (per-row loss columns)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param, HasLabelCol, HasPredictionCol
from ..core.pipeline import Transformer
from ..core.table import Table
from .metrics import (binary_classification_metrics, multiclass_metrics,
                      regression_metrics)


class ComputeModelStatistics(Transformer, HasLabelCol, HasPredictionCol):
    evaluationMetric = Param("evaluationMetric",
                             "classification | regression | all", str, "all")
    scoresCol = Param("scoresCol", "Raw score / probability column for AUC", str)

    def _transform(self, df: Table) -> Table:
        y = np.asarray(df[self.labelCol], np.float64)
        pred = np.asarray(df[self.predictionCol], np.float64)
        metric = self.evaluationMetric
        is_classification = metric == "classification" or (
            metric == "all" and len(np.unique(y)) <= max(2, min(20, len(y) // 2))
            and np.allclose(y, np.round(y)))
        if is_classification:
            score = None
            sc = self.get("scoresCol")
            if sc and sc in df:
                s = df[sc]
                score = s[:, -1] if s.ndim == 2 else np.asarray(s, np.float64)
            if len(np.unique(y)) <= 2:
                m = binary_classification_metrics(y, pred, score)
            else:
                m = multiclass_metrics(y, pred)
            cm = m.pop("confusion_matrix")
            row = {k: np.array([v]) for k, v in m.items()}
            row["confusion_matrix"] = np.array([cm])
            return Table(row)
        m = regression_metrics(y, pred)
        return Table({k: np.array([v]) for k, v in m.items()})


class ComputePerInstanceStatistics(Transformer, HasLabelCol, HasPredictionCol):
    probabilityCol = Param("probabilityCol", "Probability column (classification)", str,
                           "probability")
    evaluationMetric = Param("evaluationMetric", "classification | regression | all",
                             str, "all")

    def _transform(self, df: Table) -> Table:
        y = np.asarray(df[self.labelCol], np.float64)
        pred = np.asarray(df[self.predictionCol], np.float64)
        out = df.copy()
        pc = self.get("probabilityCol")
        if pc and pc in df and self.evaluationMetric != "regression":
            prob = df[pc]
            if prob.ndim == 2:
                idx = np.clip(y.astype(np.int64), 0, prob.shape[1] - 1)
                p_true = prob[np.arange(len(y)), idx]
            else:
                p_true = np.where(y > 0, prob, 1.0 - prob)
            out["log_loss"] = -np.log(np.maximum(p_true, 1e-15))
        else:
            out["L1_loss"] = np.abs(pred - y)
            out["L2_loss"] = (pred - y) ** 2
        return out
