"""Train helpers (SURVEY §2.7 train/, 1291 LoC in reference):
TrainClassifier/TrainRegressor (auto-featurize + fit any estimator) and
ComputeModelStatistics / ComputePerInstanceStatistics metric transformers."""

from .metrics import (MetricConstants, binary_classification_metrics,
                      multiclass_metrics, ranking_ndcg, regression_metrics)
from .stats import ComputeModelStatistics, ComputePerInstanceStatistics
from .train import TrainClassifier, TrainRegressor, TrainedClassifierModel, TrainedRegressorModel

__all__ = ["TrainClassifier", "TrainRegressor", "TrainedClassifierModel",
           "TrainedRegressorModel", "ComputeModelStatistics",
           "ComputePerInstanceStatistics", "MetricConstants",
           "binary_classification_metrics", "regression_metrics",
           "multiclass_metrics", "ranking_ndcg"]
