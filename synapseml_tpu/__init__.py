"""synapseml_tpu — a TPU-native ML framework with the capabilities of SynapseML.

Composable ``fit``/``transform`` estimator pipelines over columnar data that execute
as SPMD JAX/XLA programs on TPU meshes. See SURVEY.md at the repo root for the
structural analysis of the reference (svotaw/SynapseML) this build follows.

Layout (mirrors SURVEY.md §7 layer order):
  core/      — Params/metadata system, Estimator/Transformer/Pipeline protocol,
               columnar Table, save/load, logging, fabric/AAD auth
  parallel/  — device mesh construction, distributed bootstrap, collective
               helpers, ring attention (sequence parallelism)
  ops/       — numeric kernels (histograms, quantile binning, image ops)
  gbdt/      — histogram-GBDT engine (the LightGBM-capability centerpiece)
  models/    — GBDT estimator surface (Classifier/Regressor/Ranker)
  vw/        — hashed-feature online learners + contextual bandits
  dl/        — Flax vision/text estimators (+ HF checkpoint fine-tuning)
  onnx/      — ONNX parser + graph→JAX importer + batch inference
  stages/    — generic pipeline stages (mini-batching, repartition, udf, ...)
  featurize/ — auto-featurization, indexers, text featurizers
  explainers/— LIME / KernelSHAP / ICE;  image/ — superpixels, unroll
  nn/        — KNN / ball index;  recommendation/ — SAR + ranking
  causal/    — DoubleML / DiD / synthetic control;  cyber/ — access anomaly
  isolationforest/ — XLA isolation forest
  io/        — HTTP client layer, serving server, datasources
  services/  — REST AI-service transformers (host-side)
  native/    — C++ host helpers (ctypes) with Python fallbacks
  testing/   — fuzzing + tolerance-CSV benchmark frameworks
  codegen    — generated .pyi stubs + API docs from Param metadata
"""

__version__ = "0.2.0"  # keep in sync with pyproject.toml
