"""synapseml_tpu — a TPU-native ML framework with the capabilities of SynapseML.

Composable ``fit``/``transform`` estimator pipelines over columnar data that execute
as SPMD JAX/XLA programs on TPU meshes. See SURVEY.md at the repo root for the
structural analysis of the reference (svotaw/SynapseML) this build follows.

Layout (mirrors SURVEY.md §7 layer order):
  core/      — Params/metadata system, Estimator/Transformer/Pipeline protocol,
               columnar Table, save/load, logging + phase instrumentation
  parallel/  — device mesh construction, distributed bootstrap, collective helpers
  ops/       — numeric kernels (histograms, quantile binning, hashing, image ops)
  gbdt/      — histogram-GBDT engine (the LightGBM-capability centerpiece)
  models/    — estimator surface (gbdt, linear/online, dl, onnx, knn, sar, ...)
  stages/    — generic pipeline stages (mini-batching, repartition, udf, ...)
  featurize/ — auto-featurization, indexers, text featurizers
  explainers/— LIME / KernelSHAP / ICE
  io/        — HTTP client layer + serving
  services/  — REST AI-service transformers (host-side)
"""

__version__ = "0.1.0"
