"""Ulysses (DeepSpeed-style) sequence parallelism — all-to-all head scatter.

The second first-class long-context strategy next to ring attention
(parallel/ring_attention.py). Where ring attention keeps heads whole and
rotates K/V blocks around the ICI ring, Ulysses re-shards between the two
natural layouts with a single ``all_to_all`` each way:

    sequence-sharded [B, S/p, H,  D]   (how transformer blocks hold tokens)
      → head-sharded [B, S,   H/p, D]  (full sequence per device → EXACT
                                        attention, no online softmax)
      → back to sequence-sharded for the MLP that follows.

Comm volume per layer is 2 all-to-alls of the activation (vs ring's p-1
ppermutes of K/V); Ulysses wins when heads >= devices and the attention
kernel benefits from seeing the whole sequence (e.g. one flash/blockwise call
on the MXU), ring wins when S/p is still long or heads < devices. Both ride
ICI over the same ``seq`` mesh axis so they are interchangeable in a model.

The reference has NO sequence parallelism at all (SURVEY.md §5.7); this is
parity-plus, designed in from the start per the distributed-first mandate.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.compat import shard_map
from .mesh import DATA_AXIS, SEQ_AXIS
from .ring_attention import attention_reference


def ulysses_self_attention(q, k, v, mesh: Mesh, causal: bool = False,
                           scale=None, use_flash: Optional[bool] = None,
                           flash_interpret: bool = False,
                           kv_len: Optional[int] = None):
    """Self-attention over sequence-sharded inputs via all-to-all re-sharding.

    q/k/v: [B, S, H, D] GLOBAL shapes, sharded [data, seq, None, None] on
    ``mesh``. The number of heads H must be divisible by the seq-axis size.
    Returns the attention output with the same sharding as the inputs.

    ``use_flash`` runs the per-device full-sequence attention through the
    fused Pallas kernel (ops/attention_kernel.flash_attention) instead of
    the lax-composed reference. None = auto: on TPU when the kernel's
    on-device selftest passes. ``kv_len`` masks padded key positions when a
    non-divisible sequence was padded to the shard grid (forces the
    reference path, which plumbs the mask).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    sp = mesh.shape[SEQ_AXIS]
    if q.shape[2] % sp:
        raise ValueError(f"heads ({q.shape[2]}) must divide by the seq-axis "
                         f"size ({sp}) for Ulysses attention")
    if use_flash is None:
        from ..ops.attention_kernel import _tpu_flash_selftest

        use_flash = (jax.default_backend() == "tpu"
                     and _tpu_flash_selftest())
    if kv_len is not None:
        use_flash = False
    if use_flash:
        from ..ops.attention_kernel import flash_attention

    def _ulysses(q_blk, k_blk, v_blk):
        # per-device blocks: [B_l, S/p, H, D]
        def seq_to_heads(x):
            # scatter heads, gather sequence: [B, S/p, H, D] -> [B, S, H/p, D]
            x = jax.lax.all_to_all(x, SEQ_AXIS, split_axis=2, concat_axis=1,
                                   tiled=True)
            return x

        def heads_to_seq(x):
            # inverse all-to-all: [B, S, H/p, D] -> [B, S/p, H, D]
            return jax.lax.all_to_all(x, SEQ_AXIS, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q_blk), seq_to_heads(k_blk), seq_to_heads(v_blk)
        # full sequence per device -> exact attention: one fused flash call
        # on the MXU when available, the lax-composed oracle otherwise
        if use_flash:
            out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                  interpret=flash_interpret)
        else:
            out = attention_reference(qh, kh, vh, causal=causal, scale=scale,
                                      kv_len=kv_len)
        return heads_to_seq(out)

    batch_axis = (DATA_AXIS if DATA_AXIS in mesh.shape
                  and q.shape[0] % mesh.shape[DATA_AXIS] == 0 else None)
    spec = P(batch_axis, SEQ_AXIS, None, None)
    fn = shard_map(_ulysses, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
