"""Elastic distributed training: watchdogs, consensus restart, supervision.

The reference stack treats a lost LightGBM worker or a broken Horovod ring as
job-fatal; so did this reproduction until now — a killed or hung peer inside a
``psum`` stalls distributed gbdt and dl ZeRO training forever, because XLA
collectives have no notion of membership. This module closes that gap with
three host-side layers (docs/resilience.md "Elastic training"):

1. **Collective watchdog** — every process writes a per-rank heartbeat file
   (:class:`HeartbeatWriter`, atomic tmp+rename like the checkpoint store);
   :class:`CollectiveWatchdog` runs the hot blocking call (a train step's
   device sync, a fused gbdt chunk, a pipeline batch) on a daemon worker
   thread and joins with a budget. On expiry it consults the
   :class:`HeartbeatMonitor`: a stale peer turns the stall into a diagnosable
   :class:`PeerLostError` naming the lost ranks and their last op; peers that
   are slow-but-alive (fresh heartbeats) extend the wait up to
   ``straggler_factor`` budgets, so a straggling collective is not a false
   positive. ``parallel.collectives`` beats the heartbeat from every helper
   (trace time for jitted code) via the ``_WATCHDOG_HOOK``.
2. **Consensus restart** — survivors agree on the restart point with
   :func:`consensus_restart_step`, a digest-verified file barrier
   (generalizing ``core.checkpoint._exchange_json``, which cannot run once
   the collective fabric is broken): each rank publishes its locally-verified
   ``{step: checkpoint digest}`` map, waits for the expected survivor set
   (``CheckpointError("barrier timeout, peers=[...]")`` past the deadline),
   and the agreed step is the newest one EVERY survivor verified with an
   identical digest — a committed step is only resumed from if it is durable
   and bit-identical everywhere. :func:`elastic_train` wraps a training
   closure with this detect→agree→retry loop; the shrunken/regrown mesh
   resume itself rides the existing resharding restore paths
   (``core.checkpoint.load_sharded_from_checkpoint``, gbdt's mesh-independent
   carry snapshots).
3. **TrainingSupervisor** — the training-side sibling of
   ``io.distributed_serving.FabricSupervisor`` (same pure ``decide`` /
   ``step`` / daemon-loop shape): observes rank liveness (process exit +
   heartbeat staleness), respawns lost ranks up to a budget, then shrinks the
   gang to the survivors. ``spawn_fn(rank, world, attempt)`` is the hook —
   ``io.portforward.remote_spawn`` provides the cross-host implementation
   (closing the ROADMAP "spawn_fn is process-local" gap).

Invariant (chaos-proofed in tests/test_elastic.py): no committed checkpoint
step is ever lost, and a shrink→resume run converges to the same model
quality as an uninterrupted run (bit-for-bit when the mesh shape is
unchanged).

No jax import at module level: the watchdog/consensus machinery is pure
host-side plumbing and must stay importable from worker-management processes
that never touch a device.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..core.checkpoint import (CheckpointError, CheckpointStore,
                               atomic_write_text)
from ..core.logging import record_failure

HEARTBEAT_PREFIX = "hb_p"


class PeerLostError(RuntimeError):
    """A collective stalled past its watchdog budget.

    ``lost`` names the ranks whose heartbeats went stale (empty when every
    peer still beats — the collective itself is wedged); ``op`` is the
    operation that stalled; ``last_ops`` maps each lost rank to the last op
    its heartbeat reported, which is usually the exact collective it died
    inside."""

    def __init__(self, op: str, lost: Sequence[int], waited_s: float,
                 last_ops: Optional[Dict[int, str]] = None, detail: str = ""):
        self.op = op
        self.lost = sorted(int(r) for r in lost)
        self.waited_s = float(waited_s)
        self.last_ops = dict(last_ops or {})
        if self.lost:
            who = ", ".join(
                f"rank {r} (last op {self.last_ops.get(r, '?')!r})"
                for r in self.lost)
            msg = (f"collective {op!r} stalled {waited_s:.1f}s: peer "
                   f"heartbeat(s) stale — lost {who}")
        else:
            msg = (f"collective {op!r} stalled {waited_s:.1f}s with every "
                   f"peer heartbeat fresh — the collective itself is wedged")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class ElasticUnsupportedError(NotImplementedError):
    """A training configuration outside the elastic-capable matrix.

    Structured so callers can render the supported-config matrix
    (``.matrix``: feature -> supported?) instead of guessing from a bare
    NotImplementedError; docs/dl-scaling.md documents the same table."""

    def __init__(self, feature: str, matrix: Dict[str, bool], hint: str = ""):
        self.feature = feature
        self.matrix = dict(matrix)
        rows = "; ".join(f"{k}: {'yes' if v else 'NO'}"
                         for k, v in self.matrix.items())
        msg = f"{feature} is not supported. Supported-config matrix — {rows}."
        if hint:
            msg += f" {hint}"
        super().__init__(msg)


# --- heartbeats -------------------------------------------------------------

def _hb_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{HEARTBEAT_PREFIX}{int(rank)}.json")


class HeartbeatWriter:
    """Per-rank liveness file: ``hb_p<rank>.json`` written atomically (tmp +
    rename, same discipline as the checkpoint store) so a reader never sees a
    torn beat. ``beat(op, step)`` stamps the last operation this rank
    entered; ``start()`` adds a background daemon beater for phases with no
    natural beat sites (data loading, host-side rebuilds). Idempotent
    ``stop``; usable as a context manager."""

    def __init__(self, directory: str, rank: int, interval: float = 0.25):
        self.dir = directory
        self.rank = int(rank)
        self.interval = float(interval)
        self.path = _hb_path(directory, rank)
        self.seq = 0
        self._last_op = "start"
        self._last_step = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)
        self.beat("start")

    def beat(self, op: str = "alive", step: int = 0) -> None:
        with self._lock:
            self.seq += 1
            self._last_op, self._last_step = op, int(step)
            payload = {"rank": self.rank, "op": op, "step": int(step),
                       "seq": self.seq, "pid": os.getpid()}
        atomic_write_text(self.path, json.dumps(payload, sort_keys=True))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            with self._lock:
                op, step = self._last_op, self._last_step
            self.beat(op, step)

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"heartbeat-p{self.rank}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, remove: bool = False) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.interval + 1.0)
        if remove:
            try:
                os.remove(self.path)
            except OSError:
                pass   # already gone — a removed beat is a stopped beat

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class HeartbeatMonitor:
    """Reads the heartbeat directory: a rank is *alive* while its beat file's
    mtime is within ``timeout`` seconds, *stale* otherwise (or when the file
    is missing entirely for an ``expected`` rank). ``self_rank`` is excluded
    from staleness — a process never declares itself lost."""

    def __init__(self, directory: str, timeout: float = 2.0,
                 expected: Optional[Sequence[int]] = None,
                 self_rank: Optional[int] = None):
        self.dir = directory
        self.timeout = float(timeout)
        self.expected = (sorted(int(r) for r in expected)
                         if expected is not None else None)
        self.self_rank = self_rank

    def read(self) -> Dict[int, Dict[str, Any]]:
        """rank -> {"age": seconds since last beat, **last payload}."""
        out: Dict[int, Dict[str, Any]] = {}
        if not os.path.isdir(self.dir):
            return out
        now = time.time()
        for fn in os.listdir(self.dir):
            if not (fn.startswith(HEARTBEAT_PREFIX) and fn.endswith(".json")):
                continue
            path = os.path.join(self.dir, fn)
            try:
                rank = int(fn[len(HEARTBEAT_PREFIX):-len(".json")])
                age = now - os.path.getmtime(path)
                with open(path, "r", encoding="utf-8") as f:
                    payload = json.loads(f.read())
            except (OSError, ValueError):
                continue   # torn/vanished beat: treated as missing this poll
            out[rank] = dict(payload, age=age)
        return out

    def alive(self) -> List[int]:
        return sorted(r for r, p in self.read().items()
                      if p["age"] <= self.timeout)

    def stale(self) -> List[int]:
        """Ranks presumed lost: beat older than ``timeout`` or (for expected
        ranks) never written. ``self_rank`` is never reported."""
        seen = self.read()
        ranks = set(seen)
        if self.expected is not None:
            ranks |= set(self.expected)
        out = []
        for r in sorted(ranks):
            if self.self_rank is not None and r == int(self.self_rank):
                continue
            p = seen.get(r)
            if p is None or p["age"] > self.timeout:
                out.append(r)
        return out

    def last_ops(self, ranks: Sequence[int]) -> Dict[int, str]:
        seen = self.read()
        return {int(r): seen[r]["op"] for r in ranks if r in seen}


# --- the watchdog -----------------------------------------------------------

class CollectiveWatchdog:
    """Timeout guard around hot blocking calls (collectives, device syncs).

    ``run(fn, *args, op=...)`` executes ``fn`` on a daemon worker thread and
    joins with ``timeout``. Past the budget it consults the monitor:

    * some peer heartbeat is stale → :class:`PeerLostError` naming the lost
      ranks and their last reported op (``elastic.peer_lost`` counter);
    * every peer still beats → the wait extends, budget by budget, up to
      ``straggler_factor`` × ``timeout`` total (``elastic.straggler_wait``
      counter) — a slow-but-alive straggler is NOT a lost peer;
    * the hard cap expires with all peers fresh → :class:`PeerLostError`
      with ``lost=[]``: the collective itself is wedged
      (``elastic.collective_stall`` counter).

    ``writer`` (optional) is beaten on every ``beat()`` call — the training
    loops and ``parallel.collectives`` route their beats through here so one
    object carries both halves of the protocol. The worker thread is a
    daemon: an abandoned hung call cannot block interpreter exit."""

    def __init__(self, timeout: float = 30.0,
                 monitor: Optional[HeartbeatMonitor] = None,
                 writer: Optional[HeartbeatWriter] = None,
                 straggler_factor: float = 4.0, poll: float = 0.05):
        if timeout <= 0:
            raise ValueError(f"timeout must be > 0, got {timeout}")
        self.timeout = float(timeout)
        self.monitor = monitor
        self.writer = writer
        self.straggler_factor = max(float(straggler_factor), 1.0)
        self.poll = float(poll)
        self.stalls = 0          # budget expiries observed (incl. stragglers)
        self.ops_guarded = 0

    def beat(self, op: str = "alive", step: int = 0) -> None:
        if self.writer is not None:
            self.writer.beat(op, step)

    def run(self, fn: Callable, *args, op: Optional[str] = None,
            timeout: Optional[float] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the stall guard; returns its
        result or re-raises its exception. See class docstring for the
        timeout policy."""
        opname = op or getattr(fn, "__name__", "collective")
        budget = float(timeout) if timeout else self.timeout
        hard = budget * self.straggler_factor
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _worker():
            try:
                box["out"] = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                box["err"] = e
            finally:
                done.set()

        self.ops_guarded += 1
        t = threading.Thread(target=_worker, daemon=True,
                             name=f"watchdog-{opname}")
        t.start()
        t0 = time.monotonic()
        warned = False
        while not done.wait(self.poll):
            waited = time.monotonic() - t0
            if waited < budget:
                continue
            self.stalls += not warned
            stale = self.monitor.stale() if self.monitor is not None else []
            if stale:
                last = (self.monitor.last_ops(stale)
                        if self.monitor is not None else {})
                record_failure("elastic.peer_lost", op=opname,
                               lost=list(stale), waited_s=round(waited, 3))
                raise PeerLostError(opname, stale, waited, last_ops=last)
            if waited >= hard:
                record_failure("elastic.collective_stall", op=opname,
                               waited_s=round(waited, 3))
                raise PeerLostError(
                    opname, [], waited,
                    detail="hung past the straggler cap; no rank heartbeat "
                           "is stale — suspect a deadlocked collective or a "
                           "wedged device")
            if not warned:
                warned = True
                record_failure("elastic.straggler_wait", op=opname,
                               budget_s=budget, cap_s=hard)
        if "err" in box:
            raise box["err"]
        return box["out"]


def run_with_budget(fn: Callable, *args, budget_s: float,
                    op: str = "task", **kwargs):
    """One-shot :meth:`CollectiveWatchdog.run` without peer heartbeats: run
    ``fn`` on a reaped daemon thread and raise :class:`PeerLostError`
    (``lost=[]``) once ``budget_s`` elapses. The straggler extension is
    disabled (no monitor means no evidence the task is merely slow), so the
    budget is hard — this is the hang-reaper the elastic AutoML scheduler
    wraps every candidate fit in: the abandoned thread cannot wedge the
    pool, and the caller scores the reaped work NaN instead of waiting."""
    return CollectiveWatchdog(timeout=budget_s, straggler_factor=1.0).run(
        fn, *args, op=op, **kwargs)


# --- global watchdog registry (training loops + collectives consult it) -----

_CURRENT: Optional[CollectiveWatchdog] = None


def current_watchdog() -> Optional[CollectiveWatchdog]:
    """The installed watchdog, or None. Training loops (gbdt fused/host, dl
    trainer/pipeline) wrap their blocking step through it and beat per
    boundary when one is installed; the branch costs one global read."""
    return _CURRENT


class elastic_watchdog:
    """Context manager installing ``wd`` as the process-global watchdog AND
    hooking ``parallel.collectives`` so every collective helper beats the
    heartbeat with its op name (trace time for jitted code — the last op a
    dead rank reported is usually the collective it died inside). Nesting is
    not supported (single global slot, same pattern as the chaos hooks)."""

    def __init__(self, wd: CollectiveWatchdog):
        self.wd = wd

    def __enter__(self) -> CollectiveWatchdog:
        global _CURRENT
        from . import collectives as _c

        if _CURRENT is not None or _c._WATCHDOG_HOOK is not None:
            raise RuntimeError("elastic_watchdog does not nest")
        _CURRENT = self.wd
        _c._WATCHDOG_HOOK = lambda name: self.wd.beat(name)
        return self.wd

    def __exit__(self, *exc) -> None:
        global _CURRENT
        from . import collectives as _c

        _CURRENT = None
        _c._WATCHDOG_HOOK = None


# --- consensus restart ------------------------------------------------------

def verified_steps(store: CheckpointStore) -> Dict[int, str]:
    """step -> whole-checkpoint digest for every checkpoint in ``store`` that
    fully verifies (every artifact passes its manifest digests). A torn or
    bit-rotted checkpoint is simply absent — it cannot be agreed on."""
    out: Dict[int, str] = {}
    for step in store.steps():
        try:
            ck = store.load_step(step)
        except CheckpointError:
            continue
        out[int(step)] = ck.digest
    return out


def consensus_restart_step(store: CheckpointStore, consensus_dir: str,
                           rank: int, expected: Sequence[int], *,
                           timeout: float = 30.0, poll: float = 0.05,
                           epoch: int = 0) -> Optional[int]:
    """Digest-verified survivor barrier: agree on the last fully-committed
    checkpoint step after a failure.

    Generalizes ``core.checkpoint._exchange_json`` to a file barrier — the
    collective fabric that backs the allgather is exactly what just broke, so
    agreement must ride durable storage instead. Each survivor publishes its
    locally-verified ``{step: digest}`` map (atomic write) under
    ``consensus_dir/epoch_<epoch>/p<rank>.json`` and polls for the full
    ``expected`` set; past ``timeout`` it raises
    ``CheckpointError("barrier timeout, peers=[...]")`` naming the silent
    ranks. The agreed step is the NEWEST step present in every survivor's map
    with an identical digest (None when no common verified step exists —
    restart from scratch). ``epoch`` namespaces successive restart rounds so
    a rank re-running the barrier never reads a previous round's files."""
    d = os.path.join(consensus_dir, f"epoch_{int(epoch):04d}")
    os.makedirs(d, exist_ok=True)
    expected = sorted(set(int(r) for r in expected))
    mine = verified_steps(store)
    atomic_write_text(
        os.path.join(d, f"p{int(rank)}.json"),
        json.dumps({"rank": int(rank),
                    "steps": {str(s): dg for s, dg in mine.items()}},
                   sort_keys=True))
    deadline = time.monotonic() + float(timeout)
    maps: Dict[int, Dict[int, str]] = {}
    while True:
        for r in expected:
            if r in maps:
                continue
            path = os.path.join(d, f"p{r}.json")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    payload = json.loads(f.read())
                maps[r] = {int(s): dg
                           for s, dg in payload.get("steps", {}).items()}
            except (OSError, ValueError):
                pass   # not published yet (or torn mid-write): next poll
        if len(maps) == len(expected):
            break
        if time.monotonic() > deadline:
            missing = sorted(set(expected) - set(maps))
            record_failure("elastic.barrier_timeout", peers=missing,
                           timeout_s=timeout, dir=d)
            raise CheckpointError(
                f"barrier timeout, peers={missing} — survivor(s) never "
                f"published a verified-checkpoint map to {d} within "
                f"{timeout:.1f}s")
        time.sleep(poll)
    common = set(maps[expected[0]])
    for r in expected[1:]:
        common &= set(maps[r])
    agreed = None
    for step in sorted(common, reverse=True):
        if len({maps[r][step] for r in expected}) == 1:
            agreed = step
            break
    record_failure("elastic.consensus", agreed_step=agreed,
                   survivors=expected, epoch=int(epoch))
    return agreed


def elastic_train(train_once: Callable[[int, Optional[int]], Any], *,
                  store: CheckpointStore, consensus_dir: str, rank: int = 0,
                  expected: Sequence[int] = (0,), max_restarts: int = 2,
                  barrier_timeout: float = 30.0,
                  on_restart: Optional[Callable] = None):
    """Detect → agree → resume loop around a training closure.

    ``train_once(attempt, agreed_step)`` runs one training attempt (attempt 0
    passes ``agreed_step=None``); it should rebuild its mesh from whatever
    devices/processes survive and resume from ``store`` (both gbdt and the dl
    trainer do that resume internally). A :class:`PeerLostError` escaping it
    triggers the consensus barrier over the ``expected`` survivor set; the
    retention floor is then pinned so the agreed step still exists when the
    retry loads it. After ``max_restarts`` failed attempts the last error
    propagates. ``on_restart(attempt, agreed_step, error)`` observes each
    transition (tests assert on it; deployments log it)."""
    attempt = 0
    agreed: Optional[int] = None
    while True:
        try:
            return train_once(attempt, agreed)
        except PeerLostError as e:
            attempt += 1
            if attempt > max_restarts:
                raise
            agreed = consensus_restart_step(
                store, consensus_dir, rank, expected,
                timeout=barrier_timeout, epoch=attempt)
            record_failure("elastic.restart", attempt=attempt,
                           agreed_step=agreed, cause=str(e))
            if on_restart is not None:
                on_restart(attempt, agreed, e)


# --- the training-side supervisor -------------------------------------------

class TrainingSupervisor:
    """Respawn-or-shrink supervision of a training gang — the training-side
    sibling of ``io.distributed_serving.FabricSupervisor`` (same shape: pure
    ``decide``, one-action ``step``, optional daemon loop).

    ``spawn_fn(rank, world, attempt)`` starts one worker and returns a
    process handle exposing ``poll()``/``terminate()``/``kill()``/``wait()``
    (a ``subprocess.Popen``; ``io.portforward.remote_spawn`` is the
    cross-host implementation). A rank counts as lost when its process has
    exited OR its heartbeat is stale — covering both a clean crash and a hung
    process that never exits. Policy: each lost rank is respawned up to
    ``max_respawns`` times (regrow); past the budget the gang is shrunk to
    the survivors via ``shrink_fn(new_world)``, which must relaunch training
    at the smaller world (consensus restart + resharding resume do the
    rest). ``retire()`` reaps every child on every exit path."""

    def __init__(self, spawn_fn: Callable[[int, int, int], Any],
                 world_size: int, heartbeat_dir: str, min_world: int = 1,
                 hb_timeout: float = 2.0, interval: float = 0.5,
                 max_respawns: int = 1,
                 shrink_fn: Optional[Callable[[int], Any]] = None):
        if world_size < 1 or min_world < 1 or min_world > world_size:
            raise ValueError("need 1 <= min_world <= world_size")
        self.spawn_fn = spawn_fn
        self.world_size = int(world_size)
        self.min_world = int(min_world)
        self.heartbeat_dir = heartbeat_dir
        self.monitor = HeartbeatMonitor(heartbeat_dir, timeout=hb_timeout,
                                        expected=range(world_size))
        self.interval = float(interval)
        self.max_respawns = int(max_respawns)
        self.shrink_fn = shrink_fn
        self.procs: Dict[int, Any] = {}
        self.respawns: Dict[int, int] = {}
        self.spawned = 0
        self.shrunk = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards procs/respawns/spawned/world_size: the supervisor loop
        # mutates the gang while retire()/start_gang() run on the caller's
        # thread. Reentrant — step() takes it and calls observe()/retire().
        self._gang_lock = threading.RLock()

    # -- gang management --
    def start_gang(self) -> "TrainingSupervisor":
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        with self._gang_lock:
            for rank in range(self.world_size):
                self.procs[rank] = self.spawn_fn(rank, self.world_size, 0)
                self.spawned += 1
        return self

    def retire(self) -> None:
        """Terminate and reap every child (idempotent; called on every exit
        path — a supervisor never leaves zombies)."""
        with self._gang_lock:
            for rank, proc in list(self.procs.items()):
                if proc is None:
                    continue
                try:
                    if proc.poll() is None:
                        proc.terminate()
                        try:
                            proc.wait(timeout=5)
                        except Exception:  # noqa: BLE001 — escalate to SIGKILL
                            proc.kill()
                    proc.wait()
                except OSError:
                    pass   # already reaped
                self.procs[rank] = None

    # -- observe / decide / act (FabricSupervisor shape) --
    def observe(self):
        """(alive_ranks, lost_ranks): a rank is lost when its process exited
        or its heartbeat went stale."""
        stale = set(self.monitor.stale())
        alive, lost = [], []
        with self._gang_lock:
            for rank, proc in self.procs.items():
                if proc is None:
                    continue
                exited = proc.poll() is not None
                if exited or rank in stale:
                    lost.append(rank)
                else:
                    alive.append(rank)
        return sorted(alive), sorted(lost)

    def decide(self, n_alive: int, lost: Sequence[int]) -> Optional[str]:
        """Pure policy: "respawn" (every lost rank still under its respawn
        budget), "shrink" (budget exhausted but survivors form a viable
        world), or None (nothing lost / nothing left to do)."""
        if not lost:
            return None
        if all(self.respawns.get(r, 0) < self.max_respawns for r in lost):
            return "respawn"
        if n_alive >= self.min_world and self.shrink_fn is not None:
            return "shrink"
        return None

    def step(self) -> Optional[str]:
        """Observe -> decide -> act once; returns the action taken."""
        alive, lost = self.observe()
        action = self.decide(len(alive), lost)
        if action == "respawn":
            with self._gang_lock:
                for rank in lost:
                    proc = self.procs.get(rank)
                    if proc is not None:
                        try:      # reap the corpse before replacing it
                            if proc.poll() is None:
                                proc.kill()
                            proc.wait()
                        except OSError:
                            pass
                    attempt = self.respawns.get(rank, 0) + 1
                    self.respawns[rank] = attempt
                    self.procs[rank] = self.spawn_fn(rank, self.world_size,
                                                     attempt)
                    self.spawned += 1
                    record_failure("elastic.respawn", rank=rank,
                                   attempt=attempt, world=self.world_size)
        elif action == "shrink":
            survivors = len(alive)
            with self._gang_lock:
                self.retire()                  # drain the old gang fully
                self.world_size = survivors
                self.monitor.expected = list(range(survivors))
                self.respawns.clear()
                self.shrunk += 1
            record_failure("elastic.shrink", new_world=survivors)
            self.shrink_fn(survivors)
        return action

    # -- managed loop --
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — loop must survive a bad step
                record_failure("elastic.supervisor_error", error=str(e))

    def start(self) -> "TrainingSupervisor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="training-supervisor",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval + 5)

    def __enter__(self) -> "TrainingSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
        self.retire()
