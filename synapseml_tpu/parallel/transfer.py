"""Host-aware device-to-device transfers between stage-group submeshes.

The MPMD pipeline engine (``dl/pipeline.py``) moves microbatch activations
and backward cotangents between per-stage submeshes. Single-process, that
hop is a plain ``jax.device_put`` — XLA schedules the ICI copy and nothing
here adds work. Multi-process, the source and target submeshes may live on
different (even disjoint) process sets, where a naive ``device_put`` onto
non-addressable devices raises under the transfer guard. :func:`device_transfer`
keeps one call site for both:

* **single-process** — ``jax.device_put(x, sharding)``, unchanged math;
* **multi-process** — an all-process *rendezvous*: every process contributes
  the blocks its devices hold (zeros elsewhere) plus a coverage mask through
  one ``process_allgather``, reconstructs the full host value by taking each
  element from the lowest-indexed process claiming it, and re-places it with
  ``make_array_from_callback`` so each process materializes only the target
  blocks its own devices own. Transfer-guard-clean: no direct device_put
  ever touches a non-addressable device. (Correctness-first DCN path; an
  XLA collective-permute hop that never leaves the fabric is the follow-up
  once multi-host hardware is available to measure it.)

Because the cross-host path is a rendezvous, **every process must call it
for every hop** — processes with no addressable shard of the source pass a
``jax.ShapeDtypeStruct`` placeholder and still participate.

Every hop beats the watchdog/chaos hook pair shared with
:mod:`parallel.collectives` BEFORE moving data, so a dead downstream host
surfaces as ``PeerLostError`` with the hop's op name on record instead of a
silent wedge, and ``testing.chaos.chaos_hang(op="transfer.hop")`` can stall
one deterministically.
"""

from __future__ import annotations

import jax
import numpy as np

from . import collectives as _coll


def _beat(op: str) -> None:
    # shared hook pair with parallel.collectives: elastic_watchdog installs
    # the heartbeat writer, chaos_hang the stall — both see hop op names
    hook = _coll._WATCHDOG_HOOK
    if hook is not None:
        hook(op)
    if _coll._CHAOS_HOOK is not None:
        _coll._CHAOS_HOOK(op)


def _rendezvous(x):
    """Full host value of ``x`` on EVERY process via one all-process
    allgather. ``x`` is a ``jax.Array`` (contributes its addressable
    blocks), a ``jax.ShapeDtypeStruct`` (contributes nothing — the caller
    owns no shard), or a host array (already complete; contributes all)."""
    from jax.experimental import multihost_utils

    if isinstance(x, jax.ShapeDtypeStruct):
        shape = tuple(int(d) for d in x.shape)
        dtype = np.dtype(x.dtype)
        payload = np.zeros(shape, dtype)
        have = np.zeros(shape, np.bool_)
    elif isinstance(x, jax.Array):
        shape = tuple(int(d) for d in x.shape)
        dtype = np.dtype(x.dtype)
        payload = np.zeros(shape, dtype)
        have = np.zeros(shape, np.bool_)
        for sh in x.addressable_shards:
            payload[sh.index] = np.asarray(sh.data)
            have[sh.index] = True
    else:
        payload = np.ascontiguousarray(np.asarray(x))
        shape = payload.shape
        have = np.ones(shape, np.bool_)
    payloads = np.asarray(multihost_utils.process_allgather(payload))
    haves = np.asarray(multihost_utils.process_allgather(have))
    if not haves.any(axis=0).all():
        raise ValueError(
            "device_transfer: no process holds a shard covering part of the "
            "source array — was the hop called on every process?")
    # lowest-indexed contributor wins per element (replicated shards agree)
    src = np.argmax(haves, axis=0)
    return np.take_along_axis(payloads, src[None], axis=0)[0]


def _place(host, sharding):
    """Host value -> globally-sharded array; each process materializes only
    the blocks its local devices own."""
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx, h=host: h[idx])


def device_transfer(x, sharding, *, op: str = "transfer.hop"):
    """Move ``x`` onto ``sharding`` (a NamedSharding on a possibly different
    submesh) — the pipeline's inter-group hop.

    ``x`` may be a ``jax.Array`` (source-group owners), a host numpy array
    (replicated host inputs: microbatch rows, labels), or a
    ``jax.ShapeDtypeStruct`` placeholder (multi-process callers with no
    addressable shard of the source). Multi-process device-to-device hops
    are an all-process rendezvous — every process must make the call, in
    the same schedule order.
    """
    _beat(op)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    if not isinstance(x, (jax.Array, jax.ShapeDtypeStruct)):
        # replicated host value: every process already has it — place
        # locally, no collective needed
        return _place(x, sharding)
    return _place(_rendezvous(x), sharding)


def host_fetch(tree, *, op: str = "transfer.fetch"):
    """Full host (numpy) copy of a possibly cross-host sharded pytree, on
    every process — unlike ``mesh.host_copy`` this survives leaves whose
    owning submesh excludes the caller entirely (disjoint stage groups):
    such leaves ride the same rendezvous with zero contributed blocks."""
    _beat(op)
    if jax.process_count() == 1:
        return jax.tree.map(lambda a: np.asarray(a), tree)
    return jax.tree.map(_rendezvous, tree)


def share_scalars(values, src_process: int = 0):
    """Replicate a small list of host floats from ``src_process`` to every
    process (the pipeline's loss/acc are computed only on the last stage
    group's owners). Single-process: identity."""
    if jax.process_count() == 1:
        return [float(v) for v in values]
    from jax.experimental import multihost_utils

    arr = np.asarray([float(v) for v in values], np.float64)
    out = multihost_utils.broadcast_one_to_all(
        arr, is_source=jax.process_index() == src_process)
    return [float(v) for v in np.asarray(out)]
