"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference has NO long-context story (SURVEY.md §5.7: text DL truncates at
max_token_len=128); this framework makes sequence parallelism first-class so
the DL layer scales context length with chips. Design per Liu et al.
(Ring Attention with Blockwise Transformers) + the blockwise-parallel
formulation: Q stays resident per device; K/V blocks rotate around the ring
(``ppermute`` over ICI) while each device accumulates its queries' attention
with a numerically-stable online softmax (running max ``m``, normalizer ``l``,
unnormalized output ``o``). Compute for step t overlaps the collective for
step t+1 — XLA schedules the ppermute asynchronously on TPU.

Shapes follow flax convention: [batch, seq, heads, head_dim]; the seq axis is
sharded over the mesh's ``seq`` axis. Causal masking uses global positions
derived from each block's ring offset, so device boundaries are invisible to
the math.
"""

from __future__ import annotations

import sys
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.compat import shard_map
from .mesh import SEQ_AXIS


def _witness_observe(site, tree, expect=None):
    # dtype-witness probe (testing/dtypewitness.py): inert unless the
    # witness module is loaded — sys.modules lookup keeps product imports
    # free of the testing package
    w = sys.modules.get("synapseml_tpu.testing.dtypewitness")
    if w is not None and w.active():
        w.observe(site, tree, expect)


def _block_attention(q, k, v, m, l, o, q_offset, k_offset, causal, scale,
                     kv_len=None):
    """One blockwise online-softmax update.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m,l: [B, H, Sq]; o: [B, Sq, H, D].
    Offsets are the blocks' global sequence starts (for causal masking).
    ``kv_len`` masks keys at global positions >= kv_len — the padded tail
    when a non-divisible sequence was padded up to the shard grid.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Sq, Sk]
    mask = None
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
    if kv_len is not None:
        valid = ((k_offset + jnp.arange(k.shape[1])) < kv_len)[None, :]
        mask = valid if mask is None else mask & valid
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))          # [B, H, Sq]
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> use 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m[..., None], -jnp.inf))
    p = jnp.where(jnp.isnan(p), 0.0, p)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * correction + p.sum(axis=-1)
    o_new = (o * correction.transpose(0, 2, 1)[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, o_new


def _finalize(m, l, o):
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return o / denom


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        kv_len: Optional[int] = None) -> jnp.ndarray:
    """Plain single-device attention (the correctness oracle for the ring).

    ``kv_len`` masks key positions >= kv_len (padding introduced when a
    non-divisible sequence was padded to the shard grid); rows of padded
    queries still normalize over the real keys, and the caller slices them
    off after unpadding.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    n_q, n_k = q.shape[1], k.shape[1]
    mask = None
    if causal:
        mask = jnp.arange(n_q)[:, None] >= jnp.arange(n_k)[None, :]
    if kv_len is not None:
        valid = (jnp.arange(n_k) < kv_len)[None, :]
        mask = valid if mask is None else mask & valid
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_self_attention(q, k, v, mesh: Mesh, causal: bool = False,
                        scale: Optional[float] = None,
                        axis: str = SEQ_AXIS,
                        use_flash: Optional[bool] = None,
                        flash_interpret: bool = False,
                        kv_len: Optional[int] = None) -> jnp.ndarray:
    """Exact self-attention with q/k/v sharded on ``axis`` over ``mesh``.

    Each of the R ring ranks holds S/R of the sequence; the result equals
    :func:`attention_reference` on the gathered sequence, bit-for-near-bit
    (online softmax is associative). Peak memory per device is O(S/R · S/R)
    per step instead of O(S²).

    ``use_flash`` runs each rank's per-step block update as the FUSED
    Pallas kernel (ops/attention_kernel.flash_attention_block — scores,
    masking, online-softmax rescale, and PV matmul in one VMEM program)
    instead of the XLA ops below. None = auto: on TPU when the kernel's
    on-device selftest passes; the XLA path otherwise — both compute the
    identical update (equality-tested in tests/test_attention_kernel.py).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if use_flash is None:
        from ..ops.attention_kernel import _tpu_flash_block_selftest

        use_flash = (jax.default_backend() == "tpu"
                     and _tpu_flash_block_selftest())
    if kv_len is not None:
        # padded (non-divisible) sequences need the global key-validity mask,
        # which the fused block kernel does not plumb — XLA path only
        use_flash = False
    if use_flash:
        from ..ops.attention_kernel import flash_attention_block
    ring = mesh.shape[axis]
    # batch rides the data axis when the mesh has one (dp × sp composition) —
    # each data-rank computes only its batch shard
    from .mesh import DATA_AXIS

    batch_axis = DATA_AXIS if (DATA_AXIS in mesh.shape and DATA_AXIS != axis
                               and q.shape[0] % mesh.shape[DATA_AXIS] == 0) \
        else None
    spec = P(batch_axis, axis, None, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec,) * 3,
             out_specs=spec, check_vma=False)
    def _ring(q_blk, k_blk, v_blk):
        rank = jax.lax.axis_index(axis)
        s_local = q_blk.shape[1]
        q_offset = rank * s_local
        m0 = jnp.full(q_blk.shape[:1] + (q_blk.shape[2], s_local), -jnp.inf,
                      dtype=jnp.float32)
        l0 = jnp.zeros_like(m0)
        o0 = jnp.zeros(q_blk.shape, dtype=jnp.float32)
        perm = [(i, (i + 1) % ring) for i in range(ring)]

        def step(t, carry):
            k_cur, v_cur, m, l, o = carry
            # block currently held arrived from rank (rank - t) mod ring
            k_offset = ((rank - t) % ring) * s_local
            if use_flash:
                m, l, o = flash_attention_block(
                    q_blk.astype(jnp.float32), k_cur.astype(jnp.float32),
                    v_cur.astype(jnp.float32), m, l, o, q_offset, k_offset,
                    causal=causal, scale=scale,
                    interpret=flash_interpret)
            else:
                m, l, o = _block_attention(
                    q_blk.astype(jnp.float32), k_cur.astype(jnp.float32),
                    v_cur.astype(jnp.float32), m, l, o, q_offset, k_offset,
                    causal, scale, kv_len=kv_len)
            # rotate K/V to the next rank (overlaps next step's compute)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return k_nxt, v_nxt, m, l, o

        _, _, m, l, o = jax.lax.fori_loop(
            0, ring, step, (k_blk, v_blk, m0, l0, o0))
        # contract: the softmax accumulators stay f32 regardless of the
        # (possibly bf16) q/k/v wire dtype; output returns at q's dtype
        _witness_observe("dl.seq.ring_acc", (m, l, o), expect="float32")
        out = _finalize(m, l, o).astype(q_blk.dtype)
        _witness_observe("dl.seq.ring_out", out)
        return out

    return _ring(q, k, v)


def blockwise_attention(q, k, v, block_size: int, causal: bool = False,
                        scale: Optional[float] = None) -> jnp.ndarray:
    """Single-device blockwise attention (the memory-efficient kernel the ring
    wraps): K/V consumed in ``block_size`` chunks with the same online
    softmax — O(S·block) memory instead of O(S²). Used for long sequences on
    one chip; the remat-style scan keeps XLA from materializing the full
    score matrix."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n_k = k.shape[1]
    if n_k % block_size:
        raise ValueError(f"sequence {n_k} not divisible by block {block_size}")
    n_blocks = n_k // block_size
    kb = k.reshape(k.shape[0], n_blocks, block_size, *k.shape[2:])
    vb = v.reshape(v.shape[0], n_blocks, block_size, *v.shape[2:])

    m0 = jnp.full((q.shape[0], q.shape[2], q.shape[1]), -jnp.inf, jnp.float32)
    l0 = jnp.zeros_like(m0)
    o0 = jnp.zeros(q.shape, jnp.float32)

    def step(carry, blk):
        m, l, o = carry
        t, k_cur, v_cur = blk
        m, l, o = _block_attention(q.astype(jnp.float32),
                                   k_cur.astype(jnp.float32),
                                   v_cur.astype(jnp.float32),
                                   m, l, o, 0, t * block_size, causal, scale)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(
        step, (m0, l0, o0),
        (jnp.arange(n_blocks), kb.transpose(1, 0, 2, 3, 4),
         vb.transpose(1, 0, 2, 3, 4)))
    _witness_observe("dl.seq.block_acc", (m, l, o), expect="float32")
    out = _finalize(m, l, o).astype(q.dtype)
    _witness_observe("dl.seq.block_out", out)
    return out
