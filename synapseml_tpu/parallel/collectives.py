"""Named-axis collective helpers.

The XLA-collective replacement for the reference's three native comm stacks
(SURVEY.md §5.8): LightGBM's in-ring reduce-scatter/allreduce of histogram
buffers, VW's spanning-tree weight averaging, Horovod's gradient allreduce.
All helpers are meant to be called INSIDE ``shard_map``/``pjit`` with the mesh
axis names from :mod:`synapseml_tpu.parallel.mesh`.
"""

from __future__ import annotations

import math
import sys
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.compat import shard_map as _shard_map
from .mesh import DATA_AXIS

# Fault-injection hook (synapseml_tpu.testing.chaos.chaos_collectives): when
# set, every helper calls it with its op name BEFORE building the collective.
# For jitted callers that is trace time — the point where an off-chip chaos
# test can deterministically stall or fail the collective layer without a
# device in the loop. None in production; the branch costs one global read.
_CHAOS_HOOK = None

# Elastic-training heartbeat hook (parallel.elastic.elastic_watchdog): beats
# this process's heartbeat file with the op name before every collective, so
# a rank that dies inside one leaves its last op on record for the peers'
# PeerLostError diagnostics. Fires at trace time for jitted code — the
# host-side boundary a watchdog can actually observe.
_WATCHDOG_HOOK = None


def _chaos(name: str) -> None:
    hook = _WATCHDOG_HOOK
    if hook is not None:
        hook(name)       # beat BEFORE chaos: a killed op still leaves a trail
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK(name)


def _witness_observe(site, tree, expect=None):
    # dtype-witness probe (testing/dtypewitness.py): inert unless the
    # witness module is loaded — sys.modules lookup keeps product imports
    # free of the testing package
    w = sys.modules.get("synapseml_tpu.testing.dtypewitness")
    if w is not None and w.active():
        w.observe(site, tree, expect)


def allreduce_sum(x, axis: str = DATA_AXIS):
    """Histogram/gradient allreduce — LGBM_NetworkInit ring allreduce and
    Horovod allreduce both become one psum over ICI."""
    _chaos("allreduce_sum")
    return jax.lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: str = DATA_AXIS):
    """VW pass-boundary model averaging (VowpalWabbitBaseLearner.scala:134-188)."""
    _chaos("allreduce_mean")
    return jax.lax.pmean(x, axis_name=axis)


def reduce_scatter_sum(x, axis: str = DATA_AXIS, tiled_axis: int = 0):
    """Data-parallel GBDT histogram reduce-scatter: each worker ends up owning
    1/world of the (feature, bin) histogram space — the native
    ReduceScatter the LightGBM data_parallel learner performs internally."""
    _chaos("reduce_scatter_sum")
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=tiled_axis, tiled=True)


def allgather(x, axis: str = DATA_AXIS, tiled: bool = False):
    _chaos("allgather")
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def _axis_size(axis: str) -> int:
    """Static mesh-axis size inside shard_map (jax 0.4.x has no
    ``lax.axis_size``; a unit psum folds to a Python int at trace time)."""
    return jax.lax.psum(1, axis_name=axis)


def ppermute_ring(x, axis: str = DATA_AXIS, shift: int = 1):
    """Ring permute — building block for ring attention / pipelined collectives."""
    _chaos("ppermute_ring")
    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_rank(axis: str = DATA_AXIS):
    return jax.lax.axis_index(axis)


def shard_apply(mesh: Mesh, fn: Callable, in_specs, out_specs, check_vma: bool = False):
    """Thin shard_map wrapper with the framework's mesh conventions."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)


# ---------------------------------------------------------------------------
# Blockwise-quantized collectives (EQuARX structure, PAPERS.md
# arXiv:2506.17615), quantize-ONCE formulation: one cheap ``pmax`` agrees a
# per-`block` max-abs scale across the axis, every device snaps its local
# contribution to that SHARED int8 grid exactly once, and the reduction then
# runs as a plain integer psum/psum_scatter in int16 — int8 grid values sum
# exactly (8 * 127 << 32767), so there is no per-hop requantization and the
# total error is bounded by n * scale/2 regardless of topology. The wire
# moves 2 bytes/element (+ one f32 scale per block), which is exactly the
# dtype_bytes=2.0 the router's cost model prices for the int8 ladder rung;
# XLA lowers the integer all-reduce onto the same ring/tree schedules as a
# float one, so nothing here hand-rolls a ring and host-local meshes pay
# only the (fusible) quantize/dequantize elementwise work.
# ---------------------------------------------------------------------------


def _shared_scale_quantize(blocks, axis: str, bits: int, acc_dtype):
    """(nblocks, block) f32 -> (integer grid values, f32 per-block scales).

    ``pmax`` makes the symmetric per-block scale identical on every device,
    so each device's snap error is <= scale/2 and the integer sums below are
    exact in ``acc_dtype``."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jax.lax.pmax(jnp.max(jnp.abs(blocks), axis=-1),
                         axis_name=axis) / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe[..., None]),
                 -qmax, qmax).astype(acc_dtype)
    return q, safe


def _acc_dtype(n: int, bits: int):
    # exact integer sums need log2(n) headroom above the grid; `n` is the
    # shard_map-folded static axis size, so this resolves at trace time
    qmax = 2 ** (bits - 1) - 1
    return jnp.int16 if n * qmax <= 32767 else jnp.int32  # lint-ok: trace-safety


def reduce_scatter_sum_quantized(x, axis: str = DATA_AXIS, *, bits: int = 8,
                                 block: int = 256):
    """Quantized reduce-scatter: device ``r`` ends up owning the
    fully-summed chunk ``r`` of ``x``'s leading axis (which must divide the
    axis size). Every device snaps its chunks to the shared int8 grid once;
    ``psum_scatter`` then moves 2-byte integer partials whose sum is exact,
    and only the owner dequantizes — total error <= n * scale/2.
    """
    _chaos("reduce_scatter_sum_quantized")
    n = _axis_size(axis)
    if n == 1:                      # lint-ok: trace-safety
        return x.astype(jnp.float32)
    m = x.shape[0]
    if m % n:                       # lint-ok: trace-safety
        raise ValueError(f"leading axis {m} must divide axis size {n}")
    chunk = m // n
    if math.prod(x.shape[1:], start=chunk) % block:  # lint-ok: trace-safety
        raise ValueError(f"chunk elements must divide block={block}")
    blocks = x.astype(jnp.float32).reshape(n, -1, block)   # (n, nbc, block)
    q, safe = _shared_scale_quantize(blocks, axis, bits, _acc_dtype(n, bits))
    s = jax.lax.psum_scatter(q, axis_name=axis, scatter_dimension=0)
    r = jax.lax.axis_index(axis)
    out = s.astype(jnp.float32) * safe[r][:, None]
    out = out.reshape(chunk, *x.shape[1:])
    _witness_observe("parallel.quant.scatter_dequant", out,
                     expect="float32")
    return out


def allreduce_sum_quantized(x, axis: str = DATA_AXIS, *, bits: int = 8,
                            block: int = 256):
    """Blockwise-quantized allreduce: snap to the shared int8 grid once,
    ``psum`` the int16 grid values (exact), dequantize with the shared
    scales. The integer psum result is identical on every device, so the
    f32 output is bit-identical across the axis (collectives downstream
    stay uniform) and the only loss is each device's one-time snap:
    |error| <= n * scale/2. Effective wire cost ~2 bytes/element (+ f32
    scales at ``block`` granularity) vs 4 for f32 — the dtype_bytes=2
    pricing in ``gbdt.voting.collective_bytes_per_split``.
    """
    _chaos("allreduce_sum_quantized")
    n = _axis_size(axis)
    if n == 1:                      # lint-ok: trace-safety
        return x.astype(jnp.float32)
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    m = flat.shape[0]
    mp = -(-m // block) * block
    flat = jnp.pad(flat, (0, mp - m))
    blocks = flat.reshape(-1, block)
    q, safe = _shared_scale_quantize(blocks, axis, bits, _acc_dtype(n, bits))
    s = jax.lax.psum(q, axis_name=axis)
    out = (s.astype(jnp.float32) * safe[:, None]).reshape(-1)
    out = out[:m].reshape(shape)
    _witness_observe("parallel.quant.dequant", out, expect="float32")
    return out


def probe_link_bandwidth(mesh: Mesh, axis: str = DATA_AXIS,
                         size_bytes: int = 1 << 20, repeats: int = 3) -> float:
    """Measured allreduce bus bandwidth (bytes/s) over ``axis`` of ``mesh``
    from one cheap timed f32 psum (~``size_bytes`` payload). Used by the
    distributed-GBDT router; cache the result via
    ``core.tuned.measured_or`` — this compiles a tiny program per call.
    """
    import time

    from jax.sharding import PartitionSpec as P

    n = int(mesh.shape[axis])
    if n <= 1:
        return float("inf")
    words = max(size_bytes // 4 // n * n, n)

    def _body(v):
        return jax.lax.psum(v, axis_name=axis) / n

    _probe = jax.jit(_shard_map(_body, mesh=mesh, in_specs=P(axis),
                                out_specs=P(axis), check_vma=False))
    x = jnp.ones((words,), jnp.float32)
    _probe(x).block_until_ready()          # compile + warm
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        _probe(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    # ring algbw convention: an allreduce moves 2*(n-1)/n bytes per payload
    # byte over the slowest link
    return 2.0 * (n - 1) / n * (words * 4) / max(best, 1e-9)
