"""Named-axis collective helpers.

The XLA-collective replacement for the reference's three native comm stacks
(SURVEY.md §5.8): LightGBM's in-ring reduce-scatter/allreduce of histogram
buffers, VW's spanning-tree weight averaging, Horovod's gradient allreduce.
All helpers are meant to be called INSIDE ``shard_map``/``pjit`` with the mesh
axis names from :mod:`synapseml_tpu.parallel.mesh`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.compat import shard_map as _shard_map
from .mesh import DATA_AXIS

# Fault-injection hook (synapseml_tpu.testing.chaos.chaos_collectives): when
# set, every helper calls it with its op name BEFORE building the collective.
# For jitted callers that is trace time — the point where an off-chip chaos
# test can deterministically stall or fail the collective layer without a
# device in the loop. None in production; the branch costs one global read.
_CHAOS_HOOK = None


def _chaos(name: str) -> None:
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK(name)


def allreduce_sum(x, axis: str = DATA_AXIS):
    """Histogram/gradient allreduce — LGBM_NetworkInit ring allreduce and
    Horovod allreduce both become one psum over ICI."""
    _chaos("allreduce_sum")
    return jax.lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: str = DATA_AXIS):
    """VW pass-boundary model averaging (VowpalWabbitBaseLearner.scala:134-188)."""
    _chaos("allreduce_mean")
    return jax.lax.pmean(x, axis_name=axis)


def reduce_scatter_sum(x, axis: str = DATA_AXIS, tiled_axis: int = 0):
    """Data-parallel GBDT histogram reduce-scatter: each worker ends up owning
    1/world of the (feature, bin) histogram space — the native
    ReduceScatter the LightGBM data_parallel learner performs internally."""
    _chaos("reduce_scatter_sum")
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=tiled_axis, tiled=True)


def allgather(x, axis: str = DATA_AXIS, tiled: bool = False):
    _chaos("allgather")
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def ppermute_ring(x, axis: str = DATA_AXIS, shift: int = 1):
    """Ring permute — building block for ring attention / pipelined collectives."""
    _chaos("ppermute_ring")
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_rank(axis: str = DATA_AXIS):
    return jax.lax.axis_index(axis)


def shard_apply(mesh: Mesh, fn: Callable, in_specs, out_specs, check_vma: bool = False):
    """Thin shard_map wrapper with the framework's mesh conventions."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)


def topk_vote(local_gains: jnp.ndarray, k: int, axis: str = DATA_AXIS):
    """Voting-parallel support (LightGBM `voting_parallel`, SURVEY §2.2):
    each worker proposes its local top-k features by split gain; global vote
    counts elect 2k candidate features, and only those features' histogram
    bins are then exchanged — cutting collective volume on wide datasets.

    Returns (global_topk_feature_ids, vote_counts). local_gains: [num_features].
    """
    num_features = local_gains.shape[0]
    k = min(k, num_features)
    _, local_top = jax.lax.top_k(local_gains, k)
    votes = jnp.zeros((num_features,), jnp.int32).at[local_top].add(1)
    votes = jax.lax.psum(votes, axis_name=axis)
    _, global_top = jax.lax.top_k(votes.astype(jnp.float32), min(2 * k, num_features))
    return global_top, votes
