from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    EXPERT_AXIS,
    STAGE_AXIS,
    initialize_distributed,
    make_mesh,
    data_sharding,
    replicated,
    shard_rows,
    process_topology,
    zero_sharding,
    tree_shardings,
    apply_tree_shardings,
    host_copy,
    stage_submeshes,
)
from .ulysses import ulysses_self_attention  # noqa: F401
from .ring_attention import (  # noqa: F401
    attention_reference,
    blockwise_attention,
    ring_self_attention,
)
from .collectives import (  # noqa: F401
    allreduce_sum,
    allreduce_mean,
    reduce_scatter_sum,
    allgather,
    ppermute_ring,
    axis_rank,
    shard_apply,
    allreduce_sum_quantized,
    reduce_scatter_sum_quantized,
    probe_link_bandwidth,
)
from .elastic import (  # noqa: F401
    CollectiveWatchdog,
    ElasticUnsupportedError,
    HeartbeatMonitor,
    HeartbeatWriter,
    PeerLostError,
    TrainingSupervisor,
    consensus_restart_step,
    current_watchdog,
    elastic_train,
    elastic_watchdog,
    verified_steps,
)
