"""Device mesh construction + distributed bootstrap.

This single module replaces ALL THREE of the reference's distributed coordination
backends (SURVEY.md §5.8): the LightGBM driver-socket rendezvous + native TCP ring
(lightgbm/.../NetworkManager.scala:59-218), VowpalWabbit's driver-hosted spanning
tree (vw/.../VowpalWabbitClusterUtil.scala:15-45), and Horovod's NCCL/Gloo rings
(deep-learning/.../dl/utils.py:31-54). On TPU all of that collapses into
``jax.distributed.initialize`` + a named-axis ``jax.sharding.Mesh``: XLA compiles
the collectives onto ICI within a slice and DCN across slices, and pods are
inherently gang-scheduled, so there is no rendezvous protocol to implement.

Canonical axis names (fixed across the framework so shardings compose):
  ``data``  — batch/row sharding (the reference's only parallelism style)
  ``model`` — tensor parallelism (not in the reference; free on TPU, SURVEY §2.2)
  ``seq``   — sequence/context parallelism (ring attention, §5.7 stance)
  ``expert``— expert parallelism
  ``stage`` — MPMD pipeline stages (arXiv:2412.14374): each index of the axis
              is a device *group* running its own jitted program; the trainer
              maps backbone stages onto groups circularly (stage s → group
              s mod G) and microbatches flow between groups
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap. The entire NetworkManager rendezvous
    (driver ServerSocket + "status:host:port:partition:executor" messages +
    machine-list broadcast, NetworkManager.scala:25-218) reduces to this call;
    rank/world come from the TPU runtime or explicit args."""
    if coordinator_address is None and num_processes is None:
        return  # single-process: nothing to do (the local[*] analog)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(shape: Optional[dict] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named-axis mesh. Default: all devices on the ``data`` axis
    (parity with the reference, which is data-parallel only — SURVEY §2.2).

    ``shape`` maps axis name → size, e.g. ``{"data": 4, "model": 2}``;
    a size of -1 means "whatever is left".
    """
    devs = list(devices if devices is not None else jax.devices())
    if not shape:
        shape = {DATA_AXIS: len(devs)}
    names, sizes = list(shape), list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total > len(devs):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {len(devs)}")
    arr = np.array(devs[:total]).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def data_sharding(mesh: Mesh, *trailing_unsharded: int) -> NamedSharding:
    """Rows sharded over the data axis, trailing dims replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * len(trailing_unsharded))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def assert_equal_across_processes(values, what="local shape"):
    """Raise (rather than hang a collective) when per-process inputs differ.
    ``values``: ints that must match on every process."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    arr = np.ascontiguousarray(np.asarray(list(values), np.int64)[None])
    g = np.asarray(multihost_utils.process_allgather(arr)).reshape(
        jax.process_count(), -1)
    if not (g == g[0]).all():
        raise ValueError(
            f"every process must supply the same {what}; got {g.tolist()}")


def local_mesh_devices(mesh: Mesh) -> int:
    """Devices-per-process for a multi-process mesh; validates the mesh spans
    every process's devices evenly (anything else would mis-shape the
    process-local shards with an opaque placement error)."""
    nproc = jax.process_count()
    ndev = mesh.devices.size
    if ndev % nproc:
        raise ValueError(f"mesh has {ndev} devices across {nproc} processes; "
                         "device count must divide evenly")
    if nproc > 1:
        from collections import Counter

        per_proc = Counter(d.process_index for d in mesh.devices.ravel())
        want = ndev // nproc
        bad = {p: c for p, c in per_proc.items() if c != want}
        if len(per_proc) != nproc or bad:
            raise ValueError(
                f"mesh must take exactly {want} devices from each of the "
                f"{nproc} processes; got per-process counts {dict(per_proc)}")
    return ndev // nproc


def to_global_rows(mesh: Mesh, spec, local_np):
    """Assemble a global row-sharded array from THIS process's equal row
    shard (multi-host SPMD ingestion: every host feeds its slice)."""
    import jax as _jax

    local_np = np.asarray(local_np)
    gshape = (local_np.shape[0] * _jax.process_count(),) + local_np.shape[1:]
    return _jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_np, gshape)


def shard_rows(mesh: Mesh, *arrays):
    """Place host arrays onto the mesh with rows split over ``data``. Pads rows
    to a multiple of the data-axis size (padding repeats the last row; callers
    mask via the returned valid-row count)."""
    ndata = mesh.shape[DATA_AXIS]
    out = []
    for a in arrays:
        a = np.asarray(a)
        n = a.shape[0]
        rem = (-n) % ndata
        if rem:
            a = np.concatenate([a, np.repeat(a[-1:], rem, axis=0)])
        sh = NamedSharding(mesh, P(DATA_AXIS, *([None] * (a.ndim - 1))))
        out.append(jax.device_put(a, sh))
    return out[0] if len(out) == 1 else tuple(out)


@contextlib.contextmanager
def local_cpu_devices(n: int = 8):
    """Testing harness note: the in-process SPMD analog of the reference's
    `local[*]` Spark testing (SURVEY §4.1) is a forked CPU platform with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — set in
    tests/conftest.py BEFORE jax import. This helper only documents/asserts it."""
    if jax.device_count() < n:
        raise RuntimeError(
            f"need {n} devices for the virtual mesh; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count={n} JAX_PLATFORMS=cpu "
            "before importing jax (see tests/conftest.py)")
    yield jax.devices()[:n]


def zero_sharding(mesh: Mesh, x, axis: str = DATA_AXIS) -> NamedSharding:
    """ZeRO-style placement for one array (arXiv:2004.13336, native to XLA
    SPMD): the largest dimension divisible by the ``axis`` size is sharded
    over that axis, everything else replicated. Arrays with no divisible
    dimension (biases smaller than the axis, scalars) stay replicated — XLA
    all-gathers sharded params at use and reduce-scatters their gradients
    purely from these shardings."""
    nshard = mesh.shape[axis]
    shape = getattr(x, "shape", ())
    best = None
    for i in sorted(range(len(shape)), key=lambda j: -shape[j]):
        if shape[i] >= nshard and shape[i] % nshard == 0:
            best = i
            break
    if best is None:
        return NamedSharding(mesh, P())
    spec = [None] * len(shape)
    spec[best] = axis
    return NamedSharding(mesh, P(*spec))


def tree_shardings(mesh: Mesh, tree, mode: str = "replicated",
                   axis: str = DATA_AXIS):
    """A pytree of NamedShardings matching ``tree``: ``"zero"``/``"fsdp"``
    gives each leaf its :func:`zero_sharding`; ``"replicated"`` pins every
    leaf to the full mesh unsharded. Feed the result to
    ``jax.jit(in_shardings=..., out_shardings=...)`` and
    :func:`apply_tree_shardings`."""
    if mode in ("zero", "fsdp"):
        return jax.tree.map(lambda x: zero_sharding(mesh, x, axis), tree)
    if mode != "replicated":
        raise ValueError(f"unknown sharding mode {mode!r}")
    return jax.tree.map(lambda x: NamedSharding(mesh, P()), tree)


def apply_tree_shardings(tree, shardings):
    """Place every leaf of ``tree`` per the matching NamedSharding in
    ``shardings`` and return the globally-sharded pytree.

    Single-process this is a plain (re)``device_put``. Multi-process, leaves
    must be host-replicated numpy (identical on every process — the trainer
    guarantees this); each process contributes only the blocks its local
    devices own via ``make_array_from_callback``, so no device ever holds a
    full copy of a sharded leaf."""
    multiproc = jax.process_count() > 1

    def place(x, sh):
        if multiproc:
            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, sh, lambda idx, h=host: h[idx])
        return jax.device_put(x, sh)

    return jax.tree.map(place, tree, shardings)


def host_copy(tree):
    """Host (numpy) copy of a possibly globally-sharded pytree. Multi-process,
    sharded leaves are gathered with ``process_allgather`` so every host gets
    the full arrays; single-process ``np.asarray`` assembles across local
    devices."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return jax.tree.map(
            lambda a: np.asarray(
                multihost_utils.process_allgather(a, tiled=True)), tree)
    return jax.tree.map(lambda a: np.asarray(a), tree)


def stage_submeshes(mesh: Mesh, num_stages: int):
    """Split a mesh with a ``stage`` axis into per-group submeshes for MPMD
    pipeline parallelism, plus the circular stage→group assignment.

    Returns ``(groups, assignment)``: ``groups[g]`` is a Mesh over the
    devices at stage-axis index ``g`` keeping every *other* axis (so
    ``data``/``seq`` parallelism composes inside each stage), and
    ``assignment[s] = s % len(groups)`` — the circular/looped placement of
    arXiv:2412.14374, which lets more model stages than device groups share
    hardware round-robin."""
    if STAGE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no {STAGE_AXIS!r} axis; build one "
            "with make_mesh({'stage': G, 'data': D})")
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    k = mesh.axis_names.index(STAGE_AXIS)
    names = tuple(n for n in mesh.axis_names if n != STAGE_AXIS)
    groups = []
    for g in range(mesh.shape[STAGE_AXIS]):
        sub = np.take(mesh.devices, g, axis=k)
        if not names:
            # stage-only mesh: give each group a singleton data axis so
            # activation shardings (P("data", ...)) stay well-formed
            groups.append(Mesh(sub.reshape(1), (DATA_AXIS,)))
        else:
            groups.append(Mesh(sub, names))
    assignment = [s % len(groups) for s in range(num_stages)]
    return groups, assignment


def mesh_process_indices(mesh: Mesh):
    """Sorted process indices owning the mesh's devices. Stage submeshes on a
    multi-host mesh may land on a strict subset of processes (even disjoint
    sets per group) — the pipeline engine uses this to decide which stage
    programs THIS process executes and which hops are cross-host."""
    return tuple(sorted({d.process_index for d in mesh.devices.ravel()}))


def process_topology() -> dict:
    """ClusterUtil analog (core/.../core/utils/ClusterUtil.scala:14-161 computes
    executors, tasks/executor, rows/partition from Spark): on TPU the topology is
    a runtime property, not something to discover over sockets."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }
