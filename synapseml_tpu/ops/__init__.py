from .quantize import BinMapper, apply_bins, bin_threshold_to_value, compute_bin_mapper  # noqa: F401
from .histogram import leaf_histograms, sharded_histogram_fn  # noqa: F401
from .attention_kernel import flash_attention  # noqa: F401
