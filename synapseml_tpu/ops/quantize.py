"""Quantile bin mapper — the "reference dataset" concept on TPU.

The reference computes LightGBM bin boundaries on the driver from a row sample and
broadcasts a serialized reference dataset to all workers (LightGBMBase.scala:509-550,
dataset/ReferenceDatasetUtils.scala, dataset/SampledData.scala). Here the bin
boundaries are computed host-side with numpy from a sample (exact same role), and
binning itself is a jitted XLA op so the (N, F) → (N, F) uint8/uint16 quantized
matrix is produced TPU-resident.

Bin semantics (matching LightGBM's BinMapper):
  * boundaries[f] is a sorted vector of bin upper bounds (length <= max_bin - 1);
    bin(x) = first i with x <= boundaries[f][i]; x beyond all bounds → last
    real-value bin.
  * Features containing NaN get a DEDICATED missing bin at index
    ``num_bins[f] - 1`` (missing_type=NaN); the split finder then learns the
    missing direction per split (``default_left``), matching LightGBM's
    BinMapper + Tree::default_left semantics (SURVEY §7 hard-part 1).
  * categorical features use the category's integer value as its bin, capped by
    max_bin; rare categories overflow into bin 0; NaN categories → bin 0.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class BinMapper(NamedTuple):
    """Per-feature binning metadata. ``boundaries`` is padded to a rectangle
    (num_features, max_bin-1) with +inf so it ships to device as one array."""

    boundaries: np.ndarray      # (F, max_bin-1) float32, +inf padded
    num_bins: np.ndarray        # (F,) int32 — actual bin count per feature
    is_categorical: np.ndarray  # (F,) bool
    max_bin: int
    has_nan: np.ndarray = None  # (F,) bool — feature has a dedicated NaN bin
    cat_counts: np.ndarray = None  # (F,) int32 — DISTINCT categories observed
                                   # (sparse id encodings differ from num_bins)

    @property
    def num_features(self) -> int:
        return self.boundaries.shape[0]

    @property
    def total_bins(self) -> int:
        return self.max_bin

    @property
    def nan_mask(self) -> np.ndarray:
        if self.has_nan is None:
            return np.zeros(self.num_features, bool)
        return self.has_nan

    @property
    def nan_bins(self) -> np.ndarray:
        """(F,) int32: the NaN bin index per feature (num_bins-1 when the
        feature has missing values, else an out-of-range sentinel so equality
        against it never fires)."""
        nb = np.asarray(self.num_bins, np.int32) - 1
        return np.where(self.nan_mask, nb, np.int32(0x7FFF))


def cat_presence_bitmap(col: np.ndarray, cap: int) -> np.ndarray:
    """(cap,) bool: which identity bins a categorical column occupies.
    Values clip into [0, cap-1] exactly as identity binning does, so the
    popcount equals the number of distinct OBSERVED bins — the quantity the
    maxCatToOnehot one-vs-rest decision needs (LightGBM decides from
    full-data bin counts). O(n) bincount, no sort."""
    v = col[~np.isnan(col)]
    if not v.size:
        return np.zeros(cap, bool)
    iv = np.clip(v.astype(np.int64), 0, cap - 1)
    return np.bincount(iv, minlength=cap).astype(bool)


def compute_bin_mapper(
    X: np.ndarray,
    max_bin: int = 255,
    sample_count: int = 200_000,
    categorical_features: Optional[Sequence[int]] = None,
    seed: int = 0,
    has_nan: Optional[np.ndarray] = None,
    min_data_in_bin: int = 3,
    max_bin_by_feature: Optional[Sequence[int]] = None,
    cat_presence: Optional[np.ndarray] = None,
) -> BinMapper:
    """Driver-side boundary computation from a sample (the analog of
    LightGBMBase.getSampledRows + LGBM_DatasetCreateFromSampledColumn;
    binSampleCount param default 200000 — params/LightGBMParams.scala).

    ``has_nan`` overrides per-feature missing-ness when the caller has
    computed it on MORE data than ``X`` (e.g. the sparse path samples rows for
    boundaries but elects NaN bins from the full matrix). ``cat_presence``
    ((F, max_bin) bool) similarly overrides categorical bin occupancy when the
    caller saw more data than ``X`` — the sparse and multi-process paths pass
    full-data bitmaps so the maxCatToOnehot decision never depends on the
    sampling seed."""
    X = np.asarray(X, dtype=np.float32)
    n, f = X.shape
    cat = np.zeros(f, dtype=bool)
    if categorical_features:
        cat[list(categorical_features)] = True
    # missing-ness decided on the FULL matrix (binning must route every NaN)
    if has_nan is None:
        has_nan = np.isnan(X).any(axis=0) & ~cat
    else:
        has_nan = np.asarray(has_nan, bool) & ~cat

    X_full = X
    if n > sample_count:
        rng = np.random.default_rng(seed)
        X = X[rng.choice(n, size=sample_count, replace=False)]

    bounds = np.full((f, max_bin - 1), np.inf, dtype=np.float32)
    nbins = np.zeros(f, dtype=np.int32)
    cat_counts = np.zeros(f, dtype=np.int32)
    caps = np.full(f, max_bin, np.int64)
    if max_bin_by_feature is not None:
        mb = np.asarray(max_bin_by_feature, np.int64)
        caps[: len(mb)] = np.clip(mb[:f], 2, max_bin)
    for j in range(f):
        if cat[j]:
            # categories are small non-negative ints; identity binning capped
            # at max_bin. Bin occupancy comes from the FULL column (O(n)
            # bincount — no sort, no sampled-col copy): cat_counts drives the
            # maxCatToOnehot one-vs-rest decision, which LightGBM makes from
            # full-data bin counts — a subsample would flip split modes
            # nondeterministically with bin_sample_count for rare categories.
            # Callers whose X is itself a sample (sparse / multi-process
            # paths) pass the full-data bitmap via ``cat_presence``.
            pres = (np.asarray(cat_presence[j], bool)
                    if cat_presence is not None
                    else cat_presence_bitmap(X_full[:, j], max_bin))
            nz = np.flatnonzero(pres)
            hi = int(nz[-1]) if nz.size else 0
            nbins[j] = min(hi + 1, int(caps[j]) - 1) + 1  # +1 overflow bin
            cat_counts[j] = int(pres.sum())
            continue
        col = X[:, j]
        col = col[~np.isnan(col)]
        # features with NaN reserve one bin; real values get one fewer
        real_cap = int(caps[j]) - 1 if has_nan[j] else int(caps[j])
        uniq = np.unique(col)
        if uniq.size <= 1:
            nbins[j] = 2 + int(has_nan[j])
            continue
        if uniq.size <= real_cap - 1:
            # few distinct values: boundary at midpoints → exact value bins
            b = (uniq[:-1] + uniq[1:]) * 0.5
        else:
            qs = np.linspace(0.0, 1.0, real_cap)[1:-1]
            b = np.unique(np.quantile(col, qs).astype(np.float32))
        if min_data_in_bin > 1 and b.size:
            # merge bins whose SAMPLE occupancy is below min_data_in_bin
            # (LightGBM minDataPerBin): drop a boundary when the bin it
            # closes is under-filled
            # right-closed counting (x <= boundary belongs to the LEFT bin),
            # matching apply_bins' searchsorted side='left' semantics
            counts = np.bincount(np.searchsorted(b, col, side="left"),
                                 minlength=b.size + 1)
            keep = []
            acc = 0
            for bi in range(b.size):
                acc += counts[bi]
                if acc >= min_data_in_bin:
                    keep.append(bi)
                    acc = 0
            # the trailing (overflow) bin may be under-filled: merge backward
            if keep and counts[b.size] + acc < min_data_in_bin:
                keep.pop()
            b = b[keep]
        bounds[j, : b.size] = b
        # bins: b.size+1 real-value bins (+1 overflow shares the last), plus a
        # dedicated NaN bin when the feature has missing values
        nbins[j] = b.size + 2 + int(has_nan[j])
    return BinMapper(boundaries=bounds, num_bins=nbins, is_categorical=cat,
                     max_bin=max_bin, has_nan=has_nan, cat_counts=cat_counts)


class StreamingQuantileSketch:
    """One-pass bin-boundary builder for out-of-core ingest (gbdt/stream.py):
    feed row chunks through :meth:`update` / :meth:`update_csr` in any number
    of passes-of-one, then :meth:`finalize` into a :class:`BinMapper`.

    Two regimes, switched automatically:

    * **Exact-parity fallback** — while the stream holds at most
      ``sample_count`` rows, every row is buffered and ``finalize()`` runs
      :func:`compute_bin_mapper` over the full buffered matrix: boundaries
      are BIT-IDENTICAL to the resident path's (same rows, same algorithm),
      so fits-in-memory data streams with zero model drift.
    * **Reservoir sketch** — past ``sample_count`` rows the buffer becomes a
      seeded uniform row reservoir (Vitter's algorithm R, vectorized per
      chunk). For a reservoir of m rows, every empirical quantile of the
      sample is within eps = sqrt(ln(2/delta) / (2m)) of the stream's true
      quantile with probability 1-delta (DKW inequality) — at the default
      m=200k, eps ≈ 0.6% rank error at delta=1e-3, far inside one bin of a
      255-bin ladder. This mirrors LightGBM's own boundary-from-sample
      design (binSampleCount), just fed streamwise.

    Missing-ness and categorical bin occupancy are tracked EXACTLY over the
    FULL stream (an O(F) bitmap OR per chunk) and passed to
    :func:`compute_bin_mapper` as overrides, so NaN-bin election and the
    maxCatToOnehot one-vs-rest decision never depend on which rows the
    reservoir kept — the same contract the sparse and multi-process paths
    already hold."""

    def __init__(self, num_features: int, max_bin: int = 255,
                 sample_count: int = 200_000,
                 categorical_features: Optional[Sequence[int]] = None,
                 seed: int = 0, min_data_in_bin: int = 3,
                 max_bin_by_feature: Optional[Sequence[int]] = None):
        self.num_features = int(num_features)
        self.max_bin = int(max_bin)
        self.sample_count = int(sample_count)
        self.categorical_features = (list(categorical_features)
                                     if categorical_features else [])
        self.seed = int(seed)
        self.min_data_in_bin = int(min_data_in_bin)
        self.max_bin_by_feature = max_bin_by_feature
        self.rows_seen = 0
        self._buf = np.empty((min(self.sample_count, 4096), num_features),
                             np.float32)
        self._filled = 0
        self._overflowed = False
        self._rng = np.random.default_rng(self.seed)
        self._has_nan = np.zeros(num_features, bool)
        self._cat_pres = (np.zeros((num_features, self.max_bin), bool)
                          if self.categorical_features else None)

    def _reserve(self, extra: int) -> None:
        need = min(self._filled + extra, self.sample_count)
        if need > self._buf.shape[0]:
            cap = self._buf.shape[0]
            while cap < need:
                cap *= 2
            cap = min(cap, self.sample_count)
            self._buf = np.concatenate(
                [self._buf, np.empty((cap - self._buf.shape[0],
                                      self.num_features), np.float32)])

    def update(self, X: np.ndarray) -> "StreamingQuantileSketch":
        X = np.atleast_2d(np.asarray(X, np.float32))
        if X.shape[1] != self.num_features:
            raise ValueError(f"chunk has {X.shape[1]} features, sketch was "
                             f"built for {self.num_features}")
        c = X.shape[0]
        if c == 0:
            return self
        # exact full-stream stats (independent of the sampling regime)
        self._has_nan |= np.isnan(X).any(axis=0)
        if self._cat_pres is not None:
            for j in self.categorical_features:
                self._cat_pres[j] |= cat_presence_bitmap(X[:, j], self.max_bin)
        t0 = self.rows_seen
        self.rows_seen += c
        take_direct = min(c, self.sample_count - self._filled)
        if take_direct > 0:
            self._reserve(take_direct)
            self._buf[self._filled:self._filled + take_direct] = \
                X[:take_direct]
            self._filled += take_direct
        if take_direct < c:
            # reservoir regime (algorithm R, vectorized): row at global
            # index t replaces a uniform slot with probability m/(t+1)
            self._overflowed = True
            m = self.sample_count
            rest = X[take_direct:]
            t = t0 + take_direct + np.arange(rest.shape[0], dtype=np.int64)
            slot = (self._rng.random(rest.shape[0]) * (t + 1)).astype(
                np.int64)
            hit = np.flatnonzero(slot < m)
            # sequential assignment keeps algorithm-R semantics when two
            # chunk rows draw the same slot (the later row must win)
            for i in hit:
                self._buf[slot[i]] = rest[i]
        return self

    def update_csr(self, data, rows, cols, n_rows: int
                   ) -> "StreamingQuantileSketch":
        """Sparse chunk intake: densify host-side (implicit zeros ARE zeros,
        matching the CSR binning semantics of :class:`CsrBinner`) and feed
        the dense chunk through :meth:`update`. Chunk-sized, not
        dataset-sized — the whole point of the streamed sparse path."""
        X = np.zeros((int(n_rows), self.num_features), np.float32)
        X[np.asarray(rows, np.int64), np.asarray(cols, np.int64)] = \
            np.asarray(data, np.float32)
        return self.update(X)

    @property
    def exact(self) -> bool:
        """True while finalize() is bit-identical to the resident
        compute_bin_mapper over the full stream."""
        return not self._overflowed

    def finalize(self) -> BinMapper:
        if self.rows_seen == 0:
            raise ValueError("finalize() on an empty sketch: no rows seen")
        sample = self._buf[:self._filled]
        return compute_bin_mapper(
            sample, self.max_bin,
            # the buffer IS the sample — never re-subsample it
            sample_count=max(self._filled, 1),
            categorical_features=self.categorical_features or None,
            seed=self.seed, has_nan=self._has_nan,
            min_data_in_bin=self.min_data_in_bin,
            max_bin_by_feature=self.max_bin_by_feature,
            cat_presence=self._cat_pres)


@partial(jax.jit, static_argnames=("out_dtype",))
def _apply_bins_numeric(X: jnp.ndarray, boundaries: jnp.ndarray, out_dtype=jnp.uint8):
    def bin_one_feature(col, bounds):
        return jnp.searchsorted(bounds, col, side="left")

    binned = jax.vmap(bin_one_feature, in_axes=(1, 0), out_axes=1)(X, boundaries)
    return binned.astype(out_dtype)


def apply_bins(mapper: BinMapper, X) -> jnp.ndarray:
    """(N, F) raw floats → (N, F) bin ids. Non-NaN overflow clamps into the
    last REAL-value bin; NaN goes to the feature's dedicated NaN bin when it
    has one (else the last bin, the legacy always-right behavior)."""
    dtype = jnp.uint8 if mapper.max_bin <= 256 else jnp.uint16
    X = jnp.asarray(X, jnp.float32)
    binned = _apply_bins_numeric(X, jnp.asarray(mapper.boundaries), dtype)
    nan_mask = jnp.asarray(mapper.nan_mask)
    isnan = jnp.isnan(X)
    # clamp real values into the feature's real-value bin range
    real_limit = jnp.asarray(
        mapper.num_bins - 1 - mapper.nan_mask.astype(np.int32), np.int32)
    binned = jnp.minimum(binned.astype(jnp.int32), real_limit[None, :])
    # NaN → dedicated NaN bin (num_bins-1) for has_nan features
    nanbin = jnp.asarray(mapper.num_bins - 1, np.int32)
    binned = jnp.where(isnan & nan_mask[None, :], nanbin[None, :], binned)
    binned = binned.astype(dtype)
    if mapper.is_categorical.any():
        cats = jnp.asarray(mapper.is_categorical)
        limit = jnp.asarray(mapper.num_bins - 1, binned.dtype)
        ident = jnp.clip(jnp.nan_to_num(X, nan=0.0), 0, mapper.max_bin - 1).astype(binned.dtype)
        ident = jnp.minimum(ident, limit[None, :])
        binned = jnp.where(cats[None, :], ident, binned)
    return binned


@partial(jax.jit, static_argnames=("n_rows", "out_dtype"))
def _bin_csr_entries(data, rows, cols, zero_bins, boundaries, real_limit,
                     nan_mask, nan_bin, is_cat, max_bin, n_rows,
                     out_dtype=jnp.uint8):
    """Device-side CSR chunk binning: O(F) broadcast of each feature's
    zero-bin + O(nnz) per-entry searchsorted and scatter — implicit zeros
    never materialize (the dense detour binned rows x F values regardless of
    density). Semantics identical to :func:`apply_bins` per entry."""
    f = boundaries.shape[0]
    # per-entry numeric bin against the entry's feature boundaries
    b = jax.vmap(lambda v, c: jnp.searchsorted(boundaries[c], v,
                                               side="left"))(data, cols)
    b = jnp.minimum(b.astype(jnp.int32), real_limit[cols])
    isnan = jnp.isnan(data)
    b = jnp.where(isnan & nan_mask[cols], nan_bin[cols], b)
    # categorical identity binning (clip into [0, num_bins-1])
    cat_limit = real_limit + nan_mask.astype(jnp.int32)  # = num_bins - 1
    identb = jnp.minimum(
        jnp.clip(jnp.nan_to_num(data, nan=0.0), 0,
                 max_bin - 1).astype(jnp.int32), cat_limit[cols])
    b = jnp.where(is_cat[cols], identb, b)
    out = jnp.broadcast_to(zero_bins[None, :].astype(out_dtype), (n_rows, f))
    return out.at[rows, cols].set(b.astype(out_dtype))


class CsrBinner:
    """Device-side CSR chunk binning with the mapper state shipped ONCE:
    boundaries / limits / masks / the zero-bin row are chunk-invariant, and
    an 11M-row ingest makes hundreds of chunk calls — re-uploading them per
    chunk would spend the transfer budget the sparse path exists to save.
    nnz pads to power-of-2 buckets (pad rows point out of bounds → dropped
    by the scatter) so varying chunk occupancy reuses a handful of compiled
    programs instead of one per nnz."""

    def __init__(self, mapper: BinMapper):
        self.max_bin = mapper.max_bin
        self.dtype = jnp.uint8 if mapper.max_bin <= 256 else jnp.uint16
        self.zero = apply_bins(mapper, np.zeros((1, mapper.num_features),
                                                np.float32))[0]
        self.boundaries = jnp.asarray(mapper.boundaries)
        self.real_limit = jnp.asarray(
            mapper.num_bins - 1 - mapper.nan_mask.astype(np.int32), jnp.int32)
        self.nan_mask = jnp.asarray(mapper.nan_mask)
        self.nan_bin = jnp.asarray(np.asarray(mapper.num_bins, np.int32) - 1)
        self.is_cat = jnp.asarray(mapper.is_categorical)

    def __call__(self, data, rows, cols, n_rows) -> jnp.ndarray:
        nnz = len(data)
        cap = max(1024, 1 << max(nnz - 1, 1).bit_length())
        pad = cap - nnz
        data = np.pad(np.asarray(data, np.float32), (0, pad))
        rows = np.pad(np.asarray(rows, np.int32), (0, pad),
                      constant_values=n_rows)   # OOB scatter index: no-op
        cols = np.pad(np.asarray(cols, np.int32), (0, pad))
        return _bin_csr_entries(
            jnp.asarray(data), jnp.asarray(rows), jnp.asarray(cols),
            self.zero, self.boundaries, self.real_limit, self.nan_mask,
            self.nan_bin, self.is_cat, self.max_bin, n_rows,
            out_dtype=self.dtype)


def bin_csr_chunk(mapper: BinMapper, data, rows, cols, n_rows) -> jnp.ndarray:
    """One-shot convenience wrapper; loops should hold a :class:`CsrBinner`."""
    return CsrBinner(mapper)(data, rows, cols, n_rows)


def bin_threshold_to_value(mapper: BinMapper, feature: int, bin_id: int) -> float:
    """Real-valued split threshold for a numeric split at ``bin_id`` (the stored
    LightGBM model threshold, i.e. the bin's upper boundary). A threshold at or
    beyond the last real-value bin means "every non-missing value goes left"
    (only reachable for features with a NaN bin, where the right child holds
    the missing rows). Serialized as a large FINITE double (1e308) so model
    strings stay parseable everywhere (LightGBM also emits finite doubles
    for top-bin thresholds) while x <= threshold holds for every real x."""
    b = mapper.boundaries[feature]
    if bin_id < len(b) and np.isfinite(b[bin_id]):
        return float(b[bin_id])
    return 1e308
