"""Quantile bin mapper — the "reference dataset" concept on TPU.

The reference computes LightGBM bin boundaries on the driver from a row sample and
broadcasts a serialized reference dataset to all workers (LightGBMBase.scala:509-550,
dataset/ReferenceDatasetUtils.scala, dataset/SampledData.scala). Here the bin
boundaries are computed host-side with numpy from a sample (exact same role), and
binning itself is a jitted XLA op so the (N, F) → (N, F) uint8/uint16 quantized
matrix is produced TPU-resident.

Bin semantics (matching LightGBM's BinMapper):
  * boundaries[f] is a sorted vector of bin upper bounds (length <= max_bin - 1);
    bin(x) = first i with x <= boundaries[f][i]; x beyond all bounds → last bin.
  * NaN → last bin (missing handled as "always right of any split"; LightGBM's
    learned default_left is not implemented — documented deviation).
  * categorical features use the category's integer value as its bin, capped by
    max_bin; rare categories overflow into bin 0.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class BinMapper(NamedTuple):
    """Per-feature binning metadata. ``boundaries`` is padded to a rectangle
    (num_features, max_bin-1) with +inf so it ships to device as one array."""

    boundaries: np.ndarray      # (F, max_bin-1) float32, +inf padded
    num_bins: np.ndarray        # (F,) int32 — actual bin count per feature
    is_categorical: np.ndarray  # (F,) bool
    max_bin: int

    @property
    def num_features(self) -> int:
        return self.boundaries.shape[0]

    @property
    def total_bins(self) -> int:
        return self.max_bin


def compute_bin_mapper(
    X: np.ndarray,
    max_bin: int = 255,
    sample_count: int = 200_000,
    categorical_features: Optional[Sequence[int]] = None,
    seed: int = 0,
) -> BinMapper:
    """Driver-side boundary computation from a sample (the analog of
    LightGBMBase.getSampledRows + LGBM_DatasetCreateFromSampledColumn;
    binSampleCount param default 200000 — params/LightGBMParams.scala)."""
    X = np.asarray(X, dtype=np.float32)
    n, f = X.shape
    cat = np.zeros(f, dtype=bool)
    if categorical_features:
        cat[list(categorical_features)] = True

    if n > sample_count:
        rng = np.random.default_rng(seed)
        X = X[rng.choice(n, size=sample_count, replace=False)]

    bounds = np.full((f, max_bin - 1), np.inf, dtype=np.float32)
    nbins = np.zeros(f, dtype=np.int32)
    for j in range(f):
        col = X[:, j]
        col = col[~np.isnan(col)]
        if cat[j]:
            # categories are small non-negative ints; identity binning capped at max_bin
            hi = int(col.max()) if col.size else 0
            nbins[j] = min(hi + 1, max_bin - 1) + 1  # +1 for the NaN/overflow bin
            continue
        uniq = np.unique(col)
        if uniq.size <= 1:
            nbins[j] = 2
            continue
        if uniq.size <= max_bin - 1:
            # few distinct values: boundary at midpoints → exact value bins
            b = (uniq[:-1] + uniq[1:]) * 0.5
        else:
            qs = np.linspace(0.0, 1.0, max_bin)[1:-1]
            b = np.unique(np.quantile(col, qs).astype(np.float32))
        bounds[j, : b.size] = b
        nbins[j] = b.size + 2  # values beyond last bound + NaN share the last bin
    return BinMapper(boundaries=bounds, num_bins=nbins, is_categorical=cat, max_bin=max_bin)


@partial(jax.jit, static_argnames=("out_dtype",))
def _apply_bins_numeric(X: jnp.ndarray, boundaries: jnp.ndarray, out_dtype=jnp.uint8):
    def bin_one_feature(col, bounds):
        return jnp.searchsorted(bounds, col, side="left")

    binned = jax.vmap(bin_one_feature, in_axes=(1, 0), out_axes=1)(X, boundaries)
    return binned.astype(out_dtype)


def apply_bins(mapper: BinMapper, X) -> jnp.ndarray:
    """(N, F) raw floats → (N, F) bin ids. NaN and +inf overflow land in the last
    usable bin (searchsorted over +inf-padded bounds returns the pad start; NaN
    compares false with every bound and also returns the end)."""
    dtype = jnp.uint8 if mapper.max_bin <= 256 else jnp.uint16
    X = jnp.asarray(X, jnp.float32)
    binned = _apply_bins_numeric(X, jnp.asarray(mapper.boundaries), dtype)
    # clamp into each feature's actual bin range (NaN/overflow → num_bins-1)
    limit = jnp.asarray(mapper.num_bins - 1, binned.dtype)
    binned = jnp.minimum(binned, limit[None, :])
    if mapper.is_categorical.any():
        cats = jnp.asarray(mapper.is_categorical)
        ident = jnp.clip(jnp.nan_to_num(X, nan=0.0), 0, mapper.max_bin - 1).astype(binned.dtype)
        ident = jnp.minimum(ident, limit[None, :])
        binned = jnp.where(cats[None, :], ident, binned)
    return binned


def bin_threshold_to_value(mapper: BinMapper, feature: int, bin_id: int) -> float:
    """Real-valued split threshold for a numeric split at ``bin_id`` (the stored
    LightGBM model threshold, i.e. the bin's upper boundary)."""
    b = mapper.boundaries[feature]
    if bin_id < len(b) and np.isfinite(b[bin_id]):
        return float(b[bin_id])
    finite = b[np.isfinite(b)]
    return float(finite[-1]) if finite.size else 0.0
