"""Image decode + TPU-resident preprocessing ops.

Parity target: the reference's OpenCV-backed ImageTransformer stage set
(opencv/src/main/scala/.../ImageTransformer.scala:31-283 — ResizeImage,
CropImage, CenterCropImage, ColorFormat, Flip, Blur, Threshold, GaussianKernel;
CHW tensor conversion + per-channel normalization at :654-684). Decode runs
host-side (PIL / torchvision io); everything after decode is jax so the tensors
land on-device and fuse — the "feed TPU directly" north star of SURVEY §2.1 N4.

All device ops operate on float32 NHWC batches in [0,1].
"""

from __future__ import annotations

import io
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------------------------
# host-side decode (ImageTransformer's decode modes :702-710: image schema /
# binary file / raw bytes)
# --------------------------------------------------------------------------

def decode_image_bytes(data: bytes, size: Optional[int] = None) -> np.ndarray:
    """JPEG/PNG bytes → HWC uint8 RGB."""
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    if size:
        img = img.resize((size, size), Image.BILINEAR)
    return np.asarray(img, np.uint8)


def decode_image_files(paths: Sequence[str], size: Optional[int] = None) -> np.ndarray:
    imgs = [decode_image_bytes(open(p, "rb").read(), size) for p in paths]
    if size is None:
        shapes = {im.shape for im in imgs}
        if len(shapes) > 1:
            raise ValueError(f"images have mixed shapes {shapes}; pass a resize size")
    return np.stack(imgs)


# --------------------------------------------------------------------------
# device-side ops (jit; NHWC float32)
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("height", "width", "method"))
def resize(images: jnp.ndarray, height: int, width: int, method: str = "bilinear"):
    """ResizeImage analog (ImageTransformer.scala:88-118)."""
    n, _, _, c = images.shape
    return jax.image.resize(images, (n, height, width, c), method=method)


@partial(jax.jit, static_argnames=("x", "y", "height", "width"))
def crop(images: jnp.ndarray, x: int, y: int, height: int, width: int):
    """CropImage analog (:120-149): rectangle at (x, y)."""
    return jax.lax.dynamic_slice(images, (0, y, x, 0),
                                 (images.shape[0], height, width, images.shape[3]))


@partial(jax.jit, static_argnames=("height", "width"))
def center_crop(images: jnp.ndarray, height: int, width: int):
    """CenterCropImage analog (:151-180)."""
    h, w = images.shape[1], images.shape[2]
    y = max((h - height) // 2, 0)
    x = max((w - width) // 2, 0)
    return crop(images, x, y, min(height, h), min(width, w))


@partial(jax.jit, static_argnames=("flip_code",))
def flip(images: jnp.ndarray, flip_code: int = 1):
    """Flip analog (:216-235). OpenCV codes: 0 vertical, >0 horizontal, <0 both."""
    if flip_code == 0:
        return images[:, ::-1]
    if flip_code > 0:
        return images[:, :, ::-1]
    return images[:, ::-1, ::-1]


def gaussian_kernel(aperture: int, sigma: float) -> jnp.ndarray:
    """GaussianKernel analog (:260-283)."""
    r = (aperture - 1) / 2.0
    xs = jnp.arange(aperture) - r
    k1 = jnp.exp(-(xs ** 2) / (2 * sigma ** 2))
    k = jnp.outer(k1, k1)
    return k / k.sum()


@partial(jax.jit, static_argnames=("ksize",))
def blur(images: jnp.ndarray, ksize: int = 3, sigma: float = 1.0):
    """Blur analog (:182-199) as a depthwise gaussian conv (MXU-friendly)."""
    k = gaussian_kernel(ksize, sigma)
    c = images.shape[-1]
    kern = jnp.tile(k[:, :, None, None], (1, 1, 1, c))   # HWIO, feature_group=c
    return jax.lax.conv_general_dilated(
        images, kern, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


@jax.jit
def threshold(images: jnp.ndarray, thresh: float, maxval: float = 1.0):
    """Threshold analog (:237-258), THRESH_BINARY."""
    return jnp.where(images > thresh, maxval, 0.0)


@jax.jit
def color_to_gray(images: jnp.ndarray):
    """ColorFormat(GRAY) analog (:201-214), ITU-R 601 luma."""
    w = jnp.array([0.299, 0.587, 0.114], images.dtype)
    return (images * w[None, None, None, :]).sum(-1, keepdims=True)


@jax.jit
def normalize(images: jnp.ndarray, mean, std, scale: float = 1.0):
    """Per-channel normalize + global scale (tensor output path :654-684)."""
    mean = jnp.asarray(mean, images.dtype)
    std = jnp.asarray(std, images.dtype)
    return (images * scale - mean[None, None, None, :]) / std[None, None, None, :]


@jax.jit
def to_chw(images: jnp.ndarray):
    """NHWC → NCHW tensor output (toTensor path :654-684). On TPU NHWC is the
    native layout; CHW is provided for reference-schema compatibility only."""
    return jnp.transpose(images, (0, 3, 1, 2))
