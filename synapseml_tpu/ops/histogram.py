"""Gradient/hessian histogram accumulation — the GBDT hot loop.

The reference delegates this to LightGBM C++ (ConstructHistograms inside
LGBM_BoosterUpdateOneIter, driven from booster/LightGBMBooster.scala:355-392, with
bin reduce-scatter/allreduce over its native TCP ring in data_parallel mode —
SURVEY.md §2.2). Here it is a single XLA scatter-add keyed by
(leaf, feature, bin): each row contributes its (grad, hess, 1) triple to every
feature's bin of the leaf the row currently sits in.

Sharding: when rows are sharded over the ``data`` mesh axis and the output is
requested replicated, GSPMD inserts the cross-chip psum of the partial histograms
automatically — that ONE compiler-inserted collective over ICI is the entire
replacement for LightGBM's socket ring. ``sharded_histogram_fn`` builds the
explicitly-annotated version for multi-chip use.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS


def leaf_histograms(
    binned: jnp.ndarray,    # (N, F) uint8/uint16 bin ids
    node_of_row: jnp.ndarray,  # (N,) int32 current leaf of each row
    grad: jnp.ndarray,      # (N,) f32
    hess: jnp.ndarray,      # (N,) f32
    num_leaves: int,
    num_bins: int,
) -> jnp.ndarray:
    """→ (num_leaves, F, num_bins, 3) f32: per-leaf per-feature histograms of
    [sum_grad, sum_hess, count]. Rows with node_of_row < 0 are ignored
    (out-of-bounds scatter index → dropped), which is how padding rows and
    bagged-out rows are masked for free."""
    n, f = binned.shape
    vals = jnp.stack([grad, hess, jnp.ones_like(grad)], axis=-1)  # (N, 3)
    hist = jnp.zeros((num_leaves, f, num_bins, 3), jnp.float32)
    feat_idx = jnp.arange(f, dtype=jnp.int32)[None, :]            # (1, F)
    node = node_of_row.astype(jnp.int32)[:, None]                 # (N, 1)
    hist = hist.at[node, feat_idx, binned.astype(jnp.int32), :].add(
        vals[:, None, :], mode="drop")
    return hist


def sharded_histogram_fn(mesh: Mesh, num_leaves: int, num_bins: int):
    """Jitted histogram builder for row-sharded inputs on ``mesh``: inputs sharded
    on the data axis, output replicated — XLA materializes the partial-histogram
    psum over ICI (the LGBM histogram allreduce analog)."""
    row_sh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    row_sh1 = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    fn = partial(leaf_histograms, num_leaves=num_leaves, num_bins=num_bins)
    return jax.jit(fn, in_shardings=(row_sh2, row_sh1, row_sh1, row_sh1),
                   out_shardings=repl)
