"""Pallas TPU histogram kernel — the GBDT hot loop on the MXU.

The reference's hot loop is LightGBM C++ ``ConstructHistograms`` driven through
``LGBM_BoosterUpdateOneIter`` (booster/LightGBMBooster.scala:355-392): for every
row and feature, add (grad, hess, 1) into the (feature, bin) histogram slot.
TPUs have no fast scatter, so this kernel reformulates histogramming as a
**two-level one-hot matmul on the MXU**:

    bin = hi * 8 + lo                     (hi in [0, B/8), lo in [0, 8))
    LHS[hi, row]        = 1{bin_hi(row) == hi}          (B/8, C)  bf16
    RHS[row, ch*8 + lo] = 1{bin_lo(row) == lo} * val_ch (C, 24)   bf16
    out[hi, ch*8+lo]   += LHS @ RHS                     (B/8, 24) f32 accum

Each (row, feature) costs one 128x128 MXU output tile per C-row chunk — the
cheapest possible one-hot-matmul decomposition (a single-level one-hot needs
two tiles: M = B = 256). The one-hot factors are generated in VMEM registers
and never touch HBM; gradients are rounded to bf16 (exact 0/1 LHS, f32
accumulation), which matches the precision story of LightGBM's GPU float
histograms.

Numerically the result equals a scatter-add with bf16-rounded grad/hess. The
XLA fallback (`_hist_xla`) — used on CPU (tests' virtual mesh) and any
non-TPU backend — applies the same bf16 rounding so both paths agree bit-wise
in the accumulated sums up to f32 reduction order.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

FEATURE_BLOCK = 8     # features per kernel step (i32 sublane tile)
LANE = 128


def _eager_selftest(fn):
    """Escape any ambient trace for the duration of a selftest.

    Selftests compile+run tiny on-device programs and compare results as
    numpy — but their FIRST call can happen during an outer jit trace
    (``child_histogram`` is reached while the grower's ``lax.switch``
    branches trace). Under an active trace every jnp op — even on fresh
    concrete arrays — produces tracers of that trace, so ``np.asarray``
    raises TracerArrayConversionError (observed on-chip 2026-08-02: the
    bench's first ``train_booster`` trace died here, and
    ``_tpu_segmented_ok`` silently mis-cached False, degrading the
    segmented kernel). ``ensure_compile_time_eval`` runs the body eagerly
    regardless of tracing context; ``functools.cache`` stays outermost so
    the certified mode is computed once per process."""
    @functools.wraps(fn)
    def wrapper(*a, **k):
        with jax.ensure_compile_time_eval():
            return fn(*a, **k)
    return wrapper


def default_chunk() -> int:
    """Rows per kernel step. Resolution: SYNAPSEML_TPU_HIST_CHUNK env > the
    on-chip sweep winner in docs/tuned_defaults.json (tools/perf_tune.py
    phase D; applied only under the TPU backend — core/tuned.py) > 2048.
    A malformed env value fails HERE with the variable named, not as a
    ZeroDivisionError mid-trace (file values are validated on read)."""
    from ..core import tuned as _tuned

    v = _tuned.tuned_default("hist_chunk", "SYNAPSEML_TPU_HIST_CHUNK", 2048)
    try:
        c = int(v)
        if c <= 0:
            raise ValueError
    except (TypeError, ValueError):
        raise ValueError(
            f"SYNAPSEML_TPU_HIST_CHUNK={v!r}: want a positive integer "
            "(kernel rows per grid step)") from None
    return c


def pad_bins(max_bin: int) -> int:
    """Kernel bin-space size: power of two >= max_bin, at least 256 (so hi fits
    the MXU sublane dim and lo is exactly 3 bits)."""
    b = 256
    while b < max_bin:
        b *= 2
    return b


def features_padded(f: int) -> int:
    return -(-f // FEATURE_BLOCK) * FEATURE_BLOCK


def _kernel(bin_ref, g_ref, h_ref, m_ref, out_ref, *, C: int, K1: int,
            FB: int, PACK: int):
    """Grid (feature_blocks, row_chunks). bin_ref (FB, C) i32,
    g/h/m (C,) f32, out (FB, K1, 24) f32 accumulated over chunks.

    PACK features share ONE dot: LHS (PACK*K1, C) stacks each feature's
    hi-one-hot along M and RHS (C, PACK*24) stacks each feature's
    lo-masked values along N, so one K-step streams PACK row-features
    through the MXU instead of one. The dot computes all PACK^2 cross
    blocks; only the diagonal blocks are histograms and the rest is
    discarded — the off-diagonal MACs ride the same cycles for free
    (the MXU is K-serialized: cost is C cycles per tile-pass regardless
    of how much of the 128x128 tile is useful). With K1=32, PACK=4 fills
    M=128, N=96 — one full tile-pass per K-step, ~4x the row-feature
    throughput of the per-feature formulation."""
    from jax.experimental import pallas as pl  # deferred: CPU never imports

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    _packed_accumulate(bin_ref, out_ref, g_ref[:], h_ref[:], m_ref[:],
                       C=C, K1=K1, FB=FB, PACK=PACK)


def _packed_accumulate(bin_ref, out_ref, g1, h1, m1, *, C: int, K1: int,
                       FB: int, PACK: int):
    """Shared MXU pack body for both kernels: g1/h1/m1 are (C,) f32 value
    channels (already edge-masked by the segmented caller). All construction
    stays 2D (Mosaic-friendly: no cross-tile reshapes or gathers):
    per-position feature/hi/lo/channel ids come from iota math, and the
    per-feature bin rows are selected with PACK static where-terms."""
    from jax.experimental import pallas as pl

    M, N = PACK * K1, PACK * 24
    mf = lax.broadcasted_iota(jnp.int32, (M, C), 0) // K1        # row feature
    hi_pat = lax.broadcasted_iota(jnp.int32, (M, C), 0) % K1
    col = lax.broadcasted_iota(jnp.int32, (C, N), 1)
    nf = col // 24                                               # col feature
    rem = col - nf * 24
    ch_pat = rem >> 3
    lo_pat = rem & 7
    g2, h2, m2 = g1[:, None], h1[:, None], m1[:, None]
    val = jnp.where(ch_pat == 0, g2, jnp.where(ch_pat == 1, h2, m2))

    def pbody(p, _):
        bins_rows = jnp.zeros((M, C), jnp.int32)
        bins_cols = jnp.zeros((C, N), jnp.int32)
        for f in range(PACK):
            bf = bin_ref[pl.ds(p * PACK + f, 1), :]              # (1, C)
            bins_rows = jnp.where(mf == f, bf, bins_rows)
            bins_cols = jnp.where(nf == f, bf.T, bins_cols)
        lhs = (hi_pat == (bins_rows >> 3)).astype(jnp.bfloat16)
        rhs = jnp.where(lo_pat == (bins_cols & 7), val, 0.0
                        ).astype(jnp.bfloat16)
        acc = jnp.dot(lhs, rhs, preferred_element_type=jnp.float32)
        for f in range(PACK):                                    # diagonal
            blk = acc[f * K1:(f + 1) * K1, f * 24:(f + 1) * 24]
            out_ref[pl.ds(p * PACK + f, 1)] += blk[None]
        return 0

    lax.fori_loop(0, FB // PACK, pbody, 0)


def _pack_for(K1: int, FB: int, pack) -> int:
    """Features per dot: fill the 128-row MXU tile (M = PACK*K1) while
    keeping N = PACK*24 within one 128-lane tile; PACK must divide FB.
    ``pack`` (arg > SYNAPSEML_TPU_HIST_PACK env > tuned file) forces —
    clamped to the same tile constraints (128 // K1, 5, FB) so a forced
    value can never lose the one-tile-pass property the kernel docstring
    promises."""
    from ..core import tuned as _tuned

    force = pack or _tuned.tuned_default("hist_pack",
                                         "SYNAPSEML_TPU_HIST_PACK", None)
    return clamp_pack(int(force) if force else 128, K1, FB)


def clamp_pack(want: int, K1: int, FB: int) -> int:
    """The pure tile clamp shared by _pack_for and the tuner's
    formula-default computation (tools/perf_tune.py) — one copy of the
    constraint math, so the two sides cannot desync."""
    PACK = max(1, min(want, 128 // K1, 5, FB))
    while FB % PACK:
        PACK -= 1
    return PACK


def _epilogue(out, FP: int, K1: int, num_bins_padded: int):
    # columns are (ch, lo): (FP, K1, 3, 8) -> (FP, K1, 8, 3) -> (FP, B, 3)
    return out.reshape(FP, K1, 3, 8).transpose(0, 1, 3, 2).reshape(
        FP, num_bins_padded, 3)


@functools.partial(jax.jit,
                   static_argnames=("num_bins_padded", "chunk", "interpret",
                                    "feature_block", "pack"))
def _hist_pallas(bT, g, h, m, num_bins_padded: int, chunk: int = None,
                 interpret: bool = False, feature_block: int = None,
                 pack: int = None):
    from jax.experimental import pallas as pl

    FP, n = bT.shape
    C = min(chunk or default_chunk(), n)
    FB = feature_block or FEATURE_BLOCK
    assert n % C == 0 and FP % FB == 0
    K1 = num_bins_padded // 8
    PACK = _pack_for(K1, FB, pack)
    out = pl.pallas_call(
        functools.partial(_kernel, C=C, K1=K1, FB=FB, PACK=PACK),
        grid=(FP // FB, n // C),
        in_specs=[
            pl.BlockSpec((FB, C), lambda f, c: (f, c)),
            pl.BlockSpec((C,), lambda f, c: (c,)),
            pl.BlockSpec((C,), lambda f, c: (c,)),
            pl.BlockSpec((C,), lambda f, c: (c,)),
        ],
        out_specs=pl.BlockSpec((FB, K1, 24), lambda f, c: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((FP, K1, 24), jnp.float32),
        interpret=interpret,
    )(bT, g, h, m)
    # columns are (ch, lo): (FP, K1, 3, 8) -> (FP, K1, 8, 3) -> (FP, B, 3)
    return _epilogue(out, FP, K1, num_bins_padded)


def _range_kernel(info_ref, bin_ref, g_ref, h_ref, m_ref, out_ref, *,
                  C: int, K1: int, FB: int, PACK: int):
    """Segmented variant of :func:`_kernel`: the grid's row-chunk dimension
    starts at the block index derived from the scalar-prefetched
    ``info = [start, length]`` (see the index_maps in _hist_pallas_range),
    and edge rows outside [start, start+length) are masked HERE — so the
    caller passes the FULL row arrays and no dynamic_slice copy or
    pre-kernel mask multiply exists at all."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    start, length = info_ref[0], info_ref[1]
    n_chunks = pl.num_programs(1)
    total = jnp.int32(C) * n_chunks
    first_chunk = jnp.minimum(start // C,
                              (info_ref[2] - total) // C)   # info[2] = Np
    row0 = (first_chunk + pl.program_id(1)) * C
    rows = row0 + lax.broadcasted_iota(jnp.int32, (C,), 0)
    inr = ((rows >= start) & (rows < start + length)).astype(jnp.float32)

    _packed_accumulate(bin_ref, out_ref, g_ref[:] * inr, h_ref[:] * inr,
                       m_ref[:] * inr, C=C, K1=K1, FB=FB, PACK=PACK)


@functools.partial(jax.jit,
                   static_argnames=("num_bins_padded", "size", "chunk",
                                    "interpret", "feature_block", "pack"))
def _hist_pallas_range(bT, g, h, m, start, length, num_bins_padded: int,
                       size: int, chunk: int = None, interpret: bool = False,
                       feature_block: int = None, pack: int = None):
    """Histogram of rows [start, start+length) of the FULL (FP, Np) arrays.
    ``size`` (static) is the covered extent: a multiple of the chunk with
    size >= length + chunk, so the chunk-aligned window starting at or
    before ``start`` always covers the range (edge rows masked in-kernel).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FP, n = bT.shape
    C = min(chunk or default_chunk(), n)
    FB = feature_block or FEATURE_BLOCK
    assert n % C == 0 and FP % FB == 0 and size % C == 0 and size <= n
    K1 = num_bins_padded // 8
    PACK = _pack_for(K1, FB, pack)
    info = jnp.stack([jnp.asarray(start, jnp.int32),
                      jnp.asarray(length, jnp.int32),
                      jnp.asarray(n, jnp.int32)])

    def row_block(f, c, info_ref):
        first = jnp.minimum(info_ref[0] // C, jnp.int32((n - size) // C))
        return first + c

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(FP // FB, size // C),
        in_specs=[
            pl.BlockSpec((FB, C), lambda f, c, i: (f, row_block(f, c, i))),
            pl.BlockSpec((C,), lambda f, c, i: (row_block(f, c, i),)),
            pl.BlockSpec((C,), lambda f, c, i: (row_block(f, c, i),)),
            pl.BlockSpec((C,), lambda f, c, i: (row_block(f, c, i),)),
        ],
        out_specs=pl.BlockSpec((FB, K1, 24), lambda f, c, i: (f, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_range_kernel, C=C, K1=K1, FB=FB, PACK=PACK),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((FP, K1, 24), jnp.float32),
        interpret=interpret,
    )(info, bT, g, h, m)
    return _epilogue(out, FP, K1, num_bins_padded)


def _level_kernel(starts_ref, bin_ref, g_ref, h_ref, m_ref, out_ref, *,
                  C: int, K1: int, FB: int, PACK: int, SLOTS: int):
    """Multi-leaf kernel: ONE pass over chunk-aligned slot-partitioned rows
    histograms EVERY slot (leaf) of a level. ``starts_ref`` (SLOTS+1,) i32
    holds each slot's first chunk index (ascending; starts[SLOTS] = total
    chunks). The output block for grid step (f, c) is the slot owning chunk
    c — computed by the same compare-sum in the index_map and here; the
    block is zero-initialized on the slot's first chunk. Slot-tail padding
    rows carry g=h=m=0, so no edge masking is needed."""
    from jax.experimental import pallas as pl

    c = pl.program_id(1)
    # first chunk of the owning slot ⇔ c equals ANY slot start (starts are
    # ascending and distinct — every slot has >= one chunk of capacity);
    # unrolled: dynamic indexing of the SMEM scalar ref is not supported
    is_first = c == starts_ref[0]
    for i in range(1, SLOTS):
        is_first |= c == starts_ref[i]

    @pl.when(is_first)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    _packed_accumulate(bin_ref, out_ref.at[0], g_ref[:], h_ref[:], m_ref[:],
                       C=C, K1=K1, FB=FB, PACK=PACK)


@functools.partial(jax.jit,
                   static_argnames=("num_bins_padded", "slots", "chunk",
                                    "interpret", "feature_block", "pack"))
def _hist_pallas_level(bT, g, h, m, start_chunks, num_bins_padded: int,
                       slots: int, chunk: int = None,
                       interpret: bool = False, feature_block: int = None,
                       pack: int = None):
    """(SLOTS, FP, B, 3) histograms of ALL slots in one kernel pass.
    ``bT``/``g``/``h``/``m`` are slot-partitioned with every slot starting
    at a chunk boundary (tail padding rows must carry zero g/h/m);
    ``start_chunks`` (slots,) i32 ascending first-chunk index per slot."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FP, n = bT.shape
    C = min(chunk or default_chunk(), n)
    FB = feature_block or FEATURE_BLOCK
    assert n % C == 0 and FP % FB == 0
    K1 = num_bins_padded // 8
    PACK = _pack_for(K1, FB, pack)
    total_chunks = n // C
    starts = jnp.concatenate([
        jnp.asarray(start_chunks, jnp.int32),
        jnp.full((1,), total_chunks, jnp.int32)])

    def slot_of(c, starts_ref):
        s = jnp.int32(0)
        for i in range(1, slots):
            s += (c >= starts_ref[i]).astype(jnp.int32)
        return s

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(FP // FB, total_chunks),
        in_specs=[
            pl.BlockSpec((FB, C), lambda f, c, st: (f, c)),
            pl.BlockSpec((C,), lambda f, c, st: (c,)),
            pl.BlockSpec((C,), lambda f, c, st: (c,)),
            pl.BlockSpec((C,), lambda f, c, st: (c,)),
        ],
        out_specs=pl.BlockSpec((1, FB, K1, 24),
                               lambda f, c, st: (slot_of(c, st), f, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_level_kernel, C=C, K1=K1, FB=FB, PACK=PACK,
                          SLOTS=slots),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, FP, K1, 24), jnp.float32),
        interpret=interpret,
    )(starts, bT, g, h, m)
    return jax.vmap(lambda o: _epilogue(o, FP, K1, num_bins_padded))(out)


def _hist_level_xla(bT, g, h, m, slot_of_row, num_bins_padded: int,
                    slots: int):
    """Scatter fallback of :func:`_hist_pallas_level` (CPU/tests): one
    scatter-add into (SLOTS, FP, B, 3) keyed by each row's slot."""
    FP, n = bT.shape
    vals = jnp.stack([g, h, m], -1).astype(jnp.bfloat16).astype(jnp.float32)
    hist = jnp.zeros((slots, FP, num_bins_padded, 3), jnp.float32)
    fidx = jnp.arange(FP, dtype=jnp.int32)[:, None]
    return hist.at[slot_of_row[None, :], fidx, bT.astype(jnp.int32), :].add(
        vals[None, :, :], mode="drop")


@functools.cache
@_eager_selftest
def _tpu_level_ok(num_bins_padded: int, slots: int, pack=None) -> bool:
    """On-device check of the multi-leaf level kernel (same insurance
    contract as _tpu_segmented_ok): False (or SYNAPSEML_TPU_LEVEL=0)
    degrades depthwise growth to the slot-keyed scatter fallback."""
    import numpy as _np

    try:
        C = default_chunk()
        caps = [2, 1, 3] + [1] * max(slots - 3, 0)
        caps = caps[:slots]
        total = sum(caps) * C
        rng = _np.random.default_rng(2)
        bT = _np.zeros((8, total), _np.int32)
        g = _np.zeros(total, _np.float32)
        h = _np.zeros(total, _np.float32)
        m = _np.zeros(total, _np.float32)
        starts, slot_row = [], _np.zeros(total, _np.int32)
        off = 0
        for i, cap in enumerate(caps):
            starts.append(off // C)
            ln = cap * C - 37 if cap else 0
            bT[:, off:off + ln] = rng.integers(
                0, num_bins_padded, size=(8, ln))
            g[off:off + ln] = rng.normal(size=ln)
            h[off:off + ln] = rng.uniform(0.5, 2.0, size=ln)
            m[off:off + ln] = 1.0
            slot_row[off:off + cap * C] = i
            off += cap * C
        got = _np.asarray(_hist_pallas_level(
            jnp.asarray(bT), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
            jnp.asarray(starts, jnp.int32), num_bins_padded, slots,
            pack=pack))
        want = _np.asarray(_hist_level_xla(
            jnp.asarray(bT), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
            jnp.asarray(slot_row), num_bins_padded, slots))
        return bool(_np.allclose(got[:3], want[:3], rtol=1e-4, atol=1e-3))
    except Exception:
        return False


def level_histograms(bT, g, h, m, start_chunks, slot_of_row,
                     num_bins_padded: int, slots: int):
    """(SLOTS, FP, B, 3) histograms of slot-partitioned rows in ONE pass:
    the multi-leaf Pallas kernel on TPU (chunk-aligned slots required;
    tail padding rows must carry zero g/h/m), the slot-keyed scatter
    fallback elsewhere.

    CONTRACT (Pallas path; ADVICE r3): ``start_chunks`` must be strictly
    ascending with every slot owning >= 1 chunk of capacity — the kernel
    zero-initializes a slot's output block only when the grid reaches that
    slot's FIRST chunk, so a zero-capacity slot's block is never visited and
    returns uninitialized VMEM garbage. Callers must mask outputs by their
    own shard-uniform existence vector (grower_depthwise does: its
    ``cap_chunks`` floors every live slot at 1 and ``exists`` masks the
    gains). The XLA fallback has no such constraint."""
    mode = (_tpu_kernel_selftest(num_bins_padded)
            if jax.default_backend() == "tpu" else "xla")
    pk = 1 if mode == "pack1" else None
    if (mode != "xla"
            and os.environ.get("SYNAPSEML_TPU_LEVEL", "1") != "0"
            and _tpu_level_ok(num_bins_padded, slots, pk)):
        return _hist_pallas_level(bT, g, h, m, start_chunks,
                                  num_bins_padded, slots, pack=pk)
    return _hist_level_xla(bT, g, h, m, slot_of_row, num_bins_padded, slots)


def _hist_xla(bT, g, h, m, num_bins_padded: int):
    """Scatter-add fallback with the same bf16 value rounding as the kernel."""
    FP, n = bT.shape
    vals = jnp.stack([g, h, m], -1).astype(jnp.bfloat16).astype(jnp.float32)
    hist = jnp.zeros((FP, num_bins_padded, 3), jnp.float32)
    fidx = jnp.arange(FP, dtype=jnp.int32)[:, None]
    return hist.at[fidx, bT.astype(jnp.int32), :].add(
        vals[None, :, :], mode="drop")


@functools.cache
@_eager_selftest
def _tpu_kernel_selftest(num_bins_padded: int) -> str:
    """One small on-device compile+run per bin width decides the kernel mode
    for this process: packed dot → per-feature dot → XLA scatter. Insurance
    for unattended bench windows — a Mosaic lowering regression must degrade
    throughput, not kill the measurement. Runs at the PRODUCTION chunk and
    the requested bin width (which sets K1/PACK — the lowering-relevant
    shapes), with per-feature random bins and distinct g/h/m channels so
    cross-feature contamination or channel swaps fail the check."""
    import numpy as _np

    n = default_chunk()
    rng = _np.random.default_rng(0)
    bT = jnp.asarray(rng.integers(0, num_bins_padded, size=(8, n)),
                     jnp.int32)
    g = jnp.asarray(rng.normal(size=n).astype(_np.float32))
    h = jnp.asarray(rng.uniform(0.5, 2.0, size=n).astype(_np.float32))
    m = jnp.asarray((rng.uniform(size=n) > 0.25).astype(_np.float32))
    want = _np.asarray(_hist_xla(bT, g * m, h * m, m, num_bins_padded))
    for mode, pk in (("packed", None), ("pack1", 1)):
        try:
            got = _np.asarray(_hist_pallas(bT, g * m, h * m, m,
                                           num_bins_padded, pack=pk))
            if _np.allclose(got, want, rtol=1e-4, atol=1e-3):
                return mode
        except Exception:
            continue
    return "xla"


@functools.cache
@_eager_selftest
def _tpu_segmented_ok(num_bins_padded: int) -> bool:
    """On-device check of the scalar-prefetch segmented kernel (same
    insurance contract as _tpu_kernel_selftest): False degrades the grower
    to the dynamic_slice + plain-kernel path."""
    import numpy as _np

    try:
        n = 4 * default_chunk()
        rng = _np.random.default_rng(1)
        bT = jnp.asarray(rng.integers(0, num_bins_padded, size=(8, n)),
                         jnp.int32)
        g = jnp.asarray(rng.normal(size=n).astype(_np.float32))
        h = jnp.asarray(rng.uniform(0.5, 2.0, size=n).astype(_np.float32))
        m = jnp.asarray((rng.uniform(size=n) > 0.25).astype(_np.float32))
        # geometry satisfies the documented contract size >= length + chunk
        start, length = 1234, 2 * default_chunk() - 57
        size = 3 * default_chunk()
        got = _np.asarray(_hist_pallas_range(bT, g * m, h * m, m, start,
                                             length, num_bins_padded, size))
        idx = _np.arange(n)
        sel = jnp.asarray(((idx >= start) & (idx < start + length)
                           ).astype(_np.float32))
        want = _np.asarray(_hist_xla(bT, g * m * sel, h * m * sel, m * sel,
                                     num_bins_padded))
        return bool(_np.allclose(got, want, rtol=1e-4, atol=1e-3))
    except Exception:
        return False


def segmented_histograms_available(num_bins_padded: int) -> bool:
    """Trace-time gate for the grower: TPU backend + env not disabling +
    on-device selftest green."""
    if jax.default_backend() != "tpu":
        return False
    if os.environ.get("SYNAPSEML_TPU_SEGMENTED", "1") == "0":
        return False
    return _tpu_segmented_ok(num_bins_padded)


def range_histogram(bT, g, h, m, start, length, num_bins_padded: int,
                    size: int):
    """Public segmented entry: histogram of rows [start, start+length) of
    the FULL arrays over a chunk-aligned static window of ``size`` rows —
    no dynamic_slice copy, no pre-kernel mask multiply (callers must have
    checked :func:`segmented_histograms_available`)."""
    return _hist_pallas_range(bT, g, h, m, start, length, num_bins_padded,
                              size)


def child_histogram(bT, g, h, m, num_bins_padded: int):
    """(FP, size) i32 bins + per-row grad/hess/weight-mask →
    (FP, num_bins_padded, 3) f32 histogram of [sum_grad, sum_hess, sum_mask].

    Rows with m == 0 (outside the leaf range / bagged out / padding) contribute
    nothing PROVIDED g and h are also zeroed for those rows (callers mask all
    three). Uses the Pallas MXU kernel on TPU, XLA scatter elsewhere.
    """
    if jax.default_backend() == "tpu":
        mode = _tpu_kernel_selftest(num_bins_padded)
        if mode == "packed":
            return _hist_pallas(bT, g, h, m, num_bins_padded)
        if mode == "pack1":
            return _hist_pallas(bT, g, h, m, num_bins_padded, pack=1)
    return _hist_xla(bT, g, h, m, num_bins_padded)
