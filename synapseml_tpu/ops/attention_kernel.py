"""Pallas TPU flash-attention forward — the fused hot-op for long context.

The attention stack (parallel/ring_attention.py) already computes blockwise
online softmax, but as XLA ops: every (block_q, block_k) score tile round-
trips through HBM-visible intermediates. This kernel fuses scores, masking,
the online-softmax rescale, and the PV matmul into ONE Pallas program —
Q/K/V stream through VMEM once and the S² score matrix never exists
anywhere (the public FlashAttention / blockwise-parallel formulation; the
reference's DL stack has no long-context path at all — SURVEY §5.7 lists
this repo's long-context support as its bonus surface).

Differentiation: ``flash_attention`` carries a custom VJP whose backward
RECOMPUTES through the existing XLA blockwise path — the forward stays a
pure fused kernel, memory stays O(S·block), and gradients are exactly the
blockwise path's (itself equality-tested against attention_reference).

Degrade ladder (same insurance contract as ops/hist_kernel.py): on TPU a
one-shot on-device selftest gates the kernel; any Mosaic failure falls back
to the XLA blockwise path. Non-TPU backends always take the XLA path —
``interpret=True`` exists for CPU correctness tests of the kernel itself.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .hist_kernel import _eager_selftest

_NEG_INF = -1e30          # finite -inf stand-in: keeps exp() NaN-free


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  s_q: int, s_k: int):
    """One (bh, q-block) × sequential-k-block step of the online softmax.

    Scratch (acc, m, l) persists across the sequential last grid dimension
    (TPU grids execute in order); m/l are stored lane-replicated at width
    128 so every store stays tile-aligned."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k-block entirely above the diagonal contributes nothing —
    # skip its matmuls outright (~2x on the causal hot path)
    live = (ki * block_k <= qi * block_q + block_q - 1 if causal
            else ki >= 0)

    @pl.when(live)
    def _():
        q = q_ref[0]                                 # (block_q, D)
        k = k_ref[0]                                 # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        rows = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
        cols = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        valid = cols < s_k                           # kv padding mask
        if causal:
            valid &= rows >= cols
        s = jnp.where(valid, s, _NEG_INF)

        m_old = m_ref[...][:, :1]                    # (block_q, 1)
        l_old = l_ref[...][:, :1]
        m_new = jnp.maximum(m_old, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_old - m_new)               # finite: m monotone
        p = jnp.exp(s - m_new)                       # masked entries -> ~0
        p = jnp.where(valid, p, 0.0)                 # exact zero for padding
        l_new = l_old * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[...][:, :1] > 0, l_ref[...][:, :1], 1.0)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _blocks_and_pad(q, k, v, block_q: int, block_k: int):
    """Shared layout preamble for both kernels: 8-row-aligned block clamp
    (f32 sublane tile — a raw-seq-length clip would hand Mosaic shapes the
    one-shot selftest never exercised), (B, S, H, D) → (B·H, S, D), and
    zero-padding to block multiples (padded kv columns are masked inside
    the kernels; padded q rows are dropped by the callers)."""
    B, s_q, H, D = q.shape
    s_k = k.shape[1]
    bq = min(block_q, -(-max(s_q, 8) // 8) * 8)
    bk = min(block_k, -(-max(s_k, 8) // 8) * 8)
    pad_q = (-s_q) % bq
    pad_k = (-s_k) % bk
    qT = jnp.moveaxis(q, 2, 1).reshape(B * H, s_q, D)
    kT = jnp.moveaxis(k, 2, 1).reshape(B * H, s_k, D)
    vT = jnp.moveaxis(v, 2, 1).reshape(B * H, s_k, D)
    if pad_q:
        qT = jnp.pad(qT, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kT = jnp.pad(kT, ((0, 0), (0, pad_k), (0, 0)))
        vT = jnp.pad(vT, ((0, 0), (0, pad_k), (0, 0)))
    return B, H, D, s_q, s_k, bq, bk, pad_q, qT, kT, vT


def _vmem_state_scratch(bq: int, D: int):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM((bq, D), jnp.float32),        # acc
            pltpu.VMEM((bq, 128), jnp.float32),      # running max m
            pltpu.VMEM((bq, 128), jnp.float32)]      # normalizer l


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    """(B, S, H, D) → (B, S, H, D): pad to block multiples, run the kernel
    over a (B·H, q-blocks, k-blocks) grid, slice the padding back off."""
    from jax.experimental import pallas as pl

    (B, H, D, s_q, s_k, bq, bk, _,
     qT, kT, vT) = _blocks_and_pad(q, k, v, block_q, block_k)
    nq, nk = qT.shape[1] // bq, kT.shape[1] // bk

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, s_q=s_q, s_k=s_k),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qT.shape, q.dtype),
        scratch_shapes=_vmem_state_scratch(bq, D),
        interpret=interpret,
    )(qT, kT, vT)
    out = out[:, :s_q].reshape(B, H, s_q, D)
    return jnp.moveaxis(out, 1, 2)                   # (B, S, H, D)


def divisor_block(s: int, want: int, floor: int = 8) -> int:
    """Largest divisor of ``s`` that is <= ``want`` and >= ``floor`` (0 when
    none exists) — keeps the blockwise path available for non-divisible
    sequence lengths instead of degrading to the O(S^2) reference."""
    for b in range(min(want, s), floor - 1, -1):
        if s % b == 0:
            return b
    return 0


def _xla_fallback(q, k, v, causal: bool, scale: float, block_k: int):
    """The existing blockwise path at the largest workable block divisor,
    or the reference einsum only when no divisor >= 8 exists (near-prime
    lengths) — one semantic, chosen by shape. This is also the backward
    recompute path: memory stays O(S·block) whenever a divisor exists."""
    from ..parallel.ring_attention import (attention_reference,
                                           blockwise_attention)

    bs = divisor_block(k.shape[1], block_k)
    if bs:
        return blockwise_attention(q, k, v, block_size=bs,
                                   causal=causal, scale=scale)
    return attention_reference(q, k, v, causal=causal, scale=scale)


@functools.cache
@_eager_selftest
def _tpu_flash_selftest() -> bool:
    """One small on-device compile+run decides whether the Mosaic lowering
    is trusted for this process (insurance for unattended bench windows —
    a regression must degrade to the XLA path, not kill the run). Runs at
    the PRODUCTION block size (128) on a padded non-divisible length, so
    the lowering-relevant shapes — full 128-row tiles plus the padded edge
    block — are the ones actually certified (code-review r5: a tiny-block
    selftest would green-light a lowering the real calls never take)."""
    import numpy as np

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    try:
        for causal in (False, True):
            got = np.asarray(_flash_forward(q, k, v, causal, 0.125, 128,
                                            128, False))
            want = np.asarray(_xla_fallback(q, k, v, causal, 0.125, 128))
            if not np.allclose(got, want, rtol=3e-4, atol=3e-4):
                return False
        return True
    except Exception:
        return False


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused flash attention, differentiable. Layout (B, S, H, D) — the same
    convention as attention_reference / blockwise_attention, and the same
    outputs to kernel tolerance. Backward recomputes through the XLA
    blockwise path (O(S·block) memory both directions). ``scale`` must be
    a static scalar (it folds into the compiled kernel); concrete jax/numpy
    scalars are accepted and converted."""
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    use_kernel = interpret or (jax.default_backend() == "tpu"
                               and _tpu_flash_selftest())

    @jax.custom_vjp
    def f(q, k, v):
        if use_kernel:
            return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                                  interpret)
        return _xla_fallback(q, k, v, causal, scale, block_k)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: _xla_fallback(a, b, c, causal, scale, block_k),
            q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f(q, k, v)


# ---------------------------------------------------------------------------
# State-carrying variant: the ring's inner step (parallel/ring_attention.py
# rotates K/V blocks around the mesh and folds each into carried online-
# softmax state). Same fused math as _flash_kernel, but (m, l, acc) enter
# and leave as tensors instead of living only in scratch — so the ring can
# run its per-step block attention as ONE kernel on TPU.
# ---------------------------------------------------------------------------

def _flash_block_kernel(off_ref, q_ref, k_ref, v_ref, m_in_ref, l_in_ref,
                        o_in_ref, m_out_ref, l_out_ref, o_out_ref,
                        acc_ref, m_ref, l_ref, *, scale, causal,
                        block_q, block_k, s_k):
    """off_ref (SMEM, scalar-prefetched): [q_offset, k_offset] — the blocks'
    GLOBAL sequence starts, traced values inside the ring's shard_map (the
    rank index decides them, so they cannot be compile-time constants)."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = o_in_ref[0].astype(jnp.float32)
        m_ref[...] = jnp.broadcast_to(
            jnp.maximum(m_in_ref[0][:, None], _NEG_INF), m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_in_ref[0][:, None], l_ref.shape)

    # causal dead-block skip with RUNTIME offsets (same ~2x win as the
    # plain kernel's static guard): the whole tile is in the causal future
    # when its first global column exceeds the last global row
    live = (off_ref[1] + ki * block_k
            <= off_ref[0] + qi * block_q + block_q - 1
            if causal else ki >= 0)

    @pl.when(live)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = (off_ref[0] + qi * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
        cols_local = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        cols = off_ref[1] + cols_local
        valid = cols_local < s_k
        if causal:
            valid &= rows >= cols
        s = jnp.where(valid, s, _NEG_INF)

        m_old = m_ref[...][:, :1]
        l_old = l_ref[...][:, :1]
        m_new = jnp.maximum(m_old, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_old - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = l_old * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        m_out_ref[0] = m_ref[...][:, 0].astype(m_out_ref.dtype)
        l_out_ref[0] = l_ref[...][:, 0].astype(l_out_ref.dtype)
        o_out_ref[0] = acc_ref[...].astype(o_out_ref.dtype)


@functools.cache
@_eager_selftest
def _tpu_flash_block_selftest() -> bool:
    """On-device certification of the STATE-CARRYING lowering specifically
    (scalar prefetch, multi-output, (1, bq) state blocks) — a distinct
    Mosaic compile path from _flash_forward's, so it needs its own gate
    (code-review r5: the ring must degrade to the XLA step, not die
    mid-shard_map, when only this lowering regresses)."""
    import numpy as np

    from ..parallel.ring_attention import _block_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 140, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 128, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 128, 2, 64)), jnp.float32)
    m0 = jnp.full((2, 2, 140), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((2, 2, 140), jnp.float32)
    o0 = jnp.zeros((2, 140, 2, 64), jnp.float32)
    try:
        for causal in (False, True):
            mk, lk, ok = flash_attention_block(
                q, k, v, m0, l0, o0, q_offset=64, k_offset=0,
                causal=causal, scale=0.125, interpret=False)
            mr, lr, orf = _block_attention(q, k, v, m0, l0, o0, 64, 0,
                                           causal, 0.125)
            fin = np.isfinite(np.asarray(mr))
            if not (np.allclose(np.asarray(mk)[fin], np.asarray(mr)[fin],
                                rtol=3e-4, atol=3e-4)
                    and np.allclose(np.asarray(lk), np.asarray(lr),
                                    rtol=3e-4, atol=3e-4)
                    and np.allclose(np.asarray(ok), np.asarray(orf),
                                    rtol=3e-4, atol=3e-4)):
                return False
        return True
    except Exception:
        return False


def flash_attention_block(q, k, v, m, l, o, q_offset, k_offset,
                          causal: bool = False, scale: float = None,
                          block_q: int = 128, block_k: int = 128,
                          interpret: bool = False):
    """One fused online-softmax update of carried state — the drop-in
    kernel form of ring_attention._block_attention. Layouts match the
    ring: q (B, Sq, H, D), k/v (B, Sk, H, D), m/l (B, H, Sq) running
    max/normalizer, o (B, Sq, H, D) UNNORMALIZED accumulator; offsets are
    the blocks' global sequence starts (traced values are fine — they ride
    scalar prefetch). -inf entries in ``m`` are mapped to the kernel's
    finite sentinel; finalize with ring_attention._finalize as usual."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    (B, H, D, s_q, s_k, bq, bk, pad_q,
     qT, kT, vT) = _blocks_and_pad(q, k, v, block_q, block_k)
    mT = m.reshape(B * H, s_q)
    lT = l.reshape(B * H, s_q)
    oT = jnp.moveaxis(o, 2, 1).reshape(B * H, s_q, D)
    if pad_q:
        oT = jnp.pad(oT, ((0, 0), (0, pad_q), (0, 0)))
        mT = jnp.pad(mT, ((0, 0), (0, pad_q)),
                     constant_values=_NEG_INF)
        lT = jnp.pad(lT, ((0, 0), (0, pad_q)))
    nq, nk = qT.shape[1] // bq, kT.shape[1] // bk
    offs = jnp.asarray(
        jnp.stack([jnp.asarray(q_offset, jnp.int32).reshape(()),
                   jnp.asarray(k_offset, jnp.int32).reshape(())]))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j, off: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j, off: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j, off: (b, j, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j, off: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i, j, off: (b, i)),
            pl.BlockSpec((1, bq, D), lambda b, i, j, off: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq), lambda b, i, j, off: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i, j, off: (b, i)),
            pl.BlockSpec((1, bq, D), lambda b, i, j, off: (b, i, 0)),
        ],
        scratch_shapes=_vmem_state_scratch(bq, D),
    )
    m2, l2, o2 = pl.pallas_call(
        functools.partial(_flash_block_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, s_k=s_k),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(mT.shape, jnp.float32),
            jax.ShapeDtypeStruct(lT.shape, jnp.float32),
            jax.ShapeDtypeStruct(oT.shape, jnp.float32),
        ],
        interpret=interpret,
    )(offs, qT, kT, vT, mT, lT, oT)
    m2 = m2[:, :s_q].reshape(B, H, s_q)
    l2 = l2[:, :s_q].reshape(B, H, s_q)
    o2 = jnp.moveaxis(o2[:, :s_q].reshape(B, H, s_q, D), 1, 2)
    return m2, l2, o2
