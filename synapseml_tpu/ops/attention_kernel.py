"""Pallas TPU flash-attention forward — the fused hot-op for long context.

The attention stack (parallel/ring_attention.py) already computes blockwise
online softmax, but as XLA ops: every (block_q, block_k) score tile round-
trips through HBM-visible intermediates. This kernel fuses scores, masking,
the online-softmax rescale, and the PV matmul into ONE Pallas program —
Q/K/V stream through VMEM once and the S² score matrix never exists
anywhere (the public FlashAttention / blockwise-parallel formulation; the
reference's DL stack has no long-context path at all — SURVEY §5.7 lists
this repo's long-context support as its bonus surface).

Differentiation: ``flash_attention`` carries a custom VJP whose backward
RECOMPUTES through the existing XLA blockwise path — the forward stays a
pure fused kernel, memory stays O(S·block), and gradients are exactly the
blockwise path's (itself equality-tested against attention_reference).

Degrade ladder (same insurance contract as ops/hist_kernel.py): on TPU a
one-shot on-device selftest gates the kernel; any Mosaic failure falls back
to the XLA blockwise path. Non-TPU backends always take the XLA path —
``interpret=True`` exists for CPU correctness tests of the kernel itself.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30          # finite -inf stand-in: keeps exp() NaN-free


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  s_q: int, s_k: int):
    """One (bh, q-block) × sequential-k-block step of the online softmax.

    Scratch (acc, m, l) persists across the sequential last grid dimension
    (TPU grids execute in order); m/l are stored lane-replicated at width
    128 so every store stays tile-aligned."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k-block entirely above the diagonal contributes nothing —
    # skip its matmuls outright (~2x on the causal hot path)
    live = (ki * block_k <= qi * block_q + block_q - 1 if causal
            else ki >= 0)

    @pl.when(live)
    def _():
        q = q_ref[0]                                 # (block_q, D)
        k = k_ref[0]                                 # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        rows = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
        cols = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        valid = cols < s_k                           # kv padding mask
        if causal:
            valid &= rows >= cols
        s = jnp.where(valid, s, _NEG_INF)

        m_old = m_ref[...][:, :1]                    # (block_q, 1)
        l_old = l_ref[...][:, :1]
        m_new = jnp.maximum(m_old, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_old - m_new)               # finite: m monotone
        p = jnp.exp(s - m_new)                       # masked entries -> ~0
        p = jnp.where(valid, p, 0.0)                 # exact zero for padding
        l_new = l_old * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[...][:, :1] > 0, l_ref[...][:, :1], 1.0)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    """(B, S, H, D) → (B, S, H, D): pad to block multiples, run the kernel
    over a (B·H, q-blocks, k-blocks) grid, slice the padding back off."""
    from jax.experimental import pallas as pl

    B, s_q, H, D = q.shape
    s_k = k.shape[1]
    # block shapes stay 8-row aligned (f32 sublane tile) — a raw-seq-length
    # clip would hand Mosaic shapes the one-shot selftest never exercised,
    # breaking the degrade contract per-shape (code-review r5)
    bq = min(block_q, -(-max(s_q, 8) // 8) * 8)
    bk = min(block_k, -(-max(s_k, 8) // 8) * 8)
    pad_q = (-s_q) % bq
    pad_k = (-s_k) % bk
    # (B, S, H, D) -> (B*H, S, D), zero-padded to block multiples (padded
    # kv columns are masked inside the kernel; padded q rows are dropped)
    qT = jnp.moveaxis(q, 2, 1).reshape(B * H, s_q, D)
    kT = jnp.moveaxis(k, 2, 1).reshape(B * H, s_k, D)
    vT = jnp.moveaxis(v, 2, 1).reshape(B * H, s_k, D)
    if pad_q:
        qT = jnp.pad(qT, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kT = jnp.pad(kT, ((0, 0), (0, pad_k), (0, 0)))
        vT = jnp.pad(vT, ((0, 0), (0, pad_k), (0, 0)))
    nq, nk = qT.shape[1] // bq, kT.shape[1] // bk
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, s_q=s_q, s_k=s_k),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qT.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),        # acc
            pltpu.VMEM((bq, 128), jnp.float32),      # running max m
            pltpu.VMEM((bq, 128), jnp.float32),      # normalizer l
        ],
        interpret=interpret,
    )(qT, kT, vT)
    out = out[:, :s_q].reshape(B, H, s_q, D)
    return jnp.moveaxis(out, 1, 2)                   # (B, S, H, D)


def divisor_block(s: int, want: int, floor: int = 8) -> int:
    """Largest divisor of ``s`` that is <= ``want`` and >= ``floor`` (0 when
    none exists) — keeps the blockwise path available for non-divisible
    sequence lengths instead of degrading to the O(S^2) reference."""
    for b in range(min(want, s), floor - 1, -1):
        if s % b == 0:
            return b
    return 0


def _xla_fallback(q, k, v, causal: bool, scale: float, block_k: int):
    """The existing blockwise path at the largest workable block divisor,
    or the reference einsum only when no divisor >= 8 exists (near-prime
    lengths) — one semantic, chosen by shape. This is also the backward
    recompute path: memory stays O(S·block) whenever a divisor exists."""
    from ..parallel.ring_attention import (attention_reference,
                                           blockwise_attention)

    bs = divisor_block(k.shape[1], block_k)
    if bs:
        return blockwise_attention(q, k, v, block_size=bs,
                                   causal=causal, scale=scale)
    return attention_reference(q, k, v, causal=causal, scale=scale)


@functools.cache
def _tpu_flash_selftest() -> bool:
    """One small on-device compile+run decides whether the Mosaic lowering
    is trusted for this process (insurance for unattended bench windows —
    a regression must degrade to the XLA path, not kill the run). Runs at
    the PRODUCTION block size (128) on a padded non-divisible length, so
    the lowering-relevant shapes — full 128-row tiles plus the padded edge
    block — are the ones actually certified (code-review r5: a tiny-block
    selftest would green-light a lowering the real calls never take)."""
    import numpy as np

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 300, 2, 64)), jnp.float32)
    try:
        for causal in (False, True):
            got = np.asarray(_flash_forward(q, k, v, causal, 0.125, 128,
                                            128, False))
            want = np.asarray(_xla_fallback(q, k, v, causal, 0.125, 128))
            if not np.allclose(got, want, rtol=3e-4, atol=3e-4):
                return False
        return True
    except Exception:
        return False


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused flash attention, differentiable. Layout (B, S, H, D) — the same
    convention as attention_reference / blockwise_attention, and the same
    outputs to kernel tolerance. Backward recomputes through the XLA
    blockwise path (O(S·block) memory both directions). ``scale`` must be
    a static scalar (it folds into the compiled kernel); concrete jax/numpy
    scalars are accepted and converted."""
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    use_kernel = interpret or (jax.default_backend() == "tpu"
                               and _tpu_flash_selftest())

    @jax.custom_vjp
    def f(q, k, v):
        if use_kernel:
            return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                                  interpret)
        return _xla_fallback(q, k, v, causal, scale, block_k)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: _xla_fallback(a, b, c, causal, scale, block_k),
            q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f(q, k, v)
