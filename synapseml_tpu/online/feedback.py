"""Bounded, back-pressured feedback log — the serving→training ingress.

The serving tier emits ``(context, action, probability, reward)`` events;
real reward pipelines deliver them late, twice, or poisoned (NaN joins,
out-of-range metric bugs — exactly what ``testing.chaos.chaos_reward_stream``
injects). This log is the containment layer between that stream and the
online learner:

* **Bounded, never blocking** — a fixed-capacity ring; on overflow the
  OLDEST unconsumed event is shed (``shed_oldest`` counter) so the serving
  hot path never waits on the training side. Stale feedback is the cheapest
  feedback to lose.
* **Dedup** — a bounded LRU of recently-seen event keys; a duplicate key is
  counted (``duplicates``) and dropped, so at-least-once delivery upstream
  cannot double-count a reward into the learner or the gate's logs.
* **Quarantine** — events that fail validation (non-finite or out-of-range
  reward, propensity outside ``(0, 1]``, missing/out-of-range action) are
  counted per reason (``quarantined``) and never reach the learner. A NaN
  reward burst degrades to zero learning signal, not NaN weights.

Thread-safe: serving connection threads ``offer`` concurrently while the
learner loop ``drain``\\ s. Counters are the observable surface the chaos
suite asserts on.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..core.logging import record_failure


@dataclass(frozen=True)
class FeedbackEvent:
    """One logged bandit interaction.

    ``actions`` holds the per-action featurized sparse rows (the
    ``SPARSE_DTYPE`` rows the VW featurizer/estimators use — one row per
    available action, shared context already folded in); ``action`` is the
    1-based chosen index, ``probability`` the logging policy's propensity
    for that choice, ``reward`` the observed outcome. ``key`` is the dedup
    identity (the dsjson ``EventId`` analog)."""
    key: str
    actions: Sequence
    action: int
    probability: float
    reward: float
    meta: dict = field(default_factory=dict, compare=False)


def validate_bandit_event(ev: FeedbackEvent, reward_min: float,
                          reward_max: float) -> Optional[str]:
    """Returns a quarantine reason, or None for a clean event."""
    try:
        r = float(ev.reward)
        p = float(ev.probability)
        a = int(ev.action)
    except (TypeError, ValueError):
        return "malformed"
    if not math.isfinite(r):
        return "nonfinite_reward"
    if r < reward_min or r > reward_max:
        return "reward_out_of_range"
    if not (0.0 < p <= 1.0):
        return "bad_propensity"
    n_actions = len(ev.actions) if ev.actions is not None else 0
    if n_actions == 0 or not (1 <= a <= n_actions):
        return "bad_action"
    return None


class FeedbackLog:
    """Bounded dedup'ing quarantine queue between serving and the learner.

    ``offer`` never blocks and returns one of ``"accepted"``,
    ``"duplicate"``, ``"quarantined"``; ``drain(max_n)`` pops up to
    ``max_n`` oldest events FIFO. ``validator(event) -> reason|None``
    defaults to the contextual-bandit rules; the streaming-anomaly loop
    passes its own.
    """

    def __init__(self, capacity: int = 4096, dedup_window: int = 8192,
                 reward_min: float = 0.0, reward_max: float = 1.0,
                 validator: Optional[Callable] = None,
                 counter_prefix: str = "online.feedback"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.dedup_window = max(int(dedup_window), 0)
        self.reward_min = reward_min
        self.reward_max = reward_max
        self._validator = validator
        self._prefix = counter_prefix
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self.accepted = 0
        self.duplicates = 0
        self.shed_oldest = 0
        self.drained = 0
        self.quarantined: Dict[str, int] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def _validate(self, ev) -> Optional[str]:
        if self._validator is not None:
            return self._validator(ev)
        return validate_bandit_event(ev, self.reward_min, self.reward_max)

    def offer(self, ev) -> str:
        """Admit one event; sheds the OLDEST queued event on overflow
        instead of blocking or refusing the new one (fresh feedback beats
        stale feedback, and the serving thread never waits)."""
        reason = self._validate(ev)
        if reason is not None:
            with self._lock:
                self.quarantined[reason] = self.quarantined.get(reason, 0) + 1
            record_failure(f"{self._prefix}.quarantined", reason=reason,
                           key=str(getattr(ev, "key", "")))
            return "quarantined"
        key = getattr(ev, "key", None)
        with self._lock:
            if key is not None and self.dedup_window:
                if key in self._seen:
                    self._seen.move_to_end(key)
                    self.duplicates += 1
                    record_failure(f"{self._prefix}.duplicate", key=str(key))
                    return "duplicate"
                self._seen[key] = None
                while len(self._seen) > self.dedup_window:
                    self._seen.popitem(last=False)
            while len(self._events) >= self.capacity:
                self._events.popleft()
                self.shed_oldest += 1
                record_failure(f"{self._prefix}.shed_oldest")
            self._events.append(ev)
            self.accepted += 1
        return "accepted"

    def drain(self, max_n: int) -> List:
        """Pop up to ``max_n`` events, oldest first (never blocks)."""
        out: List = []
        with self._lock:
            while self._events and len(out) < int(max_n):
                out.append(self._events.popleft())
            self.drained += len(out)
        return out

    def clear(self) -> int:
        """Drop every queued event (close-time hygiene); returns the count
        dropped so callers can account for them."""
        with self._lock:
            n = len(self._events)
            self._events.clear()
        return n

    def snapshot(self) -> dict:
        with self._lock:
            return {"depth": len(self._events),
                    "accepted": self.accepted,
                    "duplicates": self.duplicates,
                    "shed_oldest": self.shed_oldest,
                    "drained": self.drained,
                    "quarantined": dict(self.quarantined)}
