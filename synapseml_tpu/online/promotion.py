"""Counterfactually-gated promotion: candidates earn the hot-swap.

The online learner produces a stream of candidate policy snapshots; this
gate decides which of them reach traffic. The decision is OFF-POLICY: the
candidate is scored against the live policy's propensity-logged
interactions with the ``vw/policyeval`` estimators — no A/B traffic is
risked on an unproven policy. The rule is deliberately one-sided:

    promote  iff  CR_lower(candidate) > value(incumbent) + min_improvement

where ``CR_lower`` is the Cressie-Read (empirical-likelihood) interval's
lower bound on the candidate's value (clipped importance weights, à la the
CSE transformer's ``maxImportanceWeight``), and the incumbent's value is
the plain mean of its own logged rewards (the logs ARE on-policy for the
incumbent, so no importance correction is needed or wanted). A noisy,
wide-interval candidate fails the gate by construction — the gate prefers
serving a known-good policy over gambling on an estimated-better one.

Promotion itself rides :meth:`~synapseml_tpu.io.serving.ModelRegistry.swap_to`
(zero-downtime, pre-flip failures roll back with the incumbent still
serving), and every promoted version lands in ``approved_versions`` — the
set the chaos invariant checks every served response against. After a flip
the gate watches LIVE reward through :meth:`observe_live`; a regression
beyond tolerance triggers :meth:`~synapseml_tpu.io.serving.ModelRegistry.rollback`
to the previous (also-approved) version. Counterfactual estimates are
estimates; the live check is the backstop.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..core.logging import record_failure
from ..io.serving import ModelRegistry, SwapError
from ..vw.policyeval import cressie_read_interval, snips_estimate
from .feedback import FeedbackEvent


@dataclass(frozen=True)
class GateDecision:
    """One gate verdict — every field the audit trail needs."""
    candidate_version: Optional[str]
    promoted: bool
    reason: str
    n_samples: int = 0
    incumbent_value: float = 0.0
    snips: float = 0.0
    interval: Tuple[float, float] = (0.0, 0.0)


class PromotionGate:
    """Off-policy promotion gate + post-promotion live-regression watchdog.

    Feed it the SAME accepted interactions the learner trains on
    (:meth:`record`); ask it to judge a candidate serving handler
    (:meth:`decide`) or to load→judge→swap in one motion
    (:meth:`try_promote`). The gate never raises on a failed or killed
    swap — a refused candidate is a normal outcome, reported in the
    returned :class:`GateDecision`, and the incumbent keeps serving.
    """

    # min_improvement's default is epsilon, not zero: a degenerate interval
    # sitting exactly on the incumbent's value must not promote on float
    # rounding noise
    def __init__(self, registry: ModelRegistry,
                 min_samples: int = 200, alpha: float = 0.05,
                 min_improvement: float = 1e-6, max_weight: float = 100.0,
                 reward_min: float = 0.0, reward_max: float = 1.0,
                 log_window: int = 4096,
                 regression_window: int = 100,
                 regression_tolerance: float = 0.05,
                 broadcast=None):
        if min_samples < 2:
            raise ValueError(f"min_samples must be >= 2, got {min_samples}")
        if max_weight <= 0:
            raise ValueError(f"max_weight must be > 0, got {max_weight}")
        self.registry = registry
        # fabric-wide promotion: a PromotionBroadcast (io/distributed_
        # serving.py) whose two-phase prepare/commit flips EVERY worker to
        # the approved version, rolling all of them back on any failure —
        # None keeps the single-registry swap_to path
        self.broadcast = broadcast
        self.min_samples = min_samples
        self.alpha = alpha
        self.min_improvement = min_improvement
        self.max_weight = max_weight
        self.reward_min = reward_min
        self.reward_max = reward_max
        self.regression_window = regression_window
        self.regression_tolerance = regression_tolerance
        self._lock = threading.Lock()
        self._logs: deque = deque(maxlen=log_window)
        # the version serving at construction is approved by fiat: it is the
        # incumbent every later candidate must beat
        self.approved_versions = {registry.active}
        self.decisions: List[GateDecision] = []
        self.promotions = 0
        self.rollbacks = 0
        # live-regression watchdog state (armed by a successful promotion)
        self._baseline: Optional[float] = None
        self._live: deque = deque(maxlen=regression_window)

    # -- evidence intake --
    def record(self, ev: FeedbackEvent) -> None:
        """Log one incumbent interaction (propensity + reward) as gate
        evidence. Call with the same validated events the learner drains."""
        with self._lock:
            self._logs.append(ev)

    def record_all(self, events) -> None:
        with self._lock:
            self._logs.extend(events)

    # -- judgement --
    def _columns(self, candidate_policy):
        """(reward, p_log, p_target) over the logged window, with the
        importance ratio clipped at ``max_weight`` — implemented by flooring
        the logged propensity, so the library estimators see the clipped
        weights without a separate code path."""
        with self._lock:
            logs = list(self._logs)
        r = np.asarray([float(ev.reward) for ev in logs], np.float64)
        p_log = np.asarray([float(ev.probability) for ev in logs], np.float64)
        p_tgt = np.asarray(
            [float(candidate_policy.action_probabilities(ev.actions)
                   [int(ev.action) - 1]) for ev in logs], np.float64)
        p_log = np.maximum(p_log, p_tgt / self.max_weight)
        return r, p_log, p_tgt

    def decide(self, candidate_handler, version: Optional[str] = None
               ) -> GateDecision:
        """Judge a candidate handler (as built by ``policy_builder``)
        against the logged evidence. Pure read — no swap happens here."""
        version = version if version is not None \
            else getattr(candidate_handler, "version", None)
        policy = getattr(candidate_handler, "policy", candidate_handler)
        with self._lock:
            n = len(self._logs)
        if n < self.min_samples:
            return self._finish(GateDecision(
                version, False, "insufficient_samples", n_samples=n))
        r, p_log, p_tgt = self._columns(policy)
        incumbent = float(r.mean())
        snips = snips_estimate(r, p_log, p_tgt)
        lo, hi = cressie_read_interval(
            r, p_log, p_tgt, alpha=self.alpha,
            reward_min=self.reward_min, reward_max=self.reward_max)
        promoted = lo > incumbent + self.min_improvement
        reason = "interval_clears_incumbent" if promoted \
            else "interval_overlaps_incumbent"
        return self._finish(GateDecision(
            version, promoted, reason, n_samples=n,
            incumbent_value=incumbent, snips=snips, interval=(lo, hi)))

    def _finish(self, decision: GateDecision) -> GateDecision:
        with self._lock:
            self.decisions.append(decision)
        if not decision.promoted:
            record_failure("online.gate_refused", n=1,
                           version=str(decision.candidate_version),
                           reason=decision.reason)
        return decision

    # -- promotion --
    def try_promote(self, store, builder: Callable,
                    step: Optional[int] = None) -> GateDecision:
        """Load the newest verifiable candidate snapshot, judge it, and —
        only on a clear verdict — hot-swap it in. Every failure mode
        (corrupt snapshot, builder error, injected kill mid-swap) comes back
        as a non-promoted decision with the incumbent still serving."""
        try:
            ckpt = (store.load_step(step) if step is not None
                    else store.load_latest())
        except Exception as e:  # noqa: BLE001 — a broken store refuses, not raises
            return self._finish(GateDecision(
                None, False, f"load_failed:{type(e).__name__}"))
        if ckpt is None:
            return self._finish(GateDecision(
                None, False, "no_verifiable_checkpoint"))
        if ckpt.version == self.registry.active:
            return self._finish(GateDecision(
                ckpt.version, False, "already_serving"))
        try:
            handler = builder(ckpt)
        except Exception as e:  # noqa: BLE001
            return self._finish(GateDecision(
                ckpt.version, False, f"build_failed:{type(e).__name__}"))
        decision = self.decide(handler, version=ckpt.version)
        if not decision.promoted:
            return decision
        try:
            if self.broadcast is not None:
                # fabric-wide: one gate approval flips every worker via
                # two-phase prepare/commit; any failure path converges the
                # whole fabric on ONE version (BroadcastError = old one)
                self.broadcast.broadcast(ckpt.version, handler)
            else:
                self.registry.swap_to(ckpt.version, handler)
        except (SwapError, RuntimeError) as e:
            # pre-flip failure (chaos kill, warmup fault) or a rolled-back
            # broadcast: the incumbent version serves on, fabric-wide
            with self._lock:
                self.decisions.pop()
            return self._finish(GateDecision(
                ckpt.version, False, "swap_failed",
                n_samples=decision.n_samples,
                incumbent_value=decision.incumbent_value,
                snips=decision.snips, interval=decision.interval))
        with self._lock:
            self.approved_versions.add(ckpt.version)
            self.promotions += 1
            # arm the watchdog: live reward must hold the incumbent's level
            self._baseline = decision.incumbent_value
            self._live.clear()
        record_failure("online.gate_promoted", version=ckpt.version)
        return decision

    def recover_broadcast(self) -> Optional[str]:
        """Drive a DEAD coordinator's in-doubt promotion round to its end
        (federated fabric): delegates to
        :meth:`~synapseml_tpu.io.distributed_serving.PromotionBroadcast.
        recover`, which reads the replicated 2PC phase record and converges
        every worker on exactly one version. A recovered COMMIT joins
        ``approved_versions`` — the round's prepare record only exists
        because the dead coordinator's gate approved the candidate, and the
        chaos invariant checks served versions against the survivor's gate.
        Returns the outcome (``"committed"``/``"aborted"``) or None when
        there is nothing to recover."""
        recover = getattr(self.broadcast, "recover", None)
        if recover is None:
            return None
        recovered = recover()
        if recovered is None:
            return None
        version, outcome = recovered
        if outcome == "committed":
            with self._lock:
                self.approved_versions.add(version)
                self.promotions += 1
        record_failure("online.broadcast_recovered", version=version,
                       outcome=outcome)
        return outcome

    # -- post-promotion live watchdog --
    def observe_live(self, reward: float) -> bool:
        """Feed one post-promotion LIVE reward. Once the regression window
        fills, a live mean below ``baseline - regression_tolerance`` rolls
        back to the previous approved version. Returns True iff this
        observation triggered a rollback."""
        with self._lock:
            if self._baseline is None:
                return False
            self._live.append(float(reward))
            if len(self._live) < self.regression_window:
                return False
            live_mean = float(np.mean(self._live))
            baseline = self._baseline
            if live_mean >= baseline - self.regression_tolerance:
                self._baseline = None    # candidate confirmed; disarm
                return False
            # regression: disarm before the swap so re-entry is impossible
            self._baseline = None
        demoted = self.registry.active
        try:
            self.registry.rollback()
        except SwapError as e:
            record_failure("online.rollback_failed", error=type(e).__name__)
            return False
        with self._lock:
            self.rollbacks += 1
        record_failure("online.live_regression_rollback", version=demoted,
                       live_mean=round(live_mean, 6),
                       baseline=round(baseline, 6))
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {"logs": len(self._logs),
                    "decisions": len(self.decisions),
                    "promotions": self.promotions,
                    "rollbacks": self.rollbacks,
                    "approved": sorted(self.approved_versions),
                    "watchdog_armed": self._baseline is not None}
