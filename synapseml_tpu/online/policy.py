"""Epsilon-greedy contextual-bandit policy over a VW reward model.

The policy side of the continuous-learning loop: a frozen
:class:`~synapseml_tpu.vw.learner.VWState` scores every candidate action's
hashed-feature row, and an epsilon-greedy rule turns scores into a
propensity-logged choice. Each policy instance is IMMUTABLE with respect to
its weights — a promoted snapshot serves exactly the bytes the gate scored,
which is what makes ``ModelRegistry`` version pinning meaningful.

``action_probabilities`` is the off-policy-evaluation surface: the
counterfactual gate asks a CANDIDATE policy for the probability it would
have assigned to the LOGGED action, feeding the SNIPS / Cressie-Read
estimators in ``vw/policyeval``.
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

import numpy as np

from ..core.table import Table
from ..vw.learner import VWConfig, VWState, vw_predict


def _action_matrix(actions: Sequence, pad_to: int = 1):
    """Stack per-action sparse rows into (K, P) idx/val arrays."""
    rows = [np.asarray(a) for a in actions]
    p = max([pad_to] + [r.shape[-1] for r in rows])
    idx = np.zeros((len(rows), p), np.int32)
    val = np.zeros((len(rows), p), np.float32)
    for i, r in enumerate(rows):
        k = r.shape[-1]
        idx[i, :k] = r["idx"]
        val[i, :k] = r["val"]
    return idx, val


class GreedyPolicy:
    """Epsilon-greedy over predicted rewards; deterministic per seed.

    ``choose`` returns the 1-based action plus the propensity it was drawn
    with (the ``probability`` the feedback log needs); ties break to the
    lowest index so two policies built from identical bytes always agree.
    """

    def __init__(self, state: VWState, cfg: VWConfig, epsilon: float = 0.05,
                 seed: int = 0, version: str = "v0"):
        if not (0.0 <= epsilon <= 1.0):
            raise ValueError(f"epsilon must be in [0, 1], got {epsilon}")
        self.state = state
        self.cfg = cfg
        self.epsilon = epsilon
        self.version = version
        self._rng = np.random.default_rng(seed)
        self._rng_lock = threading.Lock()

    def scores(self, actions: Sequence) -> np.ndarray:
        idx, val = _action_matrix(actions)
        return vw_predict(self.state, idx, val)

    def action_probabilities(self, actions: Sequence) -> np.ndarray:
        """Epsilon-greedy distribution over the K candidate actions —
        the ``p_target`` column of off-policy evaluation."""
        s = self.scores(actions)
        k = len(s)
        probs = np.full(k, self.epsilon / k, np.float64)
        probs[int(np.argmax(s))] += 1.0 - self.epsilon
        return probs

    def choose(self, actions: Sequence) -> Tuple[int, float]:
        """Sample one action; returns (1-based action, propensity)."""
        probs = self.action_probabilities(actions)
        with self._rng_lock:
            a = int(self._rng.choice(len(probs), p=probs))
        return a + 1, float(probs[a])


def make_policy_handler(policy: GreedyPolicy, featurize) -> "callable":
    """Build a ``Table(id, value) -> Table(id, reply)`` serving handler
    around a frozen policy: each request's JSON value goes through
    ``featurize(value) -> [per-action sparse rows]`` and the reply carries
    ``{"action", "probability", "version"}`` — everything the feedback
    producer needs to log the interaction. The handler closes over ONE
    policy version, so ``ModelRegistry`` hot-swap/pinning semantics apply
    unchanged."""

    def handler(df: Table) -> Table:
        replies: List[dict] = []
        for v in df["value"]:
            actions = featurize(v)
            a, p = policy.choose(actions)
            replies.append({"action": a, "probability": p,
                            "version": policy.version})
        out = np.empty(df.num_rows, dtype=object)
        out[:] = replies
        return df.with_column("reply", out)

    handler.policy = policy
    handler.version = policy.version
    return handler


def policy_builder(cfg: VWConfig, featurize, epsilon: float = 0.05,
                   seed: int = 0):
    """``builder(checkpoint) -> handler`` for
    :meth:`~synapseml_tpu.io.serving.ModelRegistry.swap_from_store`: parse
    the checkpoint's VWState artifact (``ValueError`` on garbage — the
    registry maps it to a rolled-back ``SwapError``) and wrap it as a
    frozen epsilon-greedy serving handler."""

    def build(ckpt):
        data = ckpt.artifacts.get(VWState.STORE_ARTIFACT)
        if data is None:
            raise ValueError(
                f"checkpoint {ckpt.base} holds no "
                f"{VWState.STORE_ARTIFACT!r} artifact — not a policy "
                "snapshot")
        state = VWState.from_bytes(data)
        policy = GreedyPolicy(state, cfg, epsilon=epsilon, seed=seed,
                              version=ckpt.version)
        return make_policy_handler(policy, featurize)

    return build
