"""Streaming anomaly scoring on the online-loop skeleton.

The same drain→update→snapshot skeleton that powers the contextual-bandit
learner (``online/loop.py``) also carries the batch anomaly detectors into
continuous operation: events stream through a
:class:`~synapseml_tpu.online.feedback.FeedbackLog` (with an
anomaly-specific validator — there is no reward/propensity to range-check,
only finite features), a frozen batch-trained model scores each micro-batch,
and the alert threshold ADAPTS to a rolling quantile of recent scores so a
drifting score distribution does not silently mute (or flood) the alert
channel. Window + threshold + counters snapshot through the same
digest-verified :class:`~synapseml_tpu.core.checkpoint.CheckpointStore`,
so kill→resume replays bit-for-bit exactly like the learner loop.

Two adapters close the loop for the existing detectors:

* :func:`iforest_stream_scorer` — scores dense feature vectors with a
  trained :class:`~synapseml_tpu.isolationforest.iforest.IsolationForestModel`
  forest (the array-encoded trees, no Table round-trip per batch).
* :func:`access_anomaly_stream_scorer` — scores ``(tenant, user, res)``
  access records with a trained
  :class:`~synapseml_tpu.cyber.access_anomaly.AccessAnomalyModel`.
"""

from __future__ import annotations

import io as _io
import math
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from ..core.table import Table
from .feedback import FeedbackLog
from .loop import StreamLoop


@dataclass(frozen=True)
class AnomalyEvent:
    """One record awaiting an anomaly score. ``features`` is whatever the
    configured scorer consumes: a dense vector for the isolation forest, a
    ``{"tenant", "user", "res"}`` mapping for access anomaly."""
    key: str
    features: object
    meta: dict = field(default_factory=dict, compare=False)


def validate_anomaly_event(ev) -> Optional[str]:
    """Quarantine reason for a streaming-anomaly event, or None."""
    feats = getattr(ev, "features", None)
    if feats is None:
        return "malformed"
    if isinstance(feats, dict):
        return None
    try:
        arr = np.asarray(feats, np.float64)
    except (TypeError, ValueError):
        return "malformed"
    if arr.size == 0:
        return "malformed"
    if not np.isfinite(arr).all():
        return "nonfinite_features"
    return None


def anomaly_feedback_log(capacity: int = 4096, dedup_window: int = 8192,
                         **kw) -> FeedbackLog:
    """A :class:`FeedbackLog` wired for anomaly events (same bounding,
    dedup, and shed-oldest semantics; anomaly validator)."""
    return FeedbackLog(capacity=capacity, dedup_window=dedup_window,
                       validator=validate_anomaly_event,
                       counter_prefix=kw.pop("counter_prefix",
                                             "online.anomaly"), **kw)


class StreamingAnomalyLoop(StreamLoop):
    """Score → threshold-adapt → snapshot.

    Each micro-batch is scored by the frozen ``scorer``, flagged against the
    threshold that was in force BEFORE the batch (so flagging is causal and
    replay-deterministic), then the rolling window absorbs the new scores
    and the threshold re-adapts to ``quantile(window, 1 - contamination)``.
    Until ``min_window`` scores have been seen the loop scores but never
    flags — a cold quantile over three points is noise, not a threshold."""

    phase = "online.anomaly"
    counter_prefix = "online.anomaly"
    WINDOW_ARTIFACT = "anomaly_window.npz"

    def __init__(self, log: FeedbackLog,
                 scorer: Callable[[List[AnomalyEvent]], np.ndarray],
                 window: int = 512, contamination: float = 0.05,
                 min_window: int = 32,
                 on_alert: Optional[Callable[[AnomalyEvent, float], None]] = None,
                 **kw):
        super().__init__(log, **kw)
        if not (0.0 < contamination < 1.0):
            raise ValueError(
                f"contamination must be in (0, 1), got {contamination}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.scorer = scorer
        self.window = window
        self.contamination = contamination
        self.min_window = max(int(min_window), 1)
        self.on_alert = on_alert
        self._scores: deque = deque(maxlen=window)
        self.threshold = math.inf    # flag nothing until the window warms up
        self.scored = 0
        self.flagged = 0

    def _update(self, events: List[AnomalyEvent]) -> None:
        scores = np.asarray(self.scorer(events), np.float64).reshape(-1)
        if scores.shape[0] != len(events):
            raise ValueError(
                f"scorer returned {scores.shape[0]} scores for "
                f"{len(events)} events")
        thr = self.threshold            # causal: pre-batch threshold
        for ev, s in zip(events, scores):
            self.scored += 1
            if s >= thr:
                self.flagged += 1
                if self.on_alert is not None:
                    self.on_alert(ev, float(s))
        self._scores.extend(scores.tolist())
        if len(self._scores) >= self.min_window:
            self.threshold = float(np.quantile(
                np.asarray(self._scores, np.float64),
                1.0 - self.contamination))

    def _artifacts(self) -> dict:
        buf = _io.BytesIO()
        np.savez(buf,
                 scores=np.asarray(self._scores, np.float64),
                 threshold=np.float64(self.threshold),
                 scored=np.int64(self.scored),
                 flagged=np.int64(self.flagged))
        return {self.WINDOW_ARTIFACT: buf.getvalue()}

    def _restore(self, ckpt) -> None:
        data = ckpt.artifacts.get(self.WINDOW_ARTIFACT)
        if data is None:
            raise ValueError(
                f"checkpoint {ckpt.base} holds no "
                f"{self.WINDOW_ARTIFACT!r} artifact")
        try:
            with np.load(_io.BytesIO(bytes(data)), allow_pickle=False) as z:
                scores = np.asarray(z["scores"], np.float64)
                self.threshold = float(z["threshold"])
                self.scored = int(z["scored"])
                self.flagged = int(z["flagged"])
        except (KeyError, ValueError, OSError, EOFError) as e:
            raise ValueError(
                f"checkpoint {ckpt.base}: anomaly window artifact is not a "
                f"valid npz payload ({e})") from e
        self._scores = deque(scores.tolist(), maxlen=self.window)

    def snapshot_stats(self) -> dict:
        stats = super().snapshot_stats()
        stats.update({"scored": self.scored, "flagged": self.flagged,
                      "threshold": self.threshold,
                      "window_fill": len(self._scores)})
        return stats


def iforest_stream_scorer(model) -> Callable[[List[AnomalyEvent]], np.ndarray]:
    """Adapt a trained ``IsolationForestModel`` to the streaming loop:
    events carry dense feature vectors; scoring runs straight on the
    array-encoded forest (no per-batch Table round-trip)."""
    from ..isolationforest.iforest import _score
    f = model.get("forest")
    feat, thresh = f["feat"], f["thresh"]
    left, plen, sub = f["left"], f["plen"], f["subSize"]

    def score(events: List[AnomalyEvent]) -> np.ndarray:
        X = np.stack([np.asarray(ev.features, np.float64) for ev in events])
        return _score(X, feat, thresh, left, plen, sub)

    return score


def access_anomaly_stream_scorer(model) -> Callable[[List[AnomalyEvent]], np.ndarray]:
    """Adapt a trained ``AccessAnomalyModel``: events carry
    ``{"tenant", "user", "res"}`` mappings, batched into one Table per
    micro-batch and scored by the model's transform."""
    t_col, u_col, r_col = (model.getTenantCol(), model.getUserCol(),
                           model.getResCol())
    out_col = model.getOutputCol()

    def score(events: List[AnomalyEvent]) -> np.ndarray:
        df = Table({
            t_col: [ev.features["tenant"] for ev in events],
            u_col: [ev.features["user"] for ev in events],
            r_col: [ev.features["res"] for ev in events],
        })
        return np.asarray(model.transform(df)[out_col], np.float64)

    return score
