"""Online learning: the serving→training loop, closed and chaos-proofed.

The batch stack trains a policy, ``io/serving`` serves it; this package
closes the loop — served decisions generate propensity-logged feedback
(:mod:`~synapseml_tpu.online.feedback`), a background learner folds that
feedback into the policy continuously (:mod:`~synapseml_tpu.online.loop`),
and a counterfactual gate decides when a learned candidate has earned the
zero-downtime hot-swap (:mod:`~synapseml_tpu.online.promotion`). The same
loop skeleton also carries the anomaly detectors into streaming operation
with adaptive thresholds (:mod:`~synapseml_tpu.online.anomaly`).

Failure model (docs/online-learning.md): every stage assumes its input
stream is late, duplicated, or poisoned, every state transition is a
preemption point, and the system-level invariant — accepted prediction
requests are always answered by a promoted, never-regressed policy
version — holds under the full chaos battery.
"""

from .feedback import FeedbackEvent, FeedbackLog, validate_bandit_event
from .loop import OnlineLearnerLoop, StreamLoop
from .policy import (GreedyPolicy, make_policy_handler, policy_builder)
from .promotion import GateDecision, PromotionGate
from .anomaly import (AnomalyEvent, StreamingAnomalyLoop,
                      access_anomaly_stream_scorer, anomaly_feedback_log,
                      iforest_stream_scorer, validate_anomaly_event)

__all__ = [
    "FeedbackEvent", "FeedbackLog", "validate_bandit_event",
    "OnlineLearnerLoop", "StreamLoop",
    "GreedyPolicy", "make_policy_handler", "policy_builder",
    "GateDecision", "PromotionGate",
    "AnomalyEvent", "StreamingAnomalyLoop", "access_anomaly_stream_scorer",
    "anomaly_feedback_log", "iforest_stream_scorer", "validate_anomaly_event",
]
