"""The online-learning loop skeleton + the VW contextual-bandit learner loop.

:class:`StreamLoop` is the shared drain→update→snapshot skeleton (ROADMAP
Open item 5): a background thread drains micro-batches from a
:class:`~synapseml_tpu.online.feedback.FeedbackLog`, applies a model update,
and snapshots its state through a digest-verified
:class:`~synapseml_tpu.core.checkpoint.CheckpointStore` every
``snapshot_every`` updates. Every update boundary is a
:func:`~synapseml_tpu.core.checkpoint.preemption_point` (phase
``online.update`` / ``online.anomaly``), so the PR 2 chaos machinery
(``ChaosPreemption``, ``torn_write``/``bit_flip``) applies unchanged and the
recovery contract is the same one the offline trainers already prove:

    kill anywhere, restore the newest VERIFIED snapshot, replay the event
    stream from the snapshot's ``events_seen`` offset → bit-for-bit the
    uninterrupted run.

Replay determinism holds because every update is a pure function of
(state, micro-batch) — the VW update is one jitted XLA program with static
shapes (``batch_size`` rows padded with zero sample weights, feature width
padded to ``pad_features``), so the same events through the same boundaries
produce the same bytes. The micro-batch boundaries themselves are part of
the replayed stream contract: ``step()`` consumes events in arrival order
in fixed-size bites.

:class:`OnlineLearnerLoop` instantiates the skeleton for the contextual
bandit: IPS-weighted reward regression on the chosen action's hashed
features (``vw/learner.py``), snapshotting ``VWState`` through the store
(satellite: the VW state now rides the same artifact path gbdt/dl/automl
use). ``online/anomaly.py`` reuses the identical skeleton for streaming
anomaly scoring.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Callable, List, Optional

import numpy as np

from ..core.checkpoint import CheckpointStore, preemption_point
from ..core.logging import record_failure
from .feedback import FeedbackLog
from ..vw.learner import VWConfig, VWState, train_vw


class StreamLoop:
    """Drain → update → snapshot skeleton shared by the bandit learner and
    the streaming anomaly scorers.

    Subclasses implement ``_update(events)``, ``_artifacts() -> dict`` and
    ``_restore(checkpoint) -> None``. Synchronous driving (``step()`` /
    ``run_until_drained()``) is the deterministic path the recovery tests
    replay; ``start()``/``close()`` run the same steps on a background
    thread for live serving — ``close()`` always joins the thread
    (resource-discipline: the drain thread may not outlive its owner)."""

    phase = "online.update"
    counter_prefix = "online.loop"

    def __init__(self, log: FeedbackLog, store: Optional[CheckpointStore] = None,
                 batch_size: int = 64, snapshot_every: int = 8,
                 drain_interval: float = 0.01,
                 on_snapshot: Optional[Callable[[int, str], None]] = None):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        self.log = log
        self.store = store
        self.batch_size = batch_size
        self.snapshot_every = snapshot_every
        self.drain_interval = drain_interval
        self.on_snapshot = on_snapshot
        self.updates = 0
        self.events_seen = 0
        self.errors = 0
        self.last_snapshot_base: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_lock = threading.Lock()   # one update at a time

    # -- subclass surface --
    def _update(self, events: List) -> None:
        raise NotImplementedError

    def _artifacts(self) -> dict:
        raise NotImplementedError

    def _restore(self, ckpt) -> None:
        raise NotImplementedError

    def _meta(self) -> dict:
        return {"updates": self.updates, "events_seen": self.events_seen}

    # -- synchronous driving (the deterministic replay surface) --
    def step(self) -> bool:
        """Drain one micro-batch and apply one update; returns False when
        the log had nothing. The preemption point fires BEFORE the drain, so
        an injected kill loses no event that an uninterrupted run would have
        consumed at this boundary."""
        with self._step_lock:
            preemption_point(self.phase, self.updates)
            events = self.log.drain(self.batch_size)
            if not events:
                return False
            self._update(events)
            self.updates += 1
            self.events_seen += len(events)
            if self.store is not None and \
                    self.updates % self.snapshot_every == 0:
                self.snapshot()
        return True

    def run_until_drained(self) -> int:
        """Synchronously step until the log is empty; returns updates run."""
        n = 0
        while self.step():
            n += 1
        return n

    # -- snapshot / restore --
    def snapshot(self) -> Optional[str]:
        """Persist current state as one atomic, digest-verified checkpoint
        (step = update count). No-op without a store."""
        if self.store is None:
            return None
        base = self.store.save(self.updates, self._artifacts(),
                               meta=self._meta())
        self.last_snapshot_base = base
        if self.on_snapshot is not None:
            self.on_snapshot(self.updates, base)
        return base

    def restore_latest(self) -> bool:
        """Restore the newest checkpoint that VERIFIES (corrupt snapshots
        fall back per the store contract). Returns False when the store is
        empty/absent — the loop then starts fresh."""
        if self.store is None:
            return False
        ckpt = self.store.load_latest()
        if ckpt is None:
            return False
        self._restore(ckpt)
        self.updates = int(ckpt.meta.get("updates", ckpt.step))
        self.events_seen = int(ckpt.meta.get("events_seen", 0))
        return True

    # -- background drive --
    def start(self) -> "StreamLoop":
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("loop already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name=f"{self.counter_prefix}.drain",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        # the drain-poll skeleton lives in the shared ingestion layer
        # (io/ingest.py pump_polling — deliberately the POLLING shape, not a
        # lookahead pump: step()'s drain is destructive and must stay behind
        # its own preemption point). Exception → count + keep draining;
        # PreemptionError is BaseException and still kills the thread like a
        # real SIGTERM would.
        from ..io.ingest import pump_polling  # lazy: io/__init__ is heavy

        def on_error(e: Exception) -> None:
            self.errors += 1
            record_failure(f"{self.counter_prefix}.update_error",
                           error=type(e).__name__)

        pump_polling(self.step, self._stop, self.drain_interval,
                     on_error=on_error)

    def close(self, timeout: float = 5.0, final_snapshot: bool = False) -> None:
        """Stop and JOIN the drain thread, then optionally take one last
        snapshot of whatever the thread had applied. Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None
        if final_snapshot and self.store is not None:
            with self._step_lock:
                self.snapshot()

    def __enter__(self) -> "StreamLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def snapshot_stats(self) -> dict:
        return {"updates": self.updates, "events_seen": self.events_seen,
                "errors": self.errors,
                "last_snapshot": self.last_snapshot_base,
                "log": self.log.snapshot()}


def _cfg_fingerprint(cfg: VWConfig) -> str:
    return hashlib.sha256(repr(cfg).encode()).hexdigest()[:16]


class OnlineLearnerLoop(StreamLoop):
    """Contextual-bandit online learner: micro-batched IPS-weighted reward
    regression on the chosen action's hashed features.

    Each update is ONE jitted XLA program with static shapes: exactly
    ``batch_size`` rows (missing rows ride along with sample weight 0 — a
    mathematical no-op for loss, gradient, and adagrad accumulators) and a
    feature width padded to at least ``pad_features`` — so steady-state
    training never recompiles and a dedicated device stream stays busy.
    ``cfg.cb_type``: ``"ips"`` importance-weights each example by
    ``1/propensity`` (unbiased under the logging policy); ``"mtr"``
    regresses on the chosen action unweighted."""

    phase = "online.update"
    counter_prefix = "online.learner"

    def __init__(self, log: FeedbackLog, cfg: VWConfig,
                 store: Optional[CheckpointStore] = None,
                 initial_state: Optional[VWState] = None,
                 pad_features: int = 16, min_propensity: float = 1e-6,
                 **kw):
        super().__init__(log, store=store,
                         batch_size=kw.pop("batch_size", cfg.batch_size), **kw)
        self.cfg = cfg
        self._train_cfg = dataclasses.replace(
            cfg, batch_size=self.batch_size, num_passes=1)
        self.state = initial_state if initial_state is not None \
            else VWState.init(cfg.num_bits)
        self.pad_features = max(int(pad_features), 1)
        self.min_propensity = min_propensity

    def _update(self, events: List) -> None:
        b = self.batch_size
        rows = [np.asarray(ev.actions[int(ev.action) - 1]) for ev in events]
        p = max([self.pad_features] + [r.shape[-1] for r in rows])
        idx = np.zeros((b, p), np.int32)
        val = np.zeros((b, p), np.float32)
        y = np.zeros(b, np.float32)
        sw = np.zeros(b, np.float32)
        for i, (ev, r) in enumerate(zip(events, rows)):
            k = r.shape[-1]
            idx[i, :k] = r["idx"]
            val[i, :k] = r["val"]
            y[i] = float(ev.reward)
            sw[i] = (1.0 / max(float(ev.probability), self.min_propensity)
                     if self.cfg.cb_type == "ips" else 1.0)
        self.state, _ = train_vw(idx, val, y, self._train_cfg,
                                 sample_weight=sw,
                                 initial_state=self.state)

    # snapshots ride VWState's CheckpointStore round-trip (the same
    # digest-verified artifact path gbdt/dl/automl write through)
    def _artifacts(self) -> dict:
        return {VWState.STORE_ARTIFACT: self.state.to_bytes()}

    def _meta(self) -> dict:
        meta = super()._meta()
        meta["cfg_fingerprint"] = _cfg_fingerprint(self.cfg)
        return meta

    def _restore(self, ckpt) -> None:
        fp = ckpt.meta.get("cfg_fingerprint")
        if fp is not None and fp != _cfg_fingerprint(self.cfg):
            raise ValueError(
                f"checkpoint {ckpt.base} was written under a different "
                f"learner config (fingerprint {fp} != "
                f"{_cfg_fingerprint(self.cfg)}); refusing to resume a "
                "mismatched policy")
        data = ckpt.artifacts.get(VWState.STORE_ARTIFACT)
        if data is None:
            raise ValueError(
                f"checkpoint {ckpt.base} holds no "
                f"{VWState.STORE_ARTIFACT!r} artifact")
        self.state = VWState.from_bytes(data)

    def snapshot(self) -> Optional[str]:
        if self.store is None:
            return None
        base = self.state.save_to_store(self.store, self.updates,
                                        meta=self._meta())
        self.last_snapshot_base = base
        if self.on_snapshot is not None:
            self.on_snapshot(self.updates, base)
        return base

    def restore_latest(self) -> bool:
        if self.store is None:
            return False
        loaded = VWState.load_from_store(self.store)
        if loaded is None:
            return False
        state, ckpt = loaded
        self._restore(ckpt)          # fingerprint check; reparses cheaply
        self.state = state
        self.updates = int(ckpt.meta.get("updates", ckpt.step))
        self.events_seen = int(ckpt.meta.get("events_seen", 0))
        return True
