"""Learned performance model behind every auto-configuration knob.

One subsystem replaces the seven independently hand-tuned decision points
(gbdt kernel variant, wire-dtype ladder, tree-learner routing, bucket-ladder
geometry, dl ``param_sharding``/``accum_steps``, ``partition_stages`` cuts,
chunk geometry) with a single measurement-backed model in the spirit of
"A Learned Performance Model for Tensor Processing Units" (arXiv:2008.01040):

* a **featurizer** maps a candidate configuration (shapes, dtypes, mesh
  fingerprint, wire dtype, chunk geometry, platform) to a numeric feature
  vector (:class:`Candidate`);
* a **regressor** predicts runtime from three sources, in order of trust:
  near-matched replay of recorded training rows, a least-squares fit of
  ``ln(runtime)`` against log1p-features (analytic roofline terms enter as
  features via ``analytic_s``), and the caller's analytic prior alone;
* :func:`predict_runtime` returns ``(seconds, confidence)`` with a
  provenance record of every input;
* :func:`choose` ranks candidates and **falls back to the hand-tuned
  default** whenever confidence is low — callers always keep their
  explicit-flag bypass, so the model can only ever replace a *default*.

Training rows live in ``docs/measurements.jsonl`` (appended by every bench
arm) plus cheap cached micro-probes reused through ``core/tuned.measured_or``.
``SYNAPSEML_TPU_PERFMODEL=0`` disables the model globally (every ``choose``
returns its fallback, tagged ``"disabled"``).

See ``docs/perf-model.md`` for the feature schema and the retrain procedure.
"""
from __future__ import annotations

import collections
import json
import math
import os
import re
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import tuned

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
MEASUREMENTS_JSONL = os.path.join(_REPO, "docs", "measurements.jsonl")
MEASUREMENTS_JSON = os.path.join(_REPO, "docs", "measurements.json")


def _journal_path() -> str:
    """Training-row journal path; ``SYNAPSEML_TPU_PERF_ROWS`` overrides the
    committed ``docs/measurements.jsonl`` (tests point it at a tempdir so
    workloads never match rows captured by real bench runs)."""
    return os.environ.get("SYNAPSEML_TPU_PERF_ROWS") or MEASUREMENTS_JSONL

SCHEMA_VERSION = 1

# Confidence/fallback policy (documented in docs/perf-model.md).
MIN_CONFIDENCE = 0.5       # below this a candidate cannot displace the fallback
HYSTERESIS = 0.05          # predicted win required to move off the fallback
MATCH_DISTANCE = 0.15      # max per-feature log-space distance for a "match"
ANALYTIC_CONFIDENCE = 0.4  # trust in a pure analytic prior (< MIN_CONFIDENCE)
_FIT_MIN_R2 = 0.5          # reject fits that do not explain the data


def enabled() -> bool:
    """Global kill switch: ``SYNAPSEML_TPU_PERFMODEL=0`` disables the model."""
    return os.environ.get("SYNAPSEML_TPU_PERFMODEL", "1") not in ("0", "false")


# ---------------------------------------------------------------------------
# calibration drift: demote a family whose audits go bad
# ---------------------------------------------------------------------------

DRIFT_WINDOW = 8        # audits kept per (kind, platform)
DRIFT_MIN_AUDITS = 5    # don't judge a family on fewer
DRIFT_RATIO = 2.0       # median predicted/observed off by >2x either way


class PerfModelDriftWarning(UserWarning):
    """A decision family's predicted-vs-observed calibration degraded past
    ``DRIFT_RATIO`` (median over the last ``DRIFT_WINDOW`` audits); the
    family is demoted to its hand-tuned fallback until the process restarts
    or :func:`reset_drift` clears it."""


_drift_lock = threading.Lock()
_drift_audits: Dict[Tuple[str, str], collections.deque] = {}
_drift_warned: set = set()


def record_audit(kind: str, ratio: float,
                 platform: Optional[str] = None) -> None:
    """Feed one predicted-over-observed ratio into the drift monitor.

    Called by :meth:`Decision.audit` whenever a call site reports what a
    priced decision actually cost — the audit trail every auto-config
    decision already journals is thereby also the model's health signal.
    Crossing into drift emits one :class:`PerfModelDriftWarning` per
    family per process.
    """
    if not (ratio and math.isfinite(ratio) and ratio > 0):
        return
    key = (str(kind), platform or current_platform())
    with _drift_lock:
        dq = _drift_audits.setdefault(key, collections.deque(
            maxlen=DRIFT_WINDOW))
        dq.append(float(ratio))
        drifted, med = _drift_eval(dq)
        if drifted and key not in _drift_warned:
            _drift_warned.add(key)
            warnings.warn(
                f"perf-model drift: family {key[0]!r} on {key[1]!r} has "
                f"median predicted/observed {med:.2f}x over the last "
                f"{len(dq)} audits (bound {DRIFT_RATIO}x) — demoting to the "
                f"hand-tuned fallback", PerfModelDriftWarning,
                stacklevel=3)


def _drift_eval(ratios) -> Tuple[bool, float]:
    if len(ratios) < DRIFT_MIN_AUDITS:
        return False, 0.0
    med = float(np.median(list(ratios)))
    return (med > DRIFT_RATIO or med < 1.0 / DRIFT_RATIO), med


def drift_demoted(kind: str, platform: Optional[str] = None) -> bool:
    """True when ``kind``'s audited calibration is past the drift bound —
    :func:`choose` then returns the hand-tuned fallback unconditionally."""
    key = (str(kind), platform or current_platform())
    with _drift_lock:
        dq = _drift_audits.get(key)
        return False if dq is None else _drift_eval(dq)[0]


def reset_drift() -> None:
    """Clear the in-process drift state (tests / operator override)."""
    with _drift_lock:
        _drift_audits.clear()
        _drift_warned.clear()


# ---------------------------------------------------------------------------
# candidates, predictions, decisions
# ---------------------------------------------------------------------------

@dataclass
class Candidate:
    """One configuration alternative for a decision point.

    ``kind`` names the decision family (e.g. ``"gbdt_tree_learner"``),
    ``arm`` the alternative (e.g. ``"voting"``).  ``features`` is the
    featurizer output: a flat dict of non-negative numerics describing the
    workload (shapes, bytes, bandwidths).  ``analytic_s`` is an optional
    analytic roofline prior in seconds (or consistent relative units within
    one ``choose`` call).  ``config`` is an opaque payload handed back to
    the caller when this arm wins.
    """

    kind: str
    arm: str
    features: Dict[str, float] = field(default_factory=dict)
    analytic_s: Optional[float] = None
    config: Any = None


@dataclass
class Prediction:
    seconds: float
    confidence: float
    source: str               # "matched" | "fitted" | "analytic" | "none"
    detail: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Decision:
    """Outcome of :func:`choose`, with full provenance for audit trails."""

    kind: str
    arm: str
    config: Any
    predicted_s: Optional[float]
    confidence: float
    used_fallback: bool
    fallback_arm: str
    source: str
    candidates: List[Dict[str, Any]] = field(default_factory=list)
    features: Dict[str, float] = field(default_factory=dict)

    def provenance(self) -> Dict[str, Any]:
        """JSON-safe audit record for model/trainer metadata."""
        return {
            "kind": self.kind,
            "arm": self.arm,
            "predicted_s": self.predicted_s,
            "confidence": round(float(self.confidence), 4),
            "used_fallback": self.used_fallback,
            "fallback_arm": self.fallback_arm,
            "source": self.source,
            "features": {k: float(v) for k, v in self.features.items()},
            "candidates": self.candidates,
        }

    def audit(self, observed_s: Optional[float] = None) -> Dict[str, Any]:
        """Provenance plus predicted-vs-observed, for post-hoc calibration.

        Ratios also feed the in-process drift monitor: a family whose
        audited median goes past ``DRIFT_RATIO`` is demoted to its
        hand-tuned fallback (see :func:`record_audit`)."""
        rec = self.provenance()
        if observed_s is not None:
            rec["observed_s"] = float(observed_s)
            if self.predicted_s and observed_s:
                ratio = float(self.predicted_s) / float(observed_s)
                rec["predicted_over_observed"] = round(ratio, 4)
                record_audit(self.kind, ratio)
        return rec


# ---------------------------------------------------------------------------
# featurizer
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {"f32": 4.0, "float32": 4.0, "bf16": 2.0, "bfloat16": 2.0,
                "int8": 2.0, "f16": 2.0, "float16": 2.0, "int32": 4.0,
                "f64": 8.0, "float64": 8.0}


def featurize(shape_like: Optional[Sequence[int]] = None,
              dtype: Optional[str] = None,
              mesh: Any = None,
              wire_dtype: Optional[str] = None,
              chunk_rows: Optional[int] = None,
              depth: Optional[int] = None,
              **extra: float) -> Dict[str, float]:
    """Map a candidate configuration to a flat numeric feature dict.

    All values are non-negative floats; distances between feature dicts are
    taken per-key in log1p space, so features should scale multiplicatively
    (rows, bytes, bandwidths), not categorically.  Categorical inputs
    (platform, wire dtype) are folded into numerics (byte widths) or left to
    the ``(kind, arm, platform)`` row key.
    """
    f: Dict[str, float] = {}
    if shape_like is not None:
        dims = [int(d) for d in shape_like]
        f["rows"] = float(dims[0]) if dims else 0.0
        if len(dims) > 1:
            f["cols"] = float(np.prod(dims[1:]))
    if dtype is not None:
        f["dtype_bytes"] = _DTYPE_BYTES.get(str(dtype), 4.0)
    if wire_dtype is not None:
        # int8 wire ships value+count planes: 2 effective bytes (see voting.py)
        f["wire_bytes"] = {"f32": 4.0, "bf16": 8.0 / 3.0,
                           "int8": 2.0}.get(str(wire_dtype), 4.0)
    if mesh is not None:
        try:
            f["workers"] = float(np.prod([d for d in mesh.devices.shape]))
        except Exception:  # feature is best-effort
            pass
    if chunk_rows is not None:
        f["chunk_rows"] = float(chunk_rows)
    if depth is not None:
        f["depth"] = float(depth)
    for k, v in extra.items():
        if v is None:
            continue
        f[k] = float(v)
    return {k: max(0.0, float(v)) for k, v in f.items()}


def current_platform() -> str:
    return tuned.initialized_platform() or "cpu"


def mesh_tag(mesh: Any) -> Optional[str]:
    if mesh is None:
        return None
    try:
        return "x".join(f"{k}{v}" for k, v in
                        zip(mesh.axis_names, mesh.devices.shape))
    except Exception:  # tag is best-effort
        return None


# ---------------------------------------------------------------------------
# training-row store (docs/measurements.jsonl)
# ---------------------------------------------------------------------------

_rows_lock = threading.Lock()
_rows_cache: Dict[str, Any] = {"stat": None, "rows": None}


def append_training_row(kind: str, arm: str, features: Dict[str, float],
                        observed_s: float,
                        platform: Optional[str] = None,
                        mesh: Any = None,
                        captured_at: Optional[str] = None,
                        path: Optional[str] = None,
                        **extra: Any) -> Dict[str, Any]:
    """Append one structured training row to ``docs/measurements.jsonl``.

    Rows are the schema the featurizer consumes: the model's training set
    grows with every bench run.  Writes are single ``O_APPEND`` lines, safe
    under concurrent bench arms.  Unlike ``bench.record_measurement`` these
    rows are honest about platform — a cpu row trains the cpu model and can
    never leak into tpu predictions (rows are keyed by platform).
    """
    if captured_at is None:
        import datetime
        captured_at = datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds")
    row = {
        "perf_row": SCHEMA_VERSION,
        "kind": str(kind),
        "arm": str(arm),
        "features": {k: float(v) for k, v in features.items()},
        "observed_s": float(observed_s),
        "platform": platform or current_platform(),
        "captured_at": captured_at,
    }
    tag = mesh_tag(mesh) if mesh is not None else None
    if tag:
        row["mesh"] = tag
    row.update(extra)
    path = path or _journal_path()
    line = json.dumps(row, sort_keys=True) + "\n"
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line.encode("utf-8"))
    finally:
        os.close(fd)
    return row


def _parse_journal(path: str) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:  # host-side journal read, never under trace
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or not rec.get("perf_row"):
                continue
            if not isinstance(rec.get("features"), dict):
                continue
            try:
                rec["observed_s"] = float(rec["observed_s"])
            except (KeyError, TypeError, ValueError):
                continue
            if rec["observed_s"] <= 0:
                continue
            rows.append(rec)
    return rows


def training_rows(kind: Optional[str] = None,
                  platform: Optional[str] = None,
                  path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse training rows from the jsonl journal (mtime/size-cached)."""
    path = path or _journal_path()
    try:
        st = os.stat(path)
        stat_key = (path, st.st_mtime_ns, st.st_size)
    except OSError:
        return []
    with _rows_lock:
        cached = _rows_cache["stat"] == stat_key
        rows = list(_rows_cache["rows"]) if cached else None
    if rows is None:
        # parse OUTSIDE the lock: the journal read is host file I/O and
        # heartbeat/monitor threads price steps through this cache — two
        # racing fills both parse the same snapshot (idempotent), nobody
        # stalls behind the file
        parsed = _parse_journal(path)
        with _rows_lock:
            _rows_cache["stat"] = stat_key
            _rows_cache["rows"] = parsed
        rows = list(parsed)
    if kind is not None:
        rows = [r for r in rows if r.get("kind") == kind]
    if platform is not None:
        rows = [r for r in rows if r.get("platform") == platform]
    return rows


def backfill_training_rows(json_path: Optional[str] = None,
                           jsonl_path: Optional[str] = None) -> int:
    """Convert legacy ``docs/measurements.json`` replay data to perf rows.

    Idempotent: rows carry ``backfilled_from`` = (metric, captured_at) and a
    second run appends nothing.  Only record families that encode a real
    A/B are converted: the gbdt kernel-variant sweep and the voting-vs-data
    collective A/B.
    """
    json_path = json_path or MEASUREMENTS_JSON
    jsonl_path = jsonl_path or _journal_path()
    try:
        with open(json_path, "r", encoding="utf-8") as fh:  # host-side journal read, never under trace
            recs = json.load(fh)
    except (OSError, ValueError):
        return 0
    have = {tuple(r.get("backfilled_from", ()))
            for r in training_rows(path=jsonl_path)}
    added = 0
    for rec in recs if isinstance(recs, list) else []:
        metric = rec.get("metric")
        src = (metric, rec.get("captured_at"))
        if src in have:
            continue
        platform = rec.get("platform", "cpu").split("-")[0]
        if metric == "gbdt_train_row_iters_per_sec_per_chip" and \
                isinstance(rec.get("variants"), dict):
            for arm, rate in rec["variants"].items():
                if not rate:
                    continue
                append_training_row(
                    "gbdt_kernel", arm, {}, 1.0 / float(rate),
                    platform=platform, captured_at=rec.get("captured_at"),
                    path=jsonl_path, backfilled_from=list(src),
                    unit="s/row-iteration")
                added += 1
            have.add(src)
        elif metric == "gbdt_voting_vs_data_parallel_speedup" and \
                "mesh" in rec.get("platform", ""):
            # rates are embedded in the unit string: "... voting 3856 r-i/s
            # ... data-parallel 26600 r-i/s ..."
            m = re.search(r"voting ([\d.]+) r-i/s.*data-parallel ([\d.]+) "
                          r"r-i/s", rec.get("unit", ""))
            if not m:
                continue
            workers = rec.get("platform", "").rsplit("-", 1)[-1]
            feats = {"workers": float(workers)} if workers.isdigit() else {}
            cm = re.search(r"(\d+) cols", rec.get("unit", ""))
            if cm:
                feats["nfeat"] = float(cm.group(1))
            for arm, rate in (("voting", m.group(1)), ("data", m.group(2))):
                append_training_row(
                    "gbdt_tree_learner", arm, feats, 1.0 / float(rate),
                    platform=platform, captured_at=rec.get("captured_at"),
                    path=jsonl_path, backfilled_from=list(src),
                    unit="s/row-iteration")
                added += 1
            have.add(src)
    return added


# ---------------------------------------------------------------------------
# the regressor
# ---------------------------------------------------------------------------

def _feature_distance(a: Dict[str, float], b: Dict[str, float]) -> float:
    """Max per-key distance in log1p space; missing keys count as far."""
    keys = set(a) | set(b)
    if not keys:
        return 0.0
    worst = 0.0
    for k in keys:
        if k not in a or k not in b:
            return math.inf
        worst = max(worst, abs(math.log1p(a[k]) - math.log1p(b[k])))
    return worst


def predict_runtime(candidate: Candidate,
                    rows: Optional[List[Dict[str, Any]]] = None,
                    platform: Optional[str] = None) -> Tuple[float, float]:
    """Predict runtime for one candidate: ``(seconds, confidence)``.

    Prefers near-matched replay of recorded rows, then a least-squares fit
    of ``ln(observed_s)`` on ``[1, log1p(features)...]``, then the caller's
    analytic prior.  Use :func:`predict` for the full provenance record.
    """
    p = predict(candidate, rows=rows, platform=platform)
    return p.seconds, p.confidence


def predict(candidate: Candidate,
            rows: Optional[List[Dict[str, Any]]] = None,
            platform: Optional[str] = None) -> Prediction:
    platform = platform or current_platform()
    if rows is None:
        rows = training_rows(kind=candidate.kind, platform=platform)
    arm_rows = [r for r in rows if r.get("arm") == candidate.arm]

    # 1. near-matched replay: the strongest evidence is a recorded run of
    #    this very (kind, arm, platform) at (log-)nearby feature values.
    scored = []
    for r in arm_rows:
        d = _feature_distance(candidate.features, r["features"])
        if d <= MATCH_DISTANCE:
            scored.append((d, r["observed_s"]))
    if scored:
        weights = [math.exp(-(d / MATCH_DISTANCE) ** 2) for d, _ in scored]
        sec = sum(w * s for w, (_, s) in zip(weights, scored)) / sum(weights)
        d_best = min(d for d, _ in scored)
        conf = max(0.6, min(0.95, 0.92 - d_best))
        return Prediction(sec, conf, "matched",
                          {"rows_matched": len(scored),
                           "distance": round(d_best, 4)})

    # 2. fitted residual model: ln(observed) ~ [1, log1p(f_k)...] by least
    #    squares across this arm's rows (analytic terms enter as features).
    keys = sorted({k for r in arm_rows for k in r["features"]})
    usable = [r for r in arm_rows
              if all(k in r["features"] for k in keys)]
    if keys and len(usable) >= len(keys) + 2 and \
            all(k in candidate.features for k in keys):
        X = np.array([[1.0] + [math.log1p(r["features"][k]) for k in keys]
                      for r in usable])
        y = np.array([math.log(r["observed_s"]) for r in usable])
        if np.linalg.matrix_rank(X) == X.shape[1]:
            beta, *_ = np.linalg.lstsq(X, y, rcond=None)
            resid = y - X @ beta
            ss_tot = float(((y - y.mean()) ** 2).sum())
            r2 = 1.0 - float((resid ** 2).sum()) / ss_tot if ss_tot > 0 else 0.0
            if r2 >= _FIT_MIN_R2:
                xc = np.array([1.0] + [math.log1p(candidate.features[k])
                                       for k in keys])
                sec = float(math.exp(float(xc @ beta)))
                conf = min(0.75, 0.5 + 0.25 * r2)
                # extrapolating past the training envelope is a guess
                for j, k in enumerate(keys, start=1):
                    lo, hi = X[:, j].min(), X[:, j].max()
                    if not (lo - 1.0 <= xc[j] <= hi + 1.0):
                        conf = min(conf, ANALYTIC_CONFIDENCE)
                return Prediction(sec, conf, "fitted",
                                  {"rows_fit": len(usable),
                                   "r2": round(r2, 4), "keys": keys})

    # 3. analytic roofline prior from the caller (bandwidth probes etc.)
    if candidate.analytic_s is not None:
        return Prediction(float(candidate.analytic_s), ANALYTIC_CONFIDENCE,
                          "analytic", {})

    return Prediction(math.inf, 0.0, "none", {})


def choose(candidates: Sequence[Candidate],
           fallback_arm: str,
           min_confidence: float = MIN_CONFIDENCE,
           hysteresis: float = HYSTERESIS,
           platform: Optional[str] = None) -> Decision:
    """Rank candidates; fall back to the hand-tuned default on low confidence.

    The fallback arm (the existing hand-tuned choice) wins unless some other
    candidate is predicted at least ``hysteresis`` faster *and* both sides of
    that comparison are confident.  Every input lands in the returned
    :class:`Decision` so call sites can audit the choice into metadata.
    """
    if not candidates:
        raise ValueError("choose() needs at least one candidate")
    kind = candidates[0].kind
    platform = platform or current_platform()
    by_arm = {c.arm: c for c in candidates}
    fb = by_arm.get(fallback_arm, candidates[0])

    if not enabled():
        return Decision(kind, fb.arm, fb.config, None, 0.0, True,
                        fallback_arm, "disabled", [], dict(fb.features))
    if drift_demoted(kind, platform):
        # audited calibration for this family went bad — the hand-tuned
        # fallback wins until the process restarts or reset_drift()
        return Decision(kind, fb.arm, fb.config, None, 0.0, True,
                        fallback_arm, "drift_demoted", [], dict(fb.features))

    rows = training_rows(kind=kind, platform=platform)
    preds = {c.arm: predict(c, rows=rows, platform=platform)
             for c in candidates}
    prov = [{"arm": a, "predicted_s": (None if math.isinf(p.seconds)
                                       else round(p.seconds, 9)),
             "confidence": round(p.confidence, 4), "source": p.source,
             **p.detail}
            for a, p in preds.items()]

    confident = {a: p for a, p in preds.items()
                 if p.confidence >= min_confidence
                 and not math.isinf(p.seconds)}
    fbp = preds[fb.arm]
    pick = fb
    used_fallback = True
    if confident:
        best_arm = min(confident, key=lambda a: confident[a].seconds)
        best = confident[best_arm]
        if best_arm == fb.arm:
            pick, used_fallback = by_arm[best_arm], False
        elif fb.arm in confident and \
                best.seconds < confident[fb.arm].seconds * (1 - hysteresis):
            # only displace the hand-tuned default on a confident, clear win
            pick, used_fallback = by_arm[best_arm], False
    p = preds[pick.arm]
    return Decision(
        kind, pick.arm, pick.config,
        None if math.isinf(p.seconds) else float(p.seconds),
        float(p.confidence) if not used_fallback else float(fbp.confidence),
        used_fallback, fallback_arm, p.source if not used_fallback
        else (fbp.source if not math.isinf(fbp.seconds) else "fallback"),
        prov, dict(pick.features))


# ---------------------------------------------------------------------------
# micro-probes (cached through core/tuned.measured_or)
# ---------------------------------------------------------------------------

def link_bandwidth(mesh: Any) -> Optional[float]:
    """Cached ~1MB timed all-reduce link probe (bytes/s), or None."""
    try:
        from ..parallel.collectives import probe_link_bandwidth
        fp = tuned.mesh_fingerprint(mesh)
        return float(tuned.measured_or(("link_bytes_per_s", fp),
                                       lambda: probe_link_bandwidth(mesh)))
    except Exception:  # probe failure means "unknown"
        return None


def h2d_bandwidth() -> Optional[float]:
    """Cached 4MiB host-to-device copy probe (bytes/s), or None."""
    try:
        from ..io.ingest import _probe_h2d_bandwidth
        return float(_probe_h2d_bandwidth())
    except Exception:  # probe failure means "unknown"
        return None


# ---------------------------------------------------------------------------
# per-picker suggestion helpers
# ---------------------------------------------------------------------------

def suggest_kernel_variant(platform: Optional[str] = None
                           ) -> Tuple[Optional[Dict[str, str]], Decision]:
    """Suggest (partition_impl, row_layout) from kernel-variant sweep rows.

    Arms mirror ``tools/perf_tune.py`` variants: ``partition_sort``,
    ``partition_scan``, ``masked``.  Returns ``(None, decision)`` when the
    model has nothing confident to say — callers keep their hand-tuned
    fallback (``sort``/``partition``).
    """
    arms = {
        "partition_sort": {"partition_impl": "sort", "row_layout": "partition"},
        "partition_scan": {"partition_impl": "scan", "row_layout": "partition"},
        "masked": {"partition_impl": "sort", "row_layout": "masked"},
    }
    cands = [Candidate("gbdt_kernel", arm, {}, config=cfg)
             for arm, cfg in arms.items()]
    dec = choose(cands, fallback_arm="partition_sort", platform=platform)
    return (None if dec.used_fallback else dict(dec.config)), dec


def suggest_wire_dtype(n_rows: float, nfeat: float, workers: float,
                       max_bin: float, num_leaves: float,
                       link_bps: Optional[float],
                       fallback: str = "f32",
                       platform: Optional[str] = None) -> Tuple[str, Decision]:
    """Suggest ``hist_allreduce_dtype`` for distributed histogram merges.

    Analytic prior: per-tree collective seconds = splits x histogram wire
    bytes / link bandwidth (matching ``voting.collective_bytes_per_split``).
    Recorded bench rows (kind ``gbdt_wire_dtype``) override it when matched.
    """
    cands = []
    for wd in ("f32", "bf16", "int8"):
        feats = featurize(wire_dtype=wd, rows=n_rows, nfeat=nfeat,
                          workers=workers, max_bin=max_bin,
                          num_leaves=num_leaves)
        analytic = None
        if link_bps:
            wire_bytes = feats["wire_bytes"]
            per_split = nfeat * max_bin * 3.0 * wire_bytes
            analytic = max(1, num_leaves - 1) * per_split / float(link_bps)
        cands.append(Candidate("gbdt_wire_dtype", wd, feats,
                               analytic_s=analytic, config=wd))
    dec = choose(cands, fallback_arm=fallback, platform=platform)
    return dec.arm, dec


def suggest_bucket_growth(max_batch_size: int,
                          fallback: float = 2.0,
                          platform: Optional[str] = None
                          ) -> Tuple[float, Decision]:
    """Suggest the bucket-ladder growth factor for :class:`BucketedRunner`.

    No analytic prior — compile cost vs padding waste is exactly the kind of
    trade only measurement settles. Arms come from recorded ladder A/Bs
    (kind ``serving_bucket_growth``, written by the ci.sh auto-config
    guard's micro benchmark); absent a near-matched row the hand-tuned 2.0
    wins.
    """
    cands = [Candidate("serving_bucket_growth", f"g{g}",
                       featurize(max_batch_size=max_batch_size),
                       config=g)
             for g in (1.5, 2.0, 4.0)]
    dec = choose(cands, fallback_arm=f"g{fallback}", platform=platform)
    return (float(dec.config) if dec.config is not None else fallback), dec


def suggest_param_sharding(param_bytes: float, batch: float, devices: float,
                           stages: float = 0.0,
                           fallback: str = "replicated",
                           platform: Optional[str] = None
                           ) -> Tuple[str, Decision]:
    """Suggest dl ``param_sharding`` from recorded sharding-arm step times."""
    arms = ["replicated", "zero"] + (["pipeline"] if stages >= 2 else [])
    cands = [Candidate("dl_param_sharding", a,
                       featurize(param_bytes=param_bytes, batch=batch,
                                 workers=devices,
                                 **({"stages": stages} if a == "pipeline"
                                    else {})),
                       config=a)
             for a in arms]
    dec = choose(cands, fallback_arm=fallback, platform=platform)
    return dec.arm, dec


def suggest_accum_steps(batch: float, param_bytes: float,
                        state_budget_bytes: Optional[float],
                        fallback: int = 1,
                        platform: Optional[str] = None
                        ) -> Tuple[int, Decision]:
    """Suggest gradient-accumulation steps.

    Analytic prior: accumulation trades per-step activation memory for more
    dispatches — runtime grows roughly linearly in the fixed per-microbatch
    overhead, so the model prefers the smallest ``accum_steps`` whose
    activation slice fits the state budget (when one is known).
    """
    divisors = [k for k in (1, 2, 4, 8) if batch % k == 0 and k <= batch]
    cands = []
    for k in divisors:
        feats = featurize(batch=batch, param_bytes=param_bytes, accum=k)
        # fixed dispatch overhead per microbatch dominates on small batches
        analytic = 1.0 + 0.05 * (k - 1)
        if state_budget_bytes and param_bytes / k > state_budget_bytes:
            analytic = None  # does not fit: never an analytic winner
        cands.append(Candidate("dl_accum_steps", f"a{k}", feats,
                               analytic_s=analytic, config=k))
    dec = choose(cands, fallback_arm=f"a{fallback}", platform=platform)
    return (int(dec.config) if dec.config is not None else fallback), dec


def suggest_pipeline_schedule(stages: float, microbatches: float,
                              fallback: str = "fill_drain",
                              platform: Optional[str] = None
                              ) -> Tuple[str, Decision]:
    """Suggest fill_drain vs overlap for MPMD pipelines.

    Analytic prior prices the bubble: fill_drain idles ``(S-1)/(M+S-1)`` of
    the schedule, overlap hides roughly half the bubble behind compute at
    some dispatch overhead.  Recorded rows from
    ``bench_dl_overlap_pipeline`` (kind ``dl_pipeline_schedule``) take over
    once captured on the target fabric.
    """
    S, M = max(1.0, stages), max(1.0, microbatches)
    total = M + S - 1.0
    cands = [
        Candidate("dl_pipeline_schedule", "fill_drain",
                  featurize(stages=S, microbatches=M),
                  analytic_s=total / M, config="fill_drain"),
        Candidate("dl_pipeline_schedule", "overlap",
                  featurize(stages=S, microbatches=M),
                  analytic_s=(M + 0.5 * (S - 1.0)) / M * 1.02,
                  config="overlap"),
    ]
    dec = choose(cands, fallback_arm=fallback, platform=platform)
    return dec.arm, dec


def suggest_seq_attention(seq_len: float, heads: float, seq_shards: float,
                          head_dim: float = 64.0, batch: float = 1.0,
                          link_bps: Optional[float] = None,
                          fallback: str = "ring",
                          platform: Optional[str] = None
                          ) -> Tuple[str, Decision]:
    """Suggest ring vs Ulysses for seq-sharded self-attention.

    Analytic prior prices per-layer wire bytes over the ``seq`` axis: ring
    rotates the local K/V blocks ``p-1`` times (each step moves
    ``2·B·(S/p)·H·D`` activation bytes point-to-point, overlapped with the
    block compute), while Ulysses re-shards with four all-to-alls (q/k/v in,
    output back), each moving ``(p-1)/p`` of the full ``B·S·H·D`` activation.
    Ring's ppermute overlaps with compute, so its wire time is discounted;
    Ulysses is only a candidate when heads divide by the shard count (the
    head-scatter all-to-all needs even splits).  Recorded rows from
    ``bench_dl_seq`` (kind ``seq_attention``) take over once captured on the
    target fabric.
    """
    p = max(1.0, seq_shards)
    S, H, D, B = (max(1.0, seq_len), max(1.0, heads), max(1.0, head_dim),
                  max(1.0, batch))
    elem_bytes = 4.0 * B * S * H * D
    # probed link bandwidth when the caller has one; a nominal constant
    # otherwise (the arm ordering is invariant to the constant)
    link = float(link_bps) if link_bps else 1e9
    feats = featurize(seq_len=S, heads=H, seq_shards=p, head_dim=D, batch=B)
    # ring: (p-1) rotations of local K+V, half hidden behind block compute
    ring_s = (p - 1.0) * 2.0 * (elem_bytes / p) / link * 0.5
    # ulysses: 4 unoverlapped all-to-alls of (p-1)/p of the activation
    uly_s = 4.0 * elem_bytes * (p - 1.0) / p / link
    cands = [Candidate("seq_attention", "ring", feats,
                       analytic_s=ring_s, config="ring")]
    if H % p == 0:
        cands.append(Candidate("seq_attention", "ulysses", feats,
                               analytic_s=uly_s, config="ulysses"))
    dec = choose(cands, fallback_arm=fallback, platform=platform)
    return dec.arm, dec


def suggest_stage_cuts(unit_costs: Sequence[float], num_stages: int
                       ) -> Tuple[List[int], Decision]:
    """Cost-balanced contiguous pipeline cuts (min-max stage cost by DP).

    Deterministic given costs; the "model" here is the per-unit cost vector
    (parameter bytes or measured per-unit step time).  Returns stage sizes
    summing to ``len(unit_costs)``.  Falls back to count-balanced cuts when
    costs are degenerate.
    """
    n, S = len(unit_costs), int(num_stages)
    base, rem = divmod(n, S)
    fallback_sizes = [base + (1 if s < rem else 0) for s in range(S)]
    costs = [max(0.0, float(c)) for c in unit_costs]
    if n < S or S < 1 or sum(costs) <= 0:
        dec = Decision("dl_stage_cuts", "count_balanced", fallback_sizes,
                       None, 0.0, True, "count_balanced", "fallback",
                       [], {"units": float(n), "stages": float(S)})
        return fallback_sizes, dec
    prefix = np.concatenate([[0.0], np.cumsum(costs)])
    # dp[s][i]: minimal max-stage-cost splitting units[:i] into s stages
    INF = math.inf
    dp = [[INF] * (n + 1) for _ in range(S + 1)]
    cut = [[0] * (n + 1) for _ in range(S + 1)]
    dp[0][0] = 0.0
    for s in range(1, S + 1):
        for i in range(s, n + 1):
            for j in range(s - 1, i):
                cost = max(dp[s - 1][j], prefix[i] - prefix[j])
                if cost < dp[s][i]:
                    dp[s][i], cut[s][i] = cost, j
    sizes: List[int] = []
    i = n
    for s in range(S, 0, -1):
        j = cut[s][i]
        sizes.append(i - j)
        i = j
    sizes.reverse()
    if min(sizes) < 1:  # degenerate costs: keep the count-balanced default
        sizes = fallback_sizes
    used_fallback = sizes == fallback_sizes
    dec = Decision("dl_stage_cuts", "cost_balanced", sizes,
                   float(dp[S][n]), 0.9, used_fallback, "count_balanced",
                   "analytic", [{"arm": "cost_balanced",
                                 "max_stage_cost": float(dp[S][n])}],
                   {"units": float(n), "stages": float(S)})
    return sizes, dec


def suggest_chunk_rows(row_bytes: float, depth: int,
                       fallback_rows: int,
                       h2d_bps: Optional[float] = None,
                       platform: Optional[str] = None
                       ) -> Tuple[int, Decision]:
    """Suggest streaming chunk rows for ``io/ingest``.

    Candidates are a power-of-two ladder around the probe-derived fallback;
    analytic prior per row: ``row_bytes / h2d_bw + dispatch_overhead /
    chunk_rows``.  Only a measured match (kind ``io_chunk_rows``) displaces
    the probe formula — the formula *is* the analytic optimum.
    """
    ladder = sorted({fallback_rows} |
                    {1 << p for p in range(13, 21)
                     if (1 << p) <= 4 * fallback_rows
                     and (1 << p) >= max(1024, fallback_rows // 4)})
    dispatch_s = 2e-4  # per-chunk dispatch + pump hand-off overhead
    cands = []
    for cr in ladder:
        analytic = None
        if h2d_bps:
            analytic = row_bytes / float(h2d_bps) + dispatch_s / float(cr)
        cands.append(Candidate(
            "io_chunk_rows", f"c{cr}",
            featurize(row_bytes=row_bytes, depth=depth, chunk_rows=cr),
            analytic_s=analytic, config=int(cr)))
    dec = choose(cands, fallback_arm=f"c{fallback_rows}", platform=platform)
    return (int(dec.config) if dec.config is not None else fallback_rows), dec


SECOND_PASS_BUDGET = 0.10  # exact re-sketch may cost this fraction of training


def suggest_sketch_second_pass(n_rows: float, nfeat: float,
                               rows_per_s: Optional[float],
                               train_s_estimate: Optional[float],
                               platform: Optional[str] = None
                               ) -> Tuple[bool, Decision]:
    """Decide whether an exact second sketch pass is worth it (ROADMAP 2d).

    When the streaming sketch fell back to reservoir sampling
    (``sketch_exact=False``), an extra full pass buys exact bin boundaries.
    This is not a runtime argmin — the pass is pure extra cost paid for
    sketch quality — so the rule is a budget: take the pass when its
    predicted cost (measured rows of kind ``gbdt_sketch_pass`` when
    available, else the analytic ``rows / sketch_rate`` prior) is under
    ``SECOND_PASS_BUDGET`` of the estimated training cost.  The fallback
    (skip) preserves today's behavior whenever the model cannot price it.
    """
    analytic = n_rows / float(rows_per_s) if rows_per_s else None
    cand = Candidate("gbdt_sketch_pass", "exact",
                     featurize(rows=n_rows, nfeat=nfeat),
                     analytic_s=analytic)
    p = predict(cand, platform=platform)
    take = bool(
        enabled() and train_s_estimate
        and not math.isinf(p.seconds)
        and p.confidence >= ANALYTIC_CONFIDENCE
        and p.seconds <= SECOND_PASS_BUDGET * float(train_s_estimate))
    dec = Decision(
        "gbdt_sketch_pass", "exact" if take else "skip", take,
        None if math.isinf(p.seconds) else float(p.seconds),
        float(p.confidence), not take, "skip",
        p.source if take else ("disabled" if not enabled() else p.source),
        [{"arm": "exact",
          "predicted_s": None if math.isinf(p.seconds) else float(p.seconds),
          "confidence": round(p.confidence, 4), "source": p.source,
          "budget_s": (SECOND_PASS_BUDGET * float(train_s_estimate)
                       if train_s_estimate else None)}],
        dict(cand.features))
    return take, dec


__all__ = [
    "Candidate", "Prediction", "Decision", "featurize", "enabled",
    "PerfModelDriftWarning", "record_audit", "drift_demoted", "reset_drift",
    "append_training_row", "training_rows", "backfill_training_rows",
    "predict_runtime", "predict", "choose",
    "link_bandwidth", "h2d_bandwidth",
    "suggest_kernel_variant", "suggest_wire_dtype", "suggest_bucket_growth",
    "suggest_param_sharding", "suggest_accum_steps",
    "suggest_pipeline_schedule", "suggest_seq_attention",
    "suggest_stage_cuts", "suggest_chunk_rows",
    "suggest_sketch_second_pass",
    "MEASUREMENTS_JSONL", "MEASUREMENTS_JSON",
]
