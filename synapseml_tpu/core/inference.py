"""Shape-bucketed, async-dispatch inference runtime (BucketedRunner).

Every inference surface in the repo — the serving micro-batcher
(io/serving.py), the distributed serving workers (io/distributed_serving.py),
ONNX batch inference (onnx/model.py) and GBDT predict/serving
(gbdt/boosting.py) — ultimately feeds variable-length micro-batches into a
jitted XLA program. On XLA hardware every distinct batch size is a fresh
compile, and with request-driven batch formation the observed sizes are
essentially arbitrary: a serving process quietly pays a multi-second compile
for batch size 17, then again for 18, then 23... while the profile shows
nothing but `jit_` compilations. Padded/misaligned shapes are a first-class
cost on TPUs (arXiv:2008.01040), and padding up to a small ladder of static
shapes is the standard fix.

:class:`BucketedRunner` wraps one callable with:

* **Bucket ladder** — batch dimension padded up to a geometric ladder of
  bucket sizes (1, 2, 4, ... ``max_batch_size`` by default), so the program
  compiles once per *bucket* instead of once per observed size. Batches
  larger than ``max_batch_size`` are chunked into full max-size buckets plus
  one bucketed tail. Padding repeats the last real row (a vectorized gather,
  never ``np.repeat`` row duplication), and outputs are sliced back to the
  real row count so padded rows can never leak into replies.
* **AOT warmup** — :meth:`warmup` compiles every bucket ahead of time
  (``jax.jit(...).lower(...).compile()`` on ShapeDtypeStructs — no example
  batch is executed) through :func:`core.compile_cache.enable_compile_cache`
  so the XLA executables persist across processes. After warmup the
  steady-state compile count is **zero** — asserted by the CI serving perf
  guard via the runner's counters.
* **Async dispatch** — :meth:`dispatch` launches the device computation for
  every chunk without blocking (jax's async dispatch) and returns a
  :class:`PendingBatch`; the host only synchronizes in
  :meth:`PendingBatch.result`, i.e. when replies are written. Input buffers
  are donated to XLA on backends that support donation (TPU/GPU), so the
  padded staging buffer is reused as the output allocation.
* **Counters** — per-bucket compile and hit counts (:meth:`stats`), the
  observability contract the serving bench and CI guard read.

The runner is deliberately framework-free: it takes any
``fn(*batch_leading_arrays) -> array | tuple`` and returns numpy. See
docs/serving-perf.md for the serving integration and tuning guidance.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["BucketedRunner", "PendingBatch", "bucket_ladder"]


def _witness_observe(site, tree, expect=None):
    # dtype-witness probe (testing/dtypewitness.py): inert unless the
    # witness module is loaded — sys.modules lookup keeps product imports
    # free of the testing package
    w = sys.modules.get("synapseml_tpu.testing.dtypewitness")
    if w is not None and w.active():
        w.observe(site, tree, expect)


def bucket_ladder(max_batch_size: int, growth: float = 2.0,
                  min_bucket: int = 1) -> Tuple[int, ...]:
    """Geometric ladder of batch buckets: ``min_bucket`` multiplied by
    ``growth`` (rounded up, strictly increasing) until ``max_batch_size``,
    which is always the last rung."""
    if max_batch_size < 1:
        raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
    if not 1 <= min_bucket <= max_batch_size:
        raise ValueError(f"min_bucket must be in [1, {max_batch_size}], "
                         f"got {min_bucket}")
    if growth <= 1.0:
        raise ValueError(f"growth must be > 1.0, got {growth}")
    ladder: List[int] = []
    b = float(min_bucket)
    while b < max_batch_size:
        nxt = int(b) if b == int(b) else int(b) + 1
        if not ladder or nxt > ladder[-1]:
            ladder.append(nxt)
        b *= growth
    if not ladder or ladder[-1] != max_batch_size:
        ladder.append(max_batch_size)
    return tuple(ladder)


def _pad_to(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad the leading dim up to ``bucket`` by repeating the last real row —
    one vectorized gather into a FRESH buffer (safe to donate; repeated rows
    keep the padded lanes numerically benign, e.g. no log(0) NaNs)."""
    n = arr.shape[0]
    if n == bucket:
        # fresh copy so donation can never invalidate a caller-owned buffer
        return np.ascontiguousarray(arr)
    idx = np.minimum(np.arange(bucket), n - 1)
    return arr[idx]


class PendingBatch:
    """Handle for dispatched-but-unsynchronized work. The device computation
    for every chunk is already in flight; :meth:`result` is the single host
    sync point (where serving writes replies)."""

    def __init__(self, chunks: List[Tuple[Any, int, int]], treedef,
                 single: bool, n_total: int):
        # chunks: (output leaves, real_rows, bucket) per dispatched chunk
        self._chunks = chunks
        self._treedef = treedef
        self._single = single
        self.num_rows = n_total

    def block_until_ready(self) -> "PendingBatch":
        import jax

        for leaves, _, _ in self._chunks:
            for leaf in leaves:
                jax.block_until_ready(leaf)
        return self

    def result(self):
        """Materialize to numpy, sliced to the real row count (padded rows
        never leak). Blocks until the device work completes."""
        per_leaf: List[List[np.ndarray]] = None
        for leaves, real, bucket in self._chunks:
            if per_leaf is None:
                per_leaf = [[] for _ in leaves]
            for slot, leaf in zip(per_leaf, leaves):
                host = np.asarray(leaf)
                if host.ndim and host.shape[0] == bucket:
                    host = host[:real]
                elif len(self._chunks) > 1:
                    raise ValueError(
                        "BucketedRunner: output leaf has no leading batch "
                        f"dimension (shape {host.shape}) but the input was "
                        "chunked; results cannot be concatenated")
                slot.append(host)
        outs = [parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
                for parts in per_leaf]
        if self._single:
            return outs[0]
        import jax

        return jax.tree_util.tree_unflatten(self._treedef, outs)


class BucketedRunner:
    """Shared bucketing + AOT-warmup + async-dispatch execution layer.

    ``fn`` is any callable over one or more batch-leading arrays (all
    sharing the same leading dimension) returning an array or a tuple/list
    of arrays. Do NOT pre-wrap ``fn`` in ``jax.jit`` — the runner owns the
    jit boundary (it compiles one executable per bucket).

    ``donate=None`` (auto) donates input buffers on TPU/GPU backends and
    skips donation on CPU, where XLA does not implement it (avoiding a
    warning per compile).
    """

    def __init__(self, fn: Callable, max_batch_size: int = 64,
                 growth: Optional[float] = None, min_bucket: int = 1,
                 donate: Optional[bool] = None, pass_mask: bool = False,
                 name: Optional[str] = None):
        self.fn = fn
        self.max_batch_size = int(max_batch_size)
        # ladder geometry: an explicit growth bypasses auto-configuration;
        # None asks core/perfmodel, whose recorded ladder A/Bs can move the
        # factor off 2.0 only for a confidently matched workload — the
        # decision (or its fallback) is auditable via stats()["autoconfig"]
        self._autoconfig: Optional[dict] = None
        if growth is None:
            growth = self._auto_growth()
        self.buckets = bucket_ladder(self.max_batch_size, growth, min_bucket)
        self.donate = donate
        self.pass_mask = pass_mask
        self.name = name or getattr(fn, "__name__", "fn")
        self._jitted = None
        self._compiled: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self._compile_counts: Dict[int, int] = {}
        self._hit_counts: Dict[int, int] = {}
        self._warmup_compiles = 0

    def _auto_growth(self) -> float:
        """Growth factor from the learned perf model (fallback 2.0)."""
        try:
            from . import perfmodel

            g, dec = perfmodel.suggest_bucket_growth(self.max_batch_size)
            self._autoconfig = dec.provenance()
            return g
        except Exception:  # model failure keeps 2.0
            return 2.0

    # --- bucket selection ------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest ladder rung covering ``n`` (``max_batch_size`` for any
        larger chunked batch)."""
        if n < 1:
            raise ValueError(f"batch of {n} rows has no bucket")
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch_size

    # --- compilation -----------------------------------------------------
    def _ensure_jitted(self) -> dict:
        """Lazy per-arity jit wrapper cache. Donation resolves here (needs
        the backend): input buffers are donated on TPU/GPU where XLA reuses
        them for outputs; CPU does not implement donation (a warning per
        compile), so auto mode skips it there."""
        import jax

        if self._jitted is None:
            donate = self.donate
            if donate is None:
                donate = jax.default_backend() not in ("cpu",)
            self._donate = bool(donate)
            self._jitted = {}
        return self._jitted

    @staticmethod
    def _spec_of(arr) -> Tuple[Tuple[int, ...], Any]:
        a = np.asarray(arr) if not hasattr(arr, "shape") else arr
        _witness_observe("core.bucketed.spec", a)
        return tuple(a.shape[1:]), np.dtype(getattr(a, "dtype", None) or
                                            np.asarray(arr).dtype)

    def _executable(self, bucket: int, specs: Tuple, *, warmup: bool = False):
        """Compiled executable for (bucket, arg specs); compiles on miss and
        counts it. ``specs`` is a tuple of (trailing-shape, dtype) per arg."""
        key = (bucket, specs)
        with self._lock:
            hit = self._compiled.get(key)
            if hit is not None:
                if not warmup:
                    self._hit_counts[bucket] = \
                        self._hit_counts.get(bucket, 0) + 1
                return hit
        import jax

        jits = self._ensure_jitted()
        nargs = len(specs) + (1 if self.pass_mask else 0)
        jfn = jits.get(nargs)
        if jfn is None:
            donate = tuple(range(len(specs))) if self._donate else ()
            jfn = jax.jit(self.fn, donate_argnums=donate)
            jits[nargs] = jfn
        avals = [jax.ShapeDtypeStruct((bucket,) + shape, dtype)
                 for shape, dtype in specs]
        if self.pass_mask:
            avals.append(jax.ShapeDtypeStruct((bucket,), np.bool_))
        compiled = jfn.lower(*avals).compile()
        with self._lock:
            # a racing thread may have compiled the same key; keep the first
            existing = self._compiled.get(key)
            if existing is not None:
                return existing
            self._compiled[key] = compiled
            self._compile_counts[bucket] = \
                self._compile_counts.get(bucket, 0) + 1
            if warmup:
                self._warmup_compiles += 1
        return compiled

    def warmup(self, *templates, persistent_cache: bool = True) -> dict:
        """AOT-compile EVERY bucket for the argument signature described by
        ``templates`` (one array-like per ``fn`` argument; only trailing
        dims and dtype matter — pass a single example row or a full batch).
        With ``persistent_cache`` the XLA executables also land in the
        on-disk jax compilation cache (core/compile_cache.py), so warmup
        cost is amortized across worker processes. Returns :meth:`stats`."""
        if not templates:
            raise ValueError("warmup needs one template array per fn "
                             "argument (trailing dims + dtype)")
        if persistent_cache:
            try:
                from .compile_cache import enable_compile_cache

                enable_compile_cache()
            except Exception:
                pass   # cache dir unwritable etc. — warmup still compiles
        specs = tuple(self._spec_of(t) for t in templates)
        for bucket in self.buckets:
            self._executable(bucket, specs, warmup=True)
        return self.stats()

    # --- execution -------------------------------------------------------
    def dispatch(self, *args) -> PendingBatch:
        """Launch the computation for ``args`` (batch-leading arrays, equal
        leading dim) WITHOUT blocking on the device: batches are padded to
        their bucket, chunked above ``max_batch_size``, and every chunk's
        executable is dispatched before any host sync. Call ``.result()``
        on the returned handle when (and only when) the replies are
        written."""
        import jax

        if not args:
            raise ValueError("dispatch needs at least one batch array")
        arrs = [a if isinstance(a, np.ndarray) else np.asarray(a)
                for a in args]
        n = arrs[0].shape[0]
        for a in arrs[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    "dispatch arguments disagree on the batch dimension: "
                    f"{[a.shape[0] for a in arrs]}")
        if n == 0:
            raise ValueError("cannot dispatch an empty batch")
        specs = tuple(self._spec_of(a) for a in arrs)
        chunks: List[Tuple[Any, int, int]] = []
        treedef = single = None
        for start in range(0, n, self.max_batch_size):
            stop = min(start + self.max_batch_size, n)
            real = stop - start
            bucket = self.bucket_for(real)
            padded = [_pad_to(a[start:stop], bucket) for a in arrs]
            if self.pass_mask:
                padded.append(np.arange(bucket) < real)
            out = self._executable(bucket, specs)(*padded)
            single = not isinstance(out, (tuple, list))
            leaves, treedef = jax.tree_util.tree_flatten(out)
            chunks.append((leaves, real, bucket))
        return PendingBatch(chunks, treedef, single, n)

    def __call__(self, *args):
        """Synchronous convenience: ``dispatch(...).result()``."""
        return self.dispatch(*args).result()

    # --- observability ---------------------------------------------------
    def warm_buckets(self) -> List[int]:
        """Ascending bucket sizes holding at least one compiled executable —
        what a fabric worker advertises in its heartbeat so the gateway can
        prefer replicas whose AOT cache already covers a batch's bucket
        (docs/resilience.md, "Multi-host fabric"). Advisory: routing built
        on this must degrade to load-based selection when it is stale."""
        with self._lock:
            return sorted(self._compile_counts)

    def stats(self) -> dict:
        with self._lock:
            compiles = dict(sorted(self._compile_counts.items()))
            hits = dict(sorted(self._hit_counts.items()))
            out = {"name": self.name,
                   "buckets": list(self.buckets),
                   "compiles": compiles,
                   "hits": hits,
                   "warmup_compiles": self._warmup_compiles,
                   "total_compiles": sum(compiles.values()),
                   "total_hits": sum(hits.values())}
            if self._autoconfig is not None:
                out["autoconfig"] = self._autoconfig
            return out

    def reset_stats(self) -> None:
        """Zero the hit counters (compile counts describe the cache contents
        and are kept — a reset must not hide a later recompile)."""
        with self._lock:
            self._hit_counts = {}

    def __repr__(self) -> str:
        return (f"BucketedRunner({self.name!r}, buckets={list(self.buckets)},"
                f" compiled={len(self._compiled)})")


class RunnerFleet:
    """Per-tenant accounting over a SHARED runner pool.

    The multi-tenant serving fleet (docs/resilience.md, "Multi-tenant
    fleet") runs N tenants' models through one worker process and one
    on-disk compile cache; each tenant's handler carries its own
    :class:`BucketedRunner`, and this registry is the fleet-wide view:
    ``register(tenant, runner)``, ``warm_all()`` off the hot path, and
    :meth:`stats` — per-tenant compile/hit counters plus fleet totals, the
    numbers ``bench_multitenant`` and the shared-cache accounting test
    assert on. Thread-safe; runners stay owned by their handlers (this
    holds references, never copies)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._runners: Dict[str, BucketedRunner] = {}

    def register(self, tenant: str, runner: BucketedRunner
                 ) -> "RunnerFleet":
        with self._lock:
            self._runners[tenant] = runner
        return self

    def runner(self, tenant: str) -> Optional[BucketedRunner]:
        with self._lock:
            return self._runners.get(tenant)

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._runners)

    def warm_all(self, templates: Dict[str, tuple]) -> dict:
        """AOT-warm every registered runner whose tenant has a template
        tuple in ``templates`` (one array-like per runner argument);
        returns :meth:`stats` after the sweep."""
        with self._lock:
            items = list(self._runners.items())
        for tenant, runner in items:
            tmpl = templates.get(tenant)
            if tmpl is not None:
                runner.warmup(*tmpl)
        return self.stats()

    def stats(self) -> dict:
        """{"tenants": {tenant: runner stats}, "total_compiles",
        "total_hits"} — the shared-fleet accounting: compiles are what the
        fleet PAID (once per (runner, bucket, spec)), hits are what each
        tenant's traffic reused."""
        with self._lock:
            items = list(self._runners.items())
        per = {t: r.stats() for t, r in items}
        return {"tenants": per,
                "total_compiles": sum(s["total_compiles"]
                                      for s in per.values()),
                "total_hits": sum(s["total_hits"] for s in per.values())}
