"""Estimator / Transformer / Pipeline protocol.

The TPU-native analog of the SparkML PipelineStage hierarchy the reference builds on
(every SynapseML component is an Estimator or Transformer; reference layer L2,
SURVEY.md §1). ``fit`` consumes a Table and returns a fitted Model (a Transformer);
``transform`` consumes and produces Tables. Save/load writes a directory with a JSON
metadata file plus any complex artifacts the stage contributes — the analog of
ComplexParamsWritable (reference: core/.../core/serialize/ComplexParamsSerializer.scala).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import List, Optional

import numpy as np

from .logging import SynapseMLLogging
from .params import Params
from .table import Table

_META_FILE = "metadata.json"


class PipelineStage(Params, SynapseMLLogging):
    """Base of every stage. Subclasses are constructible from kwargs alone plus
    whatever artifacts they persist via ``_save_extra``/``_load_extra``."""

    def __init__(self, **kwargs):
        Params.__init__(self, **kwargs)
        SynapseMLLogging.__init__(self)
        self.uid = f"{type(self).__name__}_{id(self):x}"
        self.log_class()

    # --- persistence ----------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        meta = {
            "class": f"{type(self).__module__}.{type(self).__name__}",
            "uid": self.uid,
            "params": self._simple_params_json(),
            "framework_version": _framework_version(),
        }
        with open(os.path.join(path, _META_FILE), "w") as f:
            json.dump(meta, f, indent=1, default=_json_default)
        self._save_complex_params(path)
        self._save_extra(path)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        with open(os.path.join(path, _META_FILE)) as f:
            meta = json.load(f)
        mod_name, cls_name = meta["class"].rsplit(".", 1)
        cls = getattr(importlib.import_module(mod_name), cls_name)
        stage = cls.__new__(cls)
        PipelineStage.__init__(stage)
        for k, v in meta["params"].items():
            if stage.hasParam(k):
                stage.set(k, v)
        stage.uid = meta.get("uid", stage.uid)
        stage._load_complex_params(path)
        stage._load_extra(path)
        return stage

    def _save_extra(self, path: str) -> None:  # complex artifacts (weights, trees...)
        pass

    def _load_extra(self, path: str) -> None:
        pass

    # Complex params (callables, stages, arrays) can't go in metadata.json; they
    # are pickled per-param — the analog of ComplexParam's own serialization
    # (reference: core/.../core/serialize/ComplexParam.scala). Values that
    # cannot pickle are skipped with a warning rather than failing the save.
    def _save_complex_params(self, path: str) -> None:
        import warnings

        try:
            import cloudpickle as pickler
        except ImportError:  # pragma: no cover
            import pickle as pickler
        complex_set = {k: v for k, v in self._paramMap.items()
                       if self._params[k].is_complex and v is not None}
        if not complex_set:
            return
        saved = []
        os.makedirs(os.path.join(path, "complexParams"), exist_ok=True)
        for name, value in complex_set.items():
            if isinstance(value, PipelineStage):
                value.save(os.path.join(path, "complexParams", name + ".stage"))
                saved.append([name, "stage"])
                continue
            try:
                blob = pickler.dumps(value)
            except Exception as e:  # noqa: BLE001
                warnings.warn(f"{type(self).__name__}.{name}: not serializable ({e}); "
                              "set it again after load")
                continue
            with open(os.path.join(path, "complexParams", name + ".pkl"), "wb") as f:
                f.write(blob)
            saved.append([name, "pickle"])
        with open(os.path.join(path, "complexParams", "index.json"), "w") as f:
            json.dump(saved, f)

    def _load_complex_params(self, path: str) -> None:
        idx_file = os.path.join(path, "complexParams", "index.json")
        if not os.path.exists(idx_file):
            return
        try:
            import cloudpickle as pickler
        except ImportError:  # pragma: no cover
            import pickle as pickler
        with open(idx_file) as f:
            saved = json.load(f)
        for name, kind in saved:
            if kind == "stage":
                value = PipelineStage.load(os.path.join(path, "complexParams", name + ".stage"))
            else:
                with open(os.path.join(path, "complexParams", name + ".pkl"), "rb") as f:
                    value = pickler.loads(f.read())
            self.set(name, value)


class Transformer(PipelineStage):
    def transform(self, df: Table) -> Table:
        with self.log_verb("transform", rows=df.num_rows if isinstance(df, Table) else None):
            return self._transform(_as_table(df))

    def _transform(self, df: Table) -> Table:
        raise NotImplementedError

    def __call__(self, df: Table) -> Table:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: Table, params: Optional[dict] = None) -> "Transformer":
        est = self.copy(params) if params else self
        with self.log_verb("fit", rows=df.num_rows if isinstance(df, Table) else None):
            return est._fit(_as_table(df))

    def _fit(self, df: Table) -> "Transformer":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""


class Pipeline(Estimator):
    """Sequential stage composition (SparkML Pipeline analog)."""

    def __init__(self, stages: Optional[List[PipelineStage]] = None, **kwargs):
        super().__init__(**kwargs)
        self.stages = list(stages or [])

    def setStages(self, stages) -> "Pipeline":
        self.stages = list(stages)
        return self

    def getStages(self):
        return self.stages

    def _fit(self, df: Table) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        for stage in self.stages:
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                cur = stage.transform(cur)
            else:
                raise TypeError(f"not a PipelineStage: {stage!r}")
        return PipelineModel(fitted)

    def _save_extra(self, path: str) -> None:
        _save_stage_list(self.stages, path)

    def _load_extra(self, path: str) -> None:
        self.stages = _load_stage_list(path)


class PipelineModel(Model):
    def __init__(self, stages: Optional[List[Transformer]] = None, **kwargs):
        super().__init__(**kwargs)
        self.stages = list(stages or [])

    def _transform(self, df: Table) -> Table:
        cur = df
        for stage in self.stages:
            cur = stage.transform(cur)
        return cur

    def _save_extra(self, path: str) -> None:
        _save_stage_list(self.stages, path)

    def _load_extra(self, path: str) -> None:
        self.stages = _load_stage_list(path)


# ---------------------------------------------------------------------------

def _save_stage_list(stages, path):
    order = []
    for i, s in enumerate(stages):
        sub = os.path.join(path, f"stage_{i:03d}")
        s.save(sub)
        order.append(os.path.basename(sub))
    with open(os.path.join(path, "stages.json"), "w") as f:
        json.dump(order, f)


def _load_stage_list(path):
    with open(os.path.join(path, "stages.json")) as f:
        order = json.load(f)
    return [PipelineStage.load(os.path.join(path, name)) for name in order]


def _as_table(df) -> Table:
    if isinstance(df, Table):
        return df
    # accept pandas DataFrames transparently at the API boundary
    if hasattr(df, "columns") and hasattr(df, "to_numpy"):
        return Table.from_pandas(df)
    if isinstance(df, dict):
        return Table(df)
    raise TypeError(f"expected Table / pandas DataFrame / dict of columns, got {type(df)}")


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def _framework_version():
    from .. import __version__

    return __version__
