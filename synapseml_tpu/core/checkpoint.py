"""Crash-safe checkpoint/recovery subsystem for the training paths.

TPU pods are preemptible by design: the MPMD pipeline-parallel literature
(PAPERS.md) treats worker loss as a routine scheduling event, and the
reference stack leans on Horovod/Lightning run-id checkpoint stores
(DeepVisionClassifier.py:86). This module is the unified store all three
training loops (gbdt ``train_booster``, ``dl.FlaxTrainer.fit``,
``automl.TuneHyperparameters``) write through, with the properties a real
preemption demands:

* **Atomic writes** — every artifact lands via tmp + ``os.replace``; the
  manifest is written LAST, so a checkpoint without a verifiable manifest
  never existed as far as recovery is concerned (a torn write can only
  produce a missing/failing manifest, never a silently-half-written state).
* **Integrity manifest** — per-artifact size + CRC32 + SHA-256. A torn
  ``latest``, a truncated artifact, or a flipped bit is *detected* at load
  (``checkpoint.corrupt`` failure counter), not deserialized into garbage.
* **Keep-last-N retention** — bounded disk: older steps are pruned after a
  successful save, never before the new step is fully durable.
* **Corruption fallback** — ``load_latest`` walks checkpoints newest-first
  and returns the newest one that verifies (``checkpoint.fallback``
  counter), so one bad write costs one checkpoint interval, not the run.

Layout (flat, one manifest per step)::

    dir/
      ckpt_00000007.state.msgpack    # artifact files: <prefix>_<step>.<name>
      ckpt_00000007.manifest.json    # digests; presence == checkpoint valid
      latest                         # basename of the newest step

The module also hosts the two training-robustness primitives that ride on
the store:

* :func:`preemption_point` — the cooperative kill hook every training loop
  calls at its resume-safe boundaries; ``testing.chaos.ChaosPreemption``
  installs a scheduled/seeded killer here so "kill at step k, resume,
  bit-identical model" is a CI property.
* :class:`NonFiniteGuard` — policy on a non-finite training loss
  (``raise`` | ``skip`` | ``rollback``), with structured counters via
  :func:`core.logging.record_failure` so silent NaN-poisoning of parameters
  cannot happen.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import zlib
from typing import Any, Callable, Dict, List, Optional

from .logging import record_failure

MANIFEST_SUFFIX = ".manifest.json"
_STEP_RE = re.compile(r"^(?P<prefix>[A-Za-z0-9]+)_(?P<step>\d{8})$")


class CheckpointError(ValueError):
    """A checkpoint could not be read/verified (corrupt, torn, missing)."""


class PreemptionError(BaseException):
    """An injected (or cooperative) preemption: the process is being killed.

    Derives from ``BaseException`` (like ``KeyboardInterrupt``) so generic
    ``except Exception`` recovery code cannot accidentally swallow a kill —
    a real SIGTERM would not be swallowable either.
    """


# --- atomic primitives ------------------------------------------------------

def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp + rename, same dir so the
    rename never crosses filesystems)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def _digests(data: bytes) -> Dict[str, Any]:
    return {"size": len(data),
            "crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "sha256": hashlib.sha256(data).hexdigest()}


# --- the store --------------------------------------------------------------

@dataclasses.dataclass
class Checkpoint:
    """One verified checkpoint: step number, artifact bytes by name, and the
    free-form ``meta`` dict the saver attached."""
    step: int
    artifacts: Dict[str, bytes]
    meta: Dict[str, Any]
    base: str      # e.g. "ckpt_00000007" (for diagnostics)

    @property
    def digest(self) -> str:
        """Content digest of the whole checkpoint: SHA-256 over the sorted
        per-artifact (name, sha256) pairs. Two checkpoints with identical
        bytes share a digest regardless of step number — the identity the
        serving model registry keys hot-swap versions on."""
        h = hashlib.sha256()
        for name in sorted(self.artifacts):
            h.update(name.encode("utf-8"))
            h.update(b"\x00")
            h.update(hashlib.sha256(self.artifacts[name]).hexdigest()
                     .encode("ascii"))
            h.update(b"\x00")
        return h.hexdigest()

    @property
    def version(self) -> str:
        """Human-readable version id (``<base>@<digest12>``) for the serving
        model registry: names the step AND pins the exact bytes, so a
        re-written step with different content is a different version."""
        return f"{self.base}@{self.digest[:12]}"


class CheckpointStore:
    """Atomic, manifest-verified, keep-last-N checkpoint directory.

    ``save`` never leaves a partially-visible checkpoint; ``load_latest``
    never returns bytes that fail their manifest digest. Thread-compat: one
    writer per store (training loops are single-writer by construction).
    """

    def __init__(self, directory: str, keep_last: int = 3,
                 prefix: str = "ckpt"):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if not re.fullmatch(r"[A-Za-z0-9]+", prefix):
            raise ValueError(f"prefix must be alphanumeric, got {prefix!r}")
        self.dir = directory
        self.keep_last = keep_last
        self.prefix = prefix

    # -- naming helpers --
    def _base(self, step: int) -> str:
        return f"{self.prefix}_{step:08d}"

    def _manifest_path(self, base: str) -> str:
        return os.path.join(self.dir, base + MANIFEST_SUFFIX)

    def _artifact_path(self, base: str, name: str) -> str:
        return os.path.join(self.dir, f"{base}.{name}")

    # -- write path --
    def save(self, step: int, artifacts: Dict[str, bytes],
             meta: Optional[Dict[str, Any]] = None) -> str:
        """Persist one checkpoint; returns its base name. Artifact names must
        be relative filenames (no separators). The manifest rename is the
        commit point; retention prunes only after it."""
        if not artifacts:
            raise ValueError("checkpoint needs at least one artifact")
        for name in artifacts:
            if os.sep in name or name.startswith(".") or not name:
                raise ValueError(f"bad artifact name {name!r}")
        os.makedirs(self.dir, exist_ok=True)
        base = self._base(int(step))
        manifest = {"format": 1, "step": int(step), "meta": meta or {},
                    "artifacts": {}}
        for name, data in artifacts.items():
            atomic_write_bytes(self._artifact_path(base, name), bytes(data))
            manifest["artifacts"][name] = _digests(bytes(data))
        atomic_write_text(self._manifest_path(base),
                          json.dumps(manifest, sort_keys=True))
        atomic_write_text(os.path.join(self.dir, "latest"), base)
        self._prune()
        return base

    def _prune(self) -> None:
        steps = self.steps()
        for step in steps[:-self.keep_last]:
            base = self._base(step)
            for fn in os.listdir(self.dir):
                if fn == base + MANIFEST_SUFFIX or fn.startswith(base + "."):
                    try:
                        os.remove(os.path.join(self.dir, fn))
                    except OSError:
                        pass   # a vanished file is already pruned

    # -- read path --
    def steps(self) -> List[int]:
        """Ascending step numbers that have a manifest on disk (verified or
        not)."""
        if not os.path.isdir(self.dir):
            return []
        out = []
        for fn in os.listdir(self.dir):
            if not fn.endswith(MANIFEST_SUFFIX):
                continue
            m = _STEP_RE.match(fn[: -len(MANIFEST_SUFFIX)])
            if m and m.group("prefix") == self.prefix:
                out.append(int(m.group("step")))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _load_base(self, base: str) -> Checkpoint:
        """Read + verify one checkpoint; raises CheckpointError on any
        integrity failure."""
        mpath = self._manifest_path(base)
        try:
            with open(mpath, "rb") as f:
                manifest = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            raise CheckpointError(f"checkpoint {base}: unreadable manifest "
                                  f"({e})") from e
        arts: Dict[str, bytes] = {}
        for name, want in manifest.get("artifacts", {}).items():
            apath = self._artifact_path(base, name)
            try:
                with open(apath, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise CheckpointError(
                    f"checkpoint {base}: artifact {name!r} missing "
                    f"({e})") from e
            got = _digests(data)
            for field in ("size", "crc32", "sha256"):
                if got[field] != want.get(field):
                    raise CheckpointError(
                        f"checkpoint {base}: artifact {name!r} failed "
                        f"{field} verification (torn write or bit rot): "
                        f"expected {want.get(field)!r}, got {got[field]!r}")
            arts[name] = data
        if not arts:
            raise CheckpointError(f"checkpoint {base}: empty manifest")
        return Checkpoint(step=int(manifest.get("step", -1)), artifacts=arts,
                          meta=manifest.get("meta", {}) or {}, base=base)

    def load_step(self, step: int) -> Checkpoint:
        return self._load_base(self._base(int(step)))

    def load_latest(self) -> Optional[Checkpoint]:
        """Newest checkpoint that VERIFIES, or None when the directory holds
        no usable checkpoint. A corrupt newest checkpoint is counted
        (``checkpoint.corrupt``) and recovery falls back to the previous
        good one (``checkpoint.fallback``)."""
        if not os.path.isdir(self.dir):
            return None
        candidates: List[str] = []
        latest_path = os.path.join(self.dir, "latest")
        pointed = None
        if os.path.exists(latest_path):
            try:
                with open(latest_path) as f:
                    pointed = f.read().strip()
            except OSError:
                pointed = None
        if pointed:
            candidates.append(pointed)
        for step in reversed(self.steps()):
            base = self._base(step)
            if base not in candidates:
                candidates.append(base)
        first_failure = None
        for i, base in enumerate(candidates):
            try:
                ckpt = self._load_base(base)
            except CheckpointError as e:
                record_failure("checkpoint.corrupt", base=base, error=str(e))
                if first_failure is None:
                    first_failure = str(e)
                continue
            if i > 0 or first_failure is not None:
                record_failure("checkpoint.fallback", base=base,
                               skipped=i, first_error=first_failure)
            return ckpt
        return None


# --- preemption points ------------------------------------------------------
# Training loops call preemption_point(phase, step) at every resume-safe
# boundary. Normally a no-op; testing.chaos.ChaosPreemption installs a hook
# that raises PreemptionError on its schedule, which is how the recovery
# suite proves kill-anywhere -> resume works.

_PREEMPT_HOOK: Optional[Callable[[str, int], None]] = None


def preemption_point(phase: str, step: int) -> None:
    """A resume-safe boundary in a training loop. ``phase`` is a dotted name
    (``gbdt.iteration``, ``dl.step``, ``automl.candidate``); ``step`` is the
    loop index about to run."""
    hook = _PREEMPT_HOOK
    if hook is not None:
        hook(phase, step)


# --- non-finite loss guard --------------------------------------------------

class NonFiniteLossError(FloatingPointError):
    """Raised by NonFiniteGuard(policy='raise') on a NaN/inf training loss."""


class NonFiniteGuard:
    """Policy on non-finite training losses.

    * ``raise`` — stop immediately with :class:`NonFiniteLossError` (the
      safe default: a NaN loss means every subsequent update is garbage).
    * ``skip`` — drop the poisoned step (caller reverts to its pre-step
      state) and continue; after ``max_consecutive`` *consecutive* skips the
      guard escalates to raise, so a permanently-NaN run cannot spin.
    * ``rollback`` — ask the caller to restore the last good checkpoint;
      after ``max_rollbacks`` total rollbacks the guard raises.

    Every event increments structured counters (``train.nonfinite_loss``
    plus ``train.nonfinite_skipped`` / ``train.nonfinite_rollback``) via
    :func:`core.logging.record_failure`, so the chaos suite can assert the
    policy actually fired.
    """

    POLICIES = ("raise", "skip", "rollback")

    def __init__(self, policy: str = "raise", max_consecutive: int = 10,
                 max_rollbacks: int = 3, counter_prefix: str = "train"):
        if policy not in self.POLICIES:
            raise ValueError(f"NonFiniteGuard policy={policy!r} is not one "
                             f"of {self.POLICIES}")
        self.policy = policy
        self.max_consecutive = max_consecutive
        self.max_rollbacks = max_rollbacks
        self.prefix = counter_prefix
        self.consecutive = 0
        self.total = 0
        self.rollbacks = 0

    def check(self, loss: float, step: int) -> str:
        """Inspect one step's loss. Returns ``"ok"``, ``"skip"`` (caller
        must revert the step), or ``"rollback"`` (caller must restore the
        last checkpoint); raises :class:`NonFiniteLossError` per policy."""
        import math

        if math.isfinite(loss):
            self.consecutive = 0
            return "ok"
        self.total += 1
        self.consecutive += 1
        record_failure(f"{self.prefix}.nonfinite_loss", step=int(step),
                       loss=repr(loss), policy=self.policy)
        if self.policy == "raise":
            raise NonFiniteLossError(
                f"non-finite training loss ({loss!r}) at step {step}; set "
                "the non-finite policy to 'skip' or 'rollback' to continue "
                "past poisoned steps")
        if self.policy == "skip":
            if self.consecutive > self.max_consecutive:
                raise NonFiniteLossError(
                    f"{self.consecutive} consecutive non-finite losses "
                    f"(last at step {step}); the run is not recovering — "
                    "check learning rate / data for inf/NaN")
            record_failure(f"{self.prefix}.nonfinite_skipped", step=int(step))
            return "skip"
        # rollback
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise NonFiniteLossError(
                f"non-finite loss persisted through {self.max_rollbacks} "
                f"checkpoint rollbacks (last at step {step}); aborting")
        record_failure(f"{self.prefix}.nonfinite_rollback", step=int(step),
                       rollback=self.rollbacks)
        return "rollback"
