"""Crash-safe checkpoint/recovery subsystem for the training paths.

TPU pods are preemptible by design: the MPMD pipeline-parallel literature
(PAPERS.md) treats worker loss as a routine scheduling event, and the
reference stack leans on Horovod/Lightning run-id checkpoint stores
(DeepVisionClassifier.py:86). This module is the unified store all three
training loops (gbdt ``train_booster``, ``dl.FlaxTrainer.fit``,
``automl.TuneHyperparameters``) write through, with the properties a real
preemption demands:

* **Atomic writes** — every artifact lands via tmp + ``os.replace``; the
  manifest is written LAST, so a checkpoint without a verifiable manifest
  never existed as far as recovery is concerned (a torn write can only
  produce a missing/failing manifest, never a silently-half-written state).
* **Integrity manifest** — per-artifact size + CRC32 + SHA-256. A torn
  ``latest``, a truncated artifact, or a flipped bit is *detected* at load
  (``checkpoint.corrupt`` failure counter), not deserialized into garbage.
* **Keep-last-N retention** — bounded disk: older steps are pruned after a
  successful save, never before the new step is fully durable.
* **Corruption fallback** — ``load_latest`` walks checkpoints newest-first
  and returns the newest one that verifies (``checkpoint.fallback``
  counter), so one bad write costs one checkpoint interval, not the run.

Layout (flat, one manifest per step)::

    dir/
      ckpt_00000007.state.msgpack    # artifact files: <prefix>_<step>.<name>
      ckpt_00000007.manifest.json    # digests; presence == checkpoint valid
      latest                         # basename of the newest step

The module also hosts the two training-robustness primitives that ride on
the store:

* :func:`preemption_point` — the cooperative kill hook every training loop
  calls at its resume-safe boundaries; ``testing.chaos.ChaosPreemption``
  installs a scheduled/seeded killer here so "kill at step k, resume,
  bit-identical model" is a CI property.
* :class:`NonFiniteGuard` — policy on a non-finite training loss
  (``raise`` | ``skip`` | ``rollback``), with structured counters via
  :func:`core.logging.record_failure` so silent NaN-poisoning of parameters
  cannot happen.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import sys
import zlib
from typing import Any, Callable, Dict, List, Optional

from .logging import record_failure

MANIFEST_SUFFIX = ".manifest.json"
_STEP_RE = re.compile(r"^(?P<prefix>[A-Za-z0-9]+)_(?P<step>\d{8})$")


class CheckpointError(ValueError):
    """A checkpoint could not be read/verified (corrupt, torn, missing)."""


class PreemptionError(BaseException):
    """An injected (or cooperative) preemption: the process is being killed.

    Derives from ``BaseException`` (like ``KeyboardInterrupt``) so generic
    ``except Exception`` recovery code cannot accidentally swallow a kill —
    a real SIGTERM would not be swallowable either.
    """


# --- atomic primitives ------------------------------------------------------

def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (tmp + rename, same dir so the
    rename never crosses filesystems)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def _digests(data: bytes) -> Dict[str, Any]:
    return {"size": len(data),
            "crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "sha256": hashlib.sha256(data).hexdigest()}


# --- the store --------------------------------------------------------------

@dataclasses.dataclass
class Checkpoint:
    """One verified checkpoint: step number, artifact bytes by name, and the
    free-form ``meta`` dict the saver attached."""
    step: int
    artifacts: Dict[str, bytes]
    meta: Dict[str, Any]
    base: str      # e.g. "ckpt_00000007" (for diagnostics)

    @property
    def digest(self) -> str:
        """Content digest of the whole checkpoint: SHA-256 over the sorted
        per-artifact (name, sha256) pairs. Two checkpoints with identical
        bytes share a digest regardless of step number — the identity the
        serving model registry keys hot-swap versions on."""
        h = hashlib.sha256()
        for name in sorted(self.artifacts):
            h.update(name.encode("utf-8"))
            h.update(b"\x00")
            h.update(hashlib.sha256(self.artifacts[name]).hexdigest()
                     .encode("ascii"))
            h.update(b"\x00")
        return h.hexdigest()

    @property
    def version(self) -> str:
        """Human-readable version id (``<base>@<digest12>``) for the serving
        model registry: names the step AND pins the exact bytes, so a
        re-written step with different content is a different version."""
        return f"{self.base}@{self.digest[:12]}"


class CheckpointStore:
    """Atomic, manifest-verified, keep-last-N checkpoint directory.

    ``save`` never leaves a partially-visible checkpoint; ``load_latest``
    never returns bytes that fail their manifest digest. Thread-compat: one
    writer per store (training loops are single-writer by construction).
    """

    def __init__(self, directory: str, keep_last: int = 3,
                 prefix: str = "ckpt"):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if not re.fullmatch(r"[A-Za-z0-9]+", prefix):
            raise ValueError(f"prefix must be alphanumeric, got {prefix!r}")
        self.dir = directory
        self.keep_last = keep_last
        self.prefix = prefix

    # -- naming helpers --
    def _base(self, step: int) -> str:
        return f"{self.prefix}_{step:08d}"

    def _manifest_path(self, base: str) -> str:
        return os.path.join(self.dir, base + MANIFEST_SUFFIX)

    def _artifact_path(self, base: str, name: str) -> str:
        return os.path.join(self.dir, f"{base}.{name}")

    # -- write path --
    def save(self, step: int, artifacts: Dict[str, bytes],
             meta: Optional[Dict[str, Any]] = None,
             extra_digests: Optional[Dict[str, Dict[str, Any]]] = None) -> str:
        """Persist one checkpoint; returns its base name. Artifact names must
        be relative filenames (no separators). The manifest rename is the
        commit point; retention prunes only after it.

        ``extra_digests`` lists artifacts written out-of-band (other
        processes' shard files, landed via :meth:`save_artifact_only` before
        this call) so the manifest covers them without this process ever
        holding their bytes."""
        if not artifacts:
            raise ValueError("checkpoint needs at least one artifact")
        for name in list(artifacts) + list(extra_digests or {}):
            if os.sep in name or name.startswith(".") or not name:
                raise ValueError(f"bad artifact name {name!r}")
        os.makedirs(self.dir, exist_ok=True)
        base = self._base(int(step))
        manifest = {"format": 1, "step": int(step), "meta": meta or {},
                    "artifacts": dict(extra_digests or {})}
        for name, data in artifacts.items():
            atomic_write_bytes(self._artifact_path(base, name), bytes(data))
            manifest["artifacts"][name] = _digests(bytes(data))
        atomic_write_text(self._manifest_path(base),
                          json.dumps(manifest, sort_keys=True))
        atomic_write_text(os.path.join(self.dir, "latest"), base)
        self._prune()
        return base

    def save_artifact_only(self, step: int, name: str,
                           data: bytes) -> Dict[str, Any]:
        """Atomically write ONE artifact file for ``step`` without committing
        a manifest; returns its digests. Multi-process sharded checkpoints
        use this: every process lands its own shard artifact, then process 0
        commits the manifest via ``save(..., extra_digests=...)`` — until
        that commit the checkpoint does not exist as far as recovery is
        concerned."""
        if os.sep in name or name.startswith(".") or not name:
            raise ValueError(f"bad artifact name {name!r}")
        os.makedirs(self.dir, exist_ok=True)
        base = self._base(int(step))
        atomic_write_bytes(self._artifact_path(base, name), bytes(data))
        return _digests(bytes(data))

    def _prune(self) -> None:
        steps = self.steps()
        for step in steps[:-self.keep_last]:
            base = self._base(step)
            for fn in os.listdir(self.dir):
                if fn == base + MANIFEST_SUFFIX or fn.startswith(base + "."):
                    try:
                        os.remove(os.path.join(self.dir, fn))
                    except OSError:
                        pass   # a vanished file is already pruned

    # -- read path --
    def steps(self) -> List[int]:
        """Ascending step numbers that have a manifest on disk (verified or
        not)."""
        if not os.path.isdir(self.dir):
            return []
        out = []
        for fn in os.listdir(self.dir):
            if not fn.endswith(MANIFEST_SUFFIX):
                continue
            m = _STEP_RE.match(fn[: -len(MANIFEST_SUFFIX)])
            if m and m.group("prefix") == self.prefix:
                out.append(int(m.group("step")))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _load_base(self, base: str,
                   artifact_filter: Optional[Callable[[str], bool]] = None
                   ) -> Checkpoint:
        """Read + verify one checkpoint; raises CheckpointError on any
        integrity failure. EVERY manifest artifact is verified regardless of
        ``artifact_filter`` (so corruption anywhere still triggers fallback);
        the filter only controls which artifacts' bytes are *retained* — a
        host restoring a sharded checkpoint keeps just the manifest and the
        shard files its devices need, never the full state."""
        mpath = self._manifest_path(base)
        try:
            with open(mpath, "rb") as f:
                manifest = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            raise CheckpointError(f"checkpoint {base}: unreadable manifest "
                                  f"({e})") from e
        if not manifest.get("artifacts"):
            raise CheckpointError(f"checkpoint {base}: empty manifest")
        arts: Dict[str, bytes] = {}
        for name, want in manifest.get("artifacts", {}).items():
            apath = self._artifact_path(base, name)
            try:
                with open(apath, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise CheckpointError(
                    f"checkpoint {base}: artifact {name!r} missing "
                    f"({e})") from e
            got = _digests(data)
            for field in ("size", "crc32", "sha256"):
                if got[field] != want.get(field):
                    raise CheckpointError(
                        f"checkpoint {base}: artifact {name!r} failed "
                        f"{field} verification (torn write or bit rot): "
                        f"expected {want.get(field)!r}, got {got[field]!r}")
            if artifact_filter is None or artifact_filter(name):
                arts[name] = data
        return Checkpoint(step=int(manifest.get("step", -1)), artifacts=arts,
                          meta=manifest.get("meta", {}) or {}, base=base)

    def load_step(self, step: int,
                  artifact_filter: Optional[Callable[[str], bool]] = None
                  ) -> Checkpoint:
        return self._load_base(self._base(int(step)), artifact_filter)

    def load_latest(self,
                    artifact_filter: Optional[Callable[[str], bool]] = None
                    ) -> Optional[Checkpoint]:
        """Newest checkpoint that VERIFIES, or None when the directory holds
        no usable checkpoint. A corrupt newest checkpoint is counted
        (``checkpoint.corrupt``) and recovery falls back to the previous
        good one (``checkpoint.fallback``). ``artifact_filter`` bounds which
        artifacts' bytes are kept (verification still covers all of them)."""
        if not os.path.isdir(self.dir):
            return None
        candidates: List[str] = []
        latest_path = os.path.join(self.dir, "latest")
        pointed = None
        if os.path.exists(latest_path):
            try:
                with open(latest_path) as f:
                    pointed = f.read().strip()
            except OSError:
                pointed = None
        if pointed:
            candidates.append(pointed)
        for step in reversed(self.steps()):
            base = self._base(step)
            if base not in candidates:
                candidates.append(base)
        first_failure = None
        for i, base in enumerate(candidates):
            try:
                ckpt = self._load_base(base, artifact_filter)
            except CheckpointError as e:
                record_failure("checkpoint.corrupt", base=base, error=str(e))
                if first_failure is None:
                    first_failure = str(e)
                continue
            if i > 0 or first_failure is not None:
                record_failure("checkpoint.fallback", base=base,
                               skipped=i, first_error=first_failure)
            return ckpt
        return None


# --- sharded pytree checkpoints ---------------------------------------------
# Format (one checkpoint step):
#   <prefix>.sharding.json      pytree/sharding manifest: per-leaf path,
#                               global shape, dtype, and the block table —
#                               each block names (artifact, npz key,
#                               [start, stop] per dim)
#   <prefix>.shards_p<P>.npz    process P's host-local shard blocks, one
#                               uint8 buffer per block (dtype-agnostic: raw
#                               bytes reshaped on load, so bfloat16 params
#                               round-trip bit-for-bit)
# Replicated leaves collapse to a single block (written once); sharded leaves
# contribute one block per distinct device shard, so no process ever
# serializes state its devices do not already hold. Restore assembles only
# the windows the *target* shardings need — which is also what makes loading
# across a changed mesh shape (resharding) work: any saved block layout can
# fill any requested window.

def _norm_index(idx, shape):
    """Normalize a shard ``.index`` (tuple of slices, possibly open) to
    ((start, stop), ...) against the global ``shape``."""
    out = []
    for i, sl in enumerate(idx):
        s = 0 if sl.start is None else int(sl.start)
        e = shape[i] if sl.stop is None else int(sl.stop)
        out.append((s, e))
    return tuple(out)


def _exchange_json(obj, timeout: Optional[float] = None):
    """Allgather one JSON-serializable object per process; returns the list
    ordered by process index. Doubles as the barrier that sequences
    every process's shard-artifact write before process 0 commits the
    manifest. Single-process: ``[obj]``.

    A dead or hung peer would stall the allgather forever, wedging the
    pre-manifest barrier — so the gather runs on a daemon worker thread and
    ``timeout`` (default: env ``SYNAPSEML_BARRIER_TIMEOUT_S``, 300s; <= 0
    disables) bounds the wait, converting the stall into
    ``CheckpointError("barrier timeout, peers=[...]")`` naming the other
    process indices. Survivors then agree on a restart point out-of-band via
    ``parallel.elastic.consensus_restart_step`` (a file barrier — this
    collective fabric is exactly what just broke)."""
    import jax

    if jax.process_count() == 1:
        return [obj]
    import numpy as np
    from jax.experimental import multihost_utils

    def _gather():
        raw = json.dumps(obj, sort_keys=True).encode("utf-8")
        lens = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(raw)], np.int64))).reshape(-1)
        buf = np.zeros(int(lens.max()), np.uint8)
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
        rows = np.asarray(multihost_utils.process_allgather(
            buf[None])).reshape(jax.process_count(), -1)
        return [json.loads(rows[p, : int(lens[p])].tobytes().decode("utf-8"))
                for p in range(jax.process_count())]

    if timeout is None:
        timeout = float(os.environ.get("SYNAPSEML_BARRIER_TIMEOUT_S", "300"))
    # every replica reads the same env knob / passes the same argument, so
    # the timeout branch is replica-CONSISTENT: all processes take the same
    # path and the gather below is reached (or not) collectively
    if timeout <= 0:
        return _gather()  # lint-ok: collectives
    import threading

    box: Dict[str, Any] = {}
    done = threading.Event()

    def _run():
        try:
            box["out"] = _gather()  # lint-ok: collectives
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True, name="ckpt-barrier")
    t.start()
    if not done.wait(timeout):
        peers = [p for p in range(jax.process_count())
                 if p != jax.process_index()]
        record_failure("checkpoint.barrier_timeout", peers=peers,
                       timeout_s=timeout)
        raise CheckpointError(
            f"barrier timeout, peers={peers} — a peer process died or hung "
            f"before the pre-manifest exchange completed ({timeout:.1f}s); "
            "run parallel.elastic.consensus_restart_step over the survivors "
            "to agree on the last committed step")
    if "err" in box:
        raise box["err"]
    return box["out"]


def _witness_observe(site, tree, expect=None):
    # dtype-witness probe (testing/dtypewitness.py): inert unless the
    # witness module is loaded — sys.modules lookup keeps product imports
    # free of the testing package
    w = sys.modules.get("synapseml_tpu.testing.dtypewitness")
    if w is not None and w.active():
        w.observe(site, tree, expect)


def save_sharded_tree(store: CheckpointStore, step: int, tree,
                      meta: Optional[Dict[str, Any]] = None,
                      prefix: str = "state") -> str:
    """Save a (possibly globally-sharded) pytree as per-process shard
    artifacts plus a pytree/sharding manifest; returns the checkpoint base.

    Each process packs only its devices' shard blocks into one npz; process 0
    additionally commits the ``<prefix>.sharding.json`` manifest covering
    every process's blocks (digests exchanged over the collective fabric), so
    the full state never lands on one host. Goes through ``CheckpointStore``
    — atomic writes, digest manifest as the commit point, keep-last-N."""
    import io

    import jax
    import numpy as np

    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    pid = jax.process_index()
    shard_name = f"{prefix}.shards_p{pid}.npz"
    local_blocks: Dict[str, Any] = {}
    my_leaves = []   # per leaf: the blocks THIS process contributes
    leaf_heads = []  # per leaf: path/shape/dtype (identical on all processes)
    for li, (path, leaf) in enumerate(leaves_with_paths):
        if isinstance(leaf, jax.Array):
            shape = tuple(int(d) for d in leaf.shape)
            dtype = np.dtype(leaf.dtype)
            blocks = []
            seen = set()
            for sh in leaf.addressable_shards:
                win = _norm_index(sh.index, shape)
                if win in seen:      # replicated across local devices
                    continue
                # a fully-replicated leaf is written once, by the lowest
                # process holding an addressable copy — NOT always process
                # 0: a pipeline stage group's leaves replicate over a device
                # set that may exclude process 0 entirely
                if all(s == 0 and e == d for (s, e), d in zip(win, shape)):
                    owner = min(d.process_index
                                for d in leaf.sharding.device_set)
                    if pid != owner:
                        continue
                seen.add(win)
                key = f"l{li}_b{len(blocks)}"
                local_blocks[key] = np.frombuffer(
                    np.ascontiguousarray(np.asarray(sh.data)).tobytes(),
                    np.uint8)
                blocks.append({"artifact": shard_name, "key": key,
                               "index": [[s, e] for s, e in win]})
        else:
            arr = np.ascontiguousarray(np.asarray(leaf))
            shape = tuple(arr.shape)
            dtype = arr.dtype
            blocks = []
            if pid == 0:             # host value: identical everywhere
                key = f"l{li}_b0"
                local_blocks[key] = np.frombuffer(arr.tobytes(), np.uint8)
                blocks.append({"artifact": shard_name, "key": key,
                               "index": [[0, d] for d in shape]})
        my_leaves.append(blocks)
        _witness_observe("core.ckpt.save_leaf", leaf)
        leaf_heads.append({"path": jax.tree_util.keystr(path),
                           "shape": list(shape), "dtype": dtype.name})
    buf = io.BytesIO()
    np.savez(buf, **local_blocks)
    npz_bytes = buf.getvalue()

    if jax.process_count() > 1:
        if pid != 0:
            # land the shard artifact BEFORE the exchange below — the
            # allgather is the barrier that lets process 0 commit a manifest
            # covering files already durable on disk
            digests = store.save_artifact_only(step, shard_name, npz_bytes)
        else:
            digests = _digests(npz_bytes)
        payloads = _exchange_json({"artifact": shard_name, "digests": digests,
                                   "leaves": my_leaves})
        if pid != 0:
            return store._base(int(step))
        merged = [sum((pl["leaves"][li] for pl in payloads), [])
                  for li in range(len(leaf_heads))]
        extra = {pl["artifact"]: pl["digests"] for pl in payloads[1:]}
    else:
        merged = my_leaves
        extra = None
    manifest = {"format": 1, "prefix": prefix,
                "processes": jax.process_count(),
                "leaves": [dict(h, blocks=b)
                           for h, b in zip(leaf_heads, merged)]}
    return store.save(
        int(step),
        {f"{prefix}.sharding.json": json.dumps(
            manifest, sort_keys=True).encode("utf-8"),
         shard_name: npz_bytes},
        meta=meta, extra_digests=extra)


def load_sharded_from_checkpoint(store: CheckpointStore, ckpt: Checkpoint,
                                 template, shardings=None,
                                 prefix: str = "state"):
    """Restore the pytree saved by :func:`save_sharded_tree` from an
    already-located checkpoint (``ckpt`` needs only the manifest artifact).

    ``template`` fixes the expected pytree structure and leaf shapes; any
    mismatch raises :class:`CheckpointError` naming the leaf. With
    ``shardings`` (a matching pytree of NamedShardings) each leaf is
    assembled directly into a globally-sharded ``jax.Array`` via
    ``make_array_from_callback`` — only the blocks overlapping this host's
    target windows are read, and a saved layout restores onto any target
    layout (resharding on load). Without it, full host numpy leaves are
    returned."""
    import io

    import jax
    import numpy as np

    mname = f"{prefix}.sharding.json"
    mbytes = ckpt.artifacts.get(mname)
    if mbytes is None:
        raise CheckpointError(
            f"checkpoint {ckpt.base}: no sharded-tree manifest {mname!r}")
    manifest = json.loads(mbytes.decode("utf-8"))
    entries = manifest["leaves"]
    tleaves, ttreedef = jax.tree_util.tree_flatten(template)
    if len(entries) != len(tleaves):
        raise CheckpointError(
            f"checkpoint {ckpt.base}: saved tree has {len(entries)} leaves, "
            f"template has {len(tleaves)} — the model/optimizer structure "
            "changed since it was saved")
    sleaves = (jax.tree_util.tree_leaves(shardings)
               if shardings is not None else [None] * len(tleaves))
    if len(sleaves) != len(tleaves):
        raise CheckpointError(
            f"shardings tree has {len(sleaves)} leaves, template has "
            f"{len(tleaves)}")
    for entry, tl in zip(entries, tleaves):
        want = tuple(int(d) for d in np.shape(tl))
        if tuple(entry["shape"]) != want:
            raise CheckpointError(
                f"checkpoint {ckpt.base}: leaf {entry['path']} has shape "
                f"{tuple(entry['shape'])}, model expects {want}")
        want_dt = getattr(tl, "dtype", None)
        if want_dt is not None and np.dtype(entry["dtype"]) != \
                np.dtype(want_dt):
            # the restore materializes leaves at the MANIFEST dtype — an
            # unchecked mismatch would silently retype every downstream
            # computation (e.g. a bf16 template training in f32); leaves
            # without an explicit dtype (python scalars) stay unchecked
            raise CheckpointError(
                f"checkpoint {ckpt.base}: leaf {entry['path']} has dtype "
                f"{entry['dtype']}, model expects {np.dtype(want_dt).name}")

    # read ONLY the shard artifacts whose blocks overlap a needed window
    def _overlaps(win, bidx):
        return all(max(s1, s2) < min(e1, e2) or (s1 == e1 == s2)
                   for (s1, e1), (s2, e2) in zip(win, bidx))

    needed = set()
    for entry, sh in zip(entries, sleaves):
        shape = tuple(entry["shape"])
        if sh is None:
            wins = [tuple((0, d) for d in shape)]
        else:
            wins = {_norm_index(idx, shape)
                    for idx in (d_idx for d_idx in (
                        sh.addressable_devices_indices_map(shape).values()))}
        for blk in entry["blocks"]:
            bidx = tuple((s, e) for s, e in blk["index"])
            if any(_overlaps(w, bidx) for w in wins):
                needed.add(blk["artifact"])
    full = store.load_step(ckpt.step,
                           artifact_filter=lambda n: n in needed)
    npzs = {name: np.load(io.BytesIO(data), allow_pickle=False)
            for name, data in full.artifacts.items()}

    def _read_block(blk, dtype):
        buf = npzs[blk["artifact"]][blk["key"]]
        bshape = tuple(e - s for s, e in blk["index"])
        return np.frombuffer(buf.tobytes(), dtype).reshape(bshape)

    def _window(entry, win, dtype):
        wshape = tuple(e - s for s, e in win)
        out = np.zeros(wshape, dtype)
        covered = 0
        for blk in entry["blocks"]:
            bidx = tuple((s, e) for s, e in blk["index"])
            inter = [(max(s1, s2), min(e1, e2))
                     for (s1, e1), (s2, e2) in zip(win, bidx)]
            if any(s >= e for s, e in inter):
                continue
            if blk["artifact"] not in npzs:
                raise CheckpointError(
                    f"checkpoint {ckpt.base}: block in {blk['artifact']!r} "
                    "needed but its artifact was not loaded")
            data = _read_block(blk, dtype)
            src = tuple(slice(s - bs, e - bs)
                        for (s, e), (bs, _) in zip(inter, bidx))
            dst = tuple(slice(s - ws, e - ws)
                        for (s, e), (ws, _) in zip(inter, win))
            out[dst] = data[src]
            covered += int(np.prod([e - s for s, e in inter]))
        if covered != int(np.prod(wshape)):
            raise CheckpointError(
                f"checkpoint {ckpt.base}: leaf {entry['path']} window {win} "
                f"only {covered}/{int(np.prod(wshape))} elements covered — "
                "a shard artifact from another host is missing")
        return out

    out_leaves = []
    for entry, sh in zip(entries, sleaves):
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if sh is None:
            out_leaves.append(_window(entry, tuple((0, d) for d in shape),
                                      dtype))
        else:
            out_leaves.append(jax.make_array_from_callback(
                shape, sh,
                lambda idx, e=entry, s2=shape, d=dtype:
                    _window(e, _norm_index(idx, s2), d)))
    _witness_observe("core.ckpt.load_leaf", out_leaves)
    return jax.tree_util.tree_unflatten(ttreedef, out_leaves)


def load_sharded_tree(store: CheckpointStore, template, shardings=None,
                      prefix: str = "state"):
    """Latest-checkpoint convenience wrapper around
    :func:`load_sharded_from_checkpoint`; returns ``(tree, step, meta)`` or
    ``None`` when the store holds no usable sharded checkpoint."""
    mname = f"{prefix}.sharding.json"
    ckpt = store.load_latest(artifact_filter=lambda n: n == mname)
    if ckpt is None or mname not in ckpt.artifacts:
        return None
    tree = load_sharded_from_checkpoint(store, ckpt, template,
                                        shardings=shardings, prefix=prefix)
    return tree, ckpt.step, ckpt.meta


# --- preemption points ------------------------------------------------------
# Training loops call preemption_point(phase, step) at every resume-safe
# boundary. Normally a no-op; testing.chaos.ChaosPreemption installs a hook
# that raises PreemptionError on its schedule, which is how the recovery
# suite proves kill-anywhere -> resume works.

_PREEMPT_HOOK: Optional[Callable[[str, int], None]] = None


def preemption_point(phase: str, step: int) -> None:
    """A resume-safe boundary in a training loop. ``phase`` is a dotted name
    (``gbdt.iteration``, ``dl.step``, ``automl.candidate``); ``step`` is the
    loop index about to run."""
    hook = _PREEMPT_HOOK
    if hook is not None:
        hook(phase, step)


# --- non-finite loss guard --------------------------------------------------

class NonFiniteLossError(FloatingPointError):
    """Raised by NonFiniteGuard(policy='raise') on a NaN/inf training loss."""


class NonFiniteGuard:
    """Policy on non-finite training losses.

    * ``raise`` — stop immediately with :class:`NonFiniteLossError` (the
      safe default: a NaN loss means every subsequent update is garbage).
    * ``skip`` — drop the poisoned step (caller reverts to its pre-step
      state) and continue; after ``max_consecutive`` *consecutive* skips the
      guard escalates to raise, so a permanently-NaN run cannot spin.
    * ``rollback`` — ask the caller to restore the last good checkpoint;
      after ``max_rollbacks`` total rollbacks the guard raises.

    Every event increments structured counters (``train.nonfinite_loss``
    plus ``train.nonfinite_skipped`` / ``train.nonfinite_rollback``) via
    :func:`core.logging.record_failure`, so the chaos suite can assert the
    policy actually fired.
    """

    POLICIES = ("raise", "skip", "rollback")

    def __init__(self, policy: str = "raise", max_consecutive: int = 10,
                 max_rollbacks: int = 3, counter_prefix: str = "train"):
        if policy not in self.POLICIES:
            raise ValueError(f"NonFiniteGuard policy={policy!r} is not one "
                             f"of {self.POLICIES}")
        self.policy = policy
        self.max_consecutive = max_consecutive
        self.max_rollbacks = max_rollbacks
        self.prefix = counter_prefix
        self.consecutive = 0
        self.total = 0
        self.rollbacks = 0

    def check(self, loss: float, step: int) -> str:
        """Inspect one step's loss. Returns ``"ok"``, ``"skip"`` (caller
        must revert the step), or ``"rollback"`` (caller must restore the
        last checkpoint); raises :class:`NonFiniteLossError` per policy."""
        import math

        if math.isfinite(loss):
            self.consecutive = 0
            return "ok"
        self.total += 1
        self.consecutive += 1
        record_failure(f"{self.prefix}.nonfinite_loss", step=int(step),
                       loss=repr(loss), policy=self.policy)
        if self.policy == "raise":
            raise NonFiniteLossError(
                f"non-finite training loss ({loss!r}) at step {step}; set "
                "the non-finite policy to 'skip' or 'rollback' to continue "
                "past poisoned steps")
        if self.policy == "skip":
            if self.consecutive > self.max_consecutive:
                raise NonFiniteLossError(
                    f"{self.consecutive} consecutive non-finite losses "
                    f"(last at step {step}); the run is not recovering — "
                    "check learning rate / data for inf/NaN")
            record_failure(f"{self.prefix}.nonfinite_skipped", step=int(step))
            return "skip"
        # rollback
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise NonFiniteLossError(
                f"non-finite loss persisted through {self.max_rollbacks} "
                f"checkpoint rollbacks (last at step {step}); aborting")
        record_failure(f"{self.prefix}.nonfinite_rollback", step=int(step),
                       rollback=self.rollbacks)
        return "rollback"
