"""Fabric / AAD token plumbing.

Reference: core/.../fabric/{FabricClient,TokenLibrary,OpenAITokenLibrary}.scala
and logging/common/PlatformDetails.scala — platform detection (Synapse /
Fabric / other) plus ambient-token acquisition used for keyless auth of the
service transformers. Here: environment-driven detection and a pluggable token
provider chain; on non-Fabric hosts everything degrades to explicit keys.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional

PLATFORM_SYNAPSE = "synapse"
PLATFORM_FABRIC = "fabric"
PLATFORM_DATABRICKS = "databricks"
PLATFORM_OTHER = "other"

_providers: List[Callable[[str], Optional[str]]] = []


def current_platform() -> str:
    """Platform detection (reference PlatformDetails.scala — cluster env
    vars)."""
    env = os.environ
    if "AZURE_SERVICE" in env and "fabric" in env.get("AZURE_SERVICE", "").lower():
        return PLATFORM_FABRIC
    if env.get("MMLSPARK_PLATFORM") in (PLATFORM_SYNAPSE, PLATFORM_FABRIC,
                                        PLATFORM_DATABRICKS):
        return env["MMLSPARK_PLATFORM"]
    if "SYNAPSE_WORKSPACE" in env or "AZURE_SYNAPSE_HOST" in env:
        return PLATFORM_SYNAPSE
    if "DATABRICKS_RUNTIME_VERSION" in env:
        return PLATFORM_DATABRICKS
    return PLATFORM_OTHER


def register_token_provider(fn: Callable[[str], Optional[str]]) -> None:
    """Register a provider ``audience -> token`` (the TokenLibrary hook; on
    Fabric the platform injects one)."""
    _providers.append(fn)


def get_access_token(audience: str = "cognitive") -> Optional[str]:
    """First token any provider yields, else the ``SYNAPSEML_TPU_AAD_TOKEN``
    env var, else None (callers fall back to subscription keys) —
    TokenLibrary.getAccessToken analog."""
    for p in _providers:
        try:
            tok = p(audience)
        except Exception:  # noqa: BLE001
            tok = None
        if tok:
            return tok
    return os.environ.get("SYNAPSEML_TPU_AAD_TOKEN") or None


class FabricClient:
    """Minimal Fabric REST surface (reference FabricClient.scala: workspace /
    artifact endpoints with ambient auth). Network calls go through io/http."""

    def __init__(self, base_url: str = "https://api.fabric.microsoft.com/v1",
                 token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.token = token or get_access_token("fabric")

    def _headers(self) -> dict:
        if not self.token:
            raise PermissionError(
                "no Fabric token available: register a token provider or set "
                "SYNAPSEML_TPU_AAD_TOKEN")
        return {"Authorization": f"Bearer {self.token}",
                "Content-Type": "application/json"}

    def get(self, path: str):
        from ..io.http import HTTPRequestData, send_with_retries

        resp = send_with_retries(HTTPRequestData(
            url=f"{self.base_url}/{path.lstrip('/')}", method="GET",
            headers=self._headers()))
        if not 200 <= resp.status_code < 300:
            raise RuntimeError(f"Fabric GET {path}: {resp.status_code} "
                               f"{resp.reason}")
        return resp.json()
