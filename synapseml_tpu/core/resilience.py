"""Resilience primitives shared by the serving/IO/services layers.

Three small, thread-safe, clock-injectable building blocks:

* :class:`Deadline` — an absolute per-request time budget that propagates
  end-to-end (client header → gateway forward → admission queue → batch
  formation → handler budget), so overload degrades to fast 504s instead of
  open-ended hangs.
* :class:`RetryBudget` — a token-bucket cap on the *aggregate* retry volume a
  process may emit. Per-call retry knobs (``maxRetries``/``backoff``) bound one
  request; under a correlated backend failure N concurrent requests each
  retrying K times is an N*K retry storm that keeps the backend down. A shared
  budget turns that into "first failures retry, the rest fail fast".
* :class:`CircuitBreaker` — the classic three-state (closed → open →
  half-open) breaker with escalating re-open cooldowns, used by the serving
  gateway for passive backend health.
* :class:`Membership` — a heartbeat-driven liveness table for dynamic
  worker pools. Distinct from the breaker on purpose: a breaker OPEN is a
  *traffic* judgment (this backend is failing requests right now, keep the
  link and re-probe it), while a missed-heartbeat expiry is a *membership*
  judgment (this worker is gone, free its routing state; it may re-register
  later as a clean rejoin). The serving gateway uses both.

Reference analog: the reference leans on Spark task retry plus
RESTHelpers.scala's per-call backoff and has no shared-fate machinery; these
are the pieces SURVEY §3.5's "serve heavy traffic" story actually needs, and
``synapseml_tpu/testing/chaos.py`` exists to fault-test them off-chip.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# Remaining-budget header, in integer milliseconds. Relative (not an absolute
# wall-clock instant) so it survives clock skew between client, gateway and
# worker; each hop re-anchors it against its own monotonic clock.
DEADLINE_HEADER = "X-Deadline-Ms"


class Deadline:
    """Absolute deadline on the local monotonic clock.

    ``Deadline.after(0.25)`` expires 250 ms from now; ``remaining()`` is the
    handler budget left, clamped at 0. ``None`` budgets are allowed at the
    call sites (no deadline), so helpers accept ``Optional[Deadline]``.
    """

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = at

    @classmethod
    def after(cls, seconds: float, clock=time.monotonic) -> "Deadline":
        return cls(clock() + seconds)

    @classmethod
    def from_header_ms(cls, value, cap_s: float,
                       clock=time.monotonic) -> "Deadline":
        """Deadline from an ``X-Deadline-Ms`` header value, capped by the
        server's own limit (a client must not pin server resources longer
        than the server would allow on its own)."""
        try:
            ms = float(value)
        except (TypeError, ValueError):
            return cls.after(cap_s, clock)
        return cls(clock() + min(max(ms, 0.0) / 1e3, cap_s))

    def remaining(self, clock=time.monotonic) -> float:
        return max(self.at - clock(), 0.0)

    def expired(self, clock=time.monotonic) -> bool:
        return clock() >= self.at

    def header_value(self, clock=time.monotonic) -> str:
        """Serialized remaining budget for propagation to the next hop."""
        return str(int(self.remaining(clock) * 1e3))


class RetryBudget:
    """Token bucket shared across callers: each retry spends one token;
    tokens refill at ``rate_per_sec`` up to ``burst``.

    ``try_spend()`` never blocks — an empty bucket means "do not retry",
    which is the whole point: under a correlated failure the process's total
    retry volume is capped at ``burst + rate_per_sec * t`` regardless of how
    many requests are in flight. One instance can back every
    ``send_with_retries`` / services-layer transformer in the process
    (:data:`default_retry_budget`), or a subsystem can carry its own.
    """

    def __init__(self, rate_per_sec: float = 5.0, burst: float = 20.0,
                 clock=time.monotonic):
        if burst <= 0 or rate_per_sec < 0:
            raise ValueError("RetryBudget needs burst > 0 and rate >= 0")
        self.rate = float(rate_per_sec)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()
        self.spent = 0          # retries granted
        self.denied = 0         # retries refused (budget exhausted)

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            self._refill()
            if self._tokens >= cost:
                self._tokens -= cost
                self.spent += 1
                return True
            self.denied += 1
            return False

    def available(self) -> float:
        with self._lock:
            self._refill()
            return self._tokens


#: Process-wide default budget: callers that opt into budgeted retries without
#: wiring an instance share this one, so independent transformers cannot
#: multiply each other's retry storms.
default_retry_budget = RetryBudget()


class CircuitBreaker:
    """Three-state breaker: CLOSED (normal) → OPEN after
    ``failure_threshold`` consecutive failures (all traffic refused for a
    cooldown) → HALF_OPEN (exactly one probe allowed) → CLOSED on probe
    success, or back to OPEN with an escalated cooldown on probe failure
    (cooldown * 2^reopens, capped at ``max_backoff_mult``).

    Passive: it learns only from ``record_success``/``record_failure`` calls
    made by the traffic that flows anyway — no health-check pinger thread.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3, cooldown: float = 1.0,
                 max_backoff_mult: int = 8, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.max_backoff_mult = max_backoff_mult
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.open_until = 0.0
        self._reopens = 0           # consecutive OPEN episodes (escalation)
        self._probe_inflight = False

    def available(self, now: Optional[float] = None) -> bool:
        """Would a request be admitted right now? Non-mutating — selection
        loops may call it on every candidate without consuming the
        half-open probe slot."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                return now >= self.open_until
            return not self._probe_inflight            # HALF_OPEN

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Admit one request (mutating): an elapsed OPEN transitions to
        HALF_OPEN and this caller becomes the single probe. Callers MUST
        follow with record_success/record_failure to release the probe."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN and now >= self.open_until:
                self.state = self.HALF_OPEN
                self._probe_inflight = True
                return True
            if self.state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = self.CLOSED
            self.consecutive_failures = 0
            self._reopens = 0
            self._probe_inflight = False

    def record_failure(self) -> None:
        now = self._clock()
        with self._lock:
            self.consecutive_failures += 1
            if self.state == self.HALF_OPEN:
                self._probe_inflight = False
                self._reopens += 1
                self._open(now)
            elif (self.state == self.CLOSED
                    and self.consecutive_failures >= self.failure_threshold):
                self._open(now)
            elif self.state == self.OPEN:
                # failure from the all-open fallback path: extend the window
                self._open(now)

    def _open(self, now: float) -> None:
        mult = min(2 ** self._reopens, self.max_backoff_mult)
        self.state = self.OPEN
        self.open_until = now + self.cooldown * mult

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "consecutive_failures": self.consecutive_failures,
                    "open_until": self.open_until}


class Membership:
    """Heartbeat liveness table: ``beat(member)`` marks a member alive now,
    ``expired()`` names members whose last beat is older than ``timeout``
    (callers evict them and free whatever routing state they held), and a
    later ``beat`` from an evicted member is a clean rejoin (``beat``
    returns True when the member is new or returning).

    Members registered with ``beat(member, static=True)`` are *static*:
    they never expire, which is the compatibility mode for worker pools
    configured as a fixed URL list with no heartbeat reporter — liveness
    for those stays the breaker's job alone.

    Thread-safe and clock-injectable (tests drive it with a fake clock).
    ``info`` carried by a beat (queue depth, warmed buckets, model version)
    is stored verbatim for routing/observability reads via ``snapshot()``.
    """

    def __init__(self, timeout: float = 3.0, clock=time.monotonic):
        if timeout <= 0:
            raise ValueError(f"timeout must be > 0, got {timeout}")
        self.timeout = timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._last: dict = {}        # member -> last beat (monotonic)
        self._info: dict = {}        # member -> latest info dict
        self._static: set = set()
        self.joins = 0               # first-time registrations
        self.rejoins = 0             # beats from previously-evicted members
        self.evictions = 0
        self._evicted: set = set()

    def beat(self, member, static: bool = False, **info):
        """Record a heartbeat; returns ``"join"`` when this beat admits a
        first-time member, ``"rejoin"`` when it readmits an evicted one,
        and ``None`` for an ordinary keep-alive beat (truthy iff the beat
        (re)admitted the member).

        A non-static beat for a member registered static UPGRADES it to
        dynamic: the member proved it has a live heartbeat reporter, so
        heartbeat silence becomes meaningful and it is now evictable."""
        with self._lock:
            status = None
            if member not in self._last:
                if member in self._evicted:
                    self._evicted.discard(member)
                    self.rejoins += 1
                    status = "rejoin"
                else:
                    self.joins += 1
                    status = "join"
            self._last[member] = self._clock()
            if info or member not in self._info:
                self._info[member] = dict(info)
            if static:
                self._static.add(member)
            else:
                self._static.discard(member)
            return status

    def info(self, member) -> dict:
        with self._lock:
            return dict(self._info.get(member, {}))

    def alive(self, member, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            last = self._last.get(member)
            if last is None:
                return False
            return member in self._static or now - last <= self.timeout

    def expired(self, now: Optional[float] = None) -> list:
        """Members overdue for eviction (non-static, last beat older than
        ``timeout``). Non-mutating; callers follow with :meth:`evict`."""
        now = self._clock() if now is None else now
        with self._lock:
            return [m for m, last in self._last.items()
                    if m not in self._static and now - last > self.timeout]

    def evict(self, member) -> bool:
        """Drop a member (idempotent); a later beat counts as a rejoin."""
        with self._lock:
            if member not in self._last:
                return False
            del self._last[member]
            self._info.pop(member, None)
            self._static.discard(member)
            self._evicted.add(member)
            self.evictions += 1
            return True

    def evict_if_expired(self, member, now: Optional[float] = None) -> bool:
        """Evict ``member`` only if it is STILL overdue, re-checked under
        the lock. :meth:`expired` + :meth:`evict` is a two-step read/act
        with a race in the gap: a member that heartbeats between the read
        and the unconditional evict — a rejoin in the very tick it would
        die — gets evicted anyway, dropping routing state the beat just
        refreshed. Lazy sweeps must use this instead; the unconditional
        :meth:`evict` stays for voluntary leaves (deregister), where the
        member ASKED to go regardless of beat freshness."""
        now = self._clock() if now is None else now
        with self._lock:
            last = self._last.get(member)
            if last is None or member in self._static \
                    or now - last <= self.timeout:
                return False
            del self._last[member]
            self._info.pop(member, None)
            self._evicted.add(member)
            self.evictions += 1
            return True

    def evict_stale(self, now: Optional[float] = None) -> list:
        """Evict every expired member in one sweep and return those evicted.

        :meth:`expired` + :meth:`evict` only run when something consults the
        table (the routing/health path) — an IDLE gateway holds dead workers
        indefinitely. Supervisor loops call this on their own cadence so
        membership decays even with zero traffic; each eviction is counted
        under ``fabric.evicted_idle``. Staleness is re-checked per member
        under the lock (:meth:`evict_if_expired`), so a rejoin beat racing
        the sweep keeps its membership."""
        stale = self.expired(now)
        evicted = [m for m in stale if self.evict_if_expired(m, now)]
        if evicted:
            from .logging import record_failure
            record_failure("fabric.evicted_idle", n=len(evicted),
                           members=[str(m) for m in evicted])
        return evicted

    def members(self) -> list:
        with self._lock:
            return list(self._last)

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            return {
                "members": {
                    str(m): {"age_s": round(now - last, 3),
                             "static": m in self._static,
                             **self._info.get(m, {})}
                    for m, last in self._last.items()},
                "joins": self.joins, "rejoins": self.rejoins,
                "evictions": self.evictions, "timeout_s": self.timeout}
