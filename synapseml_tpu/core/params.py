"""Typed, metadata-rich parameter system.

This is the TPU-native analog of SparkML ``Params`` plus SynapseML's ``ComplexParam``
extensions (reference: core/src/main/scala/com/microsoft/azure/synapse/ml/core/serialize/
ComplexParam.scala and core/.../param/*.scala). Every stage declares its parameters
declaratively as class attributes; the metaclass collects them, generates camelCase
getter/setters (``getFeaturesCol``/``setFeaturesCol``) for API parity with the
reference's auto-generated wrappers (reference: core/.../codegen/Wrappable.scala), and
the same metadata drives JSON serialization, ``explainParams``, and copy semantics.

Unlike the reference — where params live in Scala and Python wrappers are generated —
this framework is Python-native, so the param metadata is the single source of truth.
"""

from __future__ import annotations

import copy as _copy
import json
from typing import Any, Callable, Optional


class Param:
    """A single declared parameter: name, doc, type, default, validator.

    ``dtype`` is advisory (used for coercion and docs); ``validator`` raises or
    returns a possibly-coerced value. ``is_complex`` marks values that cannot be
    JSON-serialized (models, callables, arrays) — the analog of the reference's
    ComplexParam; such values are serialized by the owning stage's save path.
    """

    __slots__ = ("name", "doc", "dtype", "default", "validator", "is_complex", "_owner")

    def __init__(
        self,
        name: str,
        doc: str = "",
        dtype: Optional[type] = None,
        default: Any = None,
        validator: Optional[Callable[[Any], Any]] = None,
        is_complex: bool = False,
    ):
        self.name = name
        self.doc = doc
        self.dtype = dtype
        self.default = default
        self.validator = validator
        self.is_complex = is_complex
        self._owner = None

    # descriptor protocol: `stage.featuresCol` reads the current value
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj, value):
        obj.set(self.name, value)

    def __repr__(self):
        return f"Param({self.name!r}, default={self.default!r})"

    def coerce(self, value: Any) -> Any:
        if value is None:
            return value
        if self.validator is not None:
            out = self.validator(value)
            if out is not None:
                value = out
        if self.dtype is not None and not self.is_complex:
            if self.dtype is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)
            elif self.dtype is int and isinstance(value, float) and value.is_integer():
                value = int(value)
            elif not isinstance(value, self.dtype):
                # allow duck-typed sequences for list/tuple-typed params
                if self.dtype in (list, tuple) and hasattr(value, "__iter__") and not isinstance(value, (str, bytes)):
                    value = self.dtype(value)
                else:
                    raise TypeError(
                        f"Param {self.name}: expected {self.dtype.__name__}, "
                        f"got {type(value).__name__} ({value!r})"
                    )
        return value


def _make_getter(name):
    def getter(self):
        return self.get(name)

    getter.__name__ = "get" + name[0].upper() + name[1:]
    getter.__doc__ = f"Get the value of ``{name}``."
    return getter


def _make_setter(name):
    def setter(self, value):
        return self.set(name, value)

    setter.__name__ = "set" + name[0].upper() + name[1:]
    setter.__doc__ = f"Set ``{name}`` and return self (fluent)."
    return setter


class _ParamsMeta(type):
    """Collects Param class attributes (including inherited) and generates
    ``getX``/``setX`` fluent accessors, mirroring the reference's generated API."""

    def __new__(mcls, clsname, bases, ns):
        cls = super().__new__(mcls, clsname, bases, ns)
        params: dict[str, Param] = {}
        for base in reversed(cls.__mro__):
            for key, val in vars(base).items():
                if isinstance(val, Param):
                    params[val.name] = val
        cls._params = params
        for pname in params:
            cap = pname[0].upper() + pname[1:]
            if "get" + cap not in ns and not hasattr(cls, "get" + cap):
                setattr(cls, "get" + cap, _make_getter(pname))
            if "set" + cap not in ns and not hasattr(cls, "set" + cap):
                setattr(cls, "set" + cap, _make_setter(pname))
        return cls


class Params(metaclass=_ParamsMeta):
    """Base for anything with declared parameters.

    Constructor accepts any declared param as a keyword argument::

        est = LightGBMClassifier(numIterations=100, learningRate=0.1)

    Values live in ``self._paramMap`` (explicitly set) with fall-through to
    declared defaults, matching SparkML paramMap/defaultParamMap semantics.
    """

    _params: dict[str, Param] = {}

    def __init__(self, **kwargs):
        self._paramMap: dict[str, Any] = {}
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(
                    f"{type(self).__name__} has no param {k!r}. "
                    f"Available: {sorted(self._params)}"
                )
            self.set(k, v)

    # --- core accessors -------------------------------------------------
    def set(self, name: str, value: Any) -> "Params":
        p = self._params[name]
        self._paramMap[name] = p.coerce(value)
        return self

    def get(self, name: str, default: Any = None) -> Any:
        if name in self._paramMap:
            return self._paramMap[name]
        p = self._params.get(name)
        if p is not None and p.default is not None:
            d = p.default
            # mutable defaults are shared class-level objects: hand out a
            # copy so user mutation can't silently rewrite every instance
            return (list(d) if isinstance(d, list)
                    else dict(d) if isinstance(d, dict) else d)
        if p is None:
            raise KeyError(f"{type(self).__name__} has no param {name!r}")
        return p.default if default is None else default

    def isSet(self, name: str) -> bool:
        return name in self._paramMap

    def hasParam(self, name: str) -> bool:
        return name in self._params

    def setParams(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def copy(self, extra: Optional[dict] = None) -> "Params":
        out = _copy.copy(self)
        out._paramMap = dict(self._paramMap)
        if extra:
            for k, v in extra.items():
                out.set(k, v)
        return out

    # --- introspection --------------------------------------------------
    def explainParams(self) -> str:
        lines = []
        for name in sorted(self._params):
            p = self._params[name]
            cur = self._paramMap.get(name, "undefined")
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def extractParamMap(self) -> dict:
        out = {n: p.default for n, p in self._params.items() if p.default is not None}
        out.update(self._paramMap)
        return out

    # --- serialization --------------------------------------------------
    def _simple_params_json(self) -> dict:
        """Explicitly-set, JSON-able params (complex ones handled by save paths)."""
        out = {}
        for k, v in self._paramMap.items():
            if self._params[k].is_complex:
                continue
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                pass
        return out

    def __repr__(self):
        set_params = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items())
                               if not self._params[k].is_complex)
        return f"{type(self).__name__}({set_params})"


# ---------------------------------------------------------------------------
# Shared column-param mixins (reference: core/.../core/contracts/Params.scala —
# HasFeaturesCol/HasLabelCol/HasOutputCol/... traits used across every module)
# ---------------------------------------------------------------------------

class HasFeaturesCol(Params):
    featuresCol = Param("featuresCol", "The name of the features column", str, "features")


class HasLabelCol(Params):
    labelCol = Param("labelCol", "The name of the label column", str, "label")


class HasInputCol(Params):
    inputCol = Param("inputCol", "The name of the input column", str, "input")


class HasOutputCol(Params):
    outputCol = Param("outputCol", "The name of the output column", str, "output")


class HasInputCols(Params):
    inputCols = Param("inputCols", "The names of the input columns", list)


class HasOutputCols(Params):
    outputCols = Param("outputCols", "The names of the output columns", list)


class HasPredictionCol(Params):
    predictionCol = Param("predictionCol", "The name of the prediction column", str, "prediction")


class HasRawPredictionCol(Params):
    rawPredictionCol = Param("rawPredictionCol", "Raw prediction (margin) column name", str, "rawPrediction")


class HasProbabilityCol(Params):
    probabilityCol = Param("probabilityCol", "Predicted class probabilities column name", str, "probability")


class HasWeightCol(Params):
    weightCol = Param("weightCol", "The name of the instance-weight column", str)


class HasValidationIndicatorCol(Params):
    validationIndicatorCol = Param(
        "validationIndicatorCol",
        "Boolean column: true rows are used for validation, false for training", str)


class HasInitScoreCol(Params):
    initScoreCol = Param("initScoreCol", "Column with per-row initial scores (margin warm start)", str)


class HasGroupCol(Params):
    groupCol = Param("groupCol", "Column with the query/group id for ranking", str)


class HasSeed(Params):
    seed = Param("seed", "Random seed", int, 0)
