"""Structured logging + phase instrumentation.

Analog of the reference's ``SynapseMLLogging`` trait (core/.../logging/
SynapseMLLogging.scala: every stage logs construction via logClass and wraps
fit/transform in timed, structured log records) and of the LightGBM phase
instrumentation (lightgbm/.../LightGBMPerformance.scala: InstrumentationMeasures /
TaskInstrumentationMeasures with mark*Start/Stop spans). Spans integrate with the
JAX profiler when active (jax.profiler.TraceAnnotation), so phase marks show up in
TPU traces — the SURVEY §5.1 recommendation.
"""

from __future__ import annotations

import contextlib
import json
import logging
import re
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("synapseml_tpu")

PROTOCOL_VERSION = "1.0.0"

# --- secret scrubbing --------------------------------------------------------
# Every structured log line passes through scrub_payload + scrub_text before
# it reaches a handler, so a subscription key, SAS signature, bearer token or
# connection string in a param payload / error message can never land in logs.
# Analog (and superset) of the reference's SASScrubber
# (core/.../logging/common/Scrubber.scala: sig=... redaction only).

REDACTED = "####"

# key NAMES whose values are secret wherever they appear in a payload:
# either the whole key is a well-known secret word, or it contains a
# compound secret name (subscriptionKey, apiKey, accountKey, aadToken, ...)
_EXACT_SECRET_KEYS = re.compile(
    r"(?i)^(key|sig|sas|token|secret|password|pwd|auth|authorization|"
    r"bearer|credential|credentials)$")
_COMPOUND_SECRET_KEYS = re.compile(
    r"(?i)(subscription[_-]?key|api[_-]?key|account[_-]?key|shared[_-]?key|"
    r"access[_-]?token|aad[_-]?token|sas[_-]?token|refresh[_-]?token|"
    r"id[_-]?token|client[_-]?secret|connection[_-]?string|"
    r"ocp-apim-subscription-key)")

# value PATTERNS scrubbed out of any logged string (URLs in error messages,
# headers echoed by HTTP exceptions, ...)
_TEXT_PATTERNS = (
    # SAS / query-string signatures and credentials: sig=..., key=..., &c.
    (re.compile(r"(?i)\b(sig|signature|key|token|secret|password|pwd|"
                r"credential|sv|se|st|spr|sp)=([A-Za-z0-9%+/._~-]{8,}"
                r"(?:%3d|=){0,2})"), r"\1=" + REDACTED),
    # Authorization headers / bearer tokens
    (re.compile(r"(?i)\b(bearer|basic)[ :]+[A-Za-z0-9._+/=-]{8,}"),
     r"\1 " + REDACTED),
    # API-key-shaped literals (OpenAI-style)
    (re.compile(r"\bsk-[A-Za-z0-9]{16,}\b"), "sk-" + REDACTED),
    # explicit subscription-key headers serialized into text
    (re.compile(r"(?i)(ocp-apim-subscription-key[\"']?\s*[:=]\s*[\"']?)"
                r"[A-Za-z0-9-]{8,}"), r"\1" + REDACTED),
    # JWTs (three dot-separated base64url segments)
    (re.compile(r"\beyJ[A-Za-z0-9_-]{8,}\.[A-Za-z0-9_-]{8,}"
                r"\.[A-Za-z0-9_-]{8,}\b"), REDACTED),
)


def _is_secret_key(name: str) -> bool:
    return bool(_EXACT_SECRET_KEYS.match(name)
                or _COMPOUND_SECRET_KEYS.search(name))


def scrub_text(s: str) -> str:
    """Redact secret-shaped substrings from free text (error messages, URLs)."""
    for pat, repl in _TEXT_PATTERNS:
        s = pat.sub(repl, s)
    return s


def scrub_payload(obj: Any) -> Any:
    """Recursively redact secret-named fields and secret-shaped strings from
    a structured payload about to be logged."""
    if isinstance(obj, dict):
        return {k: (REDACTED if isinstance(k, str) and _is_secret_key(k)
                    else scrub_payload(v)) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        vals = [scrub_payload(v) for v in obj]
        if hasattr(obj, "_make"):          # NamedTuple
            return type(obj)._make(vals)
        try:
            return type(obj)(vals)
        except TypeError:                  # exotic sequence subclass: the
            return vals                    # scrubbed content matters, not type
    if isinstance(obj, str):
        return scrub_text(obj)
    return obj


def _framework_version() -> str:
    try:
        from .. import __version__

        return __version__
    except Exception:
        return "unknown"


class SynapseMLLogging:
    """Mixin: structured JSON log records for class creation and verbs."""

    def log_class(self) -> None:
        self._log_base("constructor")

    def _log_base(self, method: str, extra: Optional[Dict[str, Any]] = None, level=logging.DEBUG) -> None:
        if not logger.isEnabledFor(level):
            return   # skip payload build + scrub work for disabled levels
        payload = {
            "uid": getattr(self, "uid", None),
            "className": type(self).__name__,
            "method": method,
            "libraryVersion": _framework_version(),
            "protocolVersion": PROTOCOL_VERSION,
        }
        if extra:
            payload.update(extra)
        # scrub twice: structured (secret-named fields) then textual (secret-
        # shaped values that survive json.dumps, e.g. URLs inside messages)
        logger.log(level, scrub_text(json.dumps(scrub_payload(payload),
                                                default=str)))

    @contextlib.contextmanager
    def log_verb(self, verb: str, **info):
        """Time a fit/transform body, logging duration or typed error payloads
        (the logFit/logTransform/logVerb analog)."""
        t0 = time.perf_counter()
        try:
            with _maybe_jax_annotation(f"{type(self).__name__}.{verb}"):
                yield
        except Exception as e:
            self._log_base(verb, {"error": type(e).__name__, "message": str(e)[:500],
                                  **info}, level=logging.ERROR)
            raise
        else:
            ms = (time.perf_counter() - t0) * 1e3
            self._log_base(verb, {"durationMs": round(ms, 3), **info}, level=logging.INFO)


@contextlib.contextmanager
def _maybe_jax_annotation(name: str):
    # guard only annotation setup — never the yield itself (a guarded yield
    # would catch exceptions thrown into the body and yield a second time)
    try:
        import jax.profiler

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        yield


class StopWatch:
    """Reference: core/.../core/utils/StopWatch.scala — ad-hoc timing."""

    def __init__(self):
        self._t0 = None
        self.elapsed_s = 0.0

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> float:
        if self._t0 is not None:
            self.elapsed_s += time.perf_counter() - self._t0
            self._t0 = None
        return self.elapsed_s

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()


class InstrumentationMeasures:
    """Named phase spans, aggregatable across hosts — the LightGBMPerformance
    analog. Usage::

        m = InstrumentationMeasures()
        with m.span("dataPreparation"): ...
        m.report()  # {"dataPreparation": seconds, ...}
    """

    def __init__(self):
        self.spans: Dict[str, float] = {}
        self.counters: Dict[str, int] = {}

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            with _maybe_jax_annotation(name):
                yield
        finally:
            self.spans[name] = self.spans.get(name, 0.0) + time.perf_counter() - t0

    def count(self, name: str, n: int = 1):
        self.counters[name] = self.counters.get(name, 0) + n

    def report(self) -> Dict[str, float]:
        out: Dict[str, Any] = dict(self.spans)
        out.update({f"count:{k}": v for k, v in self.counters.items()})
        return out

    def merge(self, other: "InstrumentationMeasures") -> "InstrumentationMeasures":
        for k, v in other.spans.items():
            self.spans[k] = self.spans.get(k, 0.0) + v
        for k, v in other.counters.items():
            self.counters[k] = self.counters.get(k, 0) + v
        return self


# --- structured failure counters --------------------------------------------
# Process-global counters for resilience events (load shedding, deadline
# breaches, breaker trips, retry-budget denials, ...). Counting is separated
# from logging so hot paths pay one dict increment; each event still emits a
# scrubbed structured record at DEBUG for correlation with request logs.
# The chaos suite (tests/test_chaos_serving.py) asserts against these, which
# is what makes failure behavior a CI property instead of folklore.

_FAILURE_LOCK = threading.Lock()
_FAILURE_COUNTS: Dict[str, int] = {}


def record_failure(kind: str, n: int = 1, **detail: Any) -> None:
    """Count one resilience event (dotted name, e.g. ``serving.shed``) and
    emit a structured DEBUG record carrying ``detail`` (scrubbed)."""
    with _FAILURE_LOCK:
        _FAILURE_COUNTS[kind] = _FAILURE_COUNTS.get(kind, 0) + n
    if logger.isEnabledFor(logging.DEBUG):
        payload = {"event": "failure", "kind": kind, "n": n,
                   "protocolVersion": PROTOCOL_VERSION}
        if detail:
            payload.update(detail)
        logger.debug(scrub_text(json.dumps(scrub_payload(payload),
                                           default=str)))


def failure_counts() -> Dict[str, int]:
    """Snapshot of all failure counters (copy — safe to mutate)."""
    with _FAILURE_LOCK:
        return dict(_FAILURE_COUNTS)


def reset_failure_counts() -> None:
    """Zero the counters (test isolation)."""
    with _FAILURE_LOCK:
        _FAILURE_COUNTS.clear()


def retry_with_timeout(fn, retries: int = 3, initial_delay_s: float = 1.0, timeout_s: Optional[float] = None):
    """Reference: core/.../core/utils/FaultToleranceUtils.scala:9-22 (retryWithTimeout)
    and NetworkManager.scala:195-218 (exponential backoff). Host-side only."""
    delay = initial_delay_s
    last_exc: Optional[Exception] = None
    deadline = time.monotonic() + timeout_s if timeout_s else None
    for attempt in range(retries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — generic retry wrapper by design
            last_exc = e
            if deadline and time.monotonic() > deadline:
                break
            if attempt < retries - 1:
                time.sleep(delay)
                delay *= 2
    raise last_exc  # type: ignore[misc]
