"""Measured engine defaults: the tune→flip→bench loop's persistence layer.

The GBDT engine ships several hot-loop designs whose relative speed is a
property of the chip, not the code (docs/perf_notes.md). ``tools/perf_tune.py``
measures them ON REAL TPU and writes the winner to ``docs/tuned_defaults.json``;
this module is the read side consumed by ``BoosterConfig`` /
``ops.hist_kernel`` default resolution, so a tune pass inside one short
TPU-terminal window flips the shipped defaults for every subsequent run —
no code edit, no human in the loop.

Precedence (highest wins): explicit constructor arg > ``SYNAPSEML_TPU_*`` env
var > tuned file > hardcoded fallback.

The tuned file is applied ONLY when the current process is actually running
the TPU backend: the measurements are chip facts, and CPU tests must not
change behavior based on a mutable artifact. The backend check never
*initializes* a backend (``jax.devices()`` on a half-open axon tunnel hangs
forever) — an uninitialized backend reads as "not TPU" and the fallback wins;
every bench/tune flow initializes jax first, so the file takes effect exactly
where it is valid.

Reference analog: LightGBM ships per-device tuned kernel parameters the same
way (its GPU tree learner's auto-tuned work-group sizes); the reference's JVM
layer has no equivalent because its native binaries are pre-tuned.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_PATH = os.path.join(_REPO, "docs", "tuned_defaults.json")

# keys a tuned file may set, with the values the engine accepts — the write
# side (tools/perf_tune.py) and read side (BoosterConfig.__post_init__)
# validate against the same table, so a corrupt/hand-edited file fails loud
ALLOWED = {
    "partition_impl": ("sort", "sort32", "scan", "scatter"),
    "row_layout": ("partition", "masked", "gather"),
    "use_segmented": (True, False),
    "hist_chunk": int,
    # features packed per MXU dot (ops/hist_kernel._pack_for clamps to the
    # tile constraints; the tuner pins this only on a measured win)
    "hist_pack": int,
    # out-of-core ingest geometry (io/ingest.py): rows per streamed chunk
    # and in-flight chunk depth, resolved env > tuned file > the h2d
    # bandwidth micro-probe recorded in the measurement store
    "stream_chunk_rows": int,
    "stream_depth": int,
}


def _path() -> str:
    return os.environ.get("SYNAPSEML_TPU_TUNED_DEFAULTS", DEFAULT_PATH)


def initialized_platform() -> Optional[str]:
    """The platform of an ALREADY-initialized jax backend ("tpu"/"cpu"/...),
    or None when no backend is initialized. Never initializes one (this venv
    force-imports jax at startup, so module presence proves nothing, and a
    fresh init can hang on the axon tunnel). The single shared copy of this
    jax-internal sniff — bench.record_measurement uses it too."""
    try:
        from jax._src import xla_bridge as _xb

        inited = (_xb.backends_are_initialized()
                  if hasattr(_xb, "backends_are_initialized")
                  else bool(getattr(_xb, "_backends", None)))
        if not inited:
            return None
        import jax

        return jax.default_backend()
    except Exception:
        return None


def backend_is_tpu() -> bool:
    return initialized_platform() == "tpu"


@functools.lru_cache(maxsize=4)
def _load(path: str) -> dict:
    # deliberate trace-time read: tuned defaults must be resolved while the
    # kernel is being built, and the lru_cache bounds it to once per path
    try:
        with open(path) as f:  # lint-ok: blocking-io
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _value_ok(key: str, v) -> bool:
    """Type-exact validity for one tuned value. bool is an int subclass, so
    both directions need explicit guards: hist_chunk=true must not become
    chunk=1, and use_segmented=1 must not pass as a bool."""
    allowed = ALLOWED[key]
    if allowed is int:
        return isinstance(v, int) and not isinstance(v, bool) and v > 0
    if all(isinstance(a, bool) for a in allowed):
        return isinstance(v, bool)
    return v in allowed


def validated_values(raw: dict) -> dict:
    """The subset of ``raw`` that is a known key with an in-range value —
    the single filter both the read side (tuned_engine_defaults) and the
    write-side merge (tools/perf_tune.py) apply, so a corrupt entry the
    reader silently drops can never crash a later merged write."""
    return {key: raw[key] for key in ALLOWED
            if key in raw and _value_ok(key, raw[key])}


def current_file_values(path: str = None) -> dict:
    """Validated values currently in the tuned file, ignoring provenance and
    the backend gate (for write-side merges and change detection)."""
    p = path or _path()
    if p in ("", "0", "off"):
        return {}
    return validated_values(_load(p))


def tuned_engine_defaults() -> dict:
    """The validated tuned-default mapping for THIS process, or {} when no
    file exists, the env disables it, or the backend is not (yet) TPU."""
    path = _path()
    if path in ("", "0", "off"):
        return {}
    if not backend_is_tpu():
        return {}
    return validated_values(_load(path))


def tuned_default(key: str, env_var: str, fallback):
    """One field's resolved default: env var > tuned file > fallback.
    String env values are returned as-is (validation happens in the consumer's
    __post_init__ so typos fail with a message naming the variable)."""
    v = os.environ.get(env_var)
    if v is not None and v != "":
        return v
    return tuned_engine_defaults().get(key, fallback)


# ---------------------------------------------------------------------------
# In-process measurement store. Unlike the tuned FILE above (chip facts,
# persisted, TPU-gated), these are probe results valid only for the current
# process+mesh — link bandwidth, selection timing — consumed by the
# distributed-GBDT router and core/perfmodel. First caller pays the probe;
# later boosters on the same mesh read the cached number.
#
# Probe results computed by ``measured_or`` are additionally persisted to a
# small TTL'd disk cache (docs/probe_cache.json by default) so repeated CI
# runs on the same machine don't re-pay the probes. Keys embed the mesh
# fingerprint (device strings), so a cpu cache entry can never serve a tpu
# mesh. ``put_measurement`` deliberately does NOT persist: it is the test
# injection hook, and an injected fake must never leak across processes.
# ---------------------------------------------------------------------------

_MEASUREMENTS: dict = {}

PROBE_CACHE_PATH = os.path.join(_REPO, "docs", "probe_cache.json")
PROBE_CACHE_TTL_S = 24 * 3600.0


def _probe_cache_path() -> Optional[str]:
    p = os.environ.get("SYNAPSEML_TPU_PROBE_CACHE", PROBE_CACHE_PATH)
    return None if p in ("", "0", "off") else p


def _probe_cache_ttl() -> float:
    try:
        return float(os.environ.get("SYNAPSEML_TPU_PROBE_CACHE_TTL_S",
                                    PROBE_CACHE_TTL_S))
    except ValueError:
        return PROBE_CACHE_TTL_S


def _key_str(key) -> str:
    """Canonical string form of a (possibly nested-tuple) cache key."""
    def listify(k):
        if isinstance(k, (tuple, list)):
            return [listify(x) for x in k]
        return k
    try:
        return json.dumps(listify(key), sort_keys=True)
    except (TypeError, ValueError):
        return repr(key)


def _read_probe_cache(path: str) -> dict:
    try:
        with open(path) as f:  # host-side cache read, never under trace
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _disk_probe_get(key):
    """A fresh (within-TTL) persisted probe value, or None."""
    path = _probe_cache_path()
    if path is None:
        return None
    entry = _read_probe_cache(path).get(_key_str(key))
    if not isinstance(entry, dict) or "value" not in entry:
        return None
    import time
    try:
        if time.time() - float(entry.get("ts", 0)) > _probe_cache_ttl():
            return None
    except (TypeError, ValueError):
        return None
    return entry["value"]


def _disk_probe_put(key, value) -> None:
    path = _probe_cache_path()
    if path is None:
        return
    try:
        json.dumps(value)
    except (TypeError, ValueError):
        return  # only JSON-representable probe results persist
    import time
    try:
        cache = _read_probe_cache(path)
        cache[_key_str(key)] = {"value": value, "ts": time.time()}
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        pass  # persistence is best-effort; the in-process cache still holds


def mesh_fingerprint(mesh) -> tuple:
    """Hashable identity of a mesh for probe caching: axis layout plus the
    participating device strings (stable across Mesh-object recreation in one
    process, distinct across different device subsets)."""
    axes = tuple((str(k), int(v)) for k, v in dict(mesh.shape).items())
    devs = tuple(str(d) for d in mesh.devices.flat)
    return axes + devs


def measured_or(key, compute):
    """Get-or-measure: return the cached value for ``key``, running
    ``compute()`` (and caching its result) on the first call. Keys should
    start with a metric name and include ``mesh_fingerprint(mesh)``.
    Computed results also land in the TTL'd disk cache; a fresh persisted
    value short-circuits the probe entirely."""
    if key not in _MEASUREMENTS:
        persisted = _disk_probe_get(key)
        if persisted is not None:
            _MEASUREMENTS[key] = persisted
        else:
            _MEASUREMENTS[key] = compute()
            _disk_probe_put(key, _MEASUREMENTS[key])
    return _MEASUREMENTS[key]


def get_measurement(key, default=None):
    return _MEASUREMENTS.get(key, default)


def put_measurement(key, value) -> None:
    _MEASUREMENTS[key] = value


def clear_measurements() -> None:
    """Test hook: forget all probe results (forces re-measurement). Clears
    the persisted disk cache too — "clear" must mean the next probe really
    runs, not that it is re-read from disk."""
    _MEASUREMENTS.clear()
    path = _probe_cache_path()
    if path is not None:
        try:
            os.remove(path)
        except OSError:
            pass


def write_tuned_defaults(values: dict, provenance: dict,
                         path: str = None) -> Optional[str]:
    """Write the measured winners atomically (tmp + replace). Unknown keys
    and out-of-range values are refused — the write side enforces the same
    table the read side trusts. Returns the path written, or None when the
    operator disabled the mechanism (SYNAPSEML_TPU_TUNED_DEFAULTS=0) — the
    write side honors the same sentinel the read side checks."""
    path = path or _path()
    if path in ("", "0", "off"):
        return None
    clean = {}
    for key, v in values.items():
        allowed = ALLOWED.get(key)
        if allowed is None:
            raise ValueError(f"unknown tuned-default key: {key!r}")
        if not _value_ok(key, v):
            want = ("positive int (not bool)" if allowed is int
                    else f"one of {allowed} (type-exact)")
            raise ValueError(f"tuned default {key}={v!r}: want {want}")
        clean[key] = v
    clean["provenance"] = dict(provenance)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(clean, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    _load.cache_clear()
    return path
