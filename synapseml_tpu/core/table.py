"""Columnar ``Table`` — the framework's DataFrame.

The reference fronts everything with Spark DataFrames; here the front is a thin,
Arrow-friendly columnar table whose columns are numpy arrays (host) that the
execution layer moves to TPU as device arrays when compute starts. Spark's roles
(partitioned tables, task launch, collect) are played by the host-orchestration
layer + sharded ingest (SURVEY.md §7 "Design stance").

Columns may be:
  * 1-D numpy arrays (numeric, bool, or object/str) — scalar columns
  * 2-D numpy arrays — fixed-width vector columns (the SparkML `Vector` analog)
  * object arrays of variable-length sequences — list columns (minibatch outputs)

Interop: ``from_pandas`` / ``to_pandas`` / ``from_arrow`` / ``to_arrow`` /
``read_csv`` / ``read_parquet``; everything stays zero-copy where numpy allows.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping, Optional, Sequence

import numpy as np


class Table:
    """An ordered mapping of column name → numpy array, all with equal length."""

    __slots__ = ("_cols", "_nrows", "num_shards_hint", "concurrency_hint")

    def __init__(self, cols: Optional[Mapping[str, Any]] = None):
        self._cols: dict[str, np.ndarray] = {}
        self._nrows: Optional[int] = None
        # execution hints attached by Repartition / PartitionConsolidator stages
        self.num_shards_hint: Optional[int] = None
        self.concurrency_hint: Optional[int] = None
        if cols:
            for k, v in cols.items():
                self[k] = v

    # --- construction ---------------------------------------------------
    @staticmethod
    def from_pandas(df) -> "Table":
        t = Table()
        for name in df.columns:
            col = df[name]
            arr = col.to_numpy()
            t[str(name)] = arr
        return t

    @staticmethod
    def from_arrow(at) -> "Table":
        t = Table()
        for name in at.column_names:
            t[str(name)] = at.column(name).to_numpy(zero_copy_only=False)
        return t

    @staticmethod
    def read_csv(path: str, **kwargs) -> "Table":
        import pandas as pd

        return Table.from_pandas(pd.read_csv(path, **kwargs))

    @staticmethod
    def read_parquet(path: str, columns: Optional[list] = None) -> "Table":
        import pyarrow.parquet as pq

        return Table.from_arrow(pq.read_table(path, columns=columns))

    def to_pandas(self):
        import pandas as pd

        out = {}
        for k, v in self._cols.items():
            if v.ndim == 2:
                out[k] = list(v)  # vector column → column of arrays
            else:
                out[k] = v
        return pd.DataFrame(out)

    def to_arrow(self):
        import pyarrow as pa

        arrays, names = [], []
        for k, v in self._cols.items():
            if v.ndim == 2:
                arrays.append(pa.array(list(v)))
            else:
                arrays.append(pa.array(v))
            names.append(k)
        return pa.table(arrays, names=names)

    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        pq.write_table(self.to_arrow(), path)

    # --- mapping protocol -----------------------------------------------
    def __setitem__(self, name: str, value) -> None:
        arr = value if isinstance(value, np.ndarray) else np.asarray(value)
        if arr.ndim == 0:
            raise ValueError(f"column {name!r}: scalar is not a column")
        n = arr.shape[0]
        if self._nrows is not None and self._cols and n != self._nrows:
            raise ValueError(
                f"column {name!r} has {n} rows; table has {self._nrows}")
        self._cols[name] = arr
        self._nrows = n

    def __getitem__(self, name):
        if isinstance(name, (list, tuple)):
            return self.select(list(name))
        return self._cols[name]

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __delitem__(self, name: str) -> None:
        del self._cols[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._cols)

    def __len__(self) -> int:
        return self._nrows or 0

    @property
    def num_rows(self) -> int:
        return self._nrows or 0

    @property
    def columns(self) -> list:
        return list(self._cols)

    def schema(self) -> dict:
        return {k: (v.dtype, v.shape[1:]) for k, v in self._cols.items()}

    # --- relational ops --------------------------------------------------
    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self._cols[n] for n in names})

    def drop(self, *names: str) -> "Table":
        return Table({k: v for k, v in self._cols.items() if k not in names})

    def with_column(self, name: str, value) -> "Table":
        out = self.copy()
        out[name] = value
        return out

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(k, k): v for k, v in self._cols.items()})

    def copy(self) -> "Table":
        t = Table()
        t._cols = dict(self._cols)
        t._nrows = self._nrows
        t.num_shards_hint = self.num_shards_hint
        t.concurrency_hint = self.concurrency_hint
        return t

    def take(self, indices) -> "Table":
        idx = np.asarray(indices)
        return Table({k: v[idx] for k, v in self._cols.items()})

    def slice(self, start: int, stop: Optional[int] = None) -> "Table":
        return Table({k: v[start:stop] for k, v in self._cols.items()})

    def head(self, n: int = 5) -> "Table":
        return self.slice(0, n)

    def filter(self, mask) -> "Table":
        m = np.asarray(mask, dtype=bool)
        return Table({k: v[m] for k, v in self._cols.items()})

    def concat(self, *others: "Table") -> "Table":
        tables = (self,) + others
        names = self.columns
        for o in others:
            if o.columns != names:
                raise ValueError("concat requires identical column sets/order")
        return Table({n: np.concatenate([t._cols[n] for t in tables]) for n in names})

    def sample(self, fraction: float, seed: int = 0, replace: bool = False) -> "Table":
        rng = np.random.default_rng(seed)
        n = self.num_rows
        k = int(round(n * fraction))
        idx = rng.choice(n, size=k, replace=replace)
        return self.take(idx)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> list:
        """Row-wise random split, the analog of DataFrame.randomSplit (used for
        numBatches batching, reference: LightGBMBase.scala:45-60)."""
        rng = np.random.default_rng(seed)
        n = self.num_rows
        perm = rng.permutation(n)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        bounds = np.floor(np.cumsum(w) * n).astype(int)
        parts, start = [], 0
        for b in bounds:
            parts.append(self.take(np.sort(perm[start:b])))
            start = b
        return parts

    def shard(self, num_shards: int, pad: bool = True) -> list:
        """Split rows into ``num_shards`` near-equal contiguous shards (the
        partition analog). With ``pad``, every shard gets the same length by
        repeating trailing rows, so shards stack into an SPMD leading axis."""
        n = self.num_rows
        per = -(-n // num_shards)
        shards = []
        for i in range(num_shards):
            s = self.slice(i * per, min((i + 1) * per, n))
            if pad and s.num_rows < per and s.num_rows > 0:
                reps = per - s.num_rows
                filler = s.take(np.arange(reps) % s.num_rows)
                s = s.concat(filler)
            shards.append(s)
        return shards

    def group_indices(self, col: str):
        """Return (unique_values, inverse_index) for a grouping column."""
        vals, inv = np.unique(self._cols[col], return_inverse=True)
        return vals, inv

    def sort_by(self, col: str, ascending: bool = True) -> "Table":
        order = np.argsort(self._cols[col], kind="stable")
        if not ascending:
            order = order[::-1]
        return self.take(order)

    def iter_batches(self, batch_size: int) -> Iterator["Table"]:
        for start in range(0, self.num_rows, batch_size):
            yield self.slice(start, start + batch_size)

    def to_rows(self) -> list:
        names = self.columns
        cols = [self._cols[n] for n in names]
        return [dict(zip(names, vals)) for vals in zip(*cols)]

    @staticmethod
    def from_rows(rows: Iterable[Mapping[str, Any]]) -> "Table":
        rows = list(rows)
        if not rows:
            return Table()
        names = list(rows[0])
        return Table({n: np.asarray([r[n] for r in rows]) for n in names})

    def __repr__(self):
        parts = ", ".join(f"{k}:{v.dtype}{list(v.shape[1:]) or ''}" for k, v in self._cols.items())
        return f"Table[{self.num_rows} rows]({parts})"


def feature_matrix(df: Table, featuresCol: str, dtype=np.float32) -> np.ndarray:
    """Resolve the features column to a dense 2-D float matrix.

    Accepts a 2-D vector column, or — if ``featuresCol`` is absent — treats every
    numeric column except obvious label/weight names as a feature (the lightweight
    analog of running Featurize/VectorAssembler first)."""
    if featuresCol in df:
        arr = df[featuresCol]
        if arr.ndim == 1 and arr.dtype == object:
            arr = np.stack([np.asarray(a, dtype=dtype) for a in arr])
        return np.ascontiguousarray(arr, dtype=dtype)
    raise KeyError(
        f"features column {featuresCol!r} not in table (columns: {df.columns}); "
        "use Featurize or assemble_features() to build it")


def assemble_features(df: Table, input_cols: Sequence[str], output_col: str = "features") -> Table:
    """VectorAssembler analog: stack scalar/vector columns into one 2-D column."""
    mats = []
    for c in input_cols:
        a = df[c]
        mats.append(a[:, None] if a.ndim == 1 else a)
    return df.with_column(output_col, np.concatenate([np.asarray(m, np.float32) for m in mats], axis=1))
