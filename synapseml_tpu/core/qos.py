"""Per-tenant QoS: token-bucket admission, weighted-fair dequeue, and
tenant quarantine for the multi-tenant serving fleet.

N models (gbdt, dl, vw policies, onnx) share ONE worker fleet and one
compile cache (docs/resilience.md, "Multi-tenant fleet"). Sharing is only
viable if a misbehaving tenant cannot take the fleet down with it; this
module is the isolation boundary, layered ON TOP of the existing
bounded-admission/shed machinery in ``io/serving.py``:

* :class:`QoSClass` — a named admission contract (token-bucket rate/burst,
  weighted-fair share, per-tenant queue bound, quarantine thresholds).
* :class:`QoSController` — per-tenant state keyed by the ``X-Tenant``
  header: a token bucket gating admission (exhausted → **429**, the
  per-tenant rate boundary), a per-tenant :class:`~synapseml_tpu.core.
  resilience.CircuitBreaker` fed by handler failures and non-finite
  replies (OPEN → **quarantined**, requests shed at **503** without
  costing handler time), and per-tenant failure/served counters.
* :class:`WeightedFairQueue` — the admission queue for a QoS-enabled
  server: per-tenant FIFO lanes drained by virtual-time weighted-fair
  scheduling, each lane bounded on its own (a flooding tenant fills ITS
  lane and sheds at ITS 503 while other lanes keep their depth and
  latency). Implements the ``queue.Queue`` subset ``io/serving.py``
  consumes (``put_nowait``/``get``/``get_nowait``/``qsize``/``empty``),
  so the serving pipeline is unchanged above it.

Everything is thread-safe and clock-injectable (tests drive fake clocks);
nothing here imports jax — QoS is pure host-side control plane.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from .logging import record_failure
from .resilience import CircuitBreaker

#: Tenant id carried by requests; absent → DEFAULT_TENANT.
TENANT_HEADER = "X-Tenant"
DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class QoSClass:
    """One admission contract. ``rate_per_sec=None`` means un-rate-limited
    (the queue bound and quarantine still apply). ``weight`` is the
    weighted-fair share of batch-formation dequeues; ``max_queue`` bounds
    the tenant's own admission lane."""

    name: str = "standard"
    rate_per_sec: Optional[float] = None
    burst: float = 64.0
    weight: float = 1.0
    max_queue: int = 256
    #: consecutive handler failures (thrown / 500 / non-finite reply) that
    #: quarantine the tenant, and the cooldown before one probe request is
    #: readmitted (CircuitBreaker semantics: escalating on re-trips).
    quarantine_threshold: int = 5
    quarantine_cooldown: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.burst <= 0:
            raise ValueError(f"burst must be > 0, got {self.burst}")


@dataclass(frozen=True)
class AdmitDecision:
    """Outcome of one admission check. ``status`` is the HTTP status the
    server replies with when ``ok`` is False (429 rate-limited at the
    tenant's own token bucket, 503 quarantined at the tenant's own breaker
    boundary)."""

    ok: bool
    status: int = 200
    reason: str = "admitted"


class _TenantState:
    """Per-tenant bucket + breaker + counters; guarded by the controller
    lock (single writer discipline — the controller takes its lock around
    every mutation)."""

    def __init__(self, qos: QoSClass, clock):
        self.qos = qos
        self.tokens = float(qos.burst)
        self.last_refill = clock()
        self.breaker = CircuitBreaker(
            failure_threshold=qos.quarantine_threshold,
            cooldown=qos.quarantine_cooldown, clock=clock)
        self.admitted = 0
        self.rate_limited = 0
        self.quarantined = 0
        self.completed = 0
        self.failed = 0
        self.nonfinite = 0

    # called with the controller's _lock held (see class docstring)
    def refill(self, now: float, share: float = 1.0) -> None:
        """Refill at ``share`` of the class contract. ``share`` < 1 is the
        federated mode: the class rate/burst describe the GLOBAL per-tenant
        budget and each gateway enforces its leased fraction, so K gateways
        admitting independently still sum to one global rate (burst floors
        at one token — a leaseholder must always be able to admit)."""
        rate = self.qos.rate_per_sec
        burst = max(1.0, self.qos.burst * share)
        if rate is None:
            self.tokens = burst  # lint-ok: locks
        else:
            self.tokens = min(  # lint-ok: locks
                burst,
                self.tokens + (now - self.last_refill) * rate * share)
        self.last_refill = now


class QoSController:
    """Keyed per-tenant admission/quarantine state. One instance per
    :class:`~synapseml_tpu.io.serving.ServingServer`; the server calls
    :meth:`admit` at its admission boundary and feeds batch outcomes back
    through :meth:`record_success` / :meth:`record_failure`."""

    def __init__(self, default_class: Optional[QoSClass] = None,
                 classes: Optional[Dict[str, QoSClass]] = None,
                 clock=time.monotonic):
        self.default_class = default_class or QoSClass()
        self._clock = clock
        self._lock = threading.Lock()
        self._classes: Dict[str, QoSClass] = dict(classes or {})
        self._tenants: Dict[str, _TenantState] = {}
        # federated budget leasing: tenant -> this enforcer's fraction of
        # the GLOBAL class rate (1.0 = sole enforcer, the single-gateway
        # mode). Written by set_rate_share from the gossip/lease layer.
        self._shares: Dict[str, float] = {}

    def assign(self, tenant: str, qos: QoSClass) -> None:
        """(Re)assign a tenant's QoS class; existing counters are kept but
        the bucket and breaker restart under the new contract."""
        with self._lock:
            self._classes[tenant] = qos
            old = self._tenants.pop(tenant, None)
            state = self._state_locked(tenant)
            if old is not None:
                for c in ("admitted", "rate_limited", "quarantined",
                          "completed", "failed", "nonfinite"):
                    setattr(state, c, getattr(old, c))

    def qos_class(self, tenant: str) -> QoSClass:
        with self._lock:
            return self._classes.get(tenant, self.default_class)

    # -- federated budget leasing --
    def set_rate_share(self, tenant: str, share: float) -> None:
        """Set this enforcer's leased fraction of the tenant's GLOBAL
        rate/burst contract (:class:`BudgetLeaseLedger` computes it from
        live leaseholders). Clamped to (0, 1]; takes effect on the next
        refill — tokens already granted are honored (a shrinking share
        never claws back admitted requests)."""
        share = min(max(float(share), 1e-9), 1.0)
        with self._lock:
            self._shares[tenant] = share

    def rate_share(self, tenant: str) -> float:
        with self._lock:
            return self._shares.get(tenant, 1.0)

    def _state_locked(self, tenant: str) -> _TenantState:
        state = self._tenants.get(tenant)
        if state is None:
            state = _TenantState(
                self._classes.get(tenant, self.default_class), self._clock)
            self._tenants[tenant] = state
        return state

    # -- admission boundary --
    def admit(self, tenant: str) -> AdmitDecision:
        """One admission check: quarantine first (a quarantined tenant's
        requests must not drain its token bucket — readmission is the
        breaker's single half-open probe), then the token bucket."""
        now = self._clock()
        with self._lock:
            state = self._state_locked(tenant)
            if not state.breaker.try_acquire(now):
                state.quarantined += 1
                record_failure("qos.quarantined", tenant=tenant)
                return AdmitDecision(False, 503, "quarantined")
            state.refill(now, self._shares.get(tenant, 1.0))
            if state.tokens < 1.0:
                state.rate_limited += 1
                # the failed admission must not hold the half-open probe
                # slot hostage: a rate-limited probe is not a verdict on
                # the tenant's handler
                if state.breaker.state == CircuitBreaker.HALF_OPEN:
                    state.breaker._probe_inflight = False
                record_failure("qos.rate_limited", tenant=tenant)
                return AdmitDecision(False, 429, "rate_limited")
            state.tokens -= 1.0
            state.admitted += 1
            return AdmitDecision(True)

    # -- outcome feedback (fed by the server's batch path) --
    def record_success(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            state = self._state_locked(tenant)
            state.completed += n
        state.breaker.record_success()

    def record_failure(self, tenant: str, n: int = 1,
                       nonfinite: bool = False) -> None:
        """Count ``n`` handler failures for a tenant; each feeds the
        quarantine breaker (consecutive failures past the class threshold
        OPEN it and the tenant sheds at its own 503 boundary)."""
        with self._lock:
            state = self._state_locked(tenant)
            state.failed += n
            if nonfinite:
                state.nonfinite += n
        for _ in range(n):
            state.breaker.record_failure()
        record_failure("qos.tenant_failure", n=n, tenant=tenant,
                       nonfinite=bool(nonfinite))

    def is_quarantined(self, tenant: str) -> bool:
        now = self._clock()
        with self._lock:
            state = self._tenants.get(tenant)
        return state is not None and not state.breaker.available(now)

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for tenant, s in self._tenants.items():
                out[tenant] = {
                    "class": s.qos.name, "weight": s.qos.weight,
                    "tokens": round(s.tokens, 3),
                    "rate_share": self._shares.get(tenant, 1.0),
                    "admitted": s.admitted,
                    "rate_limited": s.rate_limited,
                    "quarantined": s.quarantined,
                    "completed": s.completed, "failed": s.failed,
                    "nonfinite": s.nonfinite,
                    "breaker": s.breaker.snapshot()}
            return out


class BudgetLeaseLedger:
    """Who currently holds a sub-budget lease on each tenant's global rate.

    The federated-gateway problem: K edge gateways must together enforce
    ONE per-tenant rate without a central counter on the hot path. Scheme:
    a gateway serving tenant T claims a **lease** — a gossip entry
    (``lease/<tenant>/<gateway>``) it re-publishes every replicator tick.
    Every gateway feeds the lease entries it sees (its own and merged ones)
    into this ledger via :meth:`observe`; a leaseholder is **live** while
    its entry keeps advancing, judged purely on the LOCAL monotonic instant
    of the last advance (``GossipState.advanced_at`` semantics) — no
    cross-host clock comparison. Each live holder's share is ``1/n_live``,
    pushed into :meth:`QoSController.set_rate_share`, so the fleet-wide sum
    of enforced rates is exactly the global contract.

    Safety when a leaseholder dies: its entry stops advancing everywhere,
    so after ``ttl`` of silence survivors drop it from ``n_live`` and their
    shares GROW to reabsorb the freed budget. The failure window errs
    closed — between the death and the expiry the fleet enforces less than
    the global rate (the dead gateway's slice goes unused), never more;
    over-admission is impossible by construction. Thread-safe,
    clock-injectable, transport-free (the gossip layer drives it).
    """

    def __init__(self, ttl: float = 2.0, clock=time.monotonic):
        if ttl <= 0:
            raise ValueError(f"ttl must be > 0, got {ttl}")
        self.ttl = float(ttl)
        self._clock = clock
        self._lock = threading.Lock()
        # tenant -> holder -> local monotonic time of last observed advance
        self._leases: Dict[str, Dict[str, float]] = {}
        self.expired = 0

    def observe(self, tenant: str, holder: str) -> None:
        """A lease entry for (tenant, holder) advanced — published locally
        or accepted in a merge. Resets the holder's liveness window."""
        with self._lock:
            self._leases.setdefault(tenant, {})[holder] = self._clock()

    def release(self, tenant: str, holder: str) -> None:
        """Explicit release (clean gateway shutdown / lease tombstone)."""
        with self._lock:
            holders = self._leases.get(tenant)
            if holders is not None:
                holders.pop(holder, None)
                if not holders:
                    del self._leases[tenant]

    def holders(self, tenant: str, now: Optional[float] = None) -> list:
        """Live leaseholders, pruning any whose entry went ``ttl`` without
        advancing (the dead-gateway expiry)."""
        now = self._clock() if now is None else now
        with self._lock:
            holders = self._leases.get(tenant, {})
            dead = [h for h, at in holders.items() if now - at > self.ttl]
            for h in dead:
                del holders[h]
                self.expired += 1
                record_failure("qos.lease_expired", tenant=tenant,
                               holder=h)
            return sorted(holders)

    def share(self, tenant: str, holder: str,
              now: Optional[float] = None) -> float:
        """``holder``'s fraction of the tenant's global budget: 1/n over
        the live holders, counting ``holder`` itself even before its first
        observed advance (asking for a share IS holding a lease)."""
        live = set(self.holders(tenant, now))
        live.add(holder)
        return 1.0 / len(live)

    def tenants(self) -> list:
        with self._lock:
            return sorted(self._leases)

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            return {"ttl_s": self.ttl, "expired": self.expired,
                    "tenants": {
                        t: {h: round(now - at, 3)
                            for h, at in holders.items()}
                        for t, holders in self._leases.items()}}


class WeightedFairQueue:
    """Bounded per-tenant lanes + virtual-time weighted-fair dequeue.

    Drop-in for the ``queue.Queue`` subset the serving pipeline uses; items
    must expose a ``tenant`` attribute (absent → ``DEFAULT_TENANT``).
    ``put_nowait`` raises :class:`queue.Full` when the item's OWN lane (or
    the global bound) is full — a flooding tenant backs up its lane and
    sheds at its own 503 while other lanes keep admitting.

    Dequeue picks the non-empty lane with the smallest virtual finish time
    and advances it by ``1/weight`` — tenants drain in proportion to their
    class weights under contention, strict FIFO within a lane. A lane going
    idle re-enters at the current virtual time (no credit hoarding: a burst
    after a quiet spell cannot monopolize formation)."""

    def __init__(self, maxsize: int = 1024,
                 qos: Optional[QoSController] = None):
        self.maxsize = int(maxsize)
        self.qos = qos
        self._lanes: Dict[str, deque] = {}
        self._vt: Dict[str, float] = {}
        self._now_vt = 0.0            # virtual time of the last dequeue
        self._size = 0
        self._cond = threading.Condition()

    def _lane_params(self, tenant: str):
        if self.qos is not None:
            qc = self.qos.qos_class(tenant)
            return qc.weight, min(qc.max_queue, self.maxsize)
        return 1.0, self.maxsize

    def put_nowait(self, item) -> None:
        tenant = getattr(item, "tenant", None) or DEFAULT_TENANT
        weight, cap = self._lane_params(tenant)
        with self._cond:
            lane = self._lanes.get(tenant)
            if lane is None:
                lane = self._lanes[tenant] = deque()
            if len(lane) >= cap or self._size >= self.maxsize:
                record_failure("qos.lane_full", tenant=tenant)
                raise queue.Full(f"tenant {tenant!r} lane full")
            if not lane:
                # idle lane re-enters at current virtual time
                self._vt[tenant] = max(self._vt.get(tenant, 0.0),
                                       self._now_vt)
            lane.append(item)
            self._size += 1
            self._cond.notify()

    def _pop_locked(self):
        best, best_vt = None, None
        for tenant, lane in self._lanes.items():
            if lane and (best_vt is None or self._vt[tenant] < best_vt):
                best, best_vt = tenant, self._vt[tenant]
        if best is None:
            raise queue.Empty
        item = self._lanes[best].popleft()
        weight, _ = self._lane_params(best)
        self._now_vt = best_vt
        self._vt[best] = best_vt + 1.0 / weight
        self._size -= 1
        return item

    def get_nowait(self):
        with self._cond:
            return self._pop_locked()

    def get(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._size == 0:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise queue.Empty
                self._cond.wait(remaining)
            return self._pop_locked()

    def qsize(self) -> int:
        with self._cond:
            return self._size

    def lane_depth(self, tenant: str) -> int:
        with self._cond:
            lane = self._lanes.get(tenant)
            return len(lane) if lane else 0

    def empty(self) -> bool:
        return self.qsize() == 0

    def snapshot(self) -> dict:
        with self._cond:
            return {t: len(lane) for t, lane in self._lanes.items() if lane}
