"""Spark interop adapter — the migration bridge for reference users.

The reference is Spark-native; this framework's substrate is the columnar
:class:`~synapseml_tpu.core.table.Table` (SURVEY §7 design stance: "Spark's
role is played by a thin host-orchestration layer; Spark-the-dependency is
optional (adapter), not the substrate"). This module is that adapter: when
``pyspark`` is importable, Spark DataFrames convert to/from ``Table`` and any
estimator/transformer here can run ALONGSIDE Spark code via
:func:`wrap_stage` (duck-typed fit/transform on DataFrames — not a
``pyspark.ml.PipelineStage``, so it composes in Python code rather than
inside a ``pyspark.ml.Pipeline`` object); without pyspark every entry point
raises a clear ImportError (the build image intentionally ships without
Spark).

Conversion rides pandas (both sides already speak it): Spark ``toPandas()``
uses Arrow when ``spark.sql.execution.arrow.pyspark.enabled`` is set — the
same Arrow boundary the reference crosses for its Python UDFs.
"""

from __future__ import annotations

from typing import Any

from .table import Table


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "Spark interop needs pyspark, which is not installed in this "
            "environment. Convert through pandas instead: "
            "Table.from_pandas(spark_df.toPandas()) on a machine with Spark, "
            "or feed Table.read_parquet() files written by Spark.") from e


def from_spark(spark_df) -> Table:
    """Spark DataFrame → Table (collects to the driver via Arrow/pandas —
    the same boundary the reference crosses for Python UDF interop). For
    DataFrames larger than driver memory use :func:`from_spark_streamed`
    (Table in bounded conversion memory) or
    :func:`dataset_from_spark` (GBDT Dataset with raw floats never
    materialized at all)."""
    _require_pyspark()
    return Table.from_pandas(spark_df.toPandas())


def iter_spark_chunks(spark_df, chunk_rows: int = 65536):
    """Partition-bounded streaming: yield the DataFrame as numpy column
    dicts of <= ``chunk_rows`` rows via ``toLocalIterator`` (Spark ships one
    partition at a time to the driver — peak memory is one partition + one
    chunk, never the whole DataFrame; LightGBMBase.scala:608-628's
    mapPartitions dispatch is the reference analog). Duck-typed: anything
    with ``.columns`` and ``.toLocalIterator()`` yielding row tuples works
    (tested with a fake in-memory Spark DataFrame — pyspark itself is not
    in this image)."""
    import numpy as np

    cols = list(spark_df.columns)
    buf = []
    it = spark_df.toLocalIterator()

    def _emit(rows):
        arr = list(zip(*rows))
        out = {}
        for i, c in enumerate(cols):
            a = np.asarray(arr[i])
            if a.dtype == object:
                # Spark SQL nulls arrive as None; numeric columns must map
                # them to NaN exactly as the toPandas() bridge does (the
                # missing bin handles them downstream). Non-numeric object
                # columns pass through unchanged.
                try:
                    a = np.array([np.nan if v is None else v
                                  for v in arr[i]], np.float32)
                except (TypeError, ValueError):
                    pass
            out[c] = a
        return out

    for row in it:
        buf.append(tuple(row))
        if len(buf) >= chunk_rows:
            yield _emit(buf)
            buf = []
    if buf:
        yield _emit(buf)


def from_spark_streamed(spark_df, chunk_rows: int = 65536) -> Table:
    """Spark DataFrame → Table without a whole-DF pandas copy: chunks
    accumulate as numpy parts, each column concatenates and frees its
    parts in turn — peak host memory is the final Table plus one column's
    chunks, not the 2x of a single big concatenation."""
    import numpy as np

    parts: dict = {}
    for chunk in iter_spark_chunks(spark_df, chunk_rows):
        for c, v in chunk.items():
            parts.setdefault(c, []).append(v)
    if not parts:
        raise ValueError("from_spark_streamed: empty DataFrame")
    out = {}
    for c in list(parts):
        out[c] = np.concatenate(parts.pop(c))
    return Table(out)


def _reservoir_sample_features(spark_df, feature_cols, n: int,
                               chunk_rows: int, seed: int,
                               cat_mask=None, max_bin: int = 255):
    """Algorithm-R reservoir over the streamed chunks PLUS full-stream
    per-feature stats: (sample, has_nan, cat_presence). The sample gives
    unbiased bin boundaries on ordered streams; has_nan / cat_presence are
    exact over the WHOLE stream so missing-bin allocation and the
    maxCatToOnehot decision never depend on what the sample happened to
    contain (the reference's reference-dataset flow makes the same split:
    sampled boundaries, full-data missing/occupancy —
    LightGBMBase.scala:509-550 + dataset/SampledData.scala)."""
    import numpy as np

    from ..ops.quantize import cat_presence_bitmap

    rng = np.random.default_rng(seed)
    F = len(feature_cols)
    reservoir = None
    has_nan = np.zeros(F, bool)
    presence = np.zeros((F, max_bin), bool)
    cat_mask = (np.zeros(F, bool) if cat_mask is None
                else np.asarray(cat_mask, bool))
    seen = 0
    for chunk in iter_spark_chunks(spark_df, chunk_rows):
        Xc = np.column_stack([np.asarray(chunk[c], np.float32)
                              for c in feature_cols])
        has_nan |= np.isnan(Xc).any(axis=0)
        for j in np.flatnonzero(cat_mask):
            presence[j] |= cat_presence_bitmap(Xc[:, j], max_bin)
        if reservoir is None:
            reservoir = np.empty((n, Xc.shape[1]), np.float32)
        take = min(n - seen, len(Xc)) if seen < n else 0
        if take:
            reservoir[seen:seen + take] = Xc[:take]
        rest = Xc[take:]
        if len(rest):
            pos = seen + take + np.arange(len(rest)) + 1
            accept = rng.random(len(rest)) < n / pos
            slots = rng.integers(0, n, size=int(accept.sum()))
            reservoir[slots] = rest[accept]
        seen += len(Xc)
    if reservoir is None:
        raise ValueError("dataset_from_spark: empty DataFrame")
    return reservoir[:min(seen, n)], has_nan, presence


def dataset_from_spark(spark_df, feature_cols, label_col=None,
                       weight_col=None, chunk_rows: int = 65536,
                       max_bin: int = 255, bin_sample_count: int = 200_000,
                       categorical_features=None, seed: int = 0,
                       two_pass: bool = True):
    """Spark DataFrame → pre-binned GBDT ``Dataset`` in bounded memory: raw
    float rows are binned to uint8 per chunk and dropped, so the driver
    never holds the full-precision matrix (VERDICT r4 #5 — the toPandas()
    bridge cannot fit HIGGS-class data).

    ``two_pass=True`` (default) first reservoir-samples ``bin_sample_count``
    rows across the WHOLE stream for unbiased bin boundaries (Spark
    re-executes the plan for the second pass, exactly like the reference's
    sample-then-stream reference-dataset flow); ``two_pass=False`` streams
    once and uses a prefix sample — fine for shuffled data. Train with
    ``train_booster(ds, None, cfg)``."""
    from ..gbdt.dataset import Dataset
    from ..ops.quantize import compute_bin_mapper

    import numpy as np

    mapper = None
    if two_pass:
        cat_mask = np.zeros(len(feature_cols), bool)
        if categorical_features:
            cat_mask[list(categorical_features)] = True
        sample, has_nan, presence = _reservoir_sample_features(
            spark_df, feature_cols, bin_sample_count, chunk_rows, seed,
            cat_mask=cat_mask, max_bin=max_bin)
        mapper = compute_bin_mapper(
            sample, max_bin, bin_sample_count, categorical_features, seed,
            has_nan=has_nan,
            cat_presence=presence if categorical_features else None)

    def batches():
        for chunk in iter_spark_chunks(spark_df, chunk_rows):
            Xc = np.column_stack([np.asarray(chunk[c], np.float32)
                                  for c in feature_cols])
            yc = (np.asarray(chunk[label_col], np.float32)
                  if label_col else None)
            wc = (np.asarray(chunk[weight_col], np.float32)
                  if weight_col else None)
            yield (Xc, yc, wc)

    ds = Dataset.from_batches(batches(), mapper=mapper, max_bin=max_bin,
                              bin_sample_count=bin_sample_count,
                              categorical_features=categorical_features,
                              seed=seed)
    # the mapper came from THIS function's own knobs (recorded on ds), not
    # from the user — keep the train-time config mismatch checks active
    ds._user_mapper = False
    return ds


def to_spark(table: Table, spark) -> Any:
    """Table → Spark DataFrame on the given SparkSession."""
    _require_pyspark()
    return spark.createDataFrame(table.to_pandas())


class wrap_stage:
    """Run a synapseml_tpu stage on Spark DataFrames:

    ``model = wrap_stage(LightGBMClassifier(...)).fit(spark_df)`` — fit
    collects through the adapter, transform returns a Spark DataFrame on the
    input's session. For datasets too large to collect, write parquet from
    Spark and use ``Table.read_parquet`` + the mesh-sharded training path
    instead (the reference's own per-worker native training collects each
    partition into the native library's memory just the same)."""

    def __init__(self, stage):
        self.stage = stage

    def fit(self, spark_df) -> "wrap_stage":
        fitted = self.stage.fit(from_spark(spark_df))
        return wrap_stage(fitted)

    def transform(self, spark_df):
        # DataFrame.sparkSession only exists on pyspark >= 3.3; older
        # DataFrames resolve unknown attributes as COLUMN lookups, so probe
        # the class and fall back to the sql_ctx route (3.1/3.2)
        if hasattr(type(spark_df), "sparkSession"):
            session = spark_df.sparkSession
        else:
            session = spark_df.sql_ctx.sparkSession
        out = self.stage.transform(from_spark(spark_df))
        return to_spark(out, session)

    def __getattr__(self, name: str):
        # guard: dunder/underscore lookups (pickle's __reduce_ex__, copy's
        # __copy__) arrive before self.stage exists and must not recurse
        if name.startswith("_") or name == "stage":
            raise AttributeError(name)
        return getattr(self.stage, name)


