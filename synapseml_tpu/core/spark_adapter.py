"""Spark interop adapter — the migration bridge for reference users.

The reference is Spark-native; this framework's substrate is the columnar
:class:`~synapseml_tpu.core.table.Table` (SURVEY §7 design stance: "Spark's
role is played by a thin host-orchestration layer; Spark-the-dependency is
optional (adapter), not the substrate"). This module is that adapter: when
``pyspark`` is importable, Spark DataFrames convert to/from ``Table`` and any
estimator/transformer here can run ALONGSIDE Spark code via
:func:`wrap_stage` (duck-typed fit/transform on DataFrames — not a
``pyspark.ml.PipelineStage``, so it composes in Python code rather than
inside a ``pyspark.ml.Pipeline`` object); without pyspark every entry point
raises a clear ImportError (the build image intentionally ships without
Spark).

Conversion rides pandas (both sides already speak it): Spark ``toPandas()``
uses Arrow when ``spark.sql.execution.arrow.pyspark.enabled`` is set — the
same Arrow boundary the reference crosses for its Python UDFs.
"""

from __future__ import annotations

from typing import Any

from .table import Table


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "Spark interop needs pyspark, which is not installed in this "
            "environment. Convert through pandas instead: "
            "Table.from_pandas(spark_df.toPandas()) on a machine with Spark, "
            "or feed Table.read_parquet() files written by Spark.") from e


def from_spark(spark_df) -> Table:
    """Spark DataFrame → Table (collects to the driver via Arrow/pandas —
    the same boundary the reference crosses for Python UDF interop)."""
    _require_pyspark()
    return Table.from_pandas(spark_df.toPandas())


def to_spark(table: Table, spark) -> Any:
    """Table → Spark DataFrame on the given SparkSession."""
    _require_pyspark()
    return spark.createDataFrame(table.to_pandas())


class wrap_stage:
    """Run a synapseml_tpu stage on Spark DataFrames:

    ``model = wrap_stage(LightGBMClassifier(...)).fit(spark_df)`` — fit
    collects through the adapter, transform returns a Spark DataFrame on the
    input's session. For datasets too large to collect, write parquet from
    Spark and use ``Table.read_parquet`` + the mesh-sharded training path
    instead (the reference's own per-worker native training collects each
    partition into the native library's memory just the same)."""

    def __init__(self, stage):
        self.stage = stage

    def fit(self, spark_df) -> "wrap_stage":
        fitted = self.stage.fit(from_spark(spark_df))
        return wrap_stage(fitted)

    def transform(self, spark_df):
        # DataFrame.sparkSession only exists on pyspark >= 3.3; older
        # DataFrames resolve unknown attributes as COLUMN lookups, so probe
        # the class and fall back to the sql_ctx route (3.1/3.2)
        if hasattr(type(spark_df), "sparkSession"):
            session = spark_df.sparkSession
        else:
            session = spark_df.sql_ctx.sparkSession
        out = self.stage.transform(from_spark(spark_df))
        return to_spark(out, session)

    def __getattr__(self, name: str):
        # guard: dunder/underscore lookups (pickle's __reduce_ex__, copy's
        # __copy__) arrive before self.stage exists and must not recurse
        if name.startswith("_") or name == "stage":
            raise AttributeError(name)
        return getattr(self.stage, name)


