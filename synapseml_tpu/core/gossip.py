"""Replicated gateway control plane: epoch-versioned anti-entropy gossip.

One :class:`~synapseml_tpu.io.distributed_serving.ServingGateway` process
owning all membership/affinity/QoS state is a single kill away from total
fabric loss. This module is the replication substrate that federates K peer
gateways: each holds a :class:`GossipState` — a key→entry map where every
entry carries a **lamport epoch** and its **origin gateway id** — and
periodically exchanges full state with one peer over the existing
``/__fabric/`` HTTP control plane (push-pull anti-entropy). Merge is
per-entry last-writer-wins on the ``(epoch, origin)`` tuple:

* the lamport clock only moves forward (every local publish bumps it past
  the newest epoch ever seen, including epochs learned from peers), so a
  gateway that HEARD about an entry and then overwrites it always wins over
  the stale original — causality is preserved without synchronized clocks;
* the origin id breaks exact epoch ties deterministically, so two gateways
  publishing concurrently converge on the SAME winner everywhere instead of
  flapping by exchange order.

Deletions are **tombstones** (``value=None``) — a real entry that must
out-gossip the data it deletes, or an evicted worker would be resurrected
by the next exchange with a peer that never heard the eviction. A later
re-publish (higher epoch) resurrects cleanly: worker rejoin just works.

What rides on it (io/distributed_serving.py): worker membership +
warm-ladder advertisements (``member/<url>``), gateway liveness
(``gateway/<id>``), tenant budget leases (``lease/<tenant>/<id>``,
core/qos.py), and two-phase promotion records (``promo/<version>``) — the
replicated prepare record a surviving peer reads to drive a dead
coordinator's broadcast round to commit or abort.

:class:`ConsistentHashRing` is the deterministic placement half:
tenant→gateway affinity that every converged gateway computes identically,
with minimal movement when a gateway dies (only the dead node's arcs
rehash — surviving tenants keep their home, so warm-ladder routing keeps
seeing stable shapes).

Thread-safe and clock-injectable; no jax, no sockets — transport belongs
to the gateway (chaos partitions it via ``_GOSSIP_HOOK`` there).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class GossipEntry:
    """One replicated fact. ``value=None`` is a tombstone (the deletion
    itself replicates). ``(epoch, origin)`` totally orders conflicting
    writes to the same key fabric-wide."""

    key: str
    value: Optional[dict]
    epoch: int
    origin: str

    def wire(self) -> dict:
        return {"key": self.key, "value": self.value,
                "epoch": self.epoch, "origin": self.origin}

    @classmethod
    def from_wire(cls, d: dict) -> "GossipEntry":
        value = d.get("value")
        return cls(key=str(d["key"]),
                   value=dict(value) if isinstance(value, dict) else None,
                   epoch=int(d["epoch"]), origin=str(d.get("origin", "")))


def _wins(challenger: GossipEntry, incumbent: Optional[GossipEntry]) -> bool:
    """Does ``challenger`` replace ``incumbent``? Strict — an identical
    (epoch, origin) re-delivery is a no-op, so exchanges are idempotent."""
    if incumbent is None:
        return True
    return (challenger.epoch, challenger.origin) > \
        (incumbent.epoch, incumbent.origin)


class GossipState:
    """Epoch-versioned replicated map for one gateway.

    * :meth:`publish` / :meth:`retract` — local writes; each bumps the
      lamport clock past everything this node has ever seen, stamping the
      entry so it wins over any state the write is based on.
    * :meth:`merge` — apply a peer's entries; per-entry ``(epoch, origin)``
      tie-breaking makes merge commutative, associative and idempotent
      (anti-entropy converges regardless of exchange order or repeats).
    * :meth:`advanced_at` — the LOCAL monotonic instant a key last advanced
      (changed epoch). Budget leases expire on this: a dead leaseholder's
      entries stop advancing everywhere, no cross-host clock comparison
      needed (core/qos.py, :class:`~synapseml_tpu.core.qos.BudgetLease`).
    * replication-lag accounting — peers' lamport clocks ride every
      exchange (:meth:`observe_peer_clock`); ``entries_behind`` =
      newest clock known anywhere minus what this node has merged, the
      health-endpoint number that shows a partition before it bites.
    """

    def __init__(self, node_id: str, clock=time.monotonic):
        if not node_id:
            raise ValueError("GossipState needs a non-empty node_id")
        self.node_id = str(node_id)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[str, GossipEntry] = {}
        self._lamport = 0
        self._advanced_at: Dict[str, float] = {}
        self._peer_clocks: Dict[str, int] = {}
        self.published = 0
        self.merged_in = 0          # entries accepted from peers
        self.stale_dropped = 0      # entries offered but already superseded

    # -- local writes -----------------------------------------------------
    def publish(self, key: str, value: Optional[dict]) -> GossipEntry:
        """Write ``key`` locally; the new entry's epoch is newer than every
        epoch this node has seen, so it supersedes whatever it read."""
        with self._lock:
            self._lamport += 1
            entry = GossipEntry(key=str(key),
                                value=dict(value) if value is not None
                                else None,
                                epoch=self._lamport, origin=self.node_id)
            self._entries[entry.key] = entry
            self._advanced_at[entry.key] = self._clock()
            self.published += 1
            return entry

    def retract(self, key: str) -> GossipEntry:
        """Delete via tombstone — the deletion replicates like any write."""
        return self.publish(key, None)

    # -- anti-entropy -----------------------------------------------------
    def merge(self, entries: Iterable) -> List[GossipEntry]:
        """Apply a peer's entries (wire dicts or :class:`GossipEntry`);
        returns those accepted (newer than local state). The lamport clock
        advances to the newest epoch seen, so later local writes supersede
        everything merged here."""
        accepted: List[GossipEntry] = []
        with self._lock:
            for raw in entries:
                entry = raw if isinstance(raw, GossipEntry) \
                    else GossipEntry.from_wire(raw)
                if entry.epoch > self._lamport:
                    self._lamport = entry.epoch
                if _wins(entry, self._entries.get(entry.key)):
                    self._entries[entry.key] = entry
                    self._advanced_at[entry.key] = self._clock()
                    self.merged_in += 1
                    accepted.append(entry)
                else:
                    self.stale_dropped += 1
        return accepted

    def wire(self) -> List[dict]:
        """Full state in wire form (tombstones included — they must
        out-gossip what they delete)."""
        with self._lock:
            return [e.wire() for e in self._entries.values()]

    # -- reads ------------------------------------------------------------
    def get(self, key: str) -> Optional[dict]:
        """Live value for ``key`` (None for absent OR tombstoned)."""
        with self._lock:
            entry = self._entries.get(key)
            return dict(entry.value) if entry is not None \
                and entry.value is not None else None

    def entry(self, key: str) -> Optional[GossipEntry]:
        with self._lock:
            return self._entries.get(key)

    def items(self, prefix: str = "") -> Dict[str, dict]:
        """Live (non-tombstoned) entries under ``prefix``."""
        with self._lock:
            return {k: dict(e.value) for k, e in self._entries.items()
                    if e.value is not None and k.startswith(prefix)}

    def advanced_at(self, key: str) -> Optional[float]:
        """LOCAL monotonic time ``key`` last changed epoch here (publish or
        accepted merge) — the liveness signal leases expire on."""
        with self._lock:
            return self._advanced_at.get(key)

    @property
    def lamport(self) -> int:
        with self._lock:
            return self._lamport

    # -- replication-lag accounting --------------------------------------
    def observe_peer_clock(self, peer: str, clock: int) -> None:
        """Record a peer's advertised lamport clock (rides every gossip
        request AND reply, so one-way partitions still surface lag)."""
        with self._lock:
            if clock > self._peer_clocks.get(peer, -1):
                self._peer_clocks[peer] = int(clock)

    def entries_behind(self) -> int:
        """How far behind the newest epoch known ANYWHERE this node is —
        0 when converged; grows while a partition withholds exchanges."""
        with self._lock:
            newest = max(self._peer_clocks.values(), default=0)
            return max(0, newest - self._lamport)

    def peer_clocks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._peer_clocks)

    def snapshot(self) -> dict:
        with self._lock:
            live = sum(1 for e in self._entries.values()
                       if e.value is not None)
            newest = max(self._peer_clocks.values(), default=0)
            return {"node_id": self.node_id, "clock": self._lamport,
                    "entries": live,
                    "tombstones": len(self._entries) - live,
                    "published": self.published,
                    "merged_in": self.merged_in,
                    "stale_dropped": self.stale_dropped,
                    "entries_behind": max(0, newest - self._lamport)}


class ConsistentHashRing:
    """Deterministic key→node placement with minimal movement on node
    death: each node owns ``vnodes`` pseudo-random arcs of a sha1 ring, a
    key maps to the first arc clockwise of its hash. Removing a node
    reassigns ONLY that node's arcs (≈1/K of keys); every other key keeps
    its node — the property tenant→gateway affinity needs so a gateway
    death rehomes only the dead gateway's tenants, with every surviving
    gateway computing the SAME new homes from converged membership.
    """

    def __init__(self, nodes: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._points: List[Tuple[int, str]] = []   # sorted (hash, node)
        self._nodes: set = set()
        for n in nodes:
            self.add(n)

    @staticmethod
    def _hash(data: str) -> int:
        return int.from_bytes(
            hashlib.sha1(data.encode()).digest()[:8], "big")

    def add(self, node: str) -> bool:
        node = str(node)
        with self._lock:
            if node in self._nodes:
                return False
            self._nodes.add(node)
            for i in range(self.vnodes):
                bisect.insort(self._points,
                              (self._hash(f"{node}#{i}"), node))
            return True

    def remove(self, node: str) -> bool:
        node = str(node)
        with self._lock:
            if node not in self._nodes:
                return False
            self._nodes.discard(node)
            self._points = [p for p in self._points if p[1] != node]
            return True

    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        with self._lock:
            return node in self._nodes

    def node_for(self, key: str, exclude: Sequence[str] = ()
                 ) -> Optional[str]:
        """Owning node for ``key`` — first arc clockwise of the key's hash,
        skipping ``exclude`` (walk on: the deterministic failover order).
        None when no eligible node remains."""
        skip = set(exclude)
        with self._lock:
            if not self._points:
                return None
            start = bisect.bisect(self._points, (self._hash(str(key)), ""))
            n = len(self._points)
            for off in range(n):
                node = self._points[(start + off) % n][1]
                if node not in skip:
                    return node
            return None
