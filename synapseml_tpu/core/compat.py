"""Version-compat shims for the jax API surface.

jax promoted ``jax.experimental.shard_map.shard_map`` to ``jax.shard_map``
and renamed its ``check_rep`` kwarg to ``check_vma``; the framework targets
the new spelling everywhere. On a jax that predates the promotion this module
maps the call back onto the experimental implementation so the whole
distributed path (collectives, ring attention, VW sync passes, GBDT voting)
still runs instead of collapsing with ``AttributeError`` at import/trace time.
"""

from __future__ import annotations

import functools

import jax

def donate_argnums_if_supported(*argnums):
    """``donate_argnums`` to pass to ``jax.jit``, or ``()`` on CPU.

    Buffer donation is a silent no-op on CPU: jax logs a warning per call
    and keeps both buffers, which buries real warnings in CI logs and
    makes the donation path untested. Gating through this helper turns
    donation off where it cannot work and keeps the aliasing behaviour
    identical on TPU/GPU. Call it lazily (inside a cached jit factory,
    like ``BucketedRunner``) — at module import it would force backend
    initialisation.
    """
    if jax.default_backend() in ("cpu",):
        return ()
    return tuple(argnums)


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import (
        shard_map as _experimental_shard_map,
    )

    def shard_map(f=None, **kw):
        if f is None:  # decorator/partial form: shard_map(mesh=..., ...)
            return functools.partial(shard_map, **kw)
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _experimental_shard_map(f, **kw)
