"""Version-compat shims for the jax API surface.

jax promoted ``jax.experimental.shard_map.shard_map`` to ``jax.shard_map``
and renamed its ``check_rep`` kwarg to ``check_vma``; the framework targets
the new spelling everywhere. On a jax that predates the promotion this module
maps the call back onto the experimental implementation so the whole
distributed path (collectives, ring attention, VW sync passes, GBDT voting)
still runs instead of collapsing with ``AttributeError`` at import/trace time.
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import (
        shard_map as _experimental_shard_map,
    )

    def shard_map(f=None, **kw):
        if f is None:  # decorator/partial form: shard_map(mesh=..., ...)
            return functools.partial(shard_map, **kw)
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _experimental_shard_map(f, **kw)
