from .params import (  # noqa: F401
    Param,
    Params,
    HasFeaturesCol,
    HasLabelCol,
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    HasOutputCols,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    HasValidationIndicatorCol,
    HasInitScoreCol,
    HasGroupCol,
    HasSeed,
)
from .table import Table, assemble_features, feature_matrix  # noqa: F401
from .pipeline import (  # noqa: F401
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)
from .logging import (  # noqa: F401
    InstrumentationMeasures,
    StopWatch,
    SynapseMLLogging,
    retry_with_timeout,
)
