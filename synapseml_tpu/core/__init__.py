from .params import (  # noqa: F401
    Param,
    Params,
    HasFeaturesCol,
    HasLabelCol,
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    HasOutputCols,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    HasValidationIndicatorCol,
    HasInitScoreCol,
    HasGroupCol,
    HasSeed,
)
from .table import Table, assemble_features, feature_matrix  # noqa: F401
from .pipeline import (  # noqa: F401
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)
from .logging import (  # noqa: F401
    InstrumentationMeasures,
    StopWatch,
    SynapseMLLogging,
    failure_counts,
    record_failure,
    reset_failure_counts,
    retry_with_timeout,
)
from .gossip import (  # noqa: F401
    ConsistentHashRing,
    GossipEntry,
    GossipState,
)
from .qos import (  # noqa: F401
    BudgetLeaseLedger,
    QoSClass,
    QoSController,
    WeightedFairQueue,
)
from .resilience import (  # noqa: F401
    DEADLINE_HEADER,
    CircuitBreaker,
    Deadline,
    Membership,
    RetryBudget,
    default_retry_budget,
)
from .checkpoint import (  # noqa: F401
    Checkpoint,
    CheckpointError,
    CheckpointStore,
    NonFiniteGuard,
    NonFiniteLossError,
    PreemptionError,
    atomic_write_bytes,
    atomic_write_text,
    preemption_point,
)
