"""Persistent XLA executable cache (jax compilation cache) enablement.

One shared entry point for bench.py and the test harness: this jax build
ignores the JAX_COMPILATION_CACHE_DIR env var, so the config API is used.
Large compiles (the fused training scan is ~40s through a remote-compile
tunnel) are paid once per configuration, not once per process.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None,
                         min_compile_secs: float = 1.0) -> str:
    import jax

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    return cache_dir
