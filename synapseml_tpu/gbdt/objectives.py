"""Boosting objectives: gradients/hessians, init scores, and eval metrics.

Parity targets: LightGBM's objective set as exposed through the reference's
``objective`` param (lightgbm/.../params/LightGBMParams.scala — binary,
multiclass, multiclassova, regression, regression_l1, huber, fair, poisson,
quantile, mape, gamma, tweedie, lambdarank) and the custom-objective hook
(``FObjTrait``, lightgbm/.../params/FObjParam.scala; applied per iteration at
TrainUtils.scala:80-86). All are pure jax functions of (score, label, weight)
so they fuse into the boosting step.

Scores are raw margins; ``init_score`` implements boost_from_average.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Objective(NamedTuple):
    name: str
    num_model_per_iteration: int                    # K for multiclass, else 1
    grad_hess: Callable                             # (score, label, weight) -> (g, h)
    init_score: Callable                            # (label, weight) -> scalar or (K,)
    transform: Callable                             # raw score -> prediction space


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def binary_objective(sigmoid: float = 1.0) -> Objective:
    s = sigmoid

    def gh(score, y, w):
        p = _sigmoid(s * score)
        g = s * (p - y)
        h = s * s * p * (1.0 - p)
        return g * w, jnp.maximum(h * w, 1e-16)

    def init(y, w):
        p = jnp.clip(jnp.average(y, weights=w), 1e-12, 1 - 1e-12)
        return jnp.log(p / (1 - p)) / s

    return Objective("binary", 1, gh, init, lambda sc: _sigmoid(s * sc))


def multiclass_objective(num_class: int) -> Objective:
    def gh(score, y, w):  # score (N, K), y (N,) int
        p = jax.nn.softmax(score, axis=-1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class)
        g = (p - onehot) * w[:, None]
        h = 2.0 * p * (1.0 - p) * w[:, None]   # LightGBM's factor-2 softmax hessian
        return g, jnp.maximum(h, 1e-16)

    def init(y, w):
        counts = jnp.zeros(num_class).at[y.astype(jnp.int32)].add(w)
        # all-zero weights would make this 0/0 -> NaN before the clip
        p = jnp.clip(counts / jnp.maximum(counts.sum(), 1e-12),
                     1e-12, 1.0)
        return jnp.log(p)

    return Objective("multiclass", num_class, gh, init,
                     lambda sc: jax.nn.softmax(sc, axis=-1))


def multiclassova_objective(num_class: int, sigmoid: float = 1.0) -> Objective:
    s = sigmoid

    def gh(score, y, w):
        onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class)
        p = _sigmoid(s * score)
        g = s * (p - onehot) * w[:, None]
        h = s * s * p * (1 - p) * w[:, None]
        return g, jnp.maximum(h, 1e-16)

    def init(y, w):
        counts = jnp.zeros(num_class).at[y.astype(jnp.int32)].add(w)
        # all-zero weights would make this 0/0 -> NaN before the clip
        p = jnp.clip(counts / jnp.maximum(counts.sum(), 1e-12),
                     1e-12, 1 - 1e-12)
        return jnp.log(p / (1 - p)) / s

    def tf(sc):
        # LightGBM MulticlassOVA::ConvertOutput: per-class sigmoid, NO
        # normalization (each class is an independent binary problem)
        return _sigmoid(s * sc)

    return Objective("multiclassova", num_class, gh, init, tf)


def regression_objective() -> Objective:
    def gh(score, y, w):
        return (score - y) * w, w

    return Objective("regression", 1, gh,
                     lambda y, w: jnp.average(y, weights=w), lambda sc: sc)


def _weighted_quantile(y, w, alpha):
    """Interpolating weighted quantile. Exactly matches ``jnp.quantile``'s
    linear interpolation when weights are uniform, and rows with w == 0
    (bagged-out / mesh padding) are excluded exactly — the mesh path pads
    labels with zeros before init_score sees them. LightGBM's
    WeightedPercentileFun interpolates the same way."""
    pos = w > 0
    m = jnp.maximum(pos.sum(), 1)
    yy = jnp.where(pos, y, jnp.inf)          # zero-weight rows sort last
    order = jnp.argsort(yy)
    ys = yy[order]
    ws = w[order]
    before = jnp.cumsum(ws) - ws             # weight strictly before each row
    total = jnp.sum(ws)
    r = alpha * (total - total / m)          # uniform w: alpha * (n - 1)
    j = jnp.clip(jnp.searchsorted(before, r, side="right") - 1,
                 0, y.shape[0] - 1)
    jn = jnp.clip(j + 1, 0, y.shape[0] - 1)
    frac = jnp.clip((r - before[j]) / jnp.maximum(ws[j], 1e-38), 0.0, 1.0)
    # interpolate toward ys[jn] only when it is a real row: when the quantile
    # lands inside the LAST positive-weight row's span (frac > 0 with jn on
    # the zero-weight inf tail), the partner must collapse to ys[j] or the
    # init score becomes inf and poisons training
    nxt = jnp.where(jnp.isfinite(ys[jn]) & (frac > 0), ys[jn], ys[j])
    return ys[j] + frac * (nxt - ys[j])


def regression_l1_objective() -> Objective:
    def gh(score, y, w):
        return jnp.sign(score - y) * w, w  # LightGBM uses hessian=weight for L1

    def init(y, w):
        return _weighted_quantile(y, w, 0.5)

    return Objective("regression_l1", 1, gh, init, lambda sc: sc)


def huber_objective(alpha: float = 0.9) -> Objective:
    def gh(score, y, w):
        d = score - y
        g = jnp.where(jnp.abs(d) <= alpha, d, alpha * jnp.sign(d))
        return g * w, w

    return Objective("huber", 1, gh, lambda y, w: jnp.average(y, weights=w), lambda sc: sc)


def fair_objective(c: float = 1.0) -> Objective:
    def gh(score, y, w):
        d = score - y
        g = c * d / (jnp.abs(d) + c)
        h = c * c / (jnp.abs(d) + c) ** 2
        return g * w, jnp.maximum(h * w, 1e-16)

    return Objective("fair", 1, gh, lambda y, w: jnp.average(y, weights=w), lambda sc: sc)


def poisson_objective(max_delta_step: float = 0.7) -> Objective:
    def gh(score, y, w):
        ex = jnp.exp(score)
        return (ex - y) * w, jnp.maximum(ex * jnp.exp(max_delta_step) * w, 1e-16)

    def init(y, w):
        return jnp.log(jnp.maximum(jnp.average(y, weights=w), 1e-12))

    return Objective("poisson", 1, gh, init, lambda sc: jnp.exp(sc))


def quantile_objective(alpha: float = 0.5) -> Objective:
    def gh(score, y, w):
        d = score - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return g * w, w

    def init(y, w):
        return _weighted_quantile(y, w, alpha)

    return Objective("quantile", 1, gh, init, lambda sc: sc)


def mape_objective() -> Objective:
    def gh(score, y, w):
        scale = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
        return jnp.sign(score - y) * scale * w, scale * w

    def init(y, w):
        return _weighted_quantile(y, w, 0.5)

    return Objective("mape", 1, gh, init, lambda sc: sc)


def cross_entropy_objective() -> Objective:
    """LightGBM cross_entropy (aka xentropy): binary log-loss with
    CONTINUOUS labels in [0, 1] (soft targets). Identical math to
    binary_objective at sigmoid=1 (which never assumes y in {0,1});
    xentropy has no sigmoid parameter."""
    return binary_objective(1.0)._replace(name="cross_entropy")


def gamma_objective() -> Objective:
    def gh(score, y, w):
        ey = y * jnp.exp(-score)
        return (1.0 - ey) * w, jnp.maximum(ey * w, 1e-16)

    def init(y, w):
        return jnp.log(jnp.maximum(jnp.average(y, weights=w), 1e-12))

    return Objective("gamma", 1, gh, init, lambda sc: jnp.exp(sc))


def tweedie_objective(rho: float = 1.5) -> Objective:
    def gh(score, y, w):
        a = -y * jnp.exp((1.0 - rho) * score)
        b = jnp.exp((2.0 - rho) * score)
        g = a + b
        h = a * (1.0 - rho) + b * (2.0 - rho)
        return g * w, jnp.maximum(h * w, 1e-16)

    def init(y, w):
        return jnp.log(jnp.maximum(jnp.average(y, weights=w), 1e-12))

    return Objective("tweedie", 1, gh, init, lambda sc: jnp.exp(sc))


# ---------------------------------------------------------------------------
# LambdaRank (grouped, padded-matrix formulation)
# ---------------------------------------------------------------------------

def make_grouped(labels: np.ndarray, group_sizes: np.ndarray, max_group: Optional[int] = None):
    """Host-side: rows must already be group-contiguous (the analog of the
    reference's repartition-by-group, LightGBMRanker.scala:88-116). Returns
    (group_id_per_row, padded row-index matrix (Q, Gmax) with -1 padding)."""
    sizes = np.asarray(group_sizes, np.int64)
    q = len(sizes)
    gmax = int(max_group or sizes.max())
    idx = np.full((q, gmax), -1, np.int64)
    start = 0
    for i, sz in enumerate(sizes):
        sz = min(int(sz), gmax)
        idx[i, :sz] = np.arange(start, start + sz)
        start += int(group_sizes[i])
    return idx


def _label_gain(rel, label_gain=None):
    """Relevance → gain: LightGBM's label_gain table when provided (entry i
    is the gain for label i), else the default 2^rel - 1."""
    if label_gain:
        table = jnp.asarray(label_gain, jnp.float32)
        idx = jnp.clip(rel.astype(jnp.int32), 0, len(label_gain) - 1)
        return table[idx]
    return 2.0 ** rel - 1.0


def lambdarank_objective(group_index: jnp.ndarray, sigmoid: float = 2.0,
                         truncation: int = 30,
                         label_gain: tuple = ()) -> Objective:
    """LambdaRank with NDCG weighting (LightGBM lambdarank). ``group_index`` is
    the (Q, Gmax) padded row-index matrix from :func:`make_grouped`. Gradients
    computed per group over the (Gmax, Gmax) pair matrix — MXU/VPU-friendly."""
    gi = jnp.asarray(group_index)

    def gh(score, y, w):
        pad = gi < 0
        safe = jnp.maximum(gi, 0)
        s = jnp.where(pad, -jnp.inf, score[safe])          # (Q, G)
        rel = jnp.where(pad, 0.0, y[safe])
        # pad slots must contribute ZERO gain regardless of the table's
        # entry for label 0 (ragged groups would otherwise corrupt idcg)
        gain = jnp.where(pad, 0.0, _label_gain(rel, label_gain))

        # rank by current score (descending)
        order = jnp.argsort(-s, axis=1)
        ranks = jnp.argsort(order, axis=1)                 # rank position of each item
        disc = 1.0 / jnp.log2(ranks + 2.0)
        disc = jnp.where(ranks < truncation, disc, 0.0)

        # ideal DCG for normalization
        ideal = jnp.sort(gain, axis=1)[:, ::-1]
        k = jnp.arange(gain.shape[1])
        ideal_disc = jnp.where(k < truncation, 1.0 / jnp.log2(k + 2.0), 0.0)
        idcg = (ideal * ideal_disc[None, :]).sum(axis=1)
        inv_idcg = jnp.where(idcg > 0, 1.0 / idcg, 0.0)

        ds = s[:, :, None] - s[:, None, :]                 # (Q, G, G)
        rho = jax.nn.sigmoid(-sigmoid * ds)                # 1/(1+e^{sigma*ds})
        delta = jnp.abs((gain[:, :, None] - gain[:, None, :])
                        * (disc[:, :, None] - disc[:, None, :])) * inv_idcg[:, None, None]
        better = rel[:, :, None] > rel[:, None, :]
        valid = better & ~pad[:, :, None] & ~pad[:, None, :]
        lam = jnp.where(valid, -sigmoid * rho * delta, 0.0)
        hs = jnp.where(valid, sigmoid * sigmoid * rho * (1 - rho) * delta, 0.0)

        g_item = lam.sum(axis=2) - lam.sum(axis=1)         # winners pulled up, losers down
        h_item = hs.sum(axis=2) + hs.sum(axis=1)

        g = jnp.zeros_like(score).at[safe.reshape(-1)].add(
            jnp.where(pad, 0.0, g_item).reshape(-1))
        h = jnp.zeros_like(score).at[safe.reshape(-1)].add(
            jnp.where(pad, 0.0, h_item).reshape(-1))
        return g * w, jnp.maximum(h * w, 1e-16)

    return Objective("lambdarank", 1, gh, lambda y, w: jnp.float32(0.0), lambda sc: sc)


# ---------------------------------------------------------------------------

_FACTORIES = {
    "binary": lambda p: binary_objective(p.get("sigmoid", 1.0)),
    "multiclass": lambda p: multiclass_objective(p["num_class"]),
    "softmax": lambda p: multiclass_objective(p["num_class"]),
    "multiclassova": lambda p: multiclassova_objective(p["num_class"], p.get("sigmoid", 1.0)),
    "regression": lambda p: regression_objective(),
    "mean_squared_error": lambda p: regression_objective(),
    "l2": lambda p: regression_objective(),
    "regression_l1": lambda p: regression_l1_objective(),
    "l1": lambda p: regression_l1_objective(),
    "mae": lambda p: regression_l1_objective(),
    "huber": lambda p: huber_objective(p.get("alpha", 0.9)),
    "fair": lambda p: fair_objective(p.get("fair_c", 1.0)),
    "poisson": lambda p: poisson_objective(p.get("poisson_max_delta_step", 0.7)),
    "quantile": lambda p: quantile_objective(p.get("alpha", 0.5)),
    "mape": lambda p: mape_objective(),
    "gamma": lambda p: gamma_objective(),
    "cross_entropy": lambda p: cross_entropy_objective(),
    "xentropy": lambda p: cross_entropy_objective(),
    "tweedie": lambda p: tweedie_objective(p.get("tweedie_variance_power", 1.5)),
}


def get_objective(name: str, **params) -> Objective:
    if name not in _FACTORIES:
        raise ValueError(f"unknown objective {name!r}; known: {sorted(_FACTORIES)} + lambdarank")
    return _FACTORIES[name](params)


# ---------------------------------------------------------------------------
# Metrics (eval + early stopping; reference extracts native eval metrics at
# TrainUtils.scala:137-151 — here they are jnp reductions)
# ---------------------------------------------------------------------------

def auc(y_true, y_score, sample_weight=None):
    """Weighted ROC AUC with exact tie handling: each positive counts the
    negatives scored strictly below it plus HALF the negatives it ties with
    (the trapezoid rule — what LightGBM/sklearn compute). Ties matter on
    discrete features and loaded constant-leaf models."""
    y_true = jnp.asarray(y_true, jnp.float32)
    y_score = jnp.asarray(y_score, jnp.float32)
    w = (jnp.ones_like(y_true) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    order = jnp.argsort(y_score)
    ys, ws, ss = y_true[order], w[order], y_score[order]
    wneg = jnp.where(ys == 0, ws, 0.0)
    # padded cumulative negatives: cum[i] = neg weight in rows < i
    cum = jnp.concatenate([jnp.zeros(1), jnp.cumsum(wneg)])
    left = jnp.searchsorted(ss, ss, side="left")    # first index of my tie
    right = jnp.searchsorted(ss, ss, side="right")  # one past my tie group
    neg_below = cum[left]
    tie_neg = cum[right] - cum[left]
    auc_sum = jnp.sum(jnp.where(ys > 0, ws * (neg_below + 0.5 * tie_neg),
                                0.0))
    pos = jnp.sum(jnp.where(ys > 0, ws, 0.0))
    neg = jnp.sum(wneg)
    return auc_sum / jnp.maximum(pos * neg, 1e-12)


def binary_logloss(y_true, p, eps=1e-15, weight=None):
    p = jnp.clip(p, eps, 1 - eps)
    return _wmean(-(y_true * jnp.log(p) + (1 - y_true) * jnp.log1p(-p)),
                  weight)


def multi_logloss(y_true, p, eps=1e-15, weight=None):
    p = jnp.clip(p, eps, 1.0)
    return _wmean(-jnp.log(jnp.take_along_axis(
        p, y_true.astype(jnp.int32)[:, None], 1)[:, 0]), weight)


def rmse(y_true, pred, weight=None):
    return jnp.sqrt(_wmean((y_true - pred) ** 2, weight))


def mae(y_true, pred, weight=None):
    return _wmean(jnp.abs(y_true - pred), weight)


def ndcg_at_k(labels, scores, group_index, k: int = 5, label_gain: tuple = ()):
    """Mean NDCG@k over groups; group_index as in :func:`make_grouped`."""
    gi = jnp.asarray(group_index)
    pad = gi < 0
    safe = jnp.maximum(gi, 0)
    s = jnp.where(pad, -jnp.inf, scores[safe])
    rel = jnp.where(pad, 0.0, labels[safe])
    gain = jnp.where(pad, 0.0, _label_gain(rel, label_gain))
    order = jnp.argsort(-s, axis=1)
    ranks = jnp.argsort(order, axis=1)
    disc = jnp.where(ranks < k, 1.0 / jnp.log2(ranks + 2.0), 0.0)
    dcg = (gain * disc).sum(axis=1)
    ideal = jnp.sort(gain, axis=1)[:, ::-1]
    j = jnp.arange(gain.shape[1])
    idisc = jnp.where(j < k, 1.0 / jnp.log2(j + 2.0), 0.0)
    idcg = (ideal * idisc[None, :]).sum(axis=1)
    return jnp.mean(jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 1.0))


def _wmean(v, w=None):
    """Weighted mean — every LightGBM metric weights per-row losses by the
    validation sample weights when provided."""
    if w is None:
        return jnp.mean(v)
    w = jnp.asarray(w, jnp.float32)
    return jnp.sum(v * w) / jnp.maximum(jnp.sum(w), 1e-12)


def poisson_metric(y, pred, w=None):
    """LightGBM PoissonMetric: pred - y*log(pred) (psi const dropped)."""
    p = jnp.maximum(pred, 1e-15)
    return _wmean(p - y * jnp.log(p), w)


def gamma_metric(y, pred, w=None):
    p = jnp.maximum(pred, 1e-15)
    return _wmean(y / p + jnp.log(p), w)


def gamma_deviance_metric(y, pred, w=None):
    p = jnp.maximum(pred, 1e-15)
    return 2.0 * _wmean(jnp.log(p / jnp.maximum(y, 1e-15)) + y / p - 1.0, w)


def tweedie_metric(y, pred, rho: float = 1.5, w=None):
    p = jnp.maximum(pred, 1e-15)
    return _wmean(-y * p ** (1.0 - rho) / (1.0 - rho)
                  + p ** (2.0 - rho) / (2.0 - rho), w)


def quantile_metric(y, pred, alpha: float = 0.9, w=None):
    d = y - pred
    return _wmean(jnp.maximum(alpha * d, (alpha - 1.0) * d), w)


def huber_metric(y, pred, alpha: float = 0.9, w=None):
    d = y - pred
    return _wmean(jnp.where(jnp.abs(d) <= alpha, 0.5 * d * d,
                            alpha * (jnp.abs(d) - 0.5 * alpha)), w)


def fair_metric(y, pred, c: float = 1.0, w=None):
    ad = jnp.abs(y - pred)
    return _wmean(c * c * (ad / c - jnp.log1p(ad / c)), w)





def metric_kwargs(cfg) -> dict:
    """The hyper-parameterized metrics' inputs, from one place so the fused
    and host eval paths can never drift."""
    if cfg is None:
        return {}
    return {"alpha": cfg.alpha, "fair_c": cfg.fair_c,
            "tweedie_variance_power": cfg.tweedie_variance_power}


def map_at_k(labels, scores, group_index, k: int = 5):
    """Mean average precision @k over groups (LightGBM map metric: binary
    relevance label > 0, AP normalized by min(#positives, k));
    ``group_index`` as in :func:`make_grouped`."""
    gi = jnp.asarray(group_index)
    pad = gi < 0
    safe = jnp.maximum(gi, 0)
    s = jnp.where(pad, -jnp.inf, scores[safe])
    rel = (jnp.where(pad, 0.0, labels[safe]) > 0).astype(jnp.float32)
    order = jnp.argsort(-s, axis=1)
    rel_sorted = jnp.take_along_axis(rel, order, axis=1)
    pos = jnp.arange(rel.shape[1], dtype=jnp.float32)[None, :]
    cum_hits = jnp.cumsum(rel_sorted, axis=1)
    prec = cum_hits / (pos + 1.0)
    in_k = (pos < k).astype(jnp.float32)
    ap_sum = (prec * rel_sorted * in_k).sum(axis=1)
    npos = rel.sum(axis=1)
    denom = jnp.minimum(npos, float(k))
    ap = jnp.where(denom > 0, ap_sum / jnp.maximum(denom, 1.0), 1.0)
    return jnp.mean(ap)


# Every entry honors kw["weight"] (validation sample weights) the way the
# corresponding LightGBM metric does.
METRICS = {
    "auc": lambda y, pred, **kw: auc(y, pred, kw.get("weight")),
    "binary_logloss": lambda y, pred, **kw: binary_logloss(
        y, pred, weight=kw.get("weight")),
    "binary_error": lambda y, pred, **kw: _wmean(
        ((pred > 0.5) != (y > 0.5)).astype(jnp.float32), kw.get("weight")),
    "multi_logloss": lambda y, pred, **kw: multi_logloss(
        y, pred, weight=kw.get("weight")),
    "multi_error": lambda y, pred, **kw: _wmean(
        (jnp.argmax(pred, -1) != y).astype(jnp.float32), kw.get("weight")),
    "rmse": lambda y, pred, **kw: rmse(y, pred, weight=kw.get("weight")),
    "l2": lambda y, pred, **kw: _wmean((y - pred) ** 2, kw.get("weight")),
    "mse": lambda y, pred, **kw: _wmean((y - pred) ** 2, kw.get("weight")),
    "mae": lambda y, pred, **kw: mae(y, pred, weight=kw.get("weight")),
    "l1": lambda y, pred, **kw: _wmean(jnp.abs(y - pred), kw.get("weight")),
    # LightGBM MAPEMetric: |y - pred| / max(1, |y|)
    "mape": lambda y, pred, **kw: _wmean(
        jnp.abs(y - pred) / jnp.maximum(1.0, jnp.abs(y)), kw.get("weight")),
    # loss-metrics of the exp-family / robust objectives (pred is in the
    # RESPONSE space — the exp link is already applied)
    "poisson": lambda y, pred, **kw: poisson_metric(y, pred,
                                                    w=kw.get("weight")),
    "gamma": lambda y, pred, **kw: gamma_metric(y, pred,
                                                w=kw.get("weight")),
    "gamma_deviance": lambda y, pred, **kw: gamma_deviance_metric(
        y, pred, w=kw.get("weight")),
    "tweedie": lambda y, pred, **kw: tweedie_metric(
        y, pred, kw.get("tweedie_variance_power", 1.5),
        w=kw.get("weight")),
    "quantile": lambda y, pred, **kw: quantile_metric(
        y, pred, kw.get("alpha", 0.9), w=kw.get("weight")),
    "huber": lambda y, pred, **kw: huber_metric(
        y, pred, kw.get("alpha", 0.9), w=kw.get("weight")),
    # cross_entropy metric: soft-label log loss == binary_logloss (it
    # never assumes y in {0,1})
    "cross_entropy": lambda y, pred, **kw: binary_logloss(
        y, pred, weight=kw.get("weight")),
    "xentropy": lambda y, pred, **kw: binary_logloss(
        y, pred, weight=kw.get("weight")),
    "fair": lambda y, pred, **kw: fair_metric(
        y, pred, kw.get("fair_c", 1.0), w=kw.get("weight")),
}

HIGHER_IS_BETTER = {"auc", "ndcg", "map"}
