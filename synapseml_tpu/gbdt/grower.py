"""Leaf-wise histogram tree grower — partitioned rows + MXU histogram kernel.

TPU-native redesign of the LightGBM serial/data-parallel tree learner the
reference drives through LGBM_BoosterUpdateOneIter (reference call stack:
booster/LightGBMBooster.scala:355-392 → C++ ConstructHistograms / FindBestSplit /
Split loop; SURVEY.md §3.1 "the hot loop"). v2 design, shaped by TPU costs:

  * **Row partitioning** (LightGBM's DataPartition): rows live in a position
    array kept sorted by leaf, each leaf owning a contiguous range. A split
    stably partitions only its leaf's range (bucketed static sizes via
    ``lax.switch`` — XLA needs static shapes, so ranges are processed at the
    smallest power-of-two bucket that covers them, masked to the real range).
  * **Histogram subtraction** (LightGBM's parent-minus-sibling): per split,
    only the SMALLER child's histogram is built (ops/hist_kernel.py — two-level
    one-hot matmuls on the MXU); the sibling is parent − child from the
    per-leaf histogram cache. Total histogrammed rows per tree drop from
    O(num_leaves·N) to O(N·log(num_leaves)/2).
  * The ENTIRE growth loop is one ``lax.fori_loop`` with static shapes —
    exactly ``num_leaves - 1`` iterations; when no leaf has a valid split the
    remaining iterations no-op.
  * Leaf numbering matches LightGBM's Tree::Split: splitting leaf ``l`` at step
    ``i`` creates internal node ``i``; the left child keeps leaf id ``l`` and
    the right child becomes leaf ``i + 1``. Child pointers use ``~leaf_index``,
    so the arrays serialize directly into the LightGBM model-string format
    (gbdt/model_io.py).
  * Categorical splits: bins sorted by grad/(hess + cat_smooth), prefix scan,
    chosen prefix encoded as a bitset — LightGBM's many-vs-many algorithm.
  * Monotone constraints ("basic" mode): violating splits masked.
  * **Learned missing direction**: features with NaN carry a dedicated NaN bin
    (ops/quantize.py); every candidate threshold is scored with the NaN bin's
    totals routed left AND right, and the winning direction is recorded as the
    per-split ``default_left`` bit (LightGBM missing_type=NaN semantics).

Distributed data-parallel: run under ``shard_map`` with rows sharded on the
data axis and ``axis_name`` set — each device partitions its own rows, builds
local child histograms, and ONE ``lax.psum`` of the (F, B, 3) histogram per
split replaces LightGBM's socket-ring reduce-scatter (NetworkManager.scala).
Split decisions are taken from the summed histogram, so they are bitwise
identical on every device (uniform control flow by construction).
"""

from __future__ import annotations

import math
import os
import sys
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.hist_kernel import (child_histogram, default_chunk,
                               features_padded, pad_bins, range_histogram,
                               segmented_histograms_available)

BITS = 32  # bitset word width for categorical splits
def _chunk() -> int:
    """Kernel row chunk; row counts pad to a multiple of this so the Pallas
    grid divides evenly. Resolved lazily at trace time (after backend init)
    so the SYNAPSEML_TPU_HIST_CHUNK env / docs/tuned_defaults.json knob
    takes effect without re-importing the module."""
    return default_chunk()


class GrowerConfig(NamedTuple):
    """Static (compile-time) grower configuration."""

    num_leaves: int = 31
    num_bins: int = 255
    max_depth: int = -1          # <=0: unlimited (bounded by num_leaves anyway)
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    learning_rate: float = 0.1
    max_delta_step: float = 0.0
    cat_smooth: float = 10.0
    cat_l2: float = 10.0         # extra L2 applied to categorical split gains
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4   # <= this many categories: one-vs-rest splits
    min_data_per_group: int = 100  # thin categorical groups excluded
    feature_fraction_bynode: float = 1.0  # per-NODE feature sampling
    has_categorical: bool = False  # static: traces out the categorical path
    # row-partition primitive: "sort" = stable argsort of the 4-way key
    # (XLA bitonic sort, O(n log^2 n) compare-exchange stages); "scan" =
    # cumsum + vectorized binary search for the inverse permutation
    # (O(n log n) gathers — wins when sort stages dominate the split step)
    partition_impl: str = "sort"
    # growth policy: "leafwise" (LightGBM-parity best-first; default) or
    # "depthwise" (level-batched opt-in — ~depth heavy steps per tree via
    # ONE multi-leaf histogram pass per level; trees differ from LightGBM's
    # leaf-wise order, quality gated in tests; grower_depthwise.py)
    growth_policy: str = "leafwise"
    # segmented histogram kernel (scalar-prefetch dynamic block offsets —
    # no dynamic_slice copy or pre-kernel mask multiply per split):
    # None = auto (TPU + selftest green), True/False forces (perf_tune A/B)
    use_segmented: Optional[bool] = None
    # row layout strategy: "partition" keeps rows physically sorted by leaf
    # (smaller-child histograms scan only the child's contiguous range);
    # "masked" never moves rows — each split histograms the full row set with
    # the child mask folded into the kernel's value factor; "gather" keeps
    # only the (Np,) pos permutation sorted by leaf and gathers the smaller
    # child's rows through it right before histogramming (one i32 permute
    # per split instead of the full (FP, size) two-way data movement).
    # Masked trades ~12x more rows through the MXU kernel for ZERO
    # sort/permute work per split; which of the three wins is a measured
    # property of the chip (tools/perf_tune.py)
    row_layout: str = "partition"
    # histogram allreduce wire precision ladder: "f32" (default), "bf16"
    # (2/3 wire bytes), or "int8" (blockwise-quantized allreduce — EQuARX,
    # arXiv:2506.17615 — ~2 bytes/elem effective incl. per-block scales).
    # grad/hess are ALREADY bf16-rounded before histogram accumulation
    # (ops/hist_kernel.py contract), so the lossy rungs only round the
    # SUMS once; the COUNT channel always rides an exact wire (it gates
    # min_data_in_leaf). The int8 result is dequantized ONCE to f32, so
    # the parent-minus-sibling histogram subtraction downstream never
    # compounds quantization error. Multi-host DCN is the payoff regime;
    # f32 by default for bit-parity.
    hist_allreduce_dtype: str = "f32"
    # cross-shard histogram reduction shape: "allreduce" (every device gets
    # the full (FP, B, 3) histogram — LightGBM data_parallel's logical
    # result) or "scatter" (owned-feature reduce-scatter: each of
    # ``feature_shards`` devices keeps only its FP/world slice and the
    # per-leaf best splits are exchanged as tiny (world, 5) candidate
    # rows — LightGBM data_parallel's ACTUAL wire pattern, ~halving
    # collective bytes). "scatter" requires partition layout + leafwise
    # growth + numeric-only features + FP % feature_shards == 0.
    hist_reduce: str = "allreduce"
    feature_shards: int = 1      # static world size for hist_reduce="scatter"


def resolve_wire_dtype(cfg, mesh, n_rows, nfeat):
    """Resolve ``hist_allreduce_dtype='auto'`` to a concrete ladder rung.

    Routed through ``core.perfmodel``: the analytic prior prices each rung's
    per-tree collective seconds from the cached link-bandwidth probe, but —
    because the lossy rungs trade accuracy, not just time — only a *measured*
    match (recorded ``gbdt_wire_dtype`` rows for a log-nearby workload on
    this platform) may move the choice off the conservative f32 fallback.
    Explicit ``hist_allreduce_dtype="f32"|"bf16"|"int8"`` bypasses all of
    this (the caller never invokes the resolver). Returns
    ``(wire_dtype, perfmodel.Decision)``.
    """
    from ..core import perfmodel

    if mesh is None:
        return "f32", perfmodel.Decision(
            "gbdt_wire_dtype", "f32", "f32", None, 0.0, True, "f32",
            "fallback", [], {"workers": 1.0})
    workers = 1
    try:
        from ..parallel.mesh import DATA_AXIS as _DA
        workers = int(dict(mesh.shape).get(_DA, 1))
    except Exception:  # mesh without a data axis
        pass
    link = perfmodel.link_bandwidth(mesh) if workers > 1 else None
    return perfmodel.suggest_wire_dtype(
        n_rows=float(n_rows), nfeat=float(nfeat), workers=float(workers),
        max_bin=float(cfg.max_bin), num_leaves=float(cfg.num_leaves),
        link_bps=link)


class TreeArrays(NamedTuple):
    """One grown tree in structure-of-arrays form (serializes to the LightGBM
    model-string fields of the same names — gbdt/model_io.py)."""

    split_feature: jnp.ndarray   # (L-1,) i32
    split_bin: jnp.ndarray       # (L-1,) i32 — bin-space threshold (left if bin <= t)
    split_gain: jnp.ndarray      # (L-1,) f32
    split_type: jnp.ndarray      # (L-1,) i32 — 0 numeric, 1 categorical
    default_left: jnp.ndarray    # (L-1,) bool — learned NaN direction
    cat_bitset: jnp.ndarray      # (L-1, ceil(B/32)) u32 — membership → left
    left_child: jnp.ndarray      # (L-1,) i32 — >=0 internal node, ~leaf otherwise
    right_child: jnp.ndarray     # (L-1,) i32
    internal_value: jnp.ndarray  # (L-1,) f32 (shrunk output the node would emit)
    internal_count: jnp.ndarray  # (L-1,) i32
    leaf_value: jnp.ndarray      # (L,) f32 (shrinkage applied, LightGBM-style)
    leaf_weight: jnp.ndarray     # (L,) f32 (sum of hessians)
    leaf_count: jnp.ndarray      # (L,) i32
    num_splits: jnp.ndarray      # () i32


def _threshold_l1(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_objective(g, h, l1, l2):
    """LightGBM GetLeafSplitGain: ThresholdL1(G)^2 / (H + l2)."""
    gt = _threshold_l1(g, l1)
    return gt * gt / (h + l2)


def _leaf_output(g, h, cfg: GrowerConfig):
    out = -_threshold_l1(g, cfg.lambda_l1) / (h + cfg.lambda_l2)
    if cfg.max_delta_step > 0:
        out = jnp.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


def _bucket_sizes(np_rows: int) -> list:
    """Static power-of-two bucket sizes (multiples of _chunk()) covering any
    range length up to the padded row count."""
    sizes = []
    s = min(2 * _chunk(), np_rows)
    while s < np_rows:
        sizes.append(s)
        s *= 2
    sizes.append(np_rows)
    return sizes


def _witness_observe(site, tree, expect=None):
    # dtype-witness probe (testing/dtypewitness.py): inert unless the
    # witness module is loaded — sys.modules lookup keeps product imports
    # free of the testing package
    w = sys.modules.get("synapseml_tpu.testing.dtypewitness")
    if w is not None and w.active():
        w.observe(site, tree, expect)


def _maybe_psum(x, axis_name, wire_dtype: str = "f32"):
    """Cross-shard histogram allreduce; ``wire_dtype='bf16'`` ships the
    grad/hess channels at half width (their per-row values are bf16-rounded
    already — ops/hist_kernel.py contract) while the COUNT channel stays
    exact f32: shard count partials are exact integers feeding the
    min_data_in_leaf gates, and bf16 would round them to multiples of 512
    at realistic shard sizes. Net wire bytes: 2/3 of full width."""
    if axis_name is None:
        return x
    if wire_dtype == "bf16":
        gh = lax.psum(x[..., :2].astype(jnp.bfloat16),
                      axis_name).astype(x.dtype)
        # exact f32 totals side wire (1/B of the payload), same as the
        # int8 rung: leaf G/H totals and parent gain terms must not carry
        # bf16 rounding accumulated over the whole grid
        gh = _pin_totals(gh, lax.psum(x[..., :2].sum(axis=-2), axis_name))
        cnt = lax.psum(x[..., 2:], axis_name)
        # contract: pinned totals and the count channel leave on exact f32
        _witness_observe("gbdt.wire.hist", gh, expect="float32")
        _witness_observe("gbdt.wire.count", cnt, expect="float32")
        return jnp.concatenate([gh, cnt], axis=-1)
    if wire_dtype == "int8":
        from ..parallel.collectives import allreduce_sum_quantized

        # channel-major so quantization blocks never mix grad magnitudes
        # with hess magnitudes (per-block max-abs scales stay tight)
        gh = jnp.moveaxis(x[..., :2], -1, 0)
        gh = allreduce_sum_quantized(gh, axis_name).astype(x.dtype)
        gh = jnp.moveaxis(gh, 0, -1)
        gh = _pin_totals(gh, lax.psum(x[..., :2].sum(axis=-2), axis_name))
        cnt = lax.psum(x[..., 2:], axis_name)
        _witness_observe("gbdt.wire.hist", gh, expect="float32")
        _witness_observe("gbdt.wire.count", cnt, expect="float32")
        return jnp.concatenate([gh, cnt], axis=-1)
    return lax.psum(x, axis_name)


def _pin_totals(gh, tot):
    """Pin each feature-channel row of a quantized-wire histogram to its
    exactly-reduced total (a (..., FP, 2) f32 side wire, 1/B of the payload):
    the residual is redistributed across bins proportional to |bin|, so empty
    bins stay exactly zero and the leaf G/H totals the grower reads off the
    histogram (leaf values, parent terms of every gain) carry no quantization
    error — only WITHIN-leaf split placement sees the int8 grid."""
    absg = jnp.abs(gh)
    mass = absg.sum(axis=-2, keepdims=True)
    err = (tot - gh.sum(axis=-2))[..., None, :]
    return gh + err * absg / jnp.where(mass > 0, mass, 1.0)


def _hist_reduce_scatter(x, axis_name, wire_dtype: str = "f32"):
    """Owned-feature histogram reduction: (FP, B, 3) local partials →
    fully-summed (FP/world, B, 3) slice owned by this device (reduce-scatter
    over the leading feature axis — LightGBM data_parallel's actual wire
    pattern, ~half the bytes of a full allreduce). The caller slices every
    per-feature parameter at rank*FPo and exchanges tiny per-leaf best-split
    candidates to keep split decisions uniform across devices."""
    if axis_name is None:
        return x
    scatter = partial(lax.psum_scatter, axis_name=axis_name,
                      scatter_dimension=0, tiled=True)
    if wire_dtype == "bf16":
        gh = scatter(x[..., :2].astype(jnp.bfloat16)).astype(x.dtype)
        # pin owned-slice totals over an exact f32 side wire, mirroring
        # the int8 rung: totals feed leaf values and parent gain terms
        gh = _pin_totals(gh, scatter(x[..., :2].sum(axis=1)))
    elif wire_dtype == "int8":
        from ..parallel.collectives import reduce_scatter_sum_quantized

        B = x.shape[1]
        # (FP, 2, B): channel-major within each feature so quantization
        # blocks never mix grad magnitudes with hess magnitudes
        ghT = jnp.swapaxes(x[..., :2], 1, 2)
        ghT = reduce_scatter_sum_quantized(ghT, axis_name,
                                           block=math.gcd(256, B))
        gh = jnp.swapaxes(ghT, 1, 2).astype(x.dtype)
        gh = _pin_totals(gh, scatter(x[..., :2].sum(axis=1)))
    else:
        gh = scatter(x[..., :2])
    cnt = scatter(x[..., 2:])    # counts stay on an exact wire
    _witness_observe("gbdt.wire.scatter_hist", gh, expect="float32")
    _witness_observe("gbdt.wire.scatter_count", cnt, expect="float32")
    return jnp.concatenate([gh, cnt], axis=-1)


def _aligned_window(start, size: int, np_rows: int, chunk: int):
    """Chunk-aligned static window ``[cs, cs+S)`` covering any range
    ``[start, start+len)`` with ``len <= size``: ``S = min(size+chunk,
    np_rows)`` and ``cs`` rounded down to a chunk boundary.

    Unaligned minor-dim dynamic slices cost lane rotations on TPU; the
    on-chip grow_tree trace (docs/trace_summary_gbdt.md 2026-08-02) put
    slice+copy at ~37% of device time while the histogram kernel was ~2%.
    Aligned windows turn every per-split slice/update into a clean
    tile-aligned DMA, and make the XLA fallback histogram bit-identical to
    the segmented Pallas kernel's chunk grouping (ops/hist_kernel.py
    ``_range_kernel`` uses this same first-chunk formula). Callers' routing
    keys / masks already guard rows outside [start, start+len).
    ``SYNAPSEML_TPU_ALIGN_WINDOWS=0`` restores exact-size unaligned windows
    (on-chip A/B escape hatch).

    The env var is resolved at TRACE TIME, not per call: this function runs
    inside ``grow_tree``'s jit trace, so the branch taken here is baked into
    the compiled executable. Flipping the variable after a config's first
    trace has no effect on already-cached executables — set it before the
    first ``grow_tree``/``train_booster`` call of the process (as the
    cached-kernel selftests do), and expect a retrace, not a runtime switch,
    when it changes between fresh jit keys."""
    if os.environ.get("SYNAPSEML_TPU_ALIGN_WINDOWS", "1") == "0":
        return jnp.minimum(start, np_rows - size), size
    S = min(size + chunk, np_rows)
    cs0 = jnp.minimum(start, np_rows - S)
    return (cs0 // chunk) * chunk, S


def _stable_partition_src(key: jnp.ndarray, impl: str) -> jnp.ndarray:
    """Source indices of the stable partition of ``key`` (values in
    {-1, 0, 1, 2}) — identical to ``jnp.argsort(key, stable=True)``.

    ``impl='scan'`` computes the inverse permutation directly: per-category
    cumulative counts give each output slot's rank within its category, and a
    vectorized binary search finds the rank-th member — O(n log n) gathers
    instead of the bitonic sort's O(n log^2 n) compare-exchange stages.
    """
    if impl == "sort":
        return jnp.argsort(key, stable=True).astype(jnp.int32)
    if impl == "sort32":
        # single-operand composite sort: (key+1) in the top bits, position
        # in the low bits — ascending order IS the stable partition, and the
        # bitonic network moves one u32 instead of (key, index) pairs
        n = key.shape[0]
        if n > (1 << 29):
            return jnp.argsort(key, stable=True).astype(jnp.int32)
        shift = max(n - 1, 1).bit_length()
        comp = ((key + 1).astype(jnp.uint32) << shift) | jnp.arange(
            n, dtype=jnp.uint32)
        return (jnp.sort(comp) & jnp.uint32((1 << shift) - 1)).astype(
            jnp.int32)
    if impl == "scatter":
        # destination rank per element via 4 cumsums, then ONE unique-index
        # scatter inverts the permutation — O(n) work and no compare-exchange
        # stages at all; whether XLA's TPU scatter beats its bitonic sort is
        # a measured property of the chip (tools/perf_tune.py phase 2)
        n = key.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        dst = jnp.zeros(n, jnp.int32)
        off = jnp.int32(0)
        for v in (-1, 0, 1, 2):
            isv = key == v
            rank = jnp.cumsum(isv, dtype=jnp.int32) - 1
            dst = jnp.where(isv, off + rank, dst)
            off = off + rank[-1] + 1 if v != 2 else off
        return jnp.zeros(n, jnp.int32).at[dst].set(
            iota, unique_indices=True, mode="promise_in_bounds")
    if impl != "scan":
        raise ValueError("partition_impl must be 'sort', 'sort32', 'scan' "
                         f"or 'scatter', got {impl!r}")
    n = key.shape[0]
    j = jnp.arange(n, dtype=jnp.int32)
    cums = [jnp.cumsum(key == v, dtype=jnp.int32) for v in (-1, 0, 1, 2)]
    offs = jnp.cumsum(jnp.asarray([0] + [c[-1] for c in cums[:3]]))
    src = jnp.zeros(n, jnp.int32)
    pick = jnp.full(n, 3, jnp.int32)
    for ci in (2, 1, 0):
        pick = jnp.where(j < offs[ci + 1], ci, pick)
    for ci, c in enumerate(cums):
        rank = j - offs[ci] + 1
        s = jnp.searchsorted(c, rank, side="left").astype(jnp.int32)
        src = jnp.where(pick == ci, s, src)
    return src


# ---------------------------------------------------------------------------
# Split finding over one leaf's histogram
# ---------------------------------------------------------------------------

def _best_for_leaf(hist, feature_active, is_categorical, monotone, nan_bins,
                   cfg: GrowerConfig, l1, l2, cat_nbins=None):
    """hist (FP, B, 3) → (gain, feat, bin, default_left, count_left, order).

    ``order`` is the categorical bin ordering (FP, B) used to rebuild the
    winning bitset (None when the config has no categorical features).
    """
    FP, B, _ = hist.shape
    totals = hist[0].sum(axis=0)                       # (3,) — feature 0 spans the leaf
    G, H, C = totals[0], totals[1], totals[2]
    parent_obj = _leaf_objective(G, H, l1, l2)

    def scan_gains(cum, extraG=0.0, extraH=0.0, extraC=0.0, l2_gain=None):
        l2g = l2 if l2_gain is None else l2_gain
        # the parent term uses the SAME regularization as the children
        # (LightGBM's categorical gain_shift also carries lambda_l2 + cat_l2)
        parent = (parent_obj if l2_gain is None
                  else _leaf_objective(G, H, l1, l2g))
        GL = cum[..., 0] + extraG
        HL = cum[..., 1] + extraH
        CL = cum[..., 2] + extraC
        GR, HR, CR = G - GL, H - HL, C - CL
        gain = (_leaf_objective(GL, HL, l1, l2g)
                + _leaf_objective(GR, HR, l1, l2g) - parent)
        valid = ((CL >= cfg.min_data_in_leaf) & (CR >= cfg.min_data_in_leaf)
                 & (HL >= cfg.min_sum_hessian_in_leaf)
                 & (HR >= cfg.min_sum_hessian_in_leaf))
        mc = monotone[:, None]
        vl = -GL / (HL + l2)
        vr = -GR / (HR + l2)
        mono_ok = jnp.where(mc == 0, True,
                            jnp.where(mc > 0, vl <= vr, vl >= vr))
        return jnp.where(valid & mono_ok, gain, -jnp.inf), CL

    cum = jnp.cumsum(hist, axis=1)                     # (FP, B, 3)
    # NaN-bin totals per feature (zero when the feature has no NaN bin)
    nb = jnp.clip(nan_bins, 0, B - 1)
    nan_tot = jnp.take_along_axis(hist, nb[:, None, None].repeat(3, axis=2),
                                  axis=1)[:, 0, :]     # (FP, 3)
    has_nan = (nan_bins < B)[:, None]
    nan_tot = jnp.where(has_nan, nan_tot, 0.0)

    # default-right: NaN bin sits at num_bins-1, so cum[t] for any divider
    # t < nan_bin excludes it naturally (thresholds at/after it yield CR=0 →
    # invalid); default-left adds the NaN totals to the left side.
    gain_r, CL_r = scan_gains(cum)
    gain_l, CL_l = scan_gains(cum, nan_tot[:, None, 0], nan_tot[:, None, 1],
                              nan_tot[:, None, 2])
    use_left = has_nan & (gain_l > gain_r)
    gain_num = jnp.where(use_left, gain_l, gain_r)
    CL_num = jnp.where(use_left, CL_l, CL_r)

    order = None
    if cfg.has_categorical:
        # thin groups (minDataPerGroup) never lead a split: pushed to the end
        # of the ordering and masked out of every candidate position
        order, n_usable = _cat_order_usable(hist, cfg)
        n_usable = n_usable[:, None]
        hist_sorted = jnp.take_along_axis(hist, order[..., None], axis=1)
        cum_cat = jnp.cumsum(hist_sorted, axis=1)
        # LightGBM applies an EXTRA L2 (cat_l2) to categorical split gains
        l2c = l2 + jnp.float32(cfg.cat_l2)
        gain_sorted, CL_sorted = scan_gains(cum_cat, l2_gain=l2c)
        # one-vs-rest (maxCatToOnehot): candidate = a SINGLE sorted category
        # left; scan_gains on the unsummed sorted histogram gives exactly
        # that. The mode is decided by the feature's STATIC category count
        # (LightGBM's use_onehot), not the per-leaf occupancy
        gain_one, CL_one = scan_gains(hist_sorted, l2_gain=l2c)
        kk = jnp.arange(B)[None, :]
        if cat_nbins is None:
            cat_nbins = jnp.full(hist.shape[0], B, jnp.int32)
        onehot = (cat_nbins <= cfg.max_cat_to_onehot)[:, None]
        gain_cat = jnp.where(onehot, gain_one, gain_sorted)
        CL_cat = jnp.where(onehot, CL_one, CL_sorted)
        # max_cat_threshold caps only the many-vs-many prefix size; one-hot
        # mode scans every usable category (LightGBM semantics)
        valid_k = jnp.where(onehot, kk < n_usable,
                            (kk < cfg.max_cat_threshold) & (kk < n_usable))
        gain_cat = jnp.where(valid_k, gain_cat, -jnp.inf)
        gain = jnp.where(is_categorical[:, None], gain_cat, gain_num)
        CLsel = jnp.where(is_categorical[:, None], CL_cat, CL_num)
        use_left = use_left & ~is_categorical[:, None]
    else:
        gain = gain_num
        CLsel = CL_num
    gain = jnp.where(feature_active[:, None], gain, -jnp.inf)

    flat = gain.reshape(FP * B)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    bfeat = (best // B).astype(jnp.int32)
    bbin = (best % B).astype(jnp.int32)
    bdl = use_left.reshape(FP * B)[best]
    bcl = CLsel.reshape(FP * B)[best]
    return best_gain, bfeat, bbin, bdl, bcl, order


def _cat_order_usable(hist_b3, cfg: GrowerConfig):
    """Categorical ordering state from a (..., B, 3) histogram: (order over
    bins by grad/(hess+smooth) with thin groups last, usable count). ONE
    definition shared by the split search and the winning-bitset rebuild —
    they must agree bit for bit."""
    cnt = hist_b3[..., 2]
    usable = (cnt >= cfg.min_data_per_group) & (cnt > 0)
    key = jnp.where(usable,
                    hist_b3[..., 0] / (hist_b3[..., 1] + cfg.cat_smooth),
                    jnp.inf)
    order = jnp.argsort(key, axis=-1)
    return order, usable.sum(axis=-1)


def _node_mask_fn(cfg: GrowerConfig, featp, f: int, node_key):
    """feature_fraction_bynode sampler: node id -> (FP,) bool feature mask.

    LightGBM samples a fresh feature subset for every NODE's split search
    (feature_fraction_bynode, distinct from the per-tree feature_fraction);
    here each node id folds into the tree's key and keeps exactly
    ceil(frac * F) real features."""
    if cfg.feature_fraction_bynode >= 1.0:
        return lambda nid: featp
    if node_key is None:
        raise ValueError("feature_fraction_bynode < 1 requires node_key")
    FP = featp.shape[0]
    # LightGBM ColSampler::GetByNode: the per-node count is a fraction of the
    # CURRENTLY searchable set (the per-tree feature_fraction subset, or the
    # voting winners) — computed dynamically since that mask is traced
    keep = jnp.maximum(
        1, jnp.ceil(cfg.feature_fraction_bynode
                    * jnp.sum(featp).astype(jnp.float32))).astype(jnp.int32)
    base = jax.random.wrap_key_data(node_key)

    def mask(nid):
        u = jax.random.uniform(jax.random.fold_in(base, nid), (FP,))
        u = jnp.where(featp, u, jnp.inf)
        ranks = jnp.zeros(FP, jnp.int32).at[jnp.argsort(u)].set(
            jnp.arange(FP, dtype=jnp.int32))
        return featp & (ranks < keep)

    return mask


# ---------------------------------------------------------------------------
# Tree growth — helpers shared by the "partition" and "masked" row layouts
# ---------------------------------------------------------------------------

def _pad_grow_inputs(binned, grad, hess, in_bag, feature_active,
                     is_categorical, monotone, nan_bins, FP, Np):
    """Pad rows to Np (zero mass) / features to FP (inactive), transpose bins."""
    n, f = binned.shape
    in_bag = jnp.asarray(in_bag, jnp.float32)
    g0 = jnp.asarray(grad, jnp.float32) * in_bag
    h0 = jnp.asarray(hess, jnp.float32) * in_bag
    pad_r = Np - n
    bT0 = jnp.zeros((FP, Np), jnp.int32)
    bT0 = bT0.at[:f, :n].set(binned.astype(jnp.int32).T)
    gs0 = jnp.pad(g0, (0, pad_r))
    hs0 = jnp.pad(h0, (0, pad_r))
    ms0 = jnp.pad(in_bag, (0, pad_r))
    featp = jnp.zeros(FP, bool).at[:f].set(feature_active)
    catp = jnp.zeros(FP, bool).at[:f].set(is_categorical)
    monop = jnp.zeros(FP, jnp.int32).at[:f].set(monotone)
    nanp = jnp.full(FP, 0x7FFF, jnp.int32).at[:f].set(nan_bins)
    return bT0, gs0, hs0, ms0, featp, catp, monop, nanp


def _pad_cat_nbins(cat_nbins, f: int, FP: int, B: int):
    """(F,) per-feature category counts → (FP,) padded; None → B (the
    one-hot mode then never triggers, preserving legacy direct-call use)."""
    if cat_nbins is None:
        return jnp.full(FP, B, jnp.int32)
    return jnp.full(FP, B, jnp.int32).at[:f].set(
        jnp.asarray(cat_nbins, jnp.int32))


def _winning_cat_bitset(hist_parent, fsel, bsel, catp, cfg: GrowerConfig,
                        B: int, bw: int, cat_nbins=None):
    """(bitset, cat_split) of the chosen split, rebuilt from the hist cache
    (LightGBM's many-vs-many prefix re-derived from the sorted-bin order —
    the ordering/one-hot decisions share one implementation with the split
    search, _cat_order_usable)."""
    if not cfg.has_categorical:
        return jnp.zeros((bw,), jnp.uint32), jnp.zeros((), bool)
    histf = hist_parent[fsel]                          # (B, 3)
    order_f, _ = _cat_order_usable(histf, cfg)
    nb_f = (jnp.int32(B) if cat_nbins is None else cat_nbins[fsel])
    onehot = nb_f <= cfg.max_cat_to_onehot
    idx = jnp.arange(B)
    # one-vs-rest winners take ONLY the chosen sorted position left
    take = jnp.where(onehot, idx == bsel, idx <= bsel)
    bwords = (order_f >> 5).astype(jnp.int32)
    bvals = jnp.uint32(1) << (order_f & 31).astype(jnp.uint32)
    bitset = jnp.zeros((bw,), jnp.uint32).at[bwords].add(
        jnp.where(take, bvals, jnp.uint32(0)))
    return bitset, catp[fsel]


def _route_right(binrow, bsel, dl, nanbin_f, bitset, cat_split,
                 cfg: GrowerConfig, bw: int):
    """Per-row go-right decision of one split over bin values ``binrow``
    (numeric threshold, learned NaN direction, categorical bitset)."""
    gr = binrow > bsel
    gr = jnp.where(binrow == nanbin_f, ~dl, gr)
    if cfg.has_categorical:
        w = bitset[jnp.clip(binrow >> 5, 0, bw - 1)]
        member = ((w >> (binrow & 31).astype(jnp.uint32)) & 1).astype(bool)
        gr = jnp.where(cat_split, ~member, gr)
    return gr


def _init_split_state(L: int, B: int, bw: int, hist_root, rg, rf, rb, rdl,
                      rcl, FP: int):
    """Initial per-leaf split state + tree-structure arrays (shared fields of
    both layout states): root occupies leaf 0."""
    z1 = lambda dt, fill=0: jnp.full((max(L - 1, 1),), fill, dt)
    return dict(
        hist=jnp.zeros((L, FP, B, 3), jnp.float32).at[0].set(hist_root),
        bgain=jnp.full(L, -jnp.inf, jnp.float32).at[0].set(rg),
        bfeat=jnp.zeros(L, jnp.int32).at[0].set(rf),
        bbin=jnp.zeros(L, jnp.int32).at[0].set(rb),
        bdl=jnp.zeros(L, bool).at[0].set(rdl),
        bcl=jnp.zeros(L, jnp.float32).at[0].set(rcl),
        depth=jnp.zeros(L, jnp.int32),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_right=jnp.zeros(L, bool),
        split_feature=z1(jnp.int32),
        split_bin=z1(jnp.int32, B - 1),
        split_gain=z1(jnp.float32),
        split_type=z1(jnp.int32),
        default_left=jnp.zeros((max(L - 1, 1),), bool),
        cat_bitset=jnp.zeros((max(L - 1, 1), bw), jnp.uint32),
        left_child=z1(jnp.int32, ~0),
        right_child=z1(jnp.int32, ~0),
        internal_value=z1(jnp.float32),
        internal_count=z1(jnp.int32),
        num_splits=jnp.zeros((), jnp.int32),
    )


def _select_split_leaf(s, cfg: GrowerConfig, L: int):
    """(leaf index, do-split flag) for this growth step."""
    active = jnp.arange(L) <= s.num_splits
    if cfg.max_depth > 0:
        active &= s.depth < cfg.max_depth
    masked_gain = jnp.where(active, s.bgain, -jnp.inf)
    l = jnp.argmax(masked_gain).astype(jnp.int32)
    return l, masked_gain[l] > cfg.min_gain_to_split


def _common_split_updates(s, cfg: GrowerConfig, l, fsel, bsel, gain_l, dl,
                          bitset, cat_split, hist_left, hist_right,
                          bg2, bf2, bb2, bdl2, bcl2, G_l, H_l, C_l):
    """``_replace`` kwargs shared by both layouts for one split of leaf ``l``:
    hist cache, per-leaf best-split state, and tree-structure bookkeeping
    (leaf numbering per LightGBM Tree::Split — left keeps ``l``, right becomes
    ``num_splits + 1``, child pointers ``~leaf``)."""
    new_right = s.num_splits + 1
    i_node = s.num_splits
    parent_out = _leaf_output(G_l, H_l, cfg) * cfg.learning_rate
    p = s.leaf_parent[l]
    p_idx = jnp.maximum(p, 0)
    lc = s.left_child.at[p_idx].set(
        jnp.where((p >= 0) & ~s.leaf_is_right[l], i_node, s.left_child[p_idx]))
    rc = s.right_child.at[p_idx].set(
        jnp.where((p >= 0) & s.leaf_is_right[l], i_node, s.right_child[p_idx]))
    lc = lc.at[i_node].set(~l)
    rc = rc.at[i_node].set(~new_right)
    return dict(
        hist=s.hist.at[l].set(hist_left).at[new_right].set(hist_right),
        bgain=s.bgain.at[l].set(bg2[0]).at[new_right].set(bg2[1]),
        bfeat=s.bfeat.at[l].set(bf2[0]).at[new_right].set(bf2[1]),
        bbin=s.bbin.at[l].set(bb2[0]).at[new_right].set(bb2[1]),
        bdl=s.bdl.at[l].set(bdl2[0]).at[new_right].set(bdl2[1]),
        bcl=s.bcl.at[l].set(bcl2[0]).at[new_right].set(bcl2[1]),
        depth=s.depth.at[l].add(1).at[new_right].set(s.depth[l] + 1),
        leaf_parent=s.leaf_parent.at[l].set(i_node).at[new_right].set(i_node),
        leaf_is_right=s.leaf_is_right.at[l].set(False)
                                     .at[new_right].set(True),
        split_feature=s.split_feature.at[i_node].set(fsel),
        split_bin=s.split_bin.at[i_node].set(bsel),
        split_gain=s.split_gain.at[i_node].set(gain_l),
        split_type=s.split_type.at[i_node].set(cat_split.astype(jnp.int32)),
        default_left=s.default_left.at[i_node].set(dl),
        cat_bitset=s.cat_bitset.at[i_node].set(bitset),
        left_child=lc,
        right_child=rc,
        internal_value=s.internal_value.at[i_node].set(parent_out),
        internal_count=s.internal_count.at[i_node].set(C_l.astype(jnp.int32)),
        num_splits=s.num_splits + 1,
    )


def _node_of_row_from_ranges(s, L: int, Np: int, n: int) -> jnp.ndarray:
    """Per-row final leaf id in ORIGINAL row order, from the sorted layout's
    (pos, leaf_start, leaf_len): scatter leaf ids at range starts, fill
    forward via cumulative max of marker positions, then undo the sort with
    one scatter through ``pos``. Zero-length local ranges are excluded: they
    share a start position with their sibling and the scatter collision
    would mislabel the sibling's rows. (No Np*L position encoding — that
    would overflow int32 at HIGGS-scale Np.)"""
    exists = jnp.arange(L) <= s.num_splits
    own_rows = exists & (s.leaf_len > 0)
    markers = jnp.full(Np, -1, jnp.int32).at[
        jnp.where(own_rows, s.leaf_start, Np)].set(
            jnp.arange(L, dtype=jnp.int32), mode="drop")
    last_pos = lax.associative_scan(
        jnp.maximum,
        jnp.where(markers >= 0, jnp.arange(Np, dtype=jnp.int32), -1))
    node_sorted = markers[jnp.maximum(last_pos, 0)]
    return jnp.zeros(Np, jnp.int32).at[s.pos].set(node_sorted)[:n]


def _finalize_tree(s, cfg: GrowerConfig, L: int) -> TreeArrays:
    """Leaf stats from the per-leaf histogram cache (per-leaf f32 accumulation
    — a global prefix-sum difference would catastrophically cancel for small
    leaves on large N; the cache is already psum'd across devices)."""
    leaf_tot = s.hist[:, 0].sum(axis=1)                  # (L, 3)
    sumG, sumH, sumC = leaf_tot[:, 0], leaf_tot[:, 1], leaf_tot[:, 2]
    leaf_value = _leaf_output(sumG, sumH, cfg) * cfg.learning_rate
    exists = jnp.arange(L) <= s.num_splits
    leaf_value = jnp.where(exists, leaf_value, 0.0)
    return TreeArrays(
        split_feature=s.split_feature,
        split_bin=s.split_bin,
        split_gain=s.split_gain,
        split_type=s.split_type,
        default_left=s.default_left,
        cat_bitset=s.cat_bitset,
        left_child=s.left_child,
        right_child=s.right_child,
        internal_value=s.internal_value,
        internal_count=s.internal_count,
        leaf_value=leaf_value,
        leaf_weight=sumH,
        leaf_count=sumC.astype(jnp.int32),
        num_splits=s.num_splits,
    )


class _GrowState(NamedTuple):
    pos: jnp.ndarray             # (Np,) i32: sorted position -> original row
    gs: jnp.ndarray              # (Np,) f32 grad, sorted
    hs: jnp.ndarray              # (Np,) f32 hess, sorted
    ms: jnp.ndarray              # (Np,) f32 in-bag mask, sorted
    bT: jnp.ndarray              # (FP, Np) i32 bins, sorted
    leaf_start: jnp.ndarray      # (L,) i32
    leaf_len: jnp.ndarray        # (L,) i32
    hist: jnp.ndarray            # (L, FP, B, 3) f32 cache
    bgain: jnp.ndarray           # (L,) f32 best gain per leaf
    bfeat: jnp.ndarray           # (L,) i32
    bbin: jnp.ndarray            # (L,) i32
    bdl: jnp.ndarray             # (L,) bool
    bcl: jnp.ndarray             # (L,) f32 global count-left of best split
    depth: jnp.ndarray           # (L,) i32
    leaf_parent: jnp.ndarray     # (L,) i32
    leaf_is_right: jnp.ndarray   # (L,) bool
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


def _grow_tree_impl(binned, grad, hess, in_bag, feature_active, is_categorical,
                    monotone, nan_bins, cfg: GrowerConfig,
                    axis_name: Optional[str], node_key=None, cat_nbins=None):
    n, f = binned.shape
    L = cfg.num_leaves
    B = pad_bins(cfg.num_bins)
    FP = features_padded(f)
    # owned-feature mode: each of W devices keeps only FP/W features of the
    # reduced histogram; split decisions are re-unified by a tiny per-leaf
    # candidate exchange (validated + gated in grow_tree/boosting)
    scatter_mode = (cfg.hist_reduce == "scatter" and cfg.feature_shards > 1
                    and axis_name is not None)
    W = cfg.feature_shards if scatter_mode else 1
    if scatter_mode and FP % W:
        raise ValueError(f"hist_reduce='scatter' needs features_padded({f})="
                         f"{FP} divisible by feature_shards={W}")
    FPo = FP // W
    chunk = _chunk()     # resolved ONCE per trace: within-trace consistency
    Np = -(-n // chunk) * chunk
    bw = (B + BITS - 1) // BITS
    l1 = jnp.float32(cfg.lambda_l1)
    l2 = jnp.float32(cfg.lambda_l2)
    sizes = _bucket_sizes(Np)
    sizes_arr = jnp.asarray(sizes, jnp.int32)

    bT0, gs0, hs0, ms0, featp, catp, monop, nanp = _pad_grow_inputs(
        binned, grad, hess, in_bag, feature_active, is_categorical, monotone,
        nan_bins, FP, Np)

    use_seg = (cfg.use_segmented if cfg.use_segmented is not None
               else segmented_histograms_available(B))

    def build_hist(bT, gs, hs, ms, child_start, child_len):
        """Histogram of sorted rows [child_start, child_start+child_len) via
        the bucketed kernel; psum across the data axis if present. On TPU
        the segmented kernel selects its blocks from the FULL arrays by
        scalar-prefetched offsets — no dynamic_slice copy, no mask multiply."""
        if use_seg:
            # branch i covers lengths <= sizes[i] with ONE extra chunk for
            # window alignment (S = sizes[i] + chunk >= length + chunk) —
            # not the next power of two, which could double the kernel work
            def make_branch(size):
                seg = min(size + chunk, Np)

                def br(args):
                    bT_, gs_, hs_, ms_, cstart, clen = args
                    return range_histogram(bT_, gs_, hs_, ms_, cstart, clen,
                                           B, seg)
                return br

            bidx = jnp.searchsorted(sizes_arr, child_len, side="left")
        else:
            def make_branch(size):
                def br(args):
                    bT_, gs_, hs_, ms_, cstart, clen = args
                    cs, S = _aligned_window(cstart, size, Np, chunk)
                    idx = cs + jnp.arange(S, dtype=jnp.int32)
                    mask = ((idx >= cstart)
                            & (idx < cstart + clen)).astype(jnp.float32)
                    gsl = lax.dynamic_slice(gs_, (cs,), (S,)) * mask
                    hsl = lax.dynamic_slice(hs_, (cs,), (S,)) * mask
                    msl = lax.dynamic_slice(ms_, (cs,), (S,)) * mask
                    bsl = lax.dynamic_slice(bT_, (0, cs), (FP, S))
                    return child_histogram(bsl, gsl, hsl, msl, B)
                return br

            bidx = jnp.searchsorted(sizes_arr, child_len, side="left")
        hist = lax.switch(jnp.minimum(bidx, len(sizes) - 1),
                          [make_branch(s) for s in sizes],
                          (bT, gs, hs, ms, child_start, child_len))
        if scatter_mode:
            return _hist_reduce_scatter(hist, axis_name,
                                        cfg.hist_allreduce_dtype)
        return _maybe_psum(hist, axis_name, cfg.hist_allreduce_dtype)

    nmask = _node_mask_fn(cfg, featp, f, node_key)
    catb = _pad_cat_nbins(cat_nbins, f, FP, B)

    if scatter_mode:
        off = lax.axis_index(axis_name).astype(jnp.int32) * FPo
        slice_o = lambda a: lax.dynamic_slice_in_dim(a, off, FPo)
        catp_o, monop_o = slice_o(catp), slice_o(monop)
        nanp_o, catb_o = slice_o(nanp), slice_o(catb)

        def best_of(hist_leaf, fmask):
            # fmask arrives as the full (FP,) node mask; score only the
            # owned slice — the exchange below restores the global argmax
            return _best_for_leaf(hist_leaf, slice_o(fmask), catp_o, monop_o,
                                  nanp_o, cfg, l1, l2, catb_o)

        def exchange_best(g, f_loc, b, dl, cl):
            """All-gather each shard's best owned candidate (5 floats per
            leaf) and take the global winner — every device ends up with the
            SAME (gain, global feature, bin, default_left, left_count), so
            leaf selection and partitioning stay uniform across the mesh."""
            vec = jnp.stack([g, (off + f_loc).astype(jnp.float32),
                             b.astype(jnp.float32), dl.astype(jnp.float32),
                             cl], axis=-1)                    # (..., 5)
            allv = lax.all_gather(vec, axis_name)             # (W, ..., 5)
            win = jnp.argmax(allv[..., 0], axis=0)            # low rank wins ties
            bv = jnp.take_along_axis(
                allv, win[None, ..., None], axis=0)[0]
            return (bv[..., 0], bv[..., 1].astype(jnp.int32),
                    bv[..., 2].astype(jnp.int32), bv[..., 3] > 0.5,
                    bv[..., 4])
    else:
        def best_of(hist_leaf, fmask):
            return _best_for_leaf(hist_leaf, fmask, catp, monop, nanp, cfg,
                                  l1, l2, catb)

        exchange_best = lambda *c: c

    # ---- root ------------------------------------------------------------
    hist_root = build_hist(bT0, gs0, hs0, ms0, jnp.int32(0), jnp.int32(Np))
    rg, rf, rb, rdl, rcl = exchange_best(
        *best_of(hist_root, nmask(jnp.int32(2 * (L - 1))))[:5])

    init = _GrowState(
        pos=jnp.arange(Np, dtype=jnp.int32),
        gs=gs0, hs=hs0, ms=ms0, bT=bT0,
        leaf_start=jnp.zeros(L, jnp.int32),
        leaf_len=jnp.zeros(L, jnp.int32).at[0].set(Np),
        **_init_split_state(L, B, bw, hist_root, rg, rf, rb, rdl, rcl, FPo),
    )

    def partition(pos, gs, hs, ms, bT, start, length, fsel, bsel, dl, bitset,
                  cat_split, nanbin_f):
        """Stably partition the leaf's range by the split; returns updated
        sorted arrays and the LOCAL left-child row count."""
        def make_branch(size):
            def br(args):
                pos_, gs_, hs_, ms_, bT_ = args
                cs, S = _aligned_window(start, size, Np, chunk)
                idx = cs + jnp.arange(S, dtype=jnp.int32)
                binrow = lax.dynamic_slice(bT_, (fsel, cs), (1, S))[0]
                gr = _route_right(binrow, bsel, dl, nanbin_f, bitset,
                                  cat_split, cfg, bw)
                key = jnp.where(idx < start, -1,
                                jnp.where(idx >= start + length, 2,
                                          gr.astype(jnp.int32)))
                src = _stable_partition_src(key, cfg.partition_impl)
                nl_loc = jnp.sum(key == 0).astype(jnp.int32)

                def perm1(a):
                    sl = lax.dynamic_slice(a, (cs,), (S,))
                    return lax.dynamic_update_slice(a, sl[src], (cs,))

                blk = lax.dynamic_slice(bT_, (0, cs), (FP, S))
                bT2 = lax.dynamic_update_slice(bT_, blk[:, src], (0, cs))
                return perm1(pos_), perm1(gs_), perm1(hs_), perm1(ms_), bT2, nl_loc
            return br

        bidx = jnp.searchsorted(sizes_arr, length, side="left")
        return lax.switch(jnp.minimum(bidx, len(sizes) - 1),
                          [make_branch(s) for s in sizes],
                          (pos, gs, hs, ms, bT))

    def body(i, s: _GrowState):
        l, do = _select_split_leaf(s, cfg, L)

        def step(s: _GrowState) -> _GrowState:
            gain_l, fsel, bsel, dl = s.bgain[l], s.bfeat[l], s.bbin[l], s.bdl[l]
            start = s.leaf_start[l]
            length = s.leaf_len[l]
            hist_parent = s.hist[l]                     # (FP, B, 3)
            totals = hist_parent[0].sum(axis=0)
            G_l, H_l, C_l = totals[0], totals[1], totals[2]
            bitset, cat_split = _winning_cat_bitset(hist_parent, fsel, bsel,
                                                    catp, cfg, B, bw, catb)

            pos2, gs2, hs2, ms2, bT2, nl_loc = partition(
                s.pos, s.gs, s.hs, s.ms, s.bT, start, length, fsel, bsel, dl,
                bitset, cat_split, nanp[fsel])

            # global child counts decide which side is built (uniform across
            # devices — bcl comes from the summed histogram)
            cl_glob = s.bcl[l]
            left_small = cl_glob * 2.0 <= C_l
            child_start = jnp.where(left_small, start, start + nl_loc)
            child_len = jnp.where(left_small, nl_loc, length - nl_loc)
            hist_small = build_hist(bT2, gs2, hs2, ms2, child_start, child_len)
            hist_left = jnp.where(left_small, hist_small,
                                  hist_parent - hist_small)
            hist_right = hist_parent - hist_left

            # re-evaluate best splits for the two children
            i_node_id = s.num_splits
            masks2 = jnp.stack([nmask(i_node_id * 2),
                                nmask(i_node_id * 2 + 1)])
            bg2, bf2, bb2, bdl2, bcl2, _ = jax.vmap(best_of)(
                jnp.stack([hist_left, hist_right]), masks2)
            bg2, bf2, bb2, bdl2, bcl2 = exchange_best(bg2, bf2, bb2, bdl2,
                                                      bcl2)

            new_right = s.num_splits + 1                # leaf id of right child
            return s._replace(
                pos=pos2, gs=gs2, hs=hs2, ms=ms2, bT=bT2,
                leaf_start=s.leaf_start.at[l].set(start)
                                       .at[new_right].set(start + nl_loc),
                leaf_len=s.leaf_len.at[l].set(nl_loc)
                                    .at[new_right].set(length - nl_loc),
                **_common_split_updates(s, cfg, l, fsel, bsel, gain_l, dl,
                                        bitset, cat_split, hist_left,
                                        hist_right, bg2, bf2, bb2, bdl2, bcl2,
                                        G_l, H_l, C_l),
            )

        return lax.cond(do, step, lambda s: s, s)

    s = lax.fori_loop(0, L - 1, body, init) if L > 1 else init
    return _finalize_tree(s, cfg, L), _node_of_row_from_ranges(s, L, Np, n)


class _GatherState(NamedTuple):
    pos: jnp.ndarray             # (Np,) i32: sorted position -> original row
    leaf_start: jnp.ndarray      # (L,) i32
    leaf_len: jnp.ndarray        # (L,) i32
    hist: jnp.ndarray            # (L, FP, B, 3) f32 cache
    bgain: jnp.ndarray
    bfeat: jnp.ndarray
    bbin: jnp.ndarray
    bdl: jnp.ndarray
    bcl: jnp.ndarray
    depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


def _grow_tree_impl_gather(binned, grad, hess, in_bag, feature_active,
                           is_categorical, monotone, nan_bins,
                           cfg: GrowerConfig, axis_name: Optional[str],
                           node_key=None, cat_nbins=None):
    """row_layout="gather": the third hot-loop design. Rows never move —
    grad/hess/mask/bins stay in original row order; only the (Np,) ``pos``
    permutation is maintained sorted-by-leaf. Each split permutes ONE i32
    vector, and the smaller child's rows are gathered through ``pos`` just
    before histogramming. Per split this moves O(size) i32 + O(child·FP)
    gathered bins, vs the partition layout's O(size·FP) two-way permute —
    same tree bitwise (same split decisions, same stable partition)."""
    n, f = binned.shape
    L = cfg.num_leaves
    B = pad_bins(cfg.num_bins)
    FP = features_padded(f)
    chunk = _chunk()     # resolved ONCE per trace: within-trace consistency
    Np = -(-n // chunk) * chunk
    bw = (B + BITS - 1) // BITS
    l1 = jnp.float32(cfg.lambda_l1)
    l2 = jnp.float32(cfg.lambda_l2)
    sizes = _bucket_sizes(Np)
    sizes_arr = jnp.asarray(sizes, jnp.int32)

    bT0, gs0, hs0, ms0, featp, catp, monop, nanp = _pad_grow_inputs(
        binned, grad, hess, in_bag, feature_active, is_categorical, monotone,
        nan_bins, FP, Np)

    def build_hist(pos, child_start, child_len):
        """Histogram of child rows gathered through ``pos``; psum across the
        data axis if present."""
        def make_branch(size):
            def br(args):
                pos_, cstart, clen = args
                cs, S = _aligned_window(cstart, size, Np, chunk)
                idx = cs + jnp.arange(S, dtype=jnp.int32)
                mask = ((idx >= cstart) & (idx < cstart + clen)
                        ).astype(jnp.float32)
                posl = lax.dynamic_slice(pos_, (cs,), (S,))
                gsl = gs0[posl] * mask
                hsl = hs0[posl] * mask
                msl = ms0[posl] * mask
                bsl = bT0[:, posl]
                return child_histogram(bsl, gsl, hsl, msl, B)
            return br

        bidx = jnp.searchsorted(sizes_arr, child_len, side="left")
        hist = lax.switch(jnp.minimum(bidx, len(sizes) - 1),
                          [make_branch(s) for s in sizes],
                          (pos, child_start, child_len))
        return _maybe_psum(hist, axis_name, cfg.hist_allreduce_dtype)

    nmask = _node_mask_fn(cfg, featp, f, node_key)
    catb = _pad_cat_nbins(cat_nbins, f, FP, B)

    def best_of(hist_leaf, fmask):
        return _best_for_leaf(hist_leaf, fmask, catp, monop, nanp, cfg, l1,
                              l2, catb)

    # ---- root: no gather needed (pos is identity) ------------------------
    hist_root = _maybe_psum(child_histogram(bT0, gs0, hs0, ms0, B),
                            axis_name, cfg.hist_allreduce_dtype)
    rg, rf, rb, rdl, rcl, _ = best_of(hist_root, nmask(jnp.int32(2 * (L - 1))))

    init = _GatherState(
        pos=jnp.arange(Np, dtype=jnp.int32),
        leaf_start=jnp.zeros(L, jnp.int32),
        leaf_len=jnp.zeros(L, jnp.int32).at[0].set(Np),
        **_init_split_state(L, B, bw, hist_root, rg, rf, rb, rdl, rcl, FP),
    )

    def partition(pos, start, length, fsel, bsel, dl, bitset, cat_split,
                  nanbin_f):
        """Stably partition the leaf's range of ``pos`` by the split;
        returns (updated pos, LOCAL left-child row count)."""
        def make_branch(size):
            def br(pos_):
                cs, S = _aligned_window(start, size, Np, chunk)
                idx = cs + jnp.arange(S, dtype=jnp.int32)
                posl = lax.dynamic_slice(pos_, (cs,), (S,))
                binrow = bT0[fsel, posl]
                gr = _route_right(binrow, bsel, dl, nanbin_f, bitset,
                                  cat_split, cfg, bw)
                key = jnp.where(idx < start, -1,
                                jnp.where(idx >= start + length, 2,
                                          gr.astype(jnp.int32)))
                src = _stable_partition_src(key, cfg.partition_impl)
                nl_loc = jnp.sum(key == 0).astype(jnp.int32)
                return lax.dynamic_update_slice(pos_, posl[src], (cs,)), nl_loc
            return br

        bidx = jnp.searchsorted(sizes_arr, length, side="left")
        return lax.switch(jnp.minimum(bidx, len(sizes) - 1),
                          [make_branch(s) for s in sizes], pos)

    def body(i, s: _GatherState):
        l, do = _select_split_leaf(s, cfg, L)

        def step(s: _GatherState) -> _GatherState:
            gain_l, fsel, bsel, dl = s.bgain[l], s.bfeat[l], s.bbin[l], s.bdl[l]
            start = s.leaf_start[l]
            length = s.leaf_len[l]
            hist_parent = s.hist[l]
            totals = hist_parent[0].sum(axis=0)
            G_l, H_l, C_l = totals[0], totals[1], totals[2]
            bitset, cat_split = _winning_cat_bitset(hist_parent, fsel, bsel,
                                                    catp, cfg, B, bw, catb)

            pos2, nl_loc = partition(s.pos, start, length, fsel, bsel, dl,
                                     bitset, cat_split, nanp[fsel])

            cl_glob = s.bcl[l]
            left_small = cl_glob * 2.0 <= C_l
            child_start = jnp.where(left_small, start, start + nl_loc)
            child_len = jnp.where(left_small, nl_loc, length - nl_loc)
            hist_small = build_hist(pos2, child_start, child_len)
            hist_left = jnp.where(left_small, hist_small,
                                  hist_parent - hist_small)
            hist_right = hist_parent - hist_left

            i_node_id = s.num_splits
            masks2 = jnp.stack([nmask(i_node_id * 2),
                                nmask(i_node_id * 2 + 1)])
            bg2, bf2, bb2, bdl2, bcl2, _ = jax.vmap(best_of)(
                jnp.stack([hist_left, hist_right]), masks2)

            new_right = s.num_splits + 1
            return s._replace(
                pos=pos2,
                leaf_start=s.leaf_start.at[l].set(start)
                                       .at[new_right].set(start + nl_loc),
                leaf_len=s.leaf_len.at[l].set(nl_loc)
                                    .at[new_right].set(length - nl_loc),
                **_common_split_updates(s, cfg, l, fsel, bsel, gain_l, dl,
                                        bitset, cat_split, hist_left,
                                        hist_right, bg2, bf2, bb2, bdl2, bcl2,
                                        G_l, H_l, C_l),
            )

        return lax.cond(do, step, lambda s: s, s)

    s = lax.fori_loop(0, L - 1, body, init) if L > 1 else init
    return _finalize_tree(s, cfg, L), _node_of_row_from_ranges(s, L, Np, n)


class _MaskedState(NamedTuple):
    node: jnp.ndarray            # (Np,) i32 current leaf id per row
    hist: jnp.ndarray            # (L, FP, B, 3) f32 cache — shared-field block
    bgain: jnp.ndarray           # (see _init_split_state)
    bfeat: jnp.ndarray
    bbin: jnp.ndarray
    bdl: jnp.ndarray
    bcl: jnp.ndarray
    depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


def _grow_tree_impl_masked(binned, grad, hess, in_bag, feature_active,
                           is_categorical, monotone, nan_bins,
                           cfg: GrowerConfig, axis_name: Optional[str],
                           node_key=None, cat_nbins=None):
    """Masked-row grower: rows never move. Each split routes leaf ``l``'s rows
    by updating a per-row ``node`` array and histograms the smaller child with
    the child-membership mask multiplied into the kernel's (g, h, count)
    factors over the FULL row set. Removes every per-split sort/permute at the
    cost of a full-N kernel pass per split — the winning trade when the MXU
    histogram's per-row cost is far below the partition's sort cost
    (measured: tools/perf_tune.py phases 2-3). Produces bitwise-identical
    trees to the partitioned grower (tests/test_gbdt_engine.py)."""
    n, f = binned.shape
    L = cfg.num_leaves
    B = pad_bins(cfg.num_bins)
    FP = features_padded(f)
    chunk = _chunk()     # resolved ONCE per trace: within-trace consistency
    Np = -(-n // chunk) * chunk
    bw = (B + BITS - 1) // BITS
    l1 = jnp.float32(cfg.lambda_l1)
    l2 = jnp.float32(cfg.lambda_l2)

    bT0, gs0, hs0, ms0, featp, catp, monop, nanp = _pad_grow_inputs(
        binned, grad, hess, in_bag, feature_active, is_categorical, monotone,
        nan_bins, FP, Np)

    def build_hist_masked(sel):
        hist = child_histogram(bT0, gs0 * sel, hs0 * sel, ms0 * sel, B)
        return _maybe_psum(hist, axis_name, cfg.hist_allreduce_dtype)

    nmask = _node_mask_fn(cfg, featp, f, node_key)
    catb = _pad_cat_nbins(cat_nbins, f, FP, B)

    def best_of(hist_leaf, fmask):
        return _best_for_leaf(hist_leaf, fmask, catp, monop, nanp, cfg, l1,
                              l2, catb)

    hist_root = build_hist_masked(jnp.ones(Np, jnp.float32))
    rg, rf, rb, rdl, rcl, _ = best_of(hist_root, nmask(jnp.int32(2 * (L - 1))))

    init = _MaskedState(
        node=jnp.zeros(Np, jnp.int32),
        **_init_split_state(L, B, bw, hist_root, rg, rf, rb, rdl, rcl, FP),
    )

    def body(i, s: _MaskedState):
        l, do = _select_split_leaf(s, cfg, L)

        def step(s: _MaskedState) -> _MaskedState:
            gain_l, fsel, bsel, dl = s.bgain[l], s.bfeat[l], s.bbin[l], s.bdl[l]
            hist_parent = s.hist[l]
            totals = hist_parent[0].sum(axis=0)
            G_l, H_l, C_l = totals[0], totals[1], totals[2]
            bitset, cat_split = _winning_cat_bitset(hist_parent, fsel, bsel,
                                                    catp, cfg, B, bw, catb)

            # route leaf l's rows: right-goers move to leaf id num_splits+1
            binrow = lax.dynamic_slice(bT0, (fsel, 0), (1, Np))[0]
            gr = _route_right(binrow, bsel, dl, nanp[fsel], bitset, cat_split,
                              cfg, bw)
            new_right = s.num_splits + 1
            node2 = jnp.where((s.node == l) & gr, new_right, s.node)

            # build the globally-smaller child; sibling by subtraction
            cl_glob = s.bcl[l]
            left_small = cl_glob * 2.0 <= C_l
            child_id = jnp.where(left_small, l, new_right)
            sel = (node2 == child_id).astype(jnp.float32)
            hist_small = build_hist_masked(sel)
            hist_left = jnp.where(left_small, hist_small,
                                  hist_parent - hist_small)
            hist_right = hist_parent - hist_left

            i_node_id = s.num_splits
            masks2 = jnp.stack([nmask(i_node_id * 2),
                                nmask(i_node_id * 2 + 1)])
            bg2, bf2, bb2, bdl2, bcl2, _ = jax.vmap(best_of)(
                jnp.stack([hist_left, hist_right]), masks2)

            return s._replace(
                node=node2,
                **_common_split_updates(s, cfg, l, fsel, bsel, gain_l, dl,
                                        bitset, cat_split, hist_left,
                                        hist_right, bg2, bf2, bb2, bdl2, bcl2,
                                        G_l, H_l, C_l),
            )

        return lax.cond(do, step, lambda s: s, s)

    s = lax.fori_loop(0, L - 1, body, init) if L > 1 else init
    return _finalize_tree(s, cfg, L), s.node[:n]


@partial(jax.jit, static_argnames=("cfg", "axis_name"))
def grow_tree(
    binned: jnp.ndarray,         # (N, F) uint8/uint16 bin ids
    grad: jnp.ndarray,           # (N,) f32 — pre-weighted (instance weight / GOSS amp)
    hess: jnp.ndarray,           # (N,) f32
    in_bag: jnp.ndarray,         # (N,) f32 — 1 participating, 0 bagged-out/padding
    feature_active: jnp.ndarray, # (F,) bool — feature_fraction mask
    is_categorical: jnp.ndarray, # (F,) bool
    monotone: jnp.ndarray,       # (F,) i32 in {-1, 0, +1}
    cfg: GrowerConfig,
    nan_bins: Optional[jnp.ndarray] = None,  # (F,) i32 NaN bin per feature
    axis_name: Optional[str] = None,         # shard_map data axis for psum
    node_key=None,                           # raw key data (feature_fraction_bynode)
    cat_nbins=None,                          # (F,) static per-feature category counts
) -> tuple:
    """Grow one tree; returns (TreeArrays, node_of_row) where node_of_row is
    each row's final leaf index (used for the O(1) training-score update)."""
    n, f = binned.shape
    if nan_bins is None:
        nan_bins = jnp.full(f, 0x7FFF, jnp.int32)
    if cfg.hist_reduce not in ("allreduce", "scatter"):
        raise ValueError("hist_reduce must be 'allreduce' or 'scatter', "
                         f"got {cfg.hist_reduce!r}")
    if cfg.hist_reduce == "scatter" and cfg.feature_shards > 1:
        if cfg.growth_policy != "leafwise" or cfg.row_layout != "partition":
            raise ValueError(
                "hist_reduce='scatter' (feature-parallel) supports only "
                "leafwise growth with the partition row layout")
        if cfg.has_categorical:
            raise ValueError("hist_reduce='scatter' does not support "
                             "categorical features (the winning split's "
                             "bitset needs the owner's histogram slice)")
        if axis_name is None:
            raise ValueError("hist_reduce='scatter' requires a mesh axis")
    if cfg.growth_policy == "depthwise":
        from .grower_depthwise import _grow_tree_impl_depthwise

        return _grow_tree_impl_depthwise(binned, grad, hess, in_bag,
                                         feature_active, is_categorical,
                                         monotone, nan_bins, cfg, axis_name,
                                         node_key, cat_nbins)
    if cfg.growth_policy != "leafwise":
        raise ValueError("growth_policy must be 'leafwise' or 'depthwise', "
                         f"got {cfg.growth_policy!r}")
    if cfg.row_layout == "masked":
        return _grow_tree_impl_masked(binned, grad, hess, in_bag,
                                      feature_active, is_categorical, monotone,
                                      nan_bins, cfg, axis_name, node_key,
                                      cat_nbins)
    if cfg.row_layout == "gather":
        return _grow_tree_impl_gather(binned, grad, hess, in_bag,
                                      feature_active, is_categorical, monotone,
                                      nan_bins, cfg, axis_name, node_key,
                                      cat_nbins)
    if cfg.row_layout != "partition":
        raise ValueError(
            "row_layout must be 'partition', 'masked' or 'gather', "
            f"got {cfg.row_layout!r}")
    return _grow_tree_impl(binned, grad, hess, in_bag, feature_active,
                           is_categorical, monotone, nan_bins, cfg, axis_name,
                           node_key, cat_nbins)


# ---------------------------------------------------------------------------
# Stacked-forest prediction
# ---------------------------------------------------------------------------

class Forest(NamedTuple):
    """All trees stacked on a leading tree axis; ``threshold`` is in raw feature
    space (bin upper bounds), ``split_bin`` in bin space (for binned traversal).
    Inference is a ``lax.scan`` over trees of a vectorized pointer-chase, batched
    over rows — the reference instead does row-at-a-time JNI predict
    (LightGBMBooster.scala:520-560), which SURVEY §3.2 flags as unbatched."""

    split_feature: jnp.ndarray   # (T, L-1)
    threshold: jnp.ndarray       # (T, L-1) f32
    split_bin: jnp.ndarray       # (T, L-1) i32
    split_type: jnp.ndarray      # (T, L-1) i32
    default_left: jnp.ndarray    # (T, L-1) bool
    cat_bitset: jnp.ndarray      # (T, L-1, BW) u32
    left_child: jnp.ndarray      # (T, L-1)
    right_child: jnp.ndarray     # (T, L-1)
    leaf_value: jnp.ndarray      # (T, L)
    # per-split missing handling (LightGBM decision_type bits 2-3):
    # 0 none, 1 zero (|x|<=1e-35 routes default), 2 nan. Raw-value traversal
    # only; binned traversal routes via nan_bins.
    missing_type: jnp.ndarray = None  # (T, L-1) i32

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_leaves(self) -> int:
        return self.leaf_value.shape[1]


def _descend(X, sf, thr, sbin, stype, dleft, bits, lc, rc, binned: bool,
             depth: int, nan_bins=None, mtypes=None):
    """Vectorized pointer-chase for one tree; returns leaf index per row."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        nd = jnp.maximum(node, 0)
        f = sf[nd]
        x = jnp.take_along_axis(X, f[:, None].astype(jnp.int32), axis=1)[:, 0]
        dl = dleft[nd]
        if binned:
            xb = x.astype(jnp.int32)
            num_right = xb > sbin[nd]
            if nan_bins is not None:
                is_missing = xb == nan_bins[f.astype(jnp.int32)]
                num_right = jnp.where(is_missing, ~dl, num_right)
            c = xb
        else:
            # LightGBM Tree::NumericalDecision: NaN coerces to 0.0 unless
            # missing_type is nan; zero missing routes |x| <= 1e-35 to the
            # default side (kZeroThreshold)
            t = thr[nd]
            isnan_x = jnp.isnan(x)
            if mtypes is None:
                is_missing = isnan_x
                x0 = x
            else:
                mt = mtypes[nd]
                x0 = jnp.where(isnan_x & (mt != 2), 0.0, x)
                is_missing = jnp.where(mt == 1, jnp.abs(x0) <= 1e-35,
                                       (mt == 2) & isnan_x)
            num_right = jnp.where(is_missing, ~dl, ~(x0 <= t))
            # categorical NaN: member test on category 0 unless missing_type
            # is nan, where NaN is never a member (LightGBM
            # Tree::CategoricalDecision coerces int_fval to 0 for non-nan
            # missing types)
            if mtypes is None:
                cat_nan = -1.0
            else:
                cat_nan = jnp.where(mtypes[nd] == 2, -1.0, 0.0)
            c = jnp.clip(jnp.where(isnan_x, cat_nan, x), -1,
                         bits.shape[1] * BITS - 1).astype(jnp.int32)
        cw = jnp.maximum(c, 0)
        word = bits[nd, cw >> 5]
        member = ((word >> (cw & 31).astype(jnp.uint32)) & 1).astype(bool) & (c >= 0)
        is_cat = stype[nd] == 1
        go_right = jnp.where(is_cat, ~member, num_right)
        nxt = jnp.where(go_right, rc[nd], lc[nd])
        return jnp.where(node < 0, node, nxt)

    node = jax.lax.fori_loop(0, depth, step, node)
    return ~node  # leaf index


@partial(jax.jit, static_argnames=("binned", "output", "depth"))
def forest_predict(forest: Forest, X: jnp.ndarray, binned: bool = False,
                   output: str = "sum", nan_bins=None,
                   depth: Optional[int] = None) -> jnp.ndarray:
    """Sum of tree outputs (raw score) per row. ``output='leaf'`` returns the
    (N, T) leaf indices (predictLeaf parity — LightGBMBooster.scala:408-419);
    ``output='per_tree'`` returns (N, T) leaf values (for DART drop handling).
    ``nan_bins`` (F,) routes missing-bin values by each split's default_left
    when traversing binned data. ``depth`` bounds the pointer-chase steps —
    pass the forest's true max depth (see ``forest_max_depth``) to skip the
    dead iterations of the worst-case ``num_leaves - 1`` walk."""
    X = jnp.asarray(X, jnp.float32 if not binned else X.dtype)
    L = forest.leaf_value.shape[1]
    depth = max(depth if depth is not None else L - 1, 1)

    mts = forest.missing_type

    def unpack(t):
        if mts is None:
            return t + (None,)
        return t

    xs = (forest.split_feature, forest.threshold, forest.split_bin,
          forest.split_type, forest.default_left, forest.cat_bitset,
          forest.left_child, forest.right_child, forest.leaf_value)
    if mts is not None:
        xs = xs + (mts,)

    if output == "sum":
        # accumulate in the scan CARRY: the stacked (T, N) per-tree buffer
        # is ~4 GB at 11M rows x 100 trees and plain scoring never needs it
        def one_tree_sum(carry, t):
            sf, thr, sbin, stype, dl, bits, lc, rc, lv, mt = unpack(t)
            leaf = _descend(X, sf, thr, sbin, stype, dl, bits, lc, rc,
                            binned, depth, nan_bins, mt)
            return carry + lv[leaf], None

        total, _ = jax.lax.scan(
            one_tree_sum, jnp.zeros(X.shape[0], forest.leaf_value.dtype), xs)
        return total                 # (N,)

    def one_tree(carry, t):
        sf, thr, sbin, stype, dl, bits, lc, rc, lv, mt = unpack(t)
        leaf = _descend(X, sf, thr, sbin, stype, dl, bits, lc, rc, binned,
                        depth, nan_bins, mt)
        val = lv[leaf]
        return carry, (leaf, val)

    _, (leaves, vals) = jax.lax.scan(one_tree, 0, xs)
    if output == "leaf":
        return leaves.T          # (N, T)
    return vals.T                # (N, T)  ("per_tree")


def forest_max_depth(trees: list) -> int:
    """Max internal-node depth across trees (host-side): the exact number of
    pointer-chase steps any row needs. Children are created after their
    parent, so a single forward pass suffices."""
    maxd = 1
    for t in trees:
        ns = int(t.num_splits)
        if ns <= 0:
            continue
        lc = np.asarray(t.left_child)[:ns]
        rc = np.asarray(t.right_child)[:ns]
        # BFS from the root: exact for ANY node ordering (loaded third-party
        # model strings need not create children after parents)
        depth = np.ones(ns, np.int64)
        stack = [0]
        while stack:
            i = stack.pop()
            for c in (lc[i], rc[i]):
                if 0 <= c < ns:
                    depth[c] = depth[i] + 1
                    stack.append(int(c))
        maxd = max(maxd, int(depth.max()))
    return maxd


def stack_trees(trees: list, thresholds: list,
                missing_types: Optional[list] = None) -> Forest:
    """Host-side: stack per-tree TreeArrays (+ real-valued thresholds resolved
    from the BinMapper) into a Forest. ``missing_types`` is a per-tree list of
    (L-1,) arrays of LightGBM missing-type codes (0 none / 1 zero / 2 nan)."""
    def cat(field):
        return jnp.stack([np.asarray(getattr(t, field)) for t in trees])

    return Forest(
        split_feature=cat("split_feature"),
        threshold=jnp.stack([np.asarray(t, np.float32) for t in thresholds]),
        split_bin=cat("split_bin"),
        split_type=cat("split_type"),
        default_left=cat("default_left"),
        cat_bitset=cat("cat_bitset"),
        left_child=cat("left_child"),
        right_child=cat("right_child"),
        leaf_value=cat("leaf_value"),
        missing_type=(None if missing_types is None else jnp.stack(
            [np.asarray(m, np.int32) for m in missing_types])),
    )
