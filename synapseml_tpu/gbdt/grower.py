"""Leaf-wise histogram tree grower — one jitted XLA program per tree.

TPU-native redesign of the LightGBM serial/data-parallel tree learner the
reference drives through LGBM_BoosterUpdateOneIter (reference call stack:
booster/LightGBMBooster.scala:355-392 → C++ ConstructHistograms / FindBestSplit /
Split loop; SURVEY.md §3.1 "the hot loop"). Design choices for XLA (SURVEY §7
"hard parts" — dynamic shapes):

  * The ENTIRE leaf-wise growth loop is a single ``lax.fori_loop`` with static
    shapes: exactly ``num_leaves - 1`` iterations; once no leaf has a valid
    split, remaining iterations no-op.
  * Per iteration, histograms for ALL active leaves are rebuilt with one
    scatter-add keyed by (leaf, feature, bin) (ops/histogram.py). A masked
    single-leaf pass would read the same (N, F) bytes, so recompute-all costs
    the same HBM traffic as LightGBM's smaller-child trick while keeping every
    shape static — and GSPMD turns the same scatter into partial histograms +
    one psum when rows are sharded over the ``data`` mesh axis.
  * Leaf numbering matches LightGBM's Tree::Split: splitting leaf ``l`` at step
    ``i`` creates internal node ``i``; the left child keeps leaf id ``l`` and the
    right child becomes the new leaf ``i + 1``. Child pointers use the
    ``~leaf_index`` convention, so the arrays serialize directly into the
    LightGBM model-string format (gbdt/model_io.py).
  * Categorical splits: bins sorted by grad/(hess + cat_smooth) per (leaf,
    feature), prefix-scan over the sorted order, chosen prefix encoded as a
    bitset — the LightGBM many-vs-many category algorithm, vectorized.
  * Monotone constraints ("basic" mode): candidate child outputs compared
    according to the per-feature constraint sign; violating splits are masked.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.histogram import leaf_histograms

BITS = 32  # bitset word width for categorical splits


class GrowerConfig(NamedTuple):
    """Static (compile-time) grower configuration."""

    num_leaves: int = 31
    num_bins: int = 255
    max_depth: int = -1          # <=0: unlimited (bounded by num_leaves anyway)
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    learning_rate: float = 0.1
    max_delta_step: float = 0.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    has_categorical: bool = False  # static: traces out the categorical path entirely


class TreeArrays(NamedTuple):
    """One grown tree in structure-of-arrays form (serializes to the LightGBM
    model-string fields of the same names — gbdt/model_io.py)."""

    split_feature: jnp.ndarray   # (L-1,) i32
    split_bin: jnp.ndarray       # (L-1,) i32 — bin-space threshold (left if bin <= t)
    split_gain: jnp.ndarray      # (L-1,) f32
    split_type: jnp.ndarray      # (L-1,) i32 — 0 numeric, 1 categorical
    cat_bitset: jnp.ndarray      # (L-1, ceil(B/32)) u32 — membership → left
    left_child: jnp.ndarray      # (L-1,) i32 — >=0 internal node, ~leaf otherwise
    right_child: jnp.ndarray     # (L-1,) i32
    internal_value: jnp.ndarray  # (L-1,) f32 (shrunk output the node would emit)
    internal_count: jnp.ndarray  # (L-1,) i32
    leaf_value: jnp.ndarray      # (L,) f32 (shrinkage applied, LightGBM-style)
    leaf_weight: jnp.ndarray     # (L,) f32 (sum of hessians)
    leaf_count: jnp.ndarray      # (L,) i32
    num_splits: jnp.ndarray      # () i32


def _threshold_l1(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_objective(g, h, l1, l2):
    """LightGBM GetLeafSplitGain: ThresholdL1(G)^2 / (H + l2)."""
    gt = _threshold_l1(g, l1)
    return gt * gt / (h + l2)


def _leaf_output(g, h, cfg: GrowerConfig):
    out = -_threshold_l1(g, cfg.lambda_l1) / (h + cfg.lambda_l2)
    if cfg.max_delta_step > 0:
        out = jnp.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
    return out


@partial(jax.jit, static_argnames=("cfg",))
def grow_tree(
    binned: jnp.ndarray,         # (N, F) uint8/uint16 bin ids
    grad: jnp.ndarray,           # (N,) f32 — pre-weighted (instance weight / GOSS amp)
    hess: jnp.ndarray,           # (N,) f32
    in_bag: jnp.ndarray,         # (N,) f32 — 1 participating, 0 bagged-out/padding
    feature_active: jnp.ndarray, # (F,) bool — feature_fraction mask
    is_categorical: jnp.ndarray, # (F,) bool
    monotone: jnp.ndarray,       # (F,) i32 in {-1, 0, +1}
    cfg: GrowerConfig,
) -> tuple:
    """Grow one tree; returns (TreeArrays, node_of_row) where node_of_row is each
    row's final leaf index (used for the O(1) training-score update)."""
    n, f = binned.shape
    L, B = cfg.num_leaves, cfg.num_bins
    bw = (B + BITS - 1) // BITS
    g = jnp.asarray(grad, jnp.float32) * in_bag
    h = jnp.asarray(hess, jnp.float32) * in_bag

    l1 = jnp.float32(cfg.lambda_l1)
    l2 = jnp.float32(cfg.lambda_l2)

    def best_splits(hist):
        """Per-leaf best split over all (feature, bin)/(feature, prefix).
        hist: (L, F, B, 3) → gain (L,), feat (L,), bin (L,), plus totals."""
        totals = hist[:, 0, :, :].sum(axis=1)                    # (L, 3) — feature 0 partitions the leaf
        G, H, C = totals[:, 0], totals[:, 1], totals[:, 2]
        parent_obj = _leaf_objective(G, H, l1, l2)                # (L,)

        def scan_gains(cum):
            GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
            GR = G[:, None, None] - GL
            HR = H[:, None, None] - HL
            CR = C[:, None, None] - CL
            gain = (_leaf_objective(GL, HL, l1, l2) + _leaf_objective(GR, HR, l1, l2)
                    - parent_obj[:, None, None])
            valid = ((CL >= cfg.min_data_in_leaf) & (CR >= cfg.min_data_in_leaf)
                     & (HL >= cfg.min_sum_hessian_in_leaf)
                     & (HR >= cfg.min_sum_hessian_in_leaf))
            return gain, valid, (GL, HL, GR, HR)

        # numeric: natural bin order
        cum_num = jnp.cumsum(hist, axis=2)
        gain_num, valid_num, (GL, HL, GR, HR) = scan_gains(cum_num)
        mc = monotone[None, :, None]
        vl = -GL / (HL + l2)
        vr = -GR / (HR + l2)
        mono_ok = jnp.where(mc == 0, True,
                            jnp.where(mc > 0, vl <= vr, vl >= vr))
        gain_num = jnp.where(valid_num & mono_ok, gain_num, -jnp.inf)

        if cfg.has_categorical:
            # categorical: sort bins by G/(H + cat_smooth), empty bins last
            cnt = hist[..., 2]
            key = jnp.where(cnt > 0, hist[..., 0] / (hist[..., 1] + cfg.cat_smooth), jnp.inf)
            order = jnp.argsort(key, axis=2)                     # (L, F, B)
            hist_sorted = jnp.take_along_axis(hist, order[..., None], axis=2)
            cum_cat = jnp.cumsum(hist_sorted, axis=2)
            gain_cat, valid_cat, _ = scan_gains(cum_cat)
            k = jnp.arange(B)[None, None, :]
            nonempty = (cnt > 0).sum(axis=2)[:, :, None]
            valid_k = (k < cfg.max_cat_threshold) & (k < nonempty)
            gain_cat = jnp.where(valid_cat & valid_k, gain_cat, -jnp.inf)
            gain = jnp.where(is_categorical[None, :, None], gain_cat, gain_num)
        else:
            order = None
            gain = gain_num
        gain = jnp.where(feature_active[None, :, None], gain, -jnp.inf)

        flat = gain.reshape(L, f * B)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        return best_gain, (best // B).astype(jnp.int32), (best % B).astype(jnp.int32), order, totals

    neg1 = -jnp.ones((), jnp.int32)

    class S(NamedTuple):
        node_of_row: jnp.ndarray
        depth: jnp.ndarray
        leaf_parent: jnp.ndarray
        leaf_is_right: jnp.ndarray
        split_feature: jnp.ndarray
        split_bin: jnp.ndarray
        split_gain: jnp.ndarray
        split_type: jnp.ndarray
        cat_bitset: jnp.ndarray
        left_child: jnp.ndarray
        right_child: jnp.ndarray
        internal_value: jnp.ndarray
        internal_count: jnp.ndarray
        num_splits: jnp.ndarray

    init = S(
        node_of_row=jnp.zeros((n,), jnp.int32),
        depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_is_right=jnp.zeros((L,), bool),
        split_feature=jnp.zeros((max(L - 1, 1),), jnp.int32),
        split_bin=jnp.full((max(L - 1, 1),), B - 1, jnp.int32),
        split_gain=jnp.zeros((max(L - 1, 1),), jnp.float32),
        split_type=jnp.zeros((max(L - 1, 1),), jnp.int32),
        cat_bitset=jnp.zeros((max(L - 1, 1), bw), jnp.uint32),
        left_child=jnp.full((max(L - 1, 1),), ~0, jnp.int32),
        right_child=jnp.full((max(L - 1, 1),), ~0, jnp.int32),
        internal_value=jnp.zeros((max(L - 1, 1),), jnp.float32),
        internal_count=jnp.zeros((max(L - 1, 1),), jnp.int32),
        num_splits=jnp.zeros((), jnp.int32),
    )

    def body(i, s: S):
        hist = leaf_histograms(binned, jnp.where(in_bag > 0, s.node_of_row, -1),
                               g, h, L, B)
        best_gain, best_feat, best_bin, order, totals = best_splits(hist)

        leaf_ids = jnp.arange(L)
        active = leaf_ids <= i
        if cfg.max_depth > 0:
            active &= s.depth < cfg.max_depth
        # a leaf is only splittable if it was actually created (i.e. <= num_splits)
        active &= leaf_ids <= s.num_splits
        masked_gain = jnp.where(active, best_gain, -jnp.inf)
        l = jnp.argmax(masked_gain).astype(jnp.int32)
        gain_l = masked_gain[l]
        do = gain_l > cfg.min_gain_to_split
        fsel = best_feat[l]
        bsel = best_bin[l]
        rows_bin = binned[:, fsel].astype(jnp.int32)
        if cfg.has_categorical:
            is_cat = is_categorical[fsel]
            # categorical bitset: first (bsel+1) bins in sorted order go left
            order_lf = order[l, fsel]                            # (B,)
            take = jnp.arange(B) <= bsel
            bit_words = (order_lf >> 5).astype(jnp.int32)
            bit_vals = (jnp.uint32(1) << (order_lf & 31).astype(jnp.uint32))
            bitset = jnp.zeros((bw,), jnp.uint32).at[bit_words].add(
                jnp.where(take, bit_vals, jnp.uint32(0)))
            member = ((bitset[rows_bin >> 5] >> (rows_bin & 31).astype(jnp.uint32)) & 1).astype(bool)
            go_right = jnp.where(is_cat, ~member, rows_bin > bsel)
        else:
            is_cat = jnp.zeros((), bool)
            bitset = jnp.zeros((bw,), jnp.uint32)
            go_right = rows_bin > bsel
        new_node = jnp.where(do & (s.node_of_row == l) & go_right, i + 1, s.node_of_row)

        # tree bookkeeping for internal node i
        G_l, H_l, C_l = totals[l, 0], totals[l, 1], totals[l, 2]
        parent_out = _leaf_output(G_l, H_l, cfg) * cfg.learning_rate

        def setw(arr, idx, val):
            return arr.at[idx].set(jnp.where(do, val, arr[idx]))

        p = s.leaf_parent[l]
        p_idx = jnp.maximum(p, 0)
        lc = s.left_child.at[p_idx].set(
            jnp.where(do & (p >= 0) & ~s.leaf_is_right[l], i, s.left_child[p_idx]))
        rc = s.right_child.at[p_idx].set(
            jnp.where(do & (p >= 0) & s.leaf_is_right[l], i, s.right_child[p_idx]))
        lc = lc.at[i].set(jnp.where(do, ~l, lc[i]))
        rc = rc.at[i].set(jnp.where(do, ~(i + 1), rc[i]))

        return S(
            node_of_row=new_node,
            depth=s.depth.at[l].add(jnp.where(do, 1, 0))
                        .at[i + 1].set(jnp.where(do, s.depth[l] + 1, s.depth[i + 1])),
            leaf_parent=s.leaf_parent.at[l].set(jnp.where(do, i, s.leaf_parent[l]))
                                  .at[i + 1].set(jnp.where(do, i, s.leaf_parent[i + 1])),
            leaf_is_right=s.leaf_is_right.at[l].set(jnp.where(do, False, s.leaf_is_right[l]))
                                     .at[i + 1].set(jnp.where(do, True, s.leaf_is_right[i + 1])),
            split_feature=setw(s.split_feature, i, fsel),
            split_bin=setw(s.split_bin, i, bsel),
            split_gain=setw(s.split_gain, i, gain_l),
            split_type=setw(s.split_type, i, is_cat.astype(jnp.int32)),
            cat_bitset=s.cat_bitset.at[i].set(jnp.where(do, bitset, s.cat_bitset[i])),
            left_child=lc,
            right_child=rc,
            internal_value=setw(s.internal_value, i, parent_out),
            internal_count=setw(s.internal_count, i, C_l.astype(jnp.int32)),
            num_splits=s.num_splits + jnp.where(do, 1, 0),
        )

    s = jax.lax.fori_loop(0, L - 1, body, init) if L > 1 else init

    # final leaf stats from the terminal assignment
    vals = jnp.stack([g, h, in_bag], -1)
    leaf_tot = jnp.zeros((L, 3), jnp.float32).at[
        jnp.where(in_bag > 0, s.node_of_row, L)].add(vals, mode="drop")
    leaf_value = _leaf_output(leaf_tot[:, 0], leaf_tot[:, 1], cfg) * cfg.learning_rate
    # leaves that never came into existence emit 0 (they are unreachable anyway)
    exists = jnp.arange(L) <= s.num_splits
    leaf_value = jnp.where(exists, leaf_value, 0.0)

    tree = TreeArrays(
        split_feature=s.split_feature,
        split_bin=s.split_bin,
        split_gain=s.split_gain,
        split_type=s.split_type,
        cat_bitset=s.cat_bitset,
        left_child=s.left_child,
        right_child=s.right_child,
        internal_value=s.internal_value,
        internal_count=s.internal_count,
        leaf_value=leaf_value,
        leaf_weight=leaf_tot[:, 1],
        leaf_count=leaf_tot[:, 2].astype(jnp.int32),
        num_splits=s.num_splits,
    )
    return tree, s.node_of_row


# ---------------------------------------------------------------------------
# Stacked-forest prediction
# ---------------------------------------------------------------------------

class Forest(NamedTuple):
    """All trees stacked on a leading tree axis; ``threshold`` is in raw feature
    space (bin upper bounds), ``split_bin`` in bin space (for binned traversal).
    Inference is a ``lax.scan`` over trees of a vectorized pointer-chase, batched
    over rows — the reference instead does row-at-a-time JNI predict
    (LightGBMBooster.scala:520-560), which SURVEY §3.2 flags as unbatched."""

    split_feature: jnp.ndarray   # (T, L-1)
    threshold: jnp.ndarray       # (T, L-1) f32
    split_bin: jnp.ndarray       # (T, L-1) i32
    split_type: jnp.ndarray      # (T, L-1) i32
    cat_bitset: jnp.ndarray      # (T, L-1, BW) u32
    left_child: jnp.ndarray      # (T, L-1)
    right_child: jnp.ndarray     # (T, L-1)
    leaf_value: jnp.ndarray      # (T, L)

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_leaves(self) -> int:
        return self.leaf_value.shape[1]


def _descend(X, sf, thr, sbin, stype, bits, lc, rc, binned: bool, depth: int):
    """Vectorized pointer-chase for one tree; returns leaf index per row."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def step(_, node):
        f = sf[jnp.maximum(node, 0)]
        x = jnp.take_along_axis(X, f[:, None].astype(jnp.int32), axis=1)[:, 0]
        if binned:
            num_right = x.astype(jnp.int32) > sbin[jnp.maximum(node, 0)]
            c = x.astype(jnp.int32)
        else:
            t = thr[jnp.maximum(node, 0)]
            num_right = ~(x <= t)          # NaN → right
            c = jnp.clip(jnp.nan_to_num(x, nan=-1.0), -1, bits.shape[1] * BITS - 1).astype(jnp.int32)
        cw = jnp.maximum(c, 0)
        word = bits[jnp.maximum(node, 0), cw >> 5]
        member = ((word >> (cw & 31).astype(jnp.uint32)) & 1).astype(bool) & (c >= 0)
        is_cat = stype[jnp.maximum(node, 0)] == 1
        go_right = jnp.where(is_cat, ~member, num_right)
        nxt = jnp.where(go_right, rc[jnp.maximum(node, 0)], lc[jnp.maximum(node, 0)])
        return jnp.where(node < 0, node, nxt)

    node = jax.lax.fori_loop(0, depth, step, node)
    return ~node  # leaf index


@partial(jax.jit, static_argnames=("binned", "output"))
def forest_predict(forest: Forest, X: jnp.ndarray, binned: bool = False,
                   output: str = "sum") -> jnp.ndarray:
    """Sum of tree outputs (raw score) per row. ``output='leaf'`` returns the
    (N, T) leaf indices (predictLeaf parity — LightGBMBooster.scala:408-419);
    ``output='per_tree'`` returns (N, T) leaf values (for DART drop handling)."""
    X = jnp.asarray(X, jnp.float32 if not binned else X.dtype)
    L = forest.leaf_value.shape[1]
    depth = max(L - 1, 1)

    def one_tree(carry, t):
        sf, thr, sbin, stype, bits, lc, rc, lv = t
        leaf = _descend(X, sf, thr, sbin, stype, bits, lc, rc, binned, depth)
        val = lv[leaf]
        return carry, (leaf, val)

    _, (leaves, vals) = jax.lax.scan(
        one_tree, 0,
        (forest.split_feature, forest.threshold, forest.split_bin, forest.split_type,
         forest.cat_bitset, forest.left_child, forest.right_child, forest.leaf_value))
    if output == "leaf":
        return leaves.T          # (N, T)
    if output == "per_tree":
        return vals.T            # (N, T)
    return vals.sum(axis=0)      # (N,)


def stack_trees(trees: list, thresholds: list) -> Forest:
    """Host-side: stack per-tree TreeArrays (+ real-valued thresholds resolved
    from the BinMapper) into a Forest."""
    def cat(field):
        return jnp.stack([np.asarray(getattr(t, field)) for t in trees])

    return Forest(
        split_feature=cat("split_feature"),
        threshold=jnp.stack([np.asarray(t, np.float32) for t in thresholds]),
        split_bin=cat("split_bin"),
        split_type=cat("split_type"),
        cat_bitset=cat("cat_bitset"),
        left_child=cat("left_child"),
        right_child=cat("right_child"),
        leaf_value=cat("leaf_value"),
    )
