"""LightGBM-compatible model-string serialization.

The reference's model artifact IS the LightGBM text model string (saved via
saveNativeModel, LightGBMBooster.scala:458-470; loaded into models at
LightGBMClassifier.scala:196-211). Emitting the same format keeps trained models
interoperable with the LightGBM ecosystem (native lib, treelite, shap, ...), and
lets this framework load models trained elsewhere.

Format notes (LightGBM `tree` v3 text format):
  * child pointers: >= 0 → internal node index, negative → ~leaf_index
  * decision_type bitfield: bit0 categorical, bit1 default_left, bits2-3
    missing_type (0 none, 1 zero, 2 nan). Splits on features with missing
    values emit missing_type=nan plus the LEARNED default_left bit
    (grower.py); features seen without NaN emit missing_type=none.
  * categorical thresholds: `threshold` holds an index into cat_boundaries;
    cat_threshold stores uint32 bitset words.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..ops.quantize import BinMapper
from .grower import TreeArrays

_DT_CAT = 1
_DT_DEFAULT_LEFT = 2
_DT_MISSING_NAN = 8


def _fmt(arr, fmt="{:g}") -> str:
    return " ".join(fmt.format(x) for x in arr)


def _tree_dump_seq(booster, num_iteration: int = -1):
    """Shared per-tree serialization inputs for the text and JSON dumps:
    yields (index, tree, thresholds, weight, base_shift). LightGBM stores no
    base score, so boost_from_average folds into the first tree of each class
    (every tree when the output is averaged — the mean shifts by base)."""
    k = booster.models_per_iter
    trees = booster.trees
    if num_iteration and num_iteration > 0:
        trees = trees[: num_iteration * k]
    for ti, tree in enumerate(trees):
        if booster.average_output:
            base_shift = float(booster.base_score[ti % k])
        elif ti < k:
            base_shift = float(booster.base_score[ti])
        else:
            base_shift = 0.0
        yield ti, tree, booster._thresholds(ti), booster.tree_weights[ti], \
            base_shift


def booster_to_string(booster) -> str:
    cfg = booster.config
    mapper: BinMapper = booster.mapper
    k = booster.models_per_iter
    lines: List[str] = [
        "tree",
        "version=v3",
        f"num_class={booster.num_class}",
        f"num_tree_per_iteration={k}",
        "label_index=0",
        f"max_feature_idx={mapper.num_features - 1}",
        f"objective={_objective_string(cfg)}",
        ("average_output" if booster.average_output else ""),
        "feature_names=" + " ".join(booster.feature_names),
        "feature_infos=" + " ".join(_feature_info(mapper, j) for j in range(mapper.num_features)),
    ]
    lines = [l for l in lines if l != ""]

    tree_blocks = [
        _tree_to_string(ti, tree, thr, w, cfg.learning_rate, base_shift,
                        booster._missing_types(ti))
        for ti, tree, thr, w, base_shift in _tree_dump_seq(booster)]
    sizes = [len(b) + 1 for b in tree_blocks]
    lines.append("tree_sizes=" + " ".join(str(s) for s in sizes))
    lines.append("")
    out = "\n".join(lines) + "\n" + "\n".join(tree_blocks)
    out += "\nend of trees\n\nfeature_importances:\n"
    imp = booster.feature_importances("split")
    order = np.argsort(-imp)
    for j in order:
        if imp[j] > 0:
            out += f"{booster.feature_names[j]}={int(imp[j])}\n"
    out += "\nparameters:\n[boosting: {}]\n[objective: {}]\n[learning_rate: {}]\n[num_leaves: {}]\nend of parameters\n".format(
        cfg.boosting_type, cfg.objective, cfg.learning_rate, cfg.num_leaves)
    out += "\npandas_categorical:null\n"
    return out


def _objective_string(cfg) -> str:
    """Objective + its hyper-parameters, exactly as native LightGBM stores
    them (GBDT::SaveModelToString writes objective->ToString()): loading the
    file elsewhere must reproduce the same link/loss parameters."""
    if cfg.objective == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if cfg.objective in ("multiclass", "softmax"):
        return f"multiclass num_class:{cfg.num_class}"
    if cfg.objective == "multiclassova":
        return f"multiclassova num_class:{cfg.num_class} sigmoid:{cfg.sigmoid:g}"
    if cfg.objective == "lambdarank":
        return "lambdarank"
    if cfg.objective == "quantile":
        return f"quantile alpha:{cfg.alpha:g}"
    if cfg.objective == "huber":
        return f"huber alpha:{cfg.alpha:g}"
    if cfg.objective == "fair":
        return f"fair fair_c:{cfg.fair_c:g}"
    if cfg.objective == "poisson":
        return f"poisson max_delta_step:{cfg.poisson_max_delta_step:g}"
    if cfg.objective == "tweedie":
        return (f"tweedie "
                f"tweedie_variance_power:{cfg.tweedie_variance_power:g}")
    if cfg.objective in ("cross_entropy", "xentropy"):
        # native LightGBM stores the canonical name; its model loader does
        # not resolve config-level aliases
        return "cross_entropy"
    return cfg.objective


def _feature_info(mapper: BinMapper, j: int) -> str:
    if mapper.is_categorical[j]:
        nb = int(mapper.num_bins[j])
        return ":".join(str(i) for i in range(max(nb - 1, 1)))
    b = mapper.boundaries[j]
    finite = b[np.isfinite(b)]
    if finite.size == 0:
        return "none"
    return f"[{finite[0]:g}:{finite[-1]:g}]"


def _tree_to_string(index: int, tree: TreeArrays, thresholds: np.ndarray,
                    weight: float, shrinkage: float, base_shift: float = 0.0,
                    missing_types=None) -> str:
    ns = int(tree.num_splits)
    nleaves = ns + 1
    sf = np.asarray(tree.split_feature)[:ns]
    stype = np.asarray(tree.split_type)[:ns]
    dleft = np.asarray(tree.default_left)[:ns]
    thr = np.asarray(thresholds)[:ns].astype(np.float64)
    lc = np.asarray(tree.left_child)[:ns]
    rc = np.asarray(tree.right_child)[:ns]
    lv = np.asarray(tree.leaf_value)[:nleaves].astype(np.float64) * weight + base_shift
    lw = np.asarray(tree.leaf_weight)[:nleaves]
    lcnt = np.asarray(tree.leaf_count)[:nleaves]
    gain = np.asarray(tree.split_gain)[:ns]
    iv = np.asarray(tree.internal_value)[:ns]
    icnt = np.asarray(tree.internal_count)[:ns]
    bits = np.asarray(tree.cat_bitset)[:ns]

    # leaf pointers beyond the actual leaf count can appear when num_splits <
    # num_leaves-1; clamp any dangling internal pointer to a leaf
    def fix_child(c):
        return np.where((c >= 0) & (c < ns), c, np.where(c >= 0, ~0, c))

    lc, rc = fix_child(lc), fix_child(rc)

    # missing codes come from the booster (Booster._missing_types: parsed
    # values for loaded models, NaN-mask-derived otherwise) so a loaded
    # native model's zero/none codes survive a save round trip verbatim
    mt = (np.asarray(missing_types, np.int64)[:ns]
          if missing_types is not None and len(sf)
          else np.zeros(len(sf), np.int64))
    dt = (np.where(stype == 1, _DT_CAT, 0)
          + np.where(dleft, _DT_DEFAULT_LEFT, 0)
          + (np.clip(mt, 0, 3) << 2))

    lines = [f"Tree={index}", f"num_leaves={max(nleaves, 1)}"]
    cat_lines = []
    if (stype == 1).any():
        # threshold for categorical nodes = index into cat_boundaries
        cat_idx = np.cumsum(stype) - 1
        thr = np.where(stype == 1, cat_idx.astype(np.float64), thr)
        bw = bits.shape[1]
        boundaries = [0]
        words: List[int] = []
        for i in range(ns):
            if stype[i] == 1:
                words.extend(int(w) for w in bits[i])
                boundaries.append(len(words))
        cat_lines = [f"num_cat={int((stype == 1).sum())}",
                     "cat_boundaries=" + _fmt(boundaries, "{:d}"),
                     "cat_threshold=" + _fmt(words, "{:d}")]
    else:
        lines.append("num_cat=0")

    if ns == 0:
        # single-leaf tree: LightGBM emits leaf_value only
        lines += cat_lines
        lines.append("leaf_value=" + _fmt(lv, "{:.17g}"))
        lines.append(f"shrinkage={shrinkage:g}")
        return "\n".join(lines) + "\n"

    lines += [
        "split_feature=" + _fmt(sf, "{:d}"),
        "split_gain=" + _fmt(gain),
        "threshold=" + _fmt(thr, "{:.17g}"),
        "decision_type=" + _fmt(dt, "{:d}"),
        "left_child=" + _fmt(lc, "{:d}"),
        "right_child=" + _fmt(rc, "{:d}"),
        "leaf_value=" + _fmt(lv, "{:.17g}"),
        "leaf_weight=" + _fmt(lw),
        "leaf_count=" + _fmt(lcnt, "{:d}"),
        "internal_value=" + _fmt(iv),
        # internal hessian sums are not tracked separately; counts are the
        # closest available weight proxy (harmless to downstream loaders)
        "internal_weight=" + _fmt(np.maximum(icnt.astype(np.float64), 1.0)),
        "internal_count=" + _fmt(icnt, "{:d}"),
    ] + cat_lines + [
        "is_linear=0",
        f"shrinkage={shrinkage:g}",
    ]
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Parsing (load models produced by us or by native LightGBM)
# ---------------------------------------------------------------------------

def _hdr_int(hdr, name, default):
    """Header integer with a clear diagnosis on garbage (a torn download or
    binary splice lands here, not in an int() traceback)."""
    try:
        return int(hdr.get(name, default))
    except (TypeError, ValueError):
        raise ValueError(
            f"corrupt LightGBM model string: header field {name!r} is not "
            f"an integer (got {hdr.get(name)!r})") from None


def booster_from_string(s: str):
    from .boosting import Booster, BoosterConfig

    if not s.lstrip().startswith("tree"):
        raise ValueError("not a LightGBM model string (must start with 'tree')")
    header, _, rest = s.partition("\nTree=")
    if not rest:
        raise ValueError("model string contains no trees")
    if "end of trees" not in rest:
        # every writer (ours and native LightGBM's) terminates the tree
        # section; its absence means the file was truncated mid-stream
        raise ValueError(
            "truncated LightGBM model string: missing 'end of trees' "
            "terminator — the file was cut off mid-write or mid-download")
    hdr = {}
    for line in header.splitlines():
        if "=" in line:
            key, _, val = line.partition("=")
            hdr[key.strip()] = val.strip()
    num_class = _hdr_int(hdr, "num_class", 1)
    ntpi = _hdr_int(hdr, "num_tree_per_iteration", 1)
    obj_str = hdr.get("objective", "regression").split()
    objective = obj_str[0] if obj_str else "regression"
    feature_names = hdr.get("feature_names", "").split()
    nfeat = _hdr_int(hdr, "max_feature_idx", len(feature_names) - 1) + 1
    average_output = "average_output" in header

    cfg = BoosterConfig(objective=objective, num_class=num_class,
                        boosting_type="rf" if average_output else "gbdt")
    # objective hyper-parameters (the native writer appends them as
    # name:value tokens — see _objective_string)
    _obj_fields = {"sigmoid": "sigmoid", "alpha": "alpha",
                   "fair_c": "fair_c",
                   "max_delta_step": "poisson_max_delta_step",
                   "tweedie_variance_power": "tweedie_variance_power"}
    for tok in obj_str[1:]:
        name, _, val = tok.partition(":")
        if name in _obj_fields and val:
            try:
                setattr(cfg, _obj_fields[name], float(val))
            except ValueError:
                pass

    trees = []
    max_leaves = 2
    blocks = ("Tree=" + rest).split("\nTree=")
    parsed = []
    for b in blocks:
        if not b.strip() or b.startswith("end of trees"):
            continue
        body = b.split("end of trees")[0]
        fields = {}
        for line in body.splitlines():
            if "=" in line:
                key, _, val = line.partition("=")
                fields[key.strip()] = val.strip()
        parsed.append(fields)
        try:
            nl = int(fields.get("num_leaves", 1))
        except ValueError:
            raise ValueError(
                f"corrupt LightGBM model string: tree {len(parsed) - 1} has "
                f"non-integer num_leaves={fields.get('num_leaves')!r}") \
                from None
        # a split tree with no structure arrays is a torn tree block, not a
        # model (single-leaf trees legitimately carry only leaf_value)
        if nl > 1:
            missing = [f for f in ("split_feature", "threshold", "left_child",
                                   "right_child", "leaf_value")
                       if not fields.get(f)]
            if missing:
                raise ValueError(
                    f"corrupt/truncated LightGBM model string: tree "
                    f"{len(parsed) - 1} declares num_leaves={nl} but lacks "
                    f"required fields {missing}")
        max_leaves = max(max_leaves, nl)

    # bitset width: wide enough for the largest categorical node in the model
    # (native LightGBM models can exceed 256 categories)
    bw = 8
    for fields in parsed:
        if int(fields.get("num_cat", 0)) > 0 and fields.get("cat_boundaries"):
            bounds = np.array(fields["cat_boundaries"].split(), dtype=np.int64)
            if len(bounds) > 1:
                bw = max(bw, int(np.diff(bounds).max()))
    mtypes_all = []
    for tree_idx, fields in enumerate(parsed):
        nleaves = int(fields.get("num_leaves", 1))
        ns = nleaves - 1
        L = max_leaves

        def arr(name, dtype, size, default=0):
            if name in fields and fields[name]:
                try:
                    a = np.array(fields[name].split(), dtype=np.float64)
                except ValueError:
                    raise ValueError(
                        f"corrupt LightGBM model string: tree {tree_idx} "
                        f"field {name!r} contains non-numeric data "
                        f"({fields[name][:60]!r})") from None
            else:
                a = np.full(size, default, np.float64)
            out = np.full(max(size, 1), default, np.float64)
            out[: min(len(a), size)] = a[:size]
            return out.astype(dtype)

        sf = arr("split_feature", np.int32, max(L - 1, 1))
        thr = arr("threshold", np.float32, max(L - 1, 1))
        dt = arr("decision_type", np.int32, max(L - 1, 1))
        lc = arr("left_child", np.int32, max(L - 1, 1), ~0)
        rc = arr("right_child", np.int32, max(L - 1, 1), ~0)
        lv = arr("leaf_value", np.float32, L)
        lw = arr("leaf_weight", np.float32, L)
        lcn = arr("leaf_count", np.int32, L)
        gain = arr("split_gain", np.float32, max(L - 1, 1))
        iv = arr("internal_value", np.float32, max(L - 1, 1))
        icn = arr("internal_count", np.int32, max(L - 1, 1))
        stype = (dt & 1).astype(np.int32)
        dleft = ((dt >> 1) & 1).astype(bool)
        # 0 none / 1 zero / 2 nan — drives the raw-traversal missing routing
        mtypes_all.append(((dt >> 2) & 3).astype(np.int32))

        bitset = np.zeros((max(L - 1, 1), bw), np.uint32)
        if int(fields.get("num_cat", 0)) > 0:
            try:
                bounds = np.array(fields["cat_boundaries"].split(),
                                  dtype=np.int64)
                words = np.array(fields["cat_threshold"].split(),
                                 dtype=np.uint64)
            except (KeyError, ValueError):
                raise ValueError(
                    f"corrupt LightGBM model string: tree {tree_idx} "
                    "declares num_cat>0 but its cat_boundaries/"
                    "cat_threshold are missing or non-numeric") from None
            ci = 0
            for i in range(ns):
                if stype[i]:
                    if ci + 1 >= len(bounds):
                        raise ValueError(
                            f"corrupt LightGBM model string: tree "
                            f"{tree_idx} has more categorical nodes than "
                            "cat_boundaries entries")
                    w = words[bounds[ci]: bounds[ci + 1]]
                    bitset[i, : len(w)] = w.astype(np.uint32)
                    ci += 1
                    thr[i] = 0.0

        trees.append(TreeArrays(
            split_feature=sf, split_bin=np.zeros_like(sf), split_gain=gain,
            split_type=stype, default_left=dleft, cat_bitset=bitset,
            left_child=lc, right_child=rc,
            internal_value=iv, internal_count=icn, leaf_value=lv, leaf_weight=lw,
            leaf_count=lcn, num_splits=np.int32(ns)))

    # synthesize a mapper (loaded models predict from raw values only); the
    # parsed real-valued thresholds ride along as explicit overrides
    mapper = BinMapper(boundaries=np.full((nfeat, 254), np.inf, np.float32),
                       num_bins=np.full(nfeat, 255, np.int32),
                       is_categorical=np.zeros(nfeat, bool), max_bin=255)
    thresholds = _collect_thr(parsed, max_leaves)
    return Booster(mapper, cfg, trees, [1.0] * len(trees),
                   np.zeros(max(num_class, 1)),
                   feature_names if feature_names else None,
                   thresholds=thresholds, missing_types=mtypes_all)


def _collect_thr(parsed, L):
    out = []
    for fields in parsed:
        size = max(L - 1, 1)
        if "threshold" in fields and fields["threshold"]:
            a = np.array(fields["threshold"].split(), dtype=np.float64)
        else:
            a = np.zeros(size)
        pad = np.zeros(size)
        pad[: min(len(a), size)] = a[:size]
        out.append(pad.astype(np.float32))
    return out


# ---------------------------------------------------------------------------
# JSON dump (LightGBMBooster.dumpModel parity — LightGBMBooster.scala:458-516)
# ---------------------------------------------------------------------------

def _tree_to_json(index: int, tree: TreeArrays, thresholds, weight: float,
                  shrinkage: float, base_shift: float = 0.0,
                  missing_types=None) -> dict:
    ns = int(tree.num_splits)
    sf = np.asarray(tree.split_feature)[:ns]
    stype = np.asarray(tree.split_type)[:ns]
    dleft = np.asarray(tree.default_left)[:ns]
    thr = np.asarray(thresholds)[:ns].astype(np.float64)
    lc = np.asarray(tree.left_child)[:ns]
    rc = np.asarray(tree.right_child)[:ns]
    # same base-score fold as the text serializer: LightGBM models carry no
    # separate base score, so a dump consumer summing leaves must see it
    lv = (np.asarray(tree.leaf_value).astype(np.float64) * weight + base_shift)
    lw = np.asarray(tree.leaf_weight).astype(np.float64)
    lcnt = np.asarray(tree.leaf_count)
    gain = np.asarray(tree.split_gain).astype(np.float64)
    iv = np.asarray(tree.internal_value).astype(np.float64)
    icnt = np.asarray(tree.internal_count)
    bits = np.asarray(tree.cat_bitset)[:ns]
    mt = (np.asarray(missing_types, np.int64)[:ns]
          if missing_types is not None and len(sf)
          else np.zeros(len(sf), np.int64))

    # dangling internal pointers (num_splits < num_leaves-1) clamp to leaf 0,
    # exactly like the text serializer's fix_child
    def fix_child(c):
        return int(c) if (c < 0 or c < ns) else ~0

    def leaf_node(leaf: int) -> dict:
        return {"leaf_index": int(leaf), "leaf_value": float(lv[leaf]),
                "leaf_weight": float(lw[leaf]), "leaf_count": int(lcnt[leaf])}

    def internal_node(i: int) -> dict:
        cat = bool(stype[i] == 1)
        if cat:
            # LightGBM JSON encodes the left-going category set as "a||b||c"
            cats = [str(b) for b in range(bits.shape[1] * 32)
                    if (int(bits[i][b >> 5]) >> (b & 31)) & 1]
            threshold = "||".join(cats)
        else:
            threshold = float(thr[i])
        return {
            "split_index": int(i),
            "split_feature": int(sf[i]),
            "split_gain": float(gain[i]),
            "threshold": threshold,
            "decision_type": "==" if cat else "<=",
            "default_left": bool(dleft[i]),
            "missing_type": {0: "None", 1: "Zero", 2: "NaN"}.get(
                int(mt[i]), "None"),
            "internal_value": float(iv[i]),
            "internal_weight": float(max(int(icnt[i]), 1)),
            "internal_count": int(icnt[i]),
        }

    if ns == 0:
        structure = leaf_node(0)
    else:
        # iterative build (deep skewed trees exceed Python's recursion limit)
        structure = internal_node(0)
        stack = [(structure, "left_child", fix_child(lc[0])),
                 (structure, "right_child", fix_child(rc[0]))]
        while stack:
            parent, slot, child = stack.pop()
            if child < 0:
                parent[slot] = leaf_node(~child)
            else:
                nd = internal_node(child)
                parent[slot] = nd
                stack.append((nd, "left_child", fix_child(lc[child])))
                stack.append((nd, "right_child", fix_child(rc[child])))

    return {"tree_index": index,
            "num_leaves": max(ns + 1, 1),
            "num_cat": int((stype == 1).sum()),
            "shrinkage": float(shrinkage),
            "tree_structure": structure}


def booster_dump_json(booster, num_iteration: int = -1) -> str:
    """LightGBM-format JSON model dump (``dumpModel`` parity): the same
    recursive ``tree_structure`` layout lightgbm's own dump_model emits,
    including the base-score fold and "a||b" categorical thresholds. For rf
    boosting, leaves are UNscaled and ``average_output`` is true — the
    consumer averages, as with native dumps."""
    import json

    cfg = booster.config
    mapper = booster.mapper
    k = booster.models_per_iter
    tree_info = [
        _tree_to_json(i, t, thr, w, cfg.learning_rate, base_shift,
                      booster._missing_types(i))
        for i, t, thr, w, base_shift in _tree_dump_seq(booster, num_iteration)]
    doc = {
        "name": "tree",
        "version": "v3",
        "num_class": booster.num_class if k > 1 else 1,
        "num_tree_per_iteration": k,
        "label_index": 0,
        "max_feature_idx": (mapper.num_features - 1) if mapper else 0,
        "objective": _objective_string(cfg),
        "average_output": bool(booster.average_output),
        "feature_names": list(booster.feature_names),
        "monotone_constraints": list(cfg.monotone_constraints or []),
        "tree_info": tree_info,
    }
    return json.dumps(doc)
