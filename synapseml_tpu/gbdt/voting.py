"""Voting-parallel feature selection (PV-Tree).

Reference: LightGBM's ``voting_parallel`` tree learner, surfaced through
``parallelism``/``topK`` (lightgbm/.../params/LightGBMParams.scala:25-27,
LightGBMConstants.scala:22-24 DefaultTopK=20, LightGBMBase.scala:252). In
data-parallel mode every split synchronizes histograms for ALL features;
voting-parallel cuts that to O(top_k): each worker votes its local top-k
features by split gain, the global top-2k by votes (gain-sum tie-break) are
selected, and only those features' histograms are aggregated.

TPU adaptation: selection runs once per tree at the root (one shard_map with a
``psum`` of per-feature gains + votes — cheap, (F,)-sized); the tree then grows
on the SLICED (N, 2k) bin matrix, so every per-leaf histogram allreduce inside
the growth loop moves 2k features instead of F. Split feature indices are
remapped to the full feature space afterwards.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map
from ..parallel.mesh import DATA_AXIS

# per-shard row budget for the root-selection pass: the PV-Tree vote is a
# rank statistic over 2k-of-F features, robust under row subsampling, and an
# unsampled selection pass at large shards costs a visible fraction of the
# tree it elects features for (r05: 11 s/tree eager+unsampled). Strided
# sampling (not a prefix — label-sorted inputs stay representative) with
# contributions scaled back by the stride keeps G/H/count magnitudes
# unbiased for the min_data validity filter.
DEFAULT_SELECTION_SAMPLE_ROWS = 4096


def _per_feature_root_gain(binned, g, h, in_bag, num_bins: int,
                           lambda_l2: float, min_data: int):
    """(F,) best numeric-split gain per feature over the root node, from this
    shard's rows only. Counts use ``in_bag`` so padding/bagged-out rows do not
    inflate the min_data validity filter."""
    n, f = binned.shape
    # histogram per feature: scatter (grad, hess, in_bag) into (F*B, 3)
    flat = binned.astype(jnp.int32) + jnp.arange(f)[None, :] * num_bins
    contrib = jnp.stack([g, h, in_bag], axis=1)              # (N, 3)
    tot = jnp.zeros((f * num_bins, 3), jnp.float32)
    tot = tot.at[flat].add(contrib[:, None, :])              # (N,F) idx rows
    hist = tot.reshape(f, num_bins, 3)
    cum = jnp.cumsum(hist, axis=1)                          # (F, B, 3)
    G, H = cum[:, -1, 0:1], cum[:, -1, 1:2]
    GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
    GR, HR, CR = G - GL, H - HL, cum[:, -1, 2:3] - CL
    lam = jnp.float32(lambda_l2)
    gain = (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
            - G ** 2 / (H + lam))
    valid = (CL >= min_data) & (CR >= min_data)
    return jnp.max(jnp.where(valid, gain, -jnp.inf), axis=1)  # (F,)


#: compiled selection programs keyed by (mesh, shapes, knobs) — the r05 A/B
#: measured the EAGER per-call shard_map rebuild at ~11 s/tree; the cached
#: jit brings steady-state selection to one device dispatch per tree.
_SELECT_CACHE: dict = {}
_SELECT_CACHE_MAX = 16


def _select_fn(mesh, n: int, f: int, k: int, out_k: int, num_bins: int,
               lambda_l2: float, min_data: int, stride: int):
    key = (mesh, n, f, k, out_k, num_bins, float(lambda_l2), int(min_data),
           stride)
    fn = _SELECT_CACHE.get(key)
    if fn is not None:
        return fn

    def _select(b_shard, g_shard, h_shard, bag_shard, act):
        if stride > 1:
            # strided per-shard subsample (static shapes, no collectives);
            # scaling contributions by the stride keeps G/H/counts unbiased
            b_shard, g_shard = b_shard[::stride], g_shard[::stride]
            h_shard, bag_shard = h_shard[::stride], bag_shard[::stride]
            g_shard = g_shard * float(stride)
            h_shard = h_shard * float(stride)
            bag_shard = bag_shard * float(stride)
        local_gain = _per_feature_root_gain(b_shard, g_shard, h_shard,
                                            bag_shard, num_bins, lambda_l2,
                                            min_data)
        local_gain = jnp.where(act, local_gain, -jnp.inf)
        # local top-k vote (PV-Tree step 1)
        _, top_idx = jax.lax.top_k(local_gain, k)
        votes = jnp.zeros((f,), jnp.float32).at[top_idx].add(1.0)
        votes = jax.lax.psum(votes, DATA_AXIS)
        gain_sum = jax.lax.psum(jnp.where(jnp.isfinite(local_gain),
                                          local_gain, 0.0), DATA_AXIS)
        # global selection: votes dominate, gain-sum breaks ties (step 2)
        norm_gain = gain_sum / (jnp.max(jnp.abs(gain_sum)) + 1e-12)
        score = votes * 2.0 + norm_gain
        score = jnp.where(act, score, -jnp.inf)
        _, sel = jax.lax.top_k(score, out_k)
        return jnp.sort(sel)

    fn = jax.jit(shard_map(
        _select, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P()),
        out_specs=P(), check_vma=False))
    if len(_SELECT_CACHE) >= _SELECT_CACHE_MAX:
        _SELECT_CACHE.pop(next(iter(_SELECT_CACHE)))
    _SELECT_CACHE[key] = fn
    return fn


def _selection_stride(n: int, mesh, sample_rows) -> int:
    """Static per-shard subsample stride for the selection pass."""
    if sample_rows is None:
        sample_rows = DEFAULT_SELECTION_SAMPLE_ROWS
    if sample_rows <= 0:
        return 1
    shard_rows = max(n // int(dict(mesh.shape).get(DATA_AXIS, 1)), 1)
    return max(-(-shard_rows // int(sample_rows)), 1)


def voting_select(binned, g, h, in_bag, mesh, top_k: int, num_bins: int,
                  lambda_l2: float = 0.0, min_data: int = 1,
                  feature_active=None, sample_rows=None) -> np.ndarray:
    """Global top-2k feature indices by per-shard votes (gain-sum tie-break).
    Returns a sorted int array of 2k (or fewer) feature indices, replicated.
    ``feature_active`` (F,) bool restricts voting to the feature_fraction
    sample so selection never wastes slots on masked-out features.
    ``sample_rows`` caps the per-shard rows the vote scans (default
    DEFAULT_SELECTION_SAMPLE_ROWS; <=0 disables sampling)."""
    n, f = binned.shape
    k = min(top_k, f)
    out_k = min(2 * k, f)
    active = (jnp.ones((f,), bool) if feature_active is None
              else jnp.asarray(feature_active))
    stride = _selection_stride(n, mesh, sample_rows)
    fn = _select_fn(mesh, n, f, k, out_k, num_bins, lambda_l2, min_data,
                    stride)
    return np.asarray(fn(binned, g, h, in_bag, active))


def time_selection(binned, mesh, top_k: int, num_bins: int,
                   lambda_l2: float = 0.0, min_data: int = 1,
                   sample_rows=None) -> tuple:
    """Measured (seconds_per_selection, fraction_of_shard_rows_scanned) of
    the jitted selection pass on this dataset — synthetic unit gradients,
    compile excluded (the compiled program lands in _SELECT_CACHE, so the
    training loop reuses it). Feeds ``route_parallelism``'s measured
    ``selection_s_per_tree``."""
    import time

    n, _ = binned.shape
    ones = jnp.ones((n,), jnp.float32)
    jax.block_until_ready(
        voting_select(binned, ones, ones, ones, mesh, top_k, num_bins,
                      lambda_l2, min_data, sample_rows=sample_rows))
    t0 = time.perf_counter()
    jax.block_until_ready(
        voting_select(binned, ones, ones, ones, mesh, top_k, num_bins,
                      lambda_l2, min_data, sample_rows=sample_rows))
    dt = time.perf_counter() - t0
    return dt, 1.0 / _selection_stride(n, mesh, sample_rows)


def remap_tree_features(tree, sel_idx: np.ndarray):
    """Split features of a tree grown on sliced columns → full feature space."""
    sel = jnp.asarray(sel_idx, jnp.int32)
    return tree._replace(split_feature=sel[tree.split_feature])


# ---------------------------------------------------------------------------
# Collective cost model — when does voting-parallel actually pay?
# ---------------------------------------------------------------------------
#
# The A/B on a single-host mesh (docs/measurements.json
# gbdt_voting_vs_data_parallel_speedup) shows voting as a pure cost there:
# allreduce over a host-local mesh is a memcpy, so the smaller histogram
# payload buys nothing while the root-selection pass still runs. The model
# below prices the tradeoff explicitly — logical collective bytes per split
# for both modes, the per-tree saving, and the link bandwidth below which
# that saving outweighs the measured selection overhead (PV-Tree's regime:
# many hosts on a thin DCN link). LightGBM ships the same knob pair
# (parallelism/topK, params/LightGBMParams.scala:25-27,
# LightGBMConstants.scala:22-24) but leaves the choice entirely manual.

# per-link full-duplex bandwidth, bytes/s — public figures (the scaling-book
# mental model): ICI ~1e11 B/s per link on v4/v5p-class chips; DCN per-host
# is NIC-bound, ~1.25e10 B/s (100 Gb/s) in common fleet configs.
DEFAULT_LINK_BYTES_PER_S = {"ici": 1.0e11, "dcn": 1.25e10}

# the selection pass's compute is ONE extra root-histogram build over all
# features (voting_select literally builds one); relative to a whole tree
# (whose histogram work revisits each row roughly tree-depth times) that is
# a FRACTION of per-tree compute. 0.3 is deliberately conservative (against
# voting); bench_voting_ab records the measured per-tree overhead alongside
# the model so the estimate is auditable against data.
DEFAULT_SELECTION_FRACTION = 0.3
# fallback engine throughput anchor (row-iters/sec/chip) when
# docs/measurements.json is unreadable — the BENCH_r03 capture. Conservative:
# a faster engine shrinks selection cost and favors voting.
DEFAULT_ENGINE_ROW_ITERS_PER_S = 1.69e6

#: effective wire bytes per histogram element for each
#: BoosterConfig.hist_allreduce_dtype rung: bf16 ships grad/hess at 2 bytes
#: with counts still f32 (→ 8/3 average); int8 is the blockwise-quantized
#: allreduce (int16 grid values on the wire + f32 scales per 256-block
#: ≈ 2 bytes effective, with counts exact — parallel/collectives.py).
WIRE_DTYPE_BYTES = {"f32": 4.0, "bf16": 8.0 / 3.0, "int8": 2.0}

#: fraction of a full-width histogram pass spent scanning (feature, bin)
#: cells for split gains rather than building bins from rows. Scatter-mode
#: feature-parallel scans only its owned 1/W of the features, so its
#: per-pass compute shrinks by ``scan_fraction * (1 - 1/W)``. Calibrated on
#: the 8-device CPU-mesh bench (bench_distributed_gbdt_auto): wide, narrow
#: and tall shapes all measure feature-parallel at 0.90-0.93x data-parallel
#: seconds/tree, which a pure wire model cannot explain on a host-local
#: mesh where collective bytes are ~free.
FEATURE_SCAN_FRACTION = 0.10


def default_engine_row_iters_per_s() -> float:
    """Engine throughput anchor for the selection-cost estimate: the live
    measured ``gbdt_train_row_iters_per_sec_per_chip`` record in
    docs/measurements.json when readable (the cost model then tracks the
    engine as it gets faster), else DEFAULT_ENGINE_ROW_ITERS_PER_S."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "docs",
        "measurements.json")
    try:
        with open(path) as fh:
            records = json.load(fh)
        for rec in records:
            if rec.get("metric") == "gbdt_train_row_iters_per_sec_per_chip":
                v = float(rec["value"])
                return v if v > 0 else DEFAULT_ENGINE_ROW_ITERS_PER_S
    except (OSError, ValueError, TypeError, KeyError, AttributeError):
        pass
    return DEFAULT_ENGINE_ROW_ITERS_PER_S


def collective_bytes_per_split(num_features: int, max_bin: int,
                               top_k=None, dtype_bytes: int = 4) -> int:
    """Logical allreduce payload of ONE split's histogram aggregation:
    (F_aggregated, max_bin, 3 channels) × dtype_bytes. Data-parallel
    aggregates every feature; voting-parallel only the elected 2k columns.
    ``dtype_bytes=8/3`` prices the bf16 wire option
    (BoosterConfig.hist_allreduce_dtype: grad/hess at 2 bytes, counts at
    4) — an independent 1.5x on the same comm term."""
    f_agg = (num_features if top_k is None
             else min(2 * int(top_k), num_features))
    return int(round(f_agg * int(max_bin) * 3 * dtype_bytes))


def selection_bytes_per_tree(num_features: int, dtype_bytes: int = 4) -> int:
    """The root-selection pass psums (F,) votes + (F,) gain sums once per
    tree (voting_select above)."""
    return int(num_features) * 2 * dtype_bytes


def voting_cost_model(num_features: int, max_bin: int, top_k: int,
                      num_leaves: int,
                      selection_s_per_tree: float = 1e-3,
                      dtype_bytes: float = 4) -> dict:
    """Per-tree collective accounting for both modes and the CROSSOVER link
    bandwidth: below it, the bytes voting saves per tree take longer on the
    wire than its selection pass costs — voting wins. ``dtype_bytes``
    follows the configured histogram wire precision (8/3 under bf16)."""
    splits = max(int(num_leaves) - 1, 1)
    dp = splits * collective_bytes_per_split(num_features, max_bin,
                                             dtype_bytes=dtype_bytes)
    vp = (splits * collective_bytes_per_split(num_features, max_bin, top_k,
                                              dtype_bytes=dtype_bytes)
          + selection_bytes_per_tree(num_features))
    saved = max(dp - vp, 0)
    crossover = (saved / selection_s_per_tree
                 if selection_s_per_tree > 0 else float("inf"))
    return {
        "bytes_per_split_data_parallel":
            collective_bytes_per_split(num_features, max_bin,
                                       dtype_bytes=dtype_bytes),
        "bytes_per_split_voting":
            collective_bytes_per_split(num_features, max_bin, top_k,
                                       dtype_bytes=dtype_bytes),
        "selection_bytes_per_tree": selection_bytes_per_tree(num_features),
        "bytes_per_tree_data_parallel": dp,
        "bytes_per_tree_voting": vp,
        "bytes_saved_per_tree": saved,
        "crossover_link_bytes_per_s": crossover,
    }


def recommend_tree_learner(num_features: int, max_bin: int, top_k: int,
                           num_leaves: int, n_hosts: int,
                           rows_per_host: int = None,
                           link_bytes_per_s: float = None,
                           engine_row_iters_per_s: float = None,
                           selection_fraction: float =
                           DEFAULT_SELECTION_FRACTION,
                           selection_s_per_tree: float = None,
                           dtype_bytes: float = 4) -> str:
    """The documented selection rule (VERDICT r4 #7):

    * single host — "data": every collective is intra-host (ICI/memcpy);
      the selection pass can never pay for itself.
    * narrow feature space (F <= 2k) — "data": voting would aggregate
      everything anyway.
    * multi-host — "voting" iff the per-tree wire-time saving
      ``bytes_saved_per_tree / link_bytes_per_s`` exceeds the selection
      cost. Selection cost defaults to
      ``selection_fraction * rows_per_host / engine_row_iters_per_s``
      (one extra root-histogram build, scaled by the measured engine
      throughput); pass ``selection_s_per_tree`` to override with a
      measured value (bench_voting_ab records one). With the DCN default
      this picks voting exactly for wide feature spaces on NIC-bound
      fabrics — PV-Tree's regime — and data-parallel on ICI-connected
      slices, matching the single-host A/B measurement.
    """
    if n_hosts <= 1 or num_features <= 2 * top_k:
        return "data"
    if link_bytes_per_s is None:
        link_bytes_per_s = DEFAULT_LINK_BYTES_PER_S["dcn"]
    if engine_row_iters_per_s is None:
        engine_row_iters_per_s = default_engine_row_iters_per_s()
    if selection_s_per_tree is None:
        if rows_per_host is None:
            rows_per_host = 1_000_000        # HIGGS-class shard, conservative
        selection_s_per_tree = (selection_fraction * rows_per_host
                                / engine_row_iters_per_s)
    m = voting_cost_model(num_features, max_bin, top_k, num_leaves,
                          selection_s_per_tree, dtype_bytes=dtype_bytes)
    saved_wire_s = m["bytes_saved_per_tree"] / link_bytes_per_s
    return "voting" if saved_wire_s > selection_s_per_tree else "data"


def route_parallelism(num_features: int, max_bin: int, top_k: int,
                      num_leaves: int, *, n_workers: int,
                      rows_per_worker: int, link_bytes_per_s: float,
                      selection_s_per_tree: float = None,
                      selection_fraction_of_rows: float = 1.0,
                      wire_dtype: str = "f32",
                      feature_parallel_ok: bool = False,
                      hist_passes_per_tree: float = None,
                      scan_fraction_of_pass: float = None,
                      engine_row_iters_per_s: float = None) -> tuple:
    """Measured-input router across the three distributed learners. Unlike
    :func:`recommend_tree_learner` (the byte-only rule it generalizes — kept
    for its documented behavior), this prices per-tree COMPUTE as well as
    wire time, anchored on a measured selection pass, so it can prefer
    voting even on a host-local mesh where wire bytes are ~free but the
    in-loop histogram width still dominates.

    Returns ``(choice, info)`` where info records every model input, the
    per-mode predicted s/tree, and the byte accounting — audited into
    ``Booster.metadata["routing"]`` by ``train_booster``.

    Terms, per tree (``splits = num_leaves - 1``):

    * wire: ``voting_cost_model`` bytes at the configured wire dtype
      (``WIRE_DTYPE_BYTES`` — int8 halves data-parallel bytes, shifting the
      voting crossover ~2x) divided by the measured link bandwidth.
      Feature-parallel moves ~half the allreduce bytes (reduce-scatter
      only) plus a tiny per-split (n_workers, 5)-float candidate exchange.
    * compute: one full-width root pass costs
      ``selection_s_per_tree / selection_fraction_of_rows`` (the probe may
      subsample rows); smaller-child subtraction makes a tree cost about
      ``1 + log2(L)/2`` such passes. Voting's in-loop passes run at the
      elected ``2k``-of-``F`` width (padded, as the kernel sees it); its
      selection pass is a flat per-tree add. Feature-parallel builds
      full-width histograms but split-scans only its owned ``1/W`` of the
      features, so its pass shrinks by the scan share of a pass
      (``FEATURE_SCAN_FRACTION``, calibrated on the CPU-mesh bench).

    A 5% hysteresis favors data-parallel: the probe's error bars must not
    route a marginal predicted win onto a slower mode (the bench guard
    asserts auto stays within 5% of the best manual flag, so a choice the
    hysteresis keeps on data is within guard tolerance by construction
    whenever the model is right to within its own margin).
    """
    from .grower import features_padded

    db = WIRE_DTYPE_BYTES.get(wire_dtype, 4.0)
    splits = max(int(num_leaves) - 1, 1)
    if hist_passes_per_tree is None:
        hist_passes_per_tree = 1.0 + 0.5 * math.log2(max(num_leaves, 2))
    if selection_s_per_tree is None or selection_s_per_tree <= 0:
        if engine_row_iters_per_s is None:
            engine_row_iters_per_s = default_engine_row_iters_per_s()
        selection_s_per_tree = (DEFAULT_SELECTION_FRACTION * rows_per_worker
                                / engine_row_iters_per_s)
        selection_fraction_of_rows = DEFAULT_SELECTION_FRACTION
    t_root_full = selection_s_per_tree / max(selection_fraction_of_rows,
                                             1e-9)
    t_hist_full = hist_passes_per_tree * t_root_full
    m = voting_cost_model(num_features, max_bin, top_k, num_leaves,
                          selection_s_per_tree, dtype_bytes=db)

    def wire(nbytes):
        return nbytes / max(link_bytes_per_s, 1.0)

    fp_ratio = (features_padded(min(2 * top_k, num_features))
                / max(features_padded(num_features), 1))
    if scan_fraction_of_pass is None:
        scan_fraction_of_pass = FEATURE_SCAN_FRACTION
    scatter_compute = 1.0 - scan_fraction_of_pass * (1.0
                                                     - 1.0 / max(n_workers, 1))
    exchange_bytes = splits * n_workers * 5 * 4
    predicted = {
        "data": t_hist_full + wire(m["bytes_per_tree_data_parallel"]),
        "voting": (selection_s_per_tree + t_hist_full * fp_ratio
                   + wire(m["bytes_per_tree_voting"])),
        "feature": (t_hist_full * scatter_compute
                    + wire(0.5 * m["bytes_per_tree_data_parallel"]
                           + exchange_bytes)),
    }
    candidates = {"data": predicted["data"]}
    if num_features > 2 * top_k and n_workers > 1:
        candidates["voting"] = predicted["voting"]
    if feature_parallel_ok and n_workers > 1:
        candidates["feature"] = predicted["feature"]
    choice = min(candidates, key=candidates.get)
    if choice != "data" and candidates[choice] > 0.95 * candidates["data"]:
        choice = "data"
    info = {
        "tree_learner": choice,
        "predicted_s_per_tree": predicted,
        "considered": sorted(candidates),
        "inputs": {
            "num_features": int(num_features), "max_bin": int(max_bin),
            "top_k": int(top_k), "num_leaves": int(num_leaves),
            "n_workers": int(n_workers),
            "rows_per_worker": int(rows_per_worker),
            "link_bytes_per_s": float(link_bytes_per_s),
            "selection_s_per_tree": float(selection_s_per_tree),
            "selection_fraction_of_rows": float(selection_fraction_of_rows),
            "wire_dtype": wire_dtype, "wire_dtype_bytes": db,
            "hist_passes_per_tree": float(hist_passes_per_tree),
            "scan_fraction_of_pass": float(scan_fraction_of_pass),
        },
        "cost_model": m,
    }
    return choice, info
