"""Voting-parallel feature selection (PV-Tree).

Reference: LightGBM's ``voting_parallel`` tree learner, surfaced through
``parallelism``/``topK`` (lightgbm/.../params/LightGBMParams.scala:25-27,
LightGBMConstants.scala:22-24 DefaultTopK=20, LightGBMBase.scala:252). In
data-parallel mode every split synchronizes histograms for ALL features;
voting-parallel cuts that to O(top_k): each worker votes its local top-k
features by split gain, the global top-2k by votes (gain-sum tie-break) are
selected, and only those features' histograms are aggregated.

TPU adaptation: selection runs once per tree at the root (one shard_map with a
``psum`` of per-feature gains + votes — cheap, (F,)-sized); the tree then grows
on the SLICED (N, 2k) bin matrix, so every per-leaf histogram allreduce inside
the growth loop moves 2k features instead of F. Split feature indices are
remapped to the full feature space afterwards.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS


def _per_feature_root_gain(binned, g, h, in_bag, num_bins: int,
                           lambda_l2: float, min_data: int):
    """(F,) best numeric-split gain per feature over the root node, from this
    shard's rows only. Counts use ``in_bag`` so padding/bagged-out rows do not
    inflate the min_data validity filter."""
    n, f = binned.shape
    # histogram per feature: scatter (grad, hess, in_bag) into (F*B, 3)
    flat = binned.astype(jnp.int32) + jnp.arange(f)[None, :] * num_bins
    contrib = jnp.stack([g, h, in_bag], axis=1)              # (N, 3)
    tot = jnp.zeros((f * num_bins, 3), jnp.float32)
    tot = tot.at[flat].add(contrib[:, None, :])              # (N,F) idx rows
    hist = tot.reshape(f, num_bins, 3)
    cum = jnp.cumsum(hist, axis=1)                          # (F, B, 3)
    G, H = cum[:, -1, 0:1], cum[:, -1, 1:2]
    GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
    GR, HR, CR = G - GL, H - HL, cum[:, -1, 2:3] - CL
    lam = jnp.float32(lambda_l2)
    gain = (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
            - G ** 2 / (H + lam))
    valid = (CL >= min_data) & (CR >= min_data)
    return jnp.max(jnp.where(valid, gain, -jnp.inf), axis=1)  # (F,)


def voting_select(binned, g, h, in_bag, mesh, top_k: int, num_bins: int,
                  lambda_l2: float = 0.0, min_data: int = 1,
                  feature_active=None) -> np.ndarray:
    """Global top-2k feature indices by per-shard votes (gain-sum tie-break).
    Returns a sorted int array of 2k (or fewer) feature indices, replicated.
    ``feature_active`` (F,) bool restricts voting to the feature_fraction
    sample so selection never wastes slots on masked-out features."""
    f = binned.shape[1]
    k = min(top_k, f)
    out_k = min(2 * k, f)
    active = (jnp.ones((f,), bool) if feature_active is None
              else jnp.asarray(feature_active))

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                       P(DATA_AXIS), P()),
             out_specs=P(), check_vma=False)
    def _select(b_shard, g_shard, h_shard, bag_shard, act):
        local_gain = _per_feature_root_gain(b_shard, g_shard, h_shard,
                                            bag_shard, num_bins, lambda_l2,
                                            min_data)
        local_gain = jnp.where(act, local_gain, -jnp.inf)
        # local top-k vote (PV-Tree step 1)
        _, top_idx = jax.lax.top_k(local_gain, k)
        votes = jnp.zeros((f,), jnp.float32).at[top_idx].add(1.0)
        votes = jax.lax.psum(votes, DATA_AXIS)
        gain_sum = jax.lax.psum(jnp.where(jnp.isfinite(local_gain),
                                          local_gain, 0.0), DATA_AXIS)
        # global selection: votes dominate, gain-sum breaks ties (step 2)
        norm_gain = gain_sum / (jnp.max(jnp.abs(gain_sum)) + 1e-12)
        score = votes * 2.0 + norm_gain
        score = jnp.where(act, score, -jnp.inf)
        _, sel = jax.lax.top_k(score, out_k)
        return jnp.sort(sel)

    return np.asarray(_select(binned, g, h, in_bag, active))


def remap_tree_features(tree, sel_idx: np.ndarray):
    """Split features of a tree grown on sliced columns → full feature space."""
    sel = jnp.asarray(sel_idx, jnp.int32)
    return tree._replace(split_feature=sel[tree.split_feature])
