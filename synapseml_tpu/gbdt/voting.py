"""Voting-parallel feature selection (PV-Tree).

Reference: LightGBM's ``voting_parallel`` tree learner, surfaced through
``parallelism``/``topK`` (lightgbm/.../params/LightGBMParams.scala:25-27,
LightGBMConstants.scala:22-24 DefaultTopK=20, LightGBMBase.scala:252). In
data-parallel mode every split synchronizes histograms for ALL features;
voting-parallel cuts that to O(top_k): each worker votes its local top-k
features by split gain, the global top-2k by votes (gain-sum tie-break) are
selected, and only those features' histograms are aggregated.

TPU adaptation: selection runs once per tree at the root (one shard_map with a
``psum`` of per-feature gains + votes — cheap, (F,)-sized); the tree then grows
on the SLICED (N, 2k) bin matrix, so every per-leaf histogram allreduce inside
the growth loop moves 2k features instead of F. Split feature indices are
remapped to the full feature space afterwards.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map
from ..parallel.mesh import DATA_AXIS


def _per_feature_root_gain(binned, g, h, in_bag, num_bins: int,
                           lambda_l2: float, min_data: int):
    """(F,) best numeric-split gain per feature over the root node, from this
    shard's rows only. Counts use ``in_bag`` so padding/bagged-out rows do not
    inflate the min_data validity filter."""
    n, f = binned.shape
    # histogram per feature: scatter (grad, hess, in_bag) into (F*B, 3)
    flat = binned.astype(jnp.int32) + jnp.arange(f)[None, :] * num_bins
    contrib = jnp.stack([g, h, in_bag], axis=1)              # (N, 3)
    tot = jnp.zeros((f * num_bins, 3), jnp.float32)
    tot = tot.at[flat].add(contrib[:, None, :])              # (N,F) idx rows
    hist = tot.reshape(f, num_bins, 3)
    cum = jnp.cumsum(hist, axis=1)                          # (F, B, 3)
    G, H = cum[:, -1, 0:1], cum[:, -1, 1:2]
    GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
    GR, HR, CR = G - GL, H - HL, cum[:, -1, 2:3] - CL
    lam = jnp.float32(lambda_l2)
    gain = (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
            - G ** 2 / (H + lam))
    valid = (CL >= min_data) & (CR >= min_data)
    return jnp.max(jnp.where(valid, gain, -jnp.inf), axis=1)  # (F,)


def voting_select(binned, g, h, in_bag, mesh, top_k: int, num_bins: int,
                  lambda_l2: float = 0.0, min_data: int = 1,
                  feature_active=None) -> np.ndarray:
    """Global top-2k feature indices by per-shard votes (gain-sum tie-break).
    Returns a sorted int array of 2k (or fewer) feature indices, replicated.
    ``feature_active`` (F,) bool restricts voting to the feature_fraction
    sample so selection never wastes slots on masked-out features."""
    f = binned.shape[1]
    k = min(top_k, f)
    out_k = min(2 * k, f)
    active = (jnp.ones((f,), bool) if feature_active is None
              else jnp.asarray(feature_active))

    @partial(shard_map, mesh=mesh,
             in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                       P(DATA_AXIS), P()),
             out_specs=P(), check_vma=False)
    def _select(b_shard, g_shard, h_shard, bag_shard, act):
        local_gain = _per_feature_root_gain(b_shard, g_shard, h_shard,
                                            bag_shard, num_bins, lambda_l2,
                                            min_data)
        local_gain = jnp.where(act, local_gain, -jnp.inf)
        # local top-k vote (PV-Tree step 1)
        _, top_idx = jax.lax.top_k(local_gain, k)
        votes = jnp.zeros((f,), jnp.float32).at[top_idx].add(1.0)
        votes = jax.lax.psum(votes, DATA_AXIS)
        gain_sum = jax.lax.psum(jnp.where(jnp.isfinite(local_gain),
                                          local_gain, 0.0), DATA_AXIS)
        # global selection: votes dominate, gain-sum breaks ties (step 2)
        norm_gain = gain_sum / (jnp.max(jnp.abs(gain_sum)) + 1e-12)
        score = votes * 2.0 + norm_gain
        score = jnp.where(act, score, -jnp.inf)
        _, sel = jax.lax.top_k(score, out_k)
        return jnp.sort(sel)

    return np.asarray(_select(binned, g, h, in_bag, active))


def remap_tree_features(tree, sel_idx: np.ndarray):
    """Split features of a tree grown on sliced columns → full feature space."""
    sel = jnp.asarray(sel_idx, jnp.int32)
    return tree._replace(split_feature=sel[tree.split_feature])


# ---------------------------------------------------------------------------
# Collective cost model — when does voting-parallel actually pay?
# ---------------------------------------------------------------------------
#
# The A/B on a single-host mesh (docs/measurements.json
# gbdt_voting_vs_data_parallel_speedup) shows voting as a pure cost there:
# allreduce over a host-local mesh is a memcpy, so the smaller histogram
# payload buys nothing while the root-selection pass still runs. The model
# below prices the tradeoff explicitly — logical collective bytes per split
# for both modes, the per-tree saving, and the link bandwidth below which
# that saving outweighs the measured selection overhead (PV-Tree's regime:
# many hosts on a thin DCN link). LightGBM ships the same knob pair
# (parallelism/topK, params/LightGBMParams.scala:25-27,
# LightGBMConstants.scala:22-24) but leaves the choice entirely manual.

# per-link full-duplex bandwidth, bytes/s — public figures (the scaling-book
# mental model): ICI ~1e11 B/s per link on v4/v5p-class chips; DCN per-host
# is NIC-bound, ~1.25e10 B/s (100 Gb/s) in common fleet configs.
DEFAULT_LINK_BYTES_PER_S = {"ici": 1.0e11, "dcn": 1.25e10}

# the selection pass's compute is ONE extra root-histogram build over all
# features (voting_select literally builds one); relative to a whole tree
# (whose histogram work revisits each row roughly tree-depth times) that is
# a FRACTION of per-tree compute. 0.3 is deliberately conservative (against
# voting); bench_voting_ab records the measured per-tree overhead alongside
# the model so the estimate is auditable against data.
DEFAULT_SELECTION_FRACTION = 0.3
# measured on-chip engine throughput anchor (row-iters/sec/chip, the
# primary bench capture in docs/measurements.json) — converts rows into
# seconds for the selection-cost estimate. Conservative: a faster engine
# shrinks selection cost and favors voting.
DEFAULT_ENGINE_ROW_ITERS_PER_S = 1.69e6


def collective_bytes_per_split(num_features: int, max_bin: int,
                               top_k=None, dtype_bytes: int = 4) -> int:
    """Logical allreduce payload of ONE split's histogram aggregation:
    (F_aggregated, max_bin, 3 channels) × dtype_bytes. Data-parallel
    aggregates every feature; voting-parallel only the elected 2k columns.
    ``dtype_bytes=8/3`` prices the bf16 wire option
    (BoosterConfig.hist_allreduce_dtype: grad/hess at 2 bytes, counts at
    4) — an independent 1.5x on the same comm term."""
    f_agg = (num_features if top_k is None
             else min(2 * int(top_k), num_features))
    return int(round(f_agg * int(max_bin) * 3 * dtype_bytes))


def selection_bytes_per_tree(num_features: int, dtype_bytes: int = 4) -> int:
    """The root-selection pass psums (F,) votes + (F,) gain sums once per
    tree (voting_select above)."""
    return int(num_features) * 2 * dtype_bytes


def voting_cost_model(num_features: int, max_bin: int, top_k: int,
                      num_leaves: int,
                      selection_s_per_tree: float = 1e-3,
                      dtype_bytes: float = 4) -> dict:
    """Per-tree collective accounting for both modes and the CROSSOVER link
    bandwidth: below it, the bytes voting saves per tree take longer on the
    wire than its selection pass costs — voting wins. ``dtype_bytes``
    follows the configured histogram wire precision (8/3 under bf16)."""
    splits = max(int(num_leaves) - 1, 1)
    dp = splits * collective_bytes_per_split(num_features, max_bin,
                                             dtype_bytes=dtype_bytes)
    vp = (splits * collective_bytes_per_split(num_features, max_bin, top_k,
                                              dtype_bytes=dtype_bytes)
          + selection_bytes_per_tree(num_features))
    saved = max(dp - vp, 0)
    crossover = (saved / selection_s_per_tree
                 if selection_s_per_tree > 0 else float("inf"))
    return {
        "bytes_per_split_data_parallel":
            collective_bytes_per_split(num_features, max_bin,
                                       dtype_bytes=dtype_bytes),
        "bytes_per_split_voting":
            collective_bytes_per_split(num_features, max_bin, top_k,
                                       dtype_bytes=dtype_bytes),
        "selection_bytes_per_tree": selection_bytes_per_tree(num_features),
        "bytes_per_tree_data_parallel": dp,
        "bytes_per_tree_voting": vp,
        "bytes_saved_per_tree": saved,
        "crossover_link_bytes_per_s": crossover,
    }


def recommend_tree_learner(num_features: int, max_bin: int, top_k: int,
                           num_leaves: int, n_hosts: int,
                           rows_per_host: int = None,
                           link_bytes_per_s: float = None,
                           engine_row_iters_per_s: float =
                           DEFAULT_ENGINE_ROW_ITERS_PER_S,
                           selection_fraction: float =
                           DEFAULT_SELECTION_FRACTION,
                           selection_s_per_tree: float = None,
                           dtype_bytes: float = 4) -> str:
    """The documented selection rule (VERDICT r4 #7):

    * single host — "data": every collective is intra-host (ICI/memcpy);
      the selection pass can never pay for itself.
    * narrow feature space (F <= 2k) — "data": voting would aggregate
      everything anyway.
    * multi-host — "voting" iff the per-tree wire-time saving
      ``bytes_saved_per_tree / link_bytes_per_s`` exceeds the selection
      cost. Selection cost defaults to
      ``selection_fraction * rows_per_host / engine_row_iters_per_s``
      (one extra root-histogram build, scaled by the measured engine
      throughput); pass ``selection_s_per_tree`` to override with a
      measured value (bench_voting_ab records one). With the DCN default
      this picks voting exactly for wide feature spaces on NIC-bound
      fabrics — PV-Tree's regime — and data-parallel on ICI-connected
      slices, matching the single-host A/B measurement.
    """
    if n_hosts <= 1 or num_features <= 2 * top_k:
        return "data"
    if link_bytes_per_s is None:
        link_bytes_per_s = DEFAULT_LINK_BYTES_PER_S["dcn"]
    if selection_s_per_tree is None:
        if rows_per_host is None:
            rows_per_host = 1_000_000        # HIGGS-class shard, conservative
        selection_s_per_tree = (selection_fraction * rows_per_host
                                / engine_row_iters_per_s)
    m = voting_cost_model(num_features, max_bin, top_k, num_leaves,
                          selection_s_per_tree, dtype_bytes=dtype_bytes)
    saved_wire_s = m["bytes_saved_per_tree"] / link_bytes_per_s
    return "voting" if saved_wire_s > selection_s_per_tree else "data"
